"""Intra-op tensor parallelism helpers.

Beyond-reference capability (SURVEY.md §2.6: the reference's model
parallelism is graph-partition only; true intra-op TP comes "for free" on a
mesh). The Megatron-style pair:

* **column-parallel** Dense: weight sharded on the output dim; activations
  stay sharded, no collective in forward;
* **row-parallel** Dense: weight sharded on the input dim; forward ends in a
  ``psum`` over the model axis (backward gets the broadcast automatically).

A column→row pair implements a sharded MLP with exactly one all-reduce, and
a QKV-column / out-row pair does the same for attention. These are shard_map
building blocks; under plain ``pjit`` the same layouts fall out of weight
``PartitionSpec``s — both idioms are supported.
"""

from __future__ import annotations

import functools

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tp_region(x, axis_name):
    """Megatron's *f* operator: identity forward, psum backward.

    Placed at the entry of every column-parallel region. Each shard's
    backward produces only ITS slice's contribution to the input gradient;
    the full gradient is their sum. Without this, gradients flowing back to
    REPLICATED parameters (embeddings, LayerNorms) are partial and differ
    per shard, silently desynchronizing them from the first optimizer step
    (the row-parallel side needs no twin: psum's transpose is already the
    broadcast)."""
    return x


def _copy_fwd(x, axis_name):
    return x, None


def _copy_bwd(axis_name, _, g):
    return (lax.psum(g, axis_name),)


copy_to_tp_region.defvjp(_copy_fwd, _copy_bwd)


class ColumnParallelDense(nn.Module):
    """Dense with output features split over ``axis_name``.

    In-shard features = ``features // axis_size``. Input must be replicated
    (or identically sharded) across the model axis; output is sharded on the
    feature dim. The input rides :func:`copy_to_tp_region`, so gradients
    leaving the TP region are the full cross-shard sum.
    """

    features: int
    axis_name: str
    use_bias: bool = True
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        n = lax.axis_size(self.axis_name)
        assert self.features % n == 0, (
            f"features {self.features} not divisible by axis {n}")
        local = self.features // n
        x = copy_to_tp_region(x, self.axis_name)
        y = nn.Dense(local, use_bias=self.use_bias, dtype=self.dtype)(x)
        return y


class RowParallelDense(nn.Module):
    """Dense with input features split over ``axis_name``; forward psums.

    Input is feature-sharded (the column-parallel output); the result is the
    full matmul, replicated across the model axis.
    """

    features: int
    axis_name: str
    use_bias: bool = True
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        y = nn.Dense(self.features, use_bias=False, dtype=self.dtype)(x)
        y = lax.psum(y, self.axis_name)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros_init(),
                              (self.features,))
            y = y + bias
        return y


class TensorParallelMLP(nn.Module):
    """Column → activation → row: one psum per MLP block."""

    hidden: int
    out: int
    axis_name: str
    act: Callable = nn.gelu
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        h = ColumnParallelDense(self.hidden, self.axis_name,
                                dtype=self.dtype)(x)
        h = self.act(h)
        return RowParallelDense(self.out, self.axis_name,
                                dtype=self.dtype)(h)


def vocab_parallel_cross_entropy(logits, targets, axis_name: str):
    """Cross-entropy over VOCAB-SHARDED logits — the loss-parallel epilogue
    of a column-parallel LM head.

    The full [B, L, V] logits never exist on any device: each shard holds a
    contiguous vocab slice ``[i*Vl, (i+1)*Vl)`` (the layout
    `ColumnParallelDense` produces) and the softmax normalizer, max shift,
    and target logit are assembled with one pmax and two psums of [B, L]
    arrays — communication is O(B·L), not O(B·L·V).

    logits: [..., V_local] (sharded on ``axis_name``); targets: [...] int
    GLOBAL vocab ids (replicated). Returns per-token loss [...], replicated.
    """
    vl = logits.shape[-1]
    lo = lax.axis_index(axis_name) * vl
    logits = logits.astype(jnp.float32)
    # the max shift is gradient-neutral (it cancels in softmax); pmax has
    # no differentiation rule, so route it through a zero-cotangent VJP
    m = _pmax_stop_gradient(jnp.max(logits, -1), axis_name)
    z = lax.psum(jnp.sum(jnp.exp(logits - m[..., None]), -1), axis_name)
    local_t = targets - lo
    in_shard = (local_t >= 0) & (local_t < vl)
    safe_t = jnp.clip(local_t, 0, vl - 1)
    tlogit = jnp.take_along_axis(logits, safe_t[..., None], -1)[..., 0]
    tlogit = lax.psum(jnp.where(in_shard, tlogit, 0.0), axis_name)
    return m + jnp.log(z) - tlogit


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _pmax_stop_gradient(x, axis_name):
    """lax.pmax treated as a constant by differentiation (no pmax VJP
    exists in JAX; the logsumexp max shift needs none)."""
    return lax.pmax(x, axis_name)


def _pmax_sg_fwd(x, axis_name):
    return lax.pmax(x, axis_name), None


def _pmax_sg_bwd(axis_name, _, g):
    return (jnp.zeros_like(g),)


_pmax_stop_gradient.defvjp(_pmax_sg_fwd, _pmax_sg_bwd)

# public alias: a pmax whose gradient is defined (zero cotangent) — for
# metrics computed alongside a differentiated loss
pmax_stop_gradient = _pmax_stop_gradient

"""Intra-op tensor parallelism helpers.

Beyond-reference capability (SURVEY.md §2.6: the reference's model
parallelism is graph-partition only; true intra-op TP comes "for free" on a
mesh). The Megatron-style pair:

* **column-parallel** Dense: weight sharded on the output dim; activations
  stay sharded, no collective in forward;
* **row-parallel** Dense: weight sharded on the input dim; forward ends in a
  ``psum`` over the model axis (backward gets the broadcast automatically).

A column→row pair implements a sharded MLP with exactly one all-reduce, and
a QKV-column / out-row pair does the same for attention. These are shard_map
building blocks; under plain ``pjit`` the same layouts fall out of weight
``PartitionSpec``s — both idioms are supported.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


class ColumnParallelDense(nn.Module):
    """Dense with output features split over ``axis_name``.

    In-shard features = ``features // axis_size``. Input must be replicated
    (or identically sharded) across the model axis; output is sharded on the
    feature dim.
    """

    features: int
    axis_name: str
    use_bias: bool = True
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        n = lax.axis_size(self.axis_name)
        assert self.features % n == 0, (
            f"features {self.features} not divisible by axis {n}")
        local = self.features // n
        y = nn.Dense(local, use_bias=self.use_bias, dtype=self.dtype)(x)
        return y


class RowParallelDense(nn.Module):
    """Dense with input features split over ``axis_name``; forward psums.

    Input is feature-sharded (the column-parallel output); the result is the
    full matmul, replicated across the model axis.
    """

    features: int
    axis_name: str
    use_bias: bool = True
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        y = nn.Dense(self.features, use_bias=False, dtype=self.dtype)(x)
        y = lax.psum(y, self.axis_name)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros_init(),
                              (self.features,))
            y = y + bias
        return y


class TensorParallelMLP(nn.Module):
    """Column → activation → row: one psum per MLP block."""

    hidden: int
    out: int
    axis_name: str
    act: Callable = nn.gelu
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        h = ColumnParallelDense(self.hidden, self.axis_name,
                                dtype=self.dtype)(x)
        h = self.act(h)
        return RowParallelDense(self.out, self.axis_name,
                                dtype=self.dtype)(h)

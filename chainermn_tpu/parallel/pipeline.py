"""Micro-batched pipeline parallelism over a mesh axis.

The reference's pipeline story is MultiNodeChainList's sequential fill/drain
(no micro-batch scheduler — SURVEY.md §2.6). This module is the TPU-native
performance path beyond that: homogeneous stages whose parameters are
*stacked and sharded* over the ``stage`` mesh axis (true memory scaling) and
a GPipe-style rotating schedule compiled into one ``lax.fori_loop`` whose
inter-stage hop is a single neighbor ``ppermute`` — the canonical
"pipelining with collective_permute" pattern on TPU.

Schedule: with S stages and M micro-batches, the loop runs S+M-1 ticks; at
tick t, stage s processes micro-batch t-s (when 0 ≤ t-s < M). Each shard
holds its own stage's parameters only.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.utils import match_vma


def _stage_act_dtype(stage_fn, stage_params, mb_shape, in_dtype):
    """Activation dtype of one stage; rejects non-shape-preserving stages
    (the homogeneous-pipeline contract both schedules rely on)."""
    out_aval = jax.eval_shape(
        stage_fn, stage_params, jax.ShapeDtypeStruct(mb_shape, in_dtype))
    if out_aval.shape != mb_shape:
        raise ValueError(
            f"pipeline stages must preserve the activation shape "
            f"(homogeneous pipeline); stage maps {mb_shape} -> "
            f"{out_aval.shape}"
        )
    return out_aval.dtype


def pipeline_apply(
    stage_fn: Callable,
    stage_params: Any,
    x_microbatches,
    axis_name: str,
):
    """Run the pipeline forward inside shard_map.

    Args:
      stage_fn: ``(params, h) -> h`` — one stage's compute. All stages share
        this structure (homogeneous pipeline); per-stage behavior comes from
        the sharded ``stage_params``.
      stage_params: THIS shard's stage parameters (pytree). In the driver,
        stack per-stage params on a leading axis sharded over ``axis_name``
        and strip it in-shard (in_specs does this).
      x_microbatches: [M, mb, ...] micro-batches, replicated; stage 0 feeds
        them in, the last stage's outputs are collected ([M, mb, ...]).
      axis_name: the stage mesh axis.

    Returns stacked outputs [M, mb, ...] (valid on every shard).
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    m = x_microbatches.shape[0]
    mb_shape = x_microbatches.shape[1:]

    ticks = n + m - 1
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]

    # activation dtype/shape comes from the stage itself (homogeneous
    # pipeline: output shape == input shape, but dtype may be bf16 etc.)
    act_dtype = _stage_act_dtype(stage_fn, stage_params, mb_shape,
                                 x_microbatches.dtype)

    # carry: (current activation, collected outputs) — pcast to varying so
    # the fori_loop carry matches the per-shard (varying) updates; the
    # vma reference is the union with the params' axes (TP×PP stages
    # produce outputs varying on the model axis too — see _vma_ref)
    vref = _vma_ref(my, stage_params)
    h0 = match_vma(jnp.zeros(mb_shape, act_dtype), vref)
    outs = match_vma(jnp.zeros((m,) + mb_shape, act_dtype), vref)

    def tick(t, carry):
        h, outs = carry
        # stage 0 ingests micro-batch t (if in range); others use the
        # activation that arrived over the ring
        feed = lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(t, 0, m - 1), axis=0, keepdims=False
        ).astype(act_dtype)
        h_in = jnp.where(my == 0, feed, h)
        y = stage_fn(stage_params, h_in)
        # last stage records micro-batch t-(n-1) when valid
        mb_idx = t - (n - 1)
        valid = jnp.logical_and(my == n - 1,
                                jnp.logical_and(mb_idx >= 0, mb_idx < m))
        outs = lax.cond(
            valid,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y, jnp.clip(mb_idx, 0, m - 1), axis=0),
            lambda o: o,
            outs,
        )
        # rotate activations one hop down the ring
        h_next = lax.ppermute(y, axis_name, fwd_perm)
        return h_next, outs

    _, outs = lax.fori_loop(0, ticks, tick, (h0, outs))
    # make the last stage's collection visible everywhere
    last = n - 1
    keep = (my == last)
    outs = lax.psum(jnp.where(keep, outs, jnp.zeros_like(outs)), axis_name)
    return outs


def stack_stage_params(params_list):
    """Stack per-stage param pytrees on a leading axis (shard over the
    stage mesh axis with P('stage') in_specs)."""
    return jax.tree_util.tree_map(
        lambda *ls: jnp.stack(ls), *params_list
    )


def _vma_ref(my, stage_params):
    """Carry-vma reference: the stage axis UNION every varying axis of
    the stage params. Composed TP×PP shards params over a second mesh
    axis, and values computed from them (a row-parallel block's
    post-psum bias add, the loss on its output) carry that axis in their
    vma even where the numbers are equal across it — so every kernel
    carry and cond branch must be pcast to the union or the fori_loop/
    cond types diverge. Single-axis pipelines: reduces to ``my``."""
    ref = my
    for l in jax.tree_util.tree_leaves(stage_params):
        ref = match_vma(ref, l)
    return ref


def _head_loss_grads(loss_fn, head_params_v, is_last, y, tgt, vref):
    """Loss value + output/head cotangents for the last stage's tick,
    cond-guarded so the head (an LM's d_model x vocab matmul + backward)
    runs only where the mask is true. ``loss_fn(head, out, tgt)`` must
    not contain collectives over the STAGE axis (cond branches diverge
    across stages) — but collectives over ORTHOGONAL mesh axes are fine:
    the predicate depends on the stage index only, so every member of
    such a collective takes the same branch (this is what lets a
    vocab-parallel head + cross-entropy run inside the hook, full logits
    never materializing). The skip branch mirrors the real branch's
    exact varying axes via eval_shape, whatever collectives shaped them.
    The head pytree must already be pcast to varying (``head_params_v``)
    — differentiating the replicated original would auto-psum every
    device's masked-out contribution into each device's gradient under
    shard_map's vma autodiff."""

    def _fwd_bwd(yv):
        lj, (dy, dh) = jax.value_and_grad(
            lambda y_, hp: loss_fn(hp, y_, tgt), argnums=(0, 1))(
                yv, head_params_v)
        return lj.astype(jnp.float32), dy, dh

    y = match_vma(y, vref)
    out_avals = jax.eval_shape(_fwd_bwd, y)

    def _skip(yv):
        # fresh zeros are axis-invariant; pcast each leaf UP to exactly
        # the real branch's vma (psums inside loss_fn may have REMOVED
        # axes there, so a blanket vref match would overshoot)
        def z(a):
            buf = jnp.zeros(a.shape, a.dtype)
            need = tuple(getattr(a, "vma", None) or ())
            return lax.pcast(buf, need, to="varying") if need else buf

        return jax.tree_util.tree_map(z, out_avals)

    return lax.cond(is_last, _fwd_bwd, _skip, y)


def _masked_slot_write(buf, idx, val, valid):
    """buf[idx] = val where valid (read-modify-write, NaN-safe)."""
    cur = lax.dynamic_index_in_dim(buf, idx, axis=0, keepdims=False)
    new = jnp.where(valid, val.astype(buf.dtype), cur)
    return lax.dynamic_update_index_in_dim(buf, new, idx, axis=0)


def _pipeline_aux(out, axis_name, m, x_dtype, head_params,
                  return_input_grads):
    """Assemble the optional aux dict shared by both 1F1B kernels."""
    aux = {}
    if head_params is not None:
        # the head ran on the last logical stage's device only
        aux["head_grads"] = jax.tree_util.tree_map(
            lambda h: lax.psum(h, axis_name) / m, out["hacc"])
    if return_input_grads:
        # nonzero only on the owner of logical stage 0; cast back to the
        # input dtype so the caller's emb_vjp cotangent matches its primal
        aux["input_grads"] = (
            lax.psum(out["dxs"], axis_name) / m).astype(x_dtype)
    return aux


def pipeline_1f1b_value_and_grad(
    stage_fn: Callable,
    loss_fn: Callable,
    stage_params: Any,
    x_microbatches,
    y_microbatches,
    axis_name: str,
    head_params: Any = None,
    return_input_grads: bool = False,
):
    """1F1B-scheduled pipeline training step (loss + per-stage grads).

    ``pipeline_apply`` + autodiff is GPipe: all M micro-batches flow forward
    before any backward, so every stage holds O(M) live activations. This is
    the one-forward-one-backward schedule: backward for micro-batch j starts
    as soon as j leaves the last stage, so stage s only keeps activations for
    its in-flight window — a circular buffer of 2·(S−1) slots, independent of
    M. The backward cotangent rides a reverse ``ppermute`` ring one tick
    behind schedule, and each stage re-runs its forward at backward time
    (in-stage remat — the standard TPU trade of FLOPs for HBM).

    Schedule (S stages, M micro-batches, T = 2·(S−1)+M ticks): stage s runs
    forward for micro-batch t−s and backward for micro-batch
    t−(2·(S−1)−s) when those indices are in [0, M). The last stage's forward
    and backward for a micro-batch land on the same tick, where the loss
    cotangent is computed locally from ``loss_fn``.

    Args:
      stage_fn: ``(params, h) -> h`` — one stage's compute, shape-preserving
        (homogeneous pipeline, as ``pipeline_apply``).
      loss_fn: ``(out, target) -> scalar`` — applied to the last stage's
        output per micro-batch; the objective is its mean over micro-batches.
      stage_params: THIS shard's stage parameters.
      x_microbatches: [M, mb, ...] inputs, replicated across shards.
      y_microbatches: [M, ...] per-micro-batch targets, replicated.
      axis_name: the stage mesh axis.
      head_params / return_input_grads: the same composition hooks as
        :func:`pipeline_interleaved_1f1b_value_and_grad` — a loss-side
        trainable pytree (``loss_fn(head_params, out, tgt)``; no
        collectives over the STAGE axis, but collectives over orthogonal
        mesh axes are supported — e.g. a column-parallel head with
        vocab-parallel cross-entropy, see ``_head_loss_grads``) and the
        stage-0 input cotangents.

    Returns ``(loss, grads)``, plus an ``aux`` dict (``head_grads``,
    ``input_grads``) when either hook is set: the mean loss (replicated)
    and the gradient of it w.r.t. THIS shard's ``stage_params``.
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    m = x_microbatches.shape[0]
    mb_shape = x_microbatches.shape[1:]

    act_dtype = _stage_act_dtype(stage_fn, stage_params, mb_shape,
                                 x_microbatches.dtype)

    depth = max(1, 2 * (n - 1))  # 1F1B live-activation bound per stage
    ticks = 2 * (n - 1) + m
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    bwd_perm = [(i, (i - 1) % n) for i in range(n)]

    vref = _vma_ref(my, stage_params)
    h0 = match_vma(jnp.zeros(mb_shape, act_dtype), vref)
    g0 = match_vma(jnp.zeros(mb_shape, act_dtype), vref)
    buf0 = match_vma(jnp.zeros((depth,) + mb_shape, act_dtype), vref)
    gacc0 = match_vma(
        jax.tree_util.tree_map(jnp.zeros_like, stage_params), vref)
    lacc0 = match_vma(jnp.zeros((), jnp.float32), vref)
    carry0 = dict(h=h0, g=g0, buf=buf0, gacc=gacc0, lacc=lacc0)
    if head_params is not None:
        carry0["hacc"] = match_vma(
            jax.tree_util.tree_map(jnp.zeros_like, head_params), vref)
        # see the interleaved kernel: differentiate against a varying copy
        # or vma autodiff psums every device's masked-out contribution in
        head_params_v = match_vma(head_params, vref)
    if return_input_grads:
        carry0["dxs"] = match_vma(
            jnp.zeros((m,) + mb_shape, jnp.float32), vref)

    def tick(t, carry):
        h_ring, g_ring, buf = carry["h"], carry["g"], carry["buf"]
        gacc, lacc = carry["gacc"], carry["lacc"]
        mb_f = t - my                       # micro-batch in forward here
        v_f = jnp.logical_and(mb_f >= 0, mb_f < m)
        mb_b = t - (2 * (n - 1) - my)       # micro-batch in backward here
        v_b = jnp.logical_and(mb_b >= 0, mb_b < m)

        feed = lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(mb_f, 0, m - 1), axis=0, keepdims=False
        ).astype(act_dtype)
        h_in = jnp.where(my == 0, feed, h_ring)

        # read the backward activation BEFORE writing this tick's forward:
        # at stage 0 the slot being retired is exactly the slot about to be
        # reused (lifetime == depth there)
        slot_b = jnp.clip(mb_b, 0, None) % depth
        h_saved = lax.dynamic_index_in_dim(buf, slot_b, axis=0,
                                           keepdims=False)
        # the last stage's backward is same-tick: use the live activation
        h_bwd_in = jnp.where(my == n - 1, h_in, h_saved)

        slot_f = jnp.clip(mb_f, 0, None) % depth
        buf = jnp.where(
            v_f,
            lax.dynamic_update_index_in_dim(buf, h_in, slot_f, axis=0),
            buf,
        )

        # forward step (pipeline progress)
        y_fwd = stage_fn(stage_params, h_in)

        # loss value + cotangent, meaningful on the last stage only
        tgt = lax.dynamic_index_in_dim(
            y_microbatches, jnp.clip(mb_f, 0, m - 1), axis=0, keepdims=False)
        is_last_f = jnp.logical_and(v_f, my == n - 1)
        hacc = carry.get("hacc")
        if head_params is None:
            loss_j, dldy = jax.value_and_grad(loss_fn)(y_fwd, tgt)
        else:
            loss_j, dldy, dhp = _head_loss_grads(
                loss_fn, head_params_v, is_last_f, y_fwd, tgt, vref)
            hacc = jax.tree_util.tree_map(lambda a, g: a + g, hacc, dhp)
        lacc = lacc + jnp.where(is_last_f, loss_j, 0.0)

        # backward step: rematerialize the stage at the saved activation
        g_in = jnp.where(my == n - 1, dldy.astype(act_dtype), g_ring)
        _, vjp_fn = jax.vjp(stage_fn, stage_params, h_bwd_in)
        gp, gh = vjp_fn(g_in)
        gacc = jax.tree_util.tree_map(
            lambda a, g: a + jnp.where(v_b, g, 0), gacc, gp)

        h_next = lax.ppermute(jnp.where(v_f, y_fwd, 0), axis_name, fwd_perm)
        g_next = lax.ppermute(jnp.where(v_b, gh, 0), axis_name, bwd_perm)
        new = dict(h=h_next, g=g_next, buf=buf, gacc=gacc, lacc=lacc)
        if hacc is not None:
            new["hacc"] = hacc
        if return_input_grads:
            is_first_b = jnp.logical_and(v_b, my == 0)
            new["dxs"] = _masked_slot_write(
                carry["dxs"], jnp.clip(mb_b, 0, m - 1),
                gh.astype(jnp.float32), is_first_b)
        return new

    out = lax.fori_loop(0, ticks, tick, carry0)

    loss = lax.psum(out["lacc"], axis_name) / m
    grads = jax.tree_util.tree_map(lambda g: g / m, out["gacc"])
    if head_params is None and not return_input_grads:
        return loss, grads
    return loss, grads, _pipeline_aux(
        out, axis_name, m, x_microbatches.dtype, head_params,
        return_input_grads)


class InterleavedSchedule(NamedTuple):
    """Static tick tables for the interleaved 1F1B schedule (all shapes
    [S, T], int32; invalid entries hold 0 with the valid flag 0)."""

    S: int
    V: int
    M: int
    T: int
    depth_act: int     # saved-activation ring-buffer depth per chunk
    depth_fin: int     # forward-inbox depth per chunk
    depth_bin: int     # backward-inbox depth per chunk
    f_valid: np.ndarray
    f_chunk: np.ndarray
    f_mb: np.ndarray
    b_valid: np.ndarray
    b_chunk: np.ndarray
    b_mb: np.ndarray
    fr_valid: np.ndarray   # forward-activation receive → inbox write
    fr_chunk: np.ndarray
    fr_mb: np.ndarray
    br_valid: np.ndarray   # backward-cotangent receive → inbox write
    br_chunk: np.ndarray
    br_mb: np.ndarray


def build_interleaved_schedule(S: int, V: int, M: int) -> InterleavedSchedule:
    """Event-simulate the interleaved (virtual-chunk) 1F1B schedule.

    Device ``d`` owns logical stages ``{v*S + d : v < V}`` (round-robin, the
    interleaved placement); every logical hop k→k+1 is a +1 ring transfer.
    Each synchronized tick a device runs at most ONE forward unit and ONE
    backward unit; transfers land one tick after the producer. Forward order
    is the virtual-micro-batch numbering (groups of S micro-batches per
    chunk, chunks cycled); backward mirrors it with chunks reversed; a
    device may run ahead of its backward stream by at most the interleaved
    warmup bound ``(S-d-1)*2 + (V-1)*S``. Greedy list-scheduling under
    those dependencies reproduces the classic 1F1B tick count exactly at
    V=1 (T = 2(S-1)+M) and keeps devices busy with other chunks during
    what a fused-stage pipeline would spend as bubble.

    All tables are static numpy — the compiled step indexes them with
    ``(axis_index, tick)``, so the whole schedule is data-independent.
    """
    if M % S != 0:
        raise ValueError(
            f"interleaved schedule needs M % S == 0 (M={M}, S={S})")
    if V < 1:
        raise ValueError("V must be >= 1")
    N = S * V
    MV = M * V

    order_f = []
    order_b = []
    for q in range(MV):
        mb = (q // N) * S + (q % S)
        order_f.append(((q % N) // S, mb))
        order_b.append((V - 1 - (q % N) // S, mb))
    warm = [min((S - d - 1) * 2 + (V - 1) * S, MV) for d in range(S)]

    f_done: Dict = {}
    b_done: Dict = {}
    fi = [0] * S
    bi = [0] * S
    events = []  # (tick, device, op, chunk, mb)

    t = 0
    limit = 20 * (MV + N) + 64
    while any(b < MV for b in bi):
        if t >= limit:
            raise RuntimeError("interleaved schedule did not converge")
        staged = []
        for d in range(S):
            did_f = did_b = None
            if fi[d] < MV and fi[d] - bi[d] < warm[d] + 1:
                v, j = order_f[fi[d]]
                k = v * S + d
                if k == 0 or f_done.get((k - 1, j), limit) + 1 <= t:
                    did_f = (v, j)
            if bi[d] < MV:
                v, j = order_b[bi[d]]
                k = v * S + d
                if k == N - 1:
                    ft = f_done.get((k, j))
                    if (ft is not None and ft <= t) or did_f == (v, j):
                        did_b = (v, j)
                elif b_done.get((k + 1, j), limit) + 1 <= t:
                    did_b = (v, j)
            staged.append((did_f, did_b))
        for d, (did_f, did_b) in enumerate(staged):
            if did_f:
                v, j = did_f
                f_done[(v * S + d, j)] = t
                fi[d] += 1
                events.append((t, d, "F", v, j))
            if did_b:
                v, j = did_b
                b_done[(v * S + d, j)] = t
                bi[d] += 1
                events.append((t, d, "B", v, j))
        t += 1
    T = t

    def tab():
        return (np.zeros((S, T), np.int32), np.zeros((S, T), np.int32),
                np.zeros((S, T), np.int32))

    f_valid, f_chunk, f_mb = tab()
    b_valid, b_chunk, b_mb = tab()
    fr_valid, fr_chunk, fr_mb = tab()
    br_valid, br_chunk, br_mb = tab()

    for (tk, d, op, v, j) in events:
        if op == "F":
            f_valid[d, tk], f_chunk[d, tk], f_mb[d, tk] = 1, v, j
            k = v * S + d
            if k != N - 1 and tk + 1 < T:
                # output arrives at device (d+1)%S next tick; at the wrap
                # (d == S-1) the consumer is the next chunk on device 0
                rd = (d + 1) % S
                rv = v + 1 if d == S - 1 else v
                fr_valid[rd, tk + 1] = 1
                fr_chunk[rd, tk + 1] = rv
                fr_mb[rd, tk + 1] = j
        else:
            b_valid[d, tk], b_chunk[d, tk], b_mb[d, tk] = 1, v, j
            k = v * S + d
            if k != 0 and tk + 1 < T:
                rd = (d - 1) % S
                rv = v - 1 if d == 0 else v
                br_valid[rd, tk + 1] = 1
                br_chunk[rd, tk + 1] = rv
                br_mb[rd, tk + 1] = j

    def max_overlap(intervals):
        pts = []
        for (a, b) in intervals:
            pts.append((a, 1))
            pts.append((b + 1, -1))
        peak = cur = 0
        for _, delta in sorted(pts):
            cur += delta
            peak = max(peak, cur)
        return max(peak, 1)

    # ring-buffer depths from the simulated lifetimes (FIFO per chunk, so
    # mb % depth is collision-free at depth >= max overlap)
    acts, fins, bins_ = [], [], []
    for d in range(S):
        for v in range(V):
            k = v * S + d
            acts.append(max_overlap(
                [(f_done[(k, j)], b_done[(k, j)]) for j in range(M)]))
            if k != 0:
                fins.append(max_overlap(
                    [(f_done[(k - 1, j)] + 1, f_done[(k, j)])
                     for j in range(M)]))
            bins_.append(max_overlap(
                [((f_done[(k, j)] if k == N - 1
                   else b_done[(k + 1, j)] + 1), b_done[(k, j)])
                 for j in range(M)]))
    return InterleavedSchedule(
        S=S, V=V, M=M, T=T,
        depth_act=max(acts), depth_fin=max(fins or [1]),
        depth_bin=max(bins_),
        f_valid=f_valid, f_chunk=f_chunk, f_mb=f_mb,
        b_valid=b_valid, b_chunk=b_chunk, b_mb=b_mb,
        fr_valid=fr_valid, fr_chunk=fr_chunk, fr_mb=fr_mb,
        br_valid=br_valid, br_chunk=br_chunk, br_mb=br_mb,
    )


def pipeline_interleaved_1f1b_value_and_grad(
    stage_fn: Callable,
    loss_fn: Callable,
    stage_params: Any,
    x_microbatches,
    y_microbatches,
    axis_name: str,
    n_chunks: int,
    head_params: Any = None,
    return_input_grads: bool = False,
):
    """Interleaved-1F1B pipeline training step (virtual stages).

    Each device owns ``n_chunks`` (V) non-adjacent pipeline stages —
    logical stage ``v*S + d`` lives on device ``d`` — so during a plain
    pipeline's fill/drain bubble a device works on its other chunks. Per
    tick a device runs at most one sub-stage forward and one backward
    (in-stage remat, like :func:`pipeline_1f1b_value_and_grad`); the
    schedule is the static tick table from
    :func:`build_interleaved_schedule`. Activation cost: three ring
    buffers per chunk (saved activations + two transfer inboxes) sized by
    the schedule's in-flight maxima — deeper than non-interleaved 1F1B's
    2(S−1), the known memory-for-bubble trade of interleaving.

    Args:
      stage_fn: ``(params, h) -> h`` — ONE sub-stage's compute
        (shape-preserving, homogeneous pipeline).
      loss_fn: ``(out, target) -> scalar`` per micro-batch.
      stage_params: THIS device's chunk parameters, each leaf stacked on a
        leading ``V`` axis. Arrange the global [N, ...] logical-stage stack
        as ``[V, S, ...]`` and shard axis 1 over ``axis_name`` (device d
        then holds rows ``v*S+d`` — the interleaved placement).
      x_microbatches: [M, mb, ...] inputs, replicated (M % S == 0).
      y_microbatches: [M, ...] targets, replicated.
      axis_name: the stage mesh axis.
      n_chunks: V, virtual stages per device.
      head_params: optional pytree of loss-side trainable parameters (an
        LM head / classifier). When given, ``loss_fn`` is called as
        ``loss_fn(head_params, out, target)`` and their gradient is
        returned — this is how a real model's head trains through the
        pipeline (the head runs on the last logical stage's device and
        its grads are psum-replicated).
      return_input_grads: also return d(loss)/d(x_microbatches) — the
        cotangents leaving logical stage 0 — so the caller can backprop
        into whatever produced the inputs (an embedding) with its own
        ``jax.vjp``. Composition contract: embed outside → pipeline →
        head inside ``loss_fn``.

    Returns ``(loss, grads)``, or ``(loss, grads, aux)`` when
    ``head_params``/``return_input_grads`` is set, with
    ``aux['head_grads']`` (replicated) and/or ``aux['input_grads']``
    ([M, mb, ...], replicated). ``loss`` is the micro-batch mean
    (replicated); ``grads`` is w.r.t. THIS device's ``stage_params``
    (same [V, ...] stacking).
    """
    S = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    V = n_chunks
    N = S * V
    m = x_microbatches.shape[0]
    mb_shape = x_microbatches.shape[1:]

    chunk0 = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    act_dtype = _stage_act_dtype(stage_fn, chunk0, mb_shape,
                                 x_microbatches.dtype)

    sched = build_interleaved_schedule(S, V, m)
    T, Da, Df, Db = (sched.T, sched.depth_act, sched.depth_fin,
                     sched.depth_bin)
    tabs = {k: jnp.asarray(getattr(sched, k)) for k in (
        "f_valid", "f_chunk", "f_mb", "b_valid", "b_chunk", "b_mb",
        "fr_valid", "fr_chunk", "fr_mb", "br_valid", "br_chunk", "br_mb")}

    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [(i, (i - 1) % S) for i in range(S)]

    vref = _vma_ref(my, stage_params)

    def zeros_buf(depth):
        return match_vma(jnp.zeros((V, depth) + mb_shape, act_dtype),
                         vref)

    def buf_read(buf, chunk, slot):
        sl = lax.dynamic_slice(
            buf, (chunk, slot) + (0,) * len(mb_shape),
            (1, 1) + mb_shape)
        return sl.reshape(mb_shape)

    def buf_write(buf, chunk, slot, val, valid):
        cur = buf_read(buf, chunk, slot)
        new = jnp.where(valid, val.astype(buf.dtype), cur)
        return lax.dynamic_update_slice(
            buf, new[(None, None)], (chunk, slot) + (0,) * len(mb_shape))

    carry0 = dict(
        fin=zeros_buf(Df),
        bin=zeros_buf(Db),
        act=zeros_buf(Da),
        y_send=match_vma(jnp.zeros(mb_shape, act_dtype), vref),
        g_send=match_vma(jnp.zeros(mb_shape, act_dtype), vref),
        gacc=match_vma(
            jax.tree_util.tree_map(jnp.zeros_like, stage_params), vref),
        lacc=match_vma(jnp.zeros((), jnp.float32), vref),
    )
    if head_params is not None:
        carry0["hacc"] = match_vma(
            jax.tree_util.tree_map(jnp.zeros_like, head_params), vref)
        # pcast to varying BEFORE differentiating: the grad w.r.t. an
        # axis-invariant (replicated) pytree is auto-psummed by shard_map's
        # vma tracking, which would fold every device's (mostly garbage,
        # masked-out) head contribution into each device's dhp before the
        # is_last_f mask can filter them
        head_params_v = match_vma(head_params, vref)
    if return_input_grads:
        carry0["dxs"] = match_vma(
            jnp.zeros((m,) + mb_shape, jnp.float32), vref)

    def chunk_params(c):
        return jax.tree_util.tree_map(
            lambda p: lax.dynamic_index_in_dim(p, c, 0, keepdims=False),
            stage_params)

    def tick(t, carry):
        # 1. land last tick's transfers in the inboxes
        y_recv = lax.ppermute(carry["y_send"], axis_name, fwd_perm)
        g_recv = lax.ppermute(carry["g_send"], axis_name, bwd_perm)
        frv = tabs["fr_valid"][my, t]
        fin = buf_write(carry["fin"], tabs["fr_chunk"][my, t],
                        tabs["fr_mb"][my, t] % Df, y_recv, frv)
        brv = tabs["br_valid"][my, t]
        bin_ = buf_write(carry["bin"], tabs["br_chunk"][my, t],
                         tabs["br_mb"][my, t] % Db, g_recv, brv)

        # 2. forward unit
        fv = tabs["f_valid"][my, t]
        fc = tabs["f_chunk"][my, t]
        fm = tabs["f_mb"][my, t]
        k_f = fc * S + my
        feed = lax.dynamic_index_in_dim(
            x_microbatches, fm, axis=0, keepdims=False).astype(act_dtype)
        h_in = jnp.where(k_f == 0, feed, buf_read(fin, fc, fm % Df))
        y_f = stage_fn(chunk_params(fc), h_in)
        tgt = lax.dynamic_index_in_dim(
            y_microbatches, fm, axis=0, keepdims=False)
        is_last_f = jnp.logical_and(fv, k_f == N - 1)
        hacc = carry.get("hacc")
        if head_params is None:
            loss_j, dldy = jax.value_and_grad(loss_fn)(y_f, tgt)
        else:
            loss_j, dldy, dhp = _head_loss_grads(
                loss_fn, head_params_v, is_last_f, y_f, tgt, vref)
            hacc = jax.tree_util.tree_map(
                lambda a, g: a + g, hacc, dhp)
        lacc = carry["lacc"] + jnp.where(is_last_f, loss_j, 0.0)
        # the last logical stage's cotangent is produced locally
        bin_ = buf_write(bin_, V - 1, fm % Db, dldy, is_last_f)

        # 3. backward unit (reads inboxes/activations, then F's act lands)
        bv = tabs["b_valid"][my, t]
        bc = tabs["b_chunk"][my, t]
        bm = tabs["b_mb"][my, t]
        k_b = bc * S + my
        g_in = buf_read(bin_, bc, bm % Db)
        same_tick = jnp.logical_and(
            jnp.logical_and(k_b == N - 1, is_last_f), bm == fm)
        h_bwd = jnp.where(same_tick, h_in,
                          buf_read(carry["act"], bc, bm % Da))
        act = buf_write(carry["act"], fc, fm % Da, h_in, fv)
        _, vjp_fn = jax.vjp(stage_fn, chunk_params(bc), h_bwd)
        gp, gh = vjp_fn(g_in.astype(act_dtype))
        # where, not multiply: bubble ticks run the vjp on zero-filled
        # buffers, and 0 * NaN would poison the accumulator
        gacc = jax.tree_util.tree_map(
            lambda a, g: lax.dynamic_update_index_in_dim(
                a, lax.dynamic_index_in_dim(a, bc, 0, keepdims=False)
                + jnp.where(bv != 0, g, jnp.zeros_like(g)), bc, axis=0),
            carry["gacc"], gp)

        # 4. this tick's transfers
        y_send = jnp.where(jnp.logical_and(fv, k_f != N - 1), y_f,
                           jnp.zeros_like(y_f))
        g_send = jnp.where(jnp.logical_and(bv, k_b != 0), gh,
                           jnp.zeros_like(gh)).astype(act_dtype)
        new = dict(fin=fin, bin=bin_, act=act, y_send=y_send,
                   g_send=g_send, gacc=gacc, lacc=lacc)
        if hacc is not None:
            new["hacc"] = hacc
        if return_input_grads:
            # cotangent leaving logical stage 0 = d(loss_mb)/d(x_mb)
            is_first_b = jnp.logical_and(bv, k_b == 0)
            new["dxs"] = _masked_slot_write(
                carry["dxs"], bm, gh.astype(jnp.float32), is_first_b)
        return new

    out = lax.fori_loop(0, T, tick, carry0)
    loss = lax.psum(out["lacc"], axis_name) / m
    grads = jax.tree_util.tree_map(lambda g: g / m, out["gacc"])
    if head_params is None and not return_input_grads:
        return loss, grads
    return loss, grads, _pipeline_aux(
        out, axis_name, m, x_microbatches.dtype, head_params,
        return_input_grads)

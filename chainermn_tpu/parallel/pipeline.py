"""Micro-batched pipeline parallelism over a mesh axis.

The reference's pipeline story is MultiNodeChainList's sequential fill/drain
(no micro-batch scheduler — SURVEY.md §2.6). This module is the TPU-native
performance path beyond that: homogeneous stages whose parameters are
*stacked and sharded* over the ``stage`` mesh axis (true memory scaling) and
a GPipe-style rotating schedule compiled into one ``lax.fori_loop`` whose
inter-stage hop is a single neighbor ``ppermute`` — the canonical
"pipelining with collective_permute" pattern on TPU.

Schedule: with S stages and M micro-batches, the loop runs S+M-1 ticks; at
tick t, stage s processes micro-batch t-s (when 0 ≤ t-s < M). Each shard
holds its own stage's parameters only.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.utils import match_vma


def _stage_act_dtype(stage_fn, stage_params, mb_shape, in_dtype):
    """Activation dtype of one stage; rejects non-shape-preserving stages
    (the homogeneous-pipeline contract both schedules rely on)."""
    out_aval = jax.eval_shape(
        stage_fn, stage_params, jax.ShapeDtypeStruct(mb_shape, in_dtype))
    if out_aval.shape != mb_shape:
        raise ValueError(
            f"pipeline stages must preserve the activation shape "
            f"(homogeneous pipeline); stage maps {mb_shape} -> "
            f"{out_aval.shape}"
        )
    return out_aval.dtype


def pipeline_apply(
    stage_fn: Callable,
    stage_params: Any,
    x_microbatches,
    axis_name: str,
):
    """Run the pipeline forward inside shard_map.

    Args:
      stage_fn: ``(params, h) -> h`` — one stage's compute. All stages share
        this structure (homogeneous pipeline); per-stage behavior comes from
        the sharded ``stage_params``.
      stage_params: THIS shard's stage parameters (pytree). In the driver,
        stack per-stage params on a leading axis sharded over ``axis_name``
        and strip it in-shard (in_specs does this).
      x_microbatches: [M, mb, ...] micro-batches, replicated; stage 0 feeds
        them in, the last stage's outputs are collected ([M, mb, ...]).
      axis_name: the stage mesh axis.

    Returns stacked outputs [M, mb, ...] (valid on every shard).
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    m = x_microbatches.shape[0]
    mb_shape = x_microbatches.shape[1:]

    ticks = n + m - 1
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]

    # activation dtype/shape comes from the stage itself (homogeneous
    # pipeline: output shape == input shape, but dtype may be bf16 etc.)
    act_dtype = _stage_act_dtype(stage_fn, stage_params, mb_shape,
                                 x_microbatches.dtype)

    # carry: (current activation, collected outputs) — pcast to varying so
    # the fori_loop carry matches the per-shard (varying) updates
    h0 = match_vma(jnp.zeros(mb_shape, act_dtype), my)
    outs = match_vma(jnp.zeros((m,) + mb_shape, act_dtype), my)

    def tick(t, carry):
        h, outs = carry
        # stage 0 ingests micro-batch t (if in range); others use the
        # activation that arrived over the ring
        feed = lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(t, 0, m - 1), axis=0, keepdims=False
        ).astype(act_dtype)
        h_in = jnp.where(my == 0, feed, h)
        y = stage_fn(stage_params, h_in)
        # last stage records micro-batch t-(n-1) when valid
        mb_idx = t - (n - 1)
        valid = jnp.logical_and(my == n - 1,
                                jnp.logical_and(mb_idx >= 0, mb_idx < m))
        outs = lax.cond(
            valid,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y, jnp.clip(mb_idx, 0, m - 1), axis=0),
            lambda o: o,
            outs,
        )
        # rotate activations one hop down the ring
        h_next = lax.ppermute(y, axis_name, fwd_perm)
        return h_next, outs

    _, outs = lax.fori_loop(0, ticks, tick, (h0, outs))
    # make the last stage's collection visible everywhere
    last = n - 1
    keep = (my == last)
    outs = lax.psum(jnp.where(keep, outs, jnp.zeros_like(outs)), axis_name)
    return outs


def stack_stage_params(params_list):
    """Stack per-stage param pytrees on a leading axis (shard over the
    stage mesh axis with P('stage') in_specs)."""
    return jax.tree_util.tree_map(
        lambda *ls: jnp.stack(ls), *params_list
    )


def pipeline_1f1b_value_and_grad(
    stage_fn: Callable,
    loss_fn: Callable,
    stage_params: Any,
    x_microbatches,
    y_microbatches,
    axis_name: str,
):
    """1F1B-scheduled pipeline training step (loss + per-stage grads).

    ``pipeline_apply`` + autodiff is GPipe: all M micro-batches flow forward
    before any backward, so every stage holds O(M) live activations. This is
    the one-forward-one-backward schedule: backward for micro-batch j starts
    as soon as j leaves the last stage, so stage s only keeps activations for
    its in-flight window — a circular buffer of 2·(S−1) slots, independent of
    M. The backward cotangent rides a reverse ``ppermute`` ring one tick
    behind schedule, and each stage re-runs its forward at backward time
    (in-stage remat — the standard TPU trade of FLOPs for HBM).

    Schedule (S stages, M micro-batches, T = 2·(S−1)+M ticks): stage s runs
    forward for micro-batch t−s and backward for micro-batch
    t−(2·(S−1)−s) when those indices are in [0, M). The last stage's forward
    and backward for a micro-batch land on the same tick, where the loss
    cotangent is computed locally from ``loss_fn``.

    Args:
      stage_fn: ``(params, h) -> h`` — one stage's compute, shape-preserving
        (homogeneous pipeline, as ``pipeline_apply``).
      loss_fn: ``(out, target) -> scalar`` — applied to the last stage's
        output per micro-batch; the objective is its mean over micro-batches.
      stage_params: THIS shard's stage parameters.
      x_microbatches: [M, mb, ...] inputs, replicated across shards.
      y_microbatches: [M, ...] per-micro-batch targets, replicated.
      axis_name: the stage mesh axis.

    Returns ``(loss, grads)``: the mean loss (replicated) and the gradient
    of it w.r.t. THIS shard's ``stage_params``.
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    m = x_microbatches.shape[0]
    mb_shape = x_microbatches.shape[1:]

    act_dtype = _stage_act_dtype(stage_fn, stage_params, mb_shape,
                                 x_microbatches.dtype)

    depth = max(1, 2 * (n - 1))  # 1F1B live-activation bound per stage
    ticks = 2 * (n - 1) + m
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    bwd_perm = [(i, (i - 1) % n) for i in range(n)]

    h0 = match_vma(jnp.zeros(mb_shape, act_dtype), my)
    g0 = match_vma(jnp.zeros(mb_shape, act_dtype), my)
    buf0 = match_vma(jnp.zeros((depth,) + mb_shape, act_dtype), my)
    gacc0 = match_vma(
        jax.tree_util.tree_map(jnp.zeros_like, stage_params), my)
    lacc0 = match_vma(jnp.zeros((), jnp.float32), my)

    def tick(t, carry):
        h_ring, g_ring, buf, gacc, lacc = carry
        mb_f = t - my                       # micro-batch in forward here
        v_f = jnp.logical_and(mb_f >= 0, mb_f < m)
        mb_b = t - (2 * (n - 1) - my)       # micro-batch in backward here
        v_b = jnp.logical_and(mb_b >= 0, mb_b < m)

        feed = lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(mb_f, 0, m - 1), axis=0, keepdims=False
        ).astype(act_dtype)
        h_in = jnp.where(my == 0, feed, h_ring)

        # read the backward activation BEFORE writing this tick's forward:
        # at stage 0 the slot being retired is exactly the slot about to be
        # reused (lifetime == depth there)
        slot_b = jnp.clip(mb_b, 0, None) % depth
        h_saved = lax.dynamic_index_in_dim(buf, slot_b, axis=0,
                                           keepdims=False)
        # the last stage's backward is same-tick: use the live activation
        h_bwd_in = jnp.where(my == n - 1, h_in, h_saved)

        slot_f = jnp.clip(mb_f, 0, None) % depth
        buf = jnp.where(
            v_f,
            lax.dynamic_update_index_in_dim(buf, h_in, slot_f, axis=0),
            buf,
        )

        # forward step (pipeline progress)
        y_fwd = stage_fn(stage_params, h_in)

        # loss value + cotangent, meaningful on the last stage only
        tgt = lax.dynamic_index_in_dim(
            y_microbatches, jnp.clip(mb_f, 0, m - 1), axis=0, keepdims=False)
        loss_j, dldy = jax.value_and_grad(loss_fn)(y_fwd, tgt)
        lacc = lacc + jnp.where(
            jnp.logical_and(v_f, my == n - 1), loss_j, 0.0)

        # backward step: rematerialize the stage at the saved activation
        g_in = jnp.where(my == n - 1, dldy.astype(act_dtype), g_ring)
        _, vjp_fn = jax.vjp(stage_fn, stage_params, h_bwd_in)
        gp, gh = vjp_fn(g_in)
        gacc = jax.tree_util.tree_map(
            lambda a, g: a + jnp.where(v_b, g, 0), gacc, gp)

        h_next = lax.ppermute(jnp.where(v_f, y_fwd, 0), axis_name, fwd_perm)
        g_next = lax.ppermute(jnp.where(v_b, gh, 0), axis_name, bwd_perm)
        return h_next, g_next, buf, gacc, lacc

    _, _, _, gacc, lacc = lax.fori_loop(
        0, ticks, tick, (h0, g0, buf0, gacc0, lacc0))

    loss = lax.psum(lacc, axis_name) / m
    grads = jax.tree_util.tree_map(lambda g: g / m, gacc)
    return loss, grads

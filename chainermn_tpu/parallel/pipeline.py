"""Micro-batched pipeline parallelism over a mesh axis.

The reference's pipeline story is MultiNodeChainList's sequential fill/drain
(no micro-batch scheduler — SURVEY.md §2.6). This module is the TPU-native
performance path beyond that: homogeneous stages whose parameters are
*stacked and sharded* over the ``stage`` mesh axis (true memory scaling) and
a GPipe-style rotating schedule compiled into one ``lax.fori_loop`` whose
inter-stage hop is a single neighbor ``ppermute`` — the canonical
"pipelining with collective_permute" pattern on TPU.

Schedule: with S stages and M micro-batches, the loop runs S+M-1 ticks; at
tick t, stage s processes micro-batch t-s (when 0 ≤ t-s < M). Each shard
holds its own stage's parameters only.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.utils import match_vma


def pipeline_apply(
    stage_fn: Callable,
    stage_params: Any,
    x_microbatches,
    axis_name: str,
):
    """Run the pipeline forward inside shard_map.

    Args:
      stage_fn: ``(params, h) -> h`` — one stage's compute. All stages share
        this structure (homogeneous pipeline); per-stage behavior comes from
        the sharded ``stage_params``.
      stage_params: THIS shard's stage parameters (pytree). In the driver,
        stack per-stage params on a leading axis sharded over ``axis_name``
        and strip it in-shard (in_specs does this).
      x_microbatches: [M, mb, ...] micro-batches, replicated; stage 0 feeds
        them in, the last stage's outputs are collected ([M, mb, ...]).
      axis_name: the stage mesh axis.

    Returns stacked outputs [M, mb, ...] (valid on every shard).
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    m = x_microbatches.shape[0]
    mb_shape = x_microbatches.shape[1:]

    ticks = n + m - 1
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]

    # activation dtype/shape comes from the stage itself (homogeneous
    # pipeline: output shape == input shape, but dtype may be bf16 etc.)
    out_aval = jax.eval_shape(
        stage_fn, stage_params,
        jax.ShapeDtypeStruct(mb_shape, x_microbatches.dtype),
    )
    act_dtype = out_aval.dtype
    if out_aval.shape != mb_shape:
        raise ValueError(
            f"pipeline stages must preserve the activation shape "
            f"(homogeneous pipeline); stage maps {mb_shape} -> "
            f"{out_aval.shape}"
        )

    # carry: (current activation, collected outputs) — pcast to varying so
    # the fori_loop carry matches the per-shard (varying) updates
    h0 = match_vma(jnp.zeros(mb_shape, act_dtype), my)
    outs = match_vma(jnp.zeros((m,) + mb_shape, act_dtype), my)

    def tick(t, carry):
        h, outs = carry
        # stage 0 ingests micro-batch t (if in range); others use the
        # activation that arrived over the ring
        feed = lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(t, 0, m - 1), axis=0, keepdims=False
        ).astype(act_dtype)
        h_in = jnp.where(my == 0, feed, h)
        y = stage_fn(stage_params, h_in)
        # last stage records micro-batch t-(n-1) when valid
        mb_idx = t - (n - 1)
        valid = jnp.logical_and(my == n - 1,
                                jnp.logical_and(mb_idx >= 0, mb_idx < m))
        outs = lax.cond(
            valid,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y, jnp.clip(mb_idx, 0, m - 1), axis=0),
            lambda o: o,
            outs,
        )
        # rotate activations one hop down the ring
        h_next = lax.ppermute(y, axis_name, fwd_perm)
        return h_next, outs

    _, outs = lax.fori_loop(0, ticks, tick, (h0, outs))
    # make the last stage's collection visible everywhere
    last = n - 1
    keep = (my == last)
    outs = lax.psum(jnp.where(keep, outs, jnp.zeros_like(outs)), axis_name)
    return outs


def stack_stage_params(params_list):
    """Stack per-stage param pytrees on a leading axis (shard over the
    stage mesh axis with P('stage') in_specs)."""
    return jax.tree_util.tree_map(
        lambda *ls: jnp.stack(ls), *params_list
    )

"""Ring attention — sequence/context parallelism over the ICI ring.

Beyond-reference capability (SURVEY.md §2.6: the reference predates sequence
parallelism; §5 specifies this as the TPU-native answer). The sequence axis
is sharded over a mesh axis; each shard holds a query block and rotates the
K/V blocks around the ring with ``lax.ppermute`` (XLA collective-permute over
ICI neighbor links), accumulating attention with the online-softmax
(flash-style) running max/denominator so the full sequence is never
materialized on one chip. Compute of block t overlaps the transfer of block
t+1 thanks to XLA's latency-hiding scheduler.

Causal masking works on block indices: a shard skips score positions whose
global key index exceeds the global query index.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from chainermn_tpu.ops.flash_attention import DEFAULT_BLOCKS
from jax import lax


def _block_attend(q, k, v, scale, mask):
    """Scores for one (q-block, kv-block) pair + unnormalized accumulators.

    q: [B, Lq, H, D]; k, v: [B, Lk, H, D]; mask: [Lq, Lk] or None.
    Returns (numerator [B, Lq, H, D], rowmax [B, Lq, H], rowsum [B, Lq, H]).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        s = jnp.where(mask[None, None, :, :], s, -jnp.inf)
    m = jnp.max(s, axis=-1)                          # [B, H, Lq] (may be -inf)
    # exponentiate against a finite shift; fully-masked rows produce zeros
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.where(jnp.isfinite(s), jnp.exp(s - m_safe[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)                          # [B, H, Lq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    # return the TRUE max (-inf where masked) — the merge needs it
    return o, jnp.transpose(m, (0, 2, 1)), jnp.transpose(l, (0, 2, 1))


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None):
    """Attention over a sequence sharded on ``axis_name``.

    Call inside shard_map with the sequence dimension sharded:
    q, k, v: [B, L_local, H, D] per shard. Returns [B, L_local, H, D].
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    lq = q.shape[1]
    lk = k.shape[1]

    # running accumulators: numerator, rowsum, rowmax — pcast to varying so
    # the fori_loop carry type matches the (varying) per-shard updates
    from chainermn_tpu.utils import match_vma

    acc = match_vma(jnp.zeros(q.shape, jnp.float32), q)
    lsum = match_vma(jnp.zeros(q.shape[:3], jnp.float32), q)  # [B, Lq, H]
    mrun = match_vma(jnp.full(q.shape[:3], -jnp.inf, jnp.float32), q)

    perm = [(i, (i + 1) % n) for i in range(n)]      # ring rotation

    def body(t, carry):
        acc, lsum, mrun, k_cur, v_cur = carry
        src = (my - t) % n                            # whose KV block this is

        if causal:
            # global positions: queries my*lq + iq, keys src*lk + ik
            iq = my * lq + jnp.arange(lq)[:, None]
            ik = src * lk + jnp.arange(lk)[None, :]
            mask = ik <= iq
        else:
            mask = None

        o_t, m_t, l_t = _block_attend(
            q.astype(jnp.float32), k_cur.astype(jnp.float32),
            v_cur.astype(jnp.float32), scale, mask)

        m_new = jnp.maximum(mrun, m_t)
        # rescale old accumulators; exp(-inf - m) == 0 handles the first step
        alpha = jnp.where(jnp.isfinite(mrun), jnp.exp(mrun - m_new), 0.0)
        beta = jnp.where(jnp.isfinite(m_t), jnp.exp(m_t - m_new), 0.0)
        acc = acc * alpha[..., None] + o_t * beta[..., None]
        lsum = lsum * alpha + l_t * beta
        mrun = m_new

        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return acc, lsum, mrun, k_nxt, v_nxt

    acc, lsum, mrun, _, _ = lax.fori_loop(
        0, n, body, (acc, lsum, mrun, k, v))
    out = acc / jnp.maximum(lsum[..., None], 1e-30)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Ring attention with Pallas flash inner kernels.
#
# The XLA ring above materializes each visiting [L_local, L_local] score
# block; this variant runs the fused flash kernels per visiting block, so
# scores never leave VMEM even within a block. The whole ring carries a
# custom VJP because the merge weights depend on the per-block logsumexp,
# which flash_attention's own VJP does not differentiate through — the ring
# must be the custom_vjp boundary, not the block.
#
# Exactness: the forward merges per-block (o, lse) into the GLOBAL softmax
# result; the backward feeds the global lse and dr = Σ_d dO·O into the
# per-block FlashAttention-2 kernels, whose contributions are exactly the
# global-attention partials for that (q-shard, kv-block) pair. dk/dv
# accumulators ride the same ppermute ring as the kv blocks, so after n
# rotations each block arrives home with its full gradient.
# ---------------------------------------------------------------------------


def _ring_blocks(causal, my, src, full_fn, diag_fn, skip_fn):
    """Dispatch one ring step: visiting block fully visible (src < my),
    on the causal diagonal (src == my), or fully masked (src > my)."""
    if not causal:
        return full_fn()
    idx = jnp.where(src < my, 0, jnp.where(src == my, 1, 2))
    return lax.switch(idx, [full_fn, diag_fn, skip_fn])


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def ring_flash_attention(q, k, v, axis_name: str, causal: bool = False,
                         scale: Optional[float] = None,
                         block_q: int = DEFAULT_BLOCKS[0],
                         block_k: int = DEFAULT_BLOCKS[1],
                         interpret: Optional[bool] = None):
    """`ring_attention` with the Pallas flash kernel as the per-block
    compute. Same calling convention: inside shard_map, q/k/v
    [B, L_local, H, D] sharded on ``axis_name``; returns [B, L_local, H, D].

    Equal shard sizes are required (shard_map guarantees this). Block
    sizes clamp to divisors of L_local like `flash_attention`'s.
    """
    return _ring_flash_fwd(q, k, v, axis_name, causal, scale, block_q,
                           block_k, interpret)[0]


def _to3(x):
    b, l, h, d = x.shape
    return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, l, d)


def _to4(x3, b, h):
    bh, l, d = x3.shape
    return jnp.transpose(x3.reshape(b, h, l, d), (0, 2, 1, 3))


def _ring_flash_fwd(q, k, v, axis_name, causal, scale, block_q, block_k,
                    interpret):
    from chainermn_tpu.ops.flash_attention import _flash_fwd_3d
    from chainermn_tpu.utils import match_vma

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, l, h, d = q.shape
    assert k.shape == q.shape, "ring shards must be equal-sized"

    q3, k3, v3 = _to3(q), _to3(k), _to3(v)
    fa = functools.partial(_flash_fwd_3d, scale=scale, block_q=block_q,
                           block_k=block_k, interpret=interpret)

    o = match_vma(jnp.zeros(q3.shape, jnp.float32), q3)
    lse = match_vma(jnp.full((b * h, l, 1), -jnp.inf, jnp.float32), q3)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(t, carry):
        o, lse, k_cur, v_cur = carry
        src = (my - t) % n
        o_t, lse_t = _ring_blocks(
            causal, my, src,
            lambda: fa(q3, k_cur, v_cur, causal=False),
            lambda: fa(q3, k_cur, v_cur, causal=True),
            lambda: (match_vma(jnp.zeros(q3.shape, q3.dtype), q3),
                     match_vma(jnp.full((b * h, l, 1), -jnp.inf,
                                        jnp.float32), q3)),
        )
        # streaming (o, lse) merge — weights are exp(lse_* − lse_new)
        lse_new = jnp.logaddexp(lse, lse_t)
        safe = jnp.where(jnp.isfinite(lse_new), lse_new, 0.0)
        w_old = jnp.where(jnp.isfinite(lse), jnp.exp(lse - safe), 0.0)
        w_new = jnp.where(jnp.isfinite(lse_t), jnp.exp(lse_t - safe), 0.0)
        o = o * w_old + o_t.astype(jnp.float32) * w_new
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return o, lse_new, k_nxt, v_nxt

    o, lse, _, _ = lax.fori_loop(0, n, body, (o, lse, k3, v3))
    out = _to4(o.astype(q.dtype), b, h)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, causal, scale, block_q, block_k, interpret,
                    res, g):
    from chainermn_tpu.ops.flash_attention import _flash_bwd_3d
    from chainermn_tpu.utils import match_vma

    q, k, v, out, lse = res
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    sc = scale if scale is not None else q.shape[-1] ** -0.5
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, l, h, d = q.shape

    q3, k3, v3, do3 = _to3(q), _to3(k), _to3(v), _to3(g)
    dr3 = jnp.sum(do3.astype(jnp.float32) * _to3(out).astype(jnp.float32),
                  axis=-1)                                  # [BH, L]
    fb = functools.partial(_flash_bwd_3d, scale=sc, block_q=block_q,
                           block_k=block_k, interpret=interpret)

    zero3 = lambda ref: match_vma(jnp.zeros(ref.shape, jnp.float32), q3)
    dq = zero3(q3)
    dk_acc = zero3(k3)   # rides the ring with its kv block
    dv_acc = zero3(v3)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(t, carry):
        dq, k_cur, v_cur, dk_acc, dv_acc = carry
        src = (my - t) % n
        dqt, dkt, dvt = _ring_blocks(
            causal, my, src,
            lambda: fb(q3, k_cur, v_cur, do3, lse, dr3, causal=False),
            lambda: fb(q3, k_cur, v_cur, do3, lse, dr3, causal=True),
            lambda: (zero3(q3).astype(q3.dtype), zero3(k3).astype(k3.dtype),
                     zero3(v3).astype(v3.dtype)),
        )
        dq = dq + dqt.astype(jnp.float32)
        dk_acc = dk_acc + dkt.astype(jnp.float32)
        dv_acc = dv_acc + dvt.astype(jnp.float32)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = lax.ppermute(dk_acc, axis_name, perm)
        dv_nxt = lax.ppermute(dv_acc, axis_name, perm)
        return dq, k_nxt, v_nxt, dk_nxt, dv_nxt

    dq, _, _, dk_acc, dv_acc = lax.fori_loop(
        0, n, body, (dq, k3, v3, dk_acc, dv_acc))
    return (_to4(dq, b, h).astype(q.dtype),
            _to4(dk_acc, b, h).astype(k.dtype),
            _to4(dv_acc, b, h).astype(v.dtype))


ring_flash_attention.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def local_attention_reference(q, k, v, causal: bool = False,
                              scale: Optional[float] = None):
    """Single-device full attention (the correctness oracle)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        mask = jnp.arange(lk)[None, :] <= jnp.arange(lq)[:, None]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)

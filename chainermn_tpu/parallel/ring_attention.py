"""Ring attention — sequence/context parallelism over the ICI ring.

Beyond-reference capability (SURVEY.md §2.6: the reference predates sequence
parallelism; §5 specifies this as the TPU-native answer). The sequence axis
is sharded over a mesh axis; each shard holds a query block and rotates the
K/V blocks around the ring with ``lax.ppermute`` (XLA collective-permute over
ICI neighbor links), accumulating attention with the online-softmax
(flash-style) running max/denominator so the full sequence is never
materialized on one chip. Compute of block t overlaps the transfer of block
t+1 thanks to XLA's latency-hiding scheduler.

Causal masking works on block indices: a shard skips score positions whose
global key index exceeds the global query index.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _block_attend(q, k, v, scale, mask):
    """Scores for one (q-block, kv-block) pair + unnormalized accumulators.

    q: [B, Lq, H, D]; k, v: [B, Lk, H, D]; mask: [Lq, Lk] or None.
    Returns (numerator [B, Lq, H, D], rowmax [B, Lq, H], rowsum [B, Lq, H]).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        s = jnp.where(mask[None, None, :, :], s, -jnp.inf)
    m = jnp.max(s, axis=-1)                          # [B, H, Lq] (may be -inf)
    # exponentiate against a finite shift; fully-masked rows produce zeros
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.where(jnp.isfinite(s), jnp.exp(s - m_safe[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)                          # [B, H, Lq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    # return the TRUE max (-inf where masked) — the merge needs it
    return o, jnp.transpose(m, (0, 2, 1)), jnp.transpose(l, (0, 2, 1))


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None):
    """Attention over a sequence sharded on ``axis_name``.

    Call inside shard_map with the sequence dimension sharded:
    q, k, v: [B, L_local, H, D] per shard. Returns [B, L_local, H, D].
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    lq = q.shape[1]
    lk = k.shape[1]

    # running accumulators: numerator, rowsum, rowmax — pcast to varying so
    # the fori_loop carry type matches the (varying) per-shard updates
    from chainermn_tpu.utils import match_vma

    acc = match_vma(jnp.zeros(q.shape, jnp.float32), q)
    lsum = match_vma(jnp.zeros(q.shape[:3], jnp.float32), q)  # [B, Lq, H]
    mrun = match_vma(jnp.full(q.shape[:3], -jnp.inf, jnp.float32), q)

    perm = [(i, (i + 1) % n) for i in range(n)]      # ring rotation

    def body(t, carry):
        acc, lsum, mrun, k_cur, v_cur = carry
        src = (my - t) % n                            # whose KV block this is

        if causal:
            # global positions: queries my*lq + iq, keys src*lk + ik
            iq = my * lq + jnp.arange(lq)[:, None]
            ik = src * lk + jnp.arange(lk)[None, :]
            mask = ik <= iq
        else:
            mask = None

        o_t, m_t, l_t = _block_attend(
            q.astype(jnp.float32), k_cur.astype(jnp.float32),
            v_cur.astype(jnp.float32), scale, mask)

        m_new = jnp.maximum(mrun, m_t)
        # rescale old accumulators; exp(-inf - m) == 0 handles the first step
        alpha = jnp.where(jnp.isfinite(mrun), jnp.exp(mrun - m_new), 0.0)
        beta = jnp.where(jnp.isfinite(m_t), jnp.exp(m_t - m_new), 0.0)
        acc = acc * alpha[..., None] + o_t * beta[..., None]
        lsum = lsum * alpha + l_t * beta
        mrun = m_new

        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return acc, lsum, mrun, k_nxt, v_nxt

    acc, lsum, mrun, _, _ = lax.fori_loop(
        0, n, body, (acc, lsum, mrun, k, v))
    out = acc / jnp.maximum(lsum[..., None], 1e-30)
    return out.astype(q.dtype)


def local_attention_reference(q, k, v, causal: bool = False,
                              scale: Optional[float] = None):
    """Single-device full attention (the correctness oracle)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        mask = jnp.arange(lk)[None, :] <= jnp.arange(lq)[:, None]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)

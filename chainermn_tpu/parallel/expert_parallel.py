"""Expert parallelism: Switch-style mixture-of-experts over a mesh axis.

Beyond-reference capability (SURVEY.md §2.6 records EP as absent upstream;
the rebuild provides it as a first-class parallelism strategy alongside
dp/tp/pp/sp). TPU-first design constraints drive everything here:

* **Static shapes.** Routing is data-dependent, but XLA needs static
  shapes, so dispatch uses a fixed per-expert ``capacity`` with overflow
  tokens dropped (Switch Transformer's discipline) — no dynamic gather.
* **all_to_all over ICI.** Token exchange is one ``lax.all_to_all`` each
  way, the bandwidth-optimal expert shuffle; XLA lowers it onto the ICI
  torus directly.
* **MXU-shaped expert compute.** Tokens arrive as a dense
  ``[experts_local, n_dev * capacity, d]`` block so the expert FFN is a
  plain batched matmul.

The dispatch/combine construction (one-hot + cumsum position bookkeeping)
is the standard public GShard/Switch formulation.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "switch_dispatch",
    "topk_dispatch",
    "ExpertParallelMLP",
]


def switch_dispatch(router_probs, capacity: int):
    """Top-1 dispatch/combine tensors with a static per-expert capacity.

    Args:
      router_probs: ``[tokens, experts]`` softmax router output.
      capacity: max tokens any expert accepts (from this shard).

    Returns:
      ``(dispatch, combine, aux_loss)`` where ``dispatch`` is a 0/1
      ``[tokens, experts, capacity]`` routing tensor, ``combine`` is
      ``dispatch`` scaled by the router gate, and ``aux_loss`` is the
      Switch load-balancing loss (experts * sum(fraction_routed *
      mean_prob), minimized at uniform routing).
    """
    t, e = router_probs.shape
    expert_idx = jnp.argmax(router_probs, axis=-1)
    gate = jnp.take_along_axis(
        router_probs, expert_idx[:, None], axis=-1)[:, 0]

    onehot = jax.nn.one_hot(expert_idx, e, dtype=router_probs.dtype)
    # 1-based arrival position of each token within its expert's queue.
    # Position bookkeeping is exact int32 — a low-precision (bf16) cumsum
    # would collide positions past 256 tokens and double-book slots.
    onehot_i = jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)
    pos = jnp.cumsum(onehot_i, axis=0) * onehot_i
    keep = (pos > 0) & (pos <= capacity)
    slot = jax.nn.one_hot(
        jnp.sum(pos, axis=-1) - 1, capacity, dtype=router_probs.dtype)
    dispatch = (onehot * keep.astype(router_probs.dtype)
                )[:, :, None] * slot[:, None, :]
    combine = dispatch * gate[:, None, None]

    fraction_routed = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(router_probs, axis=0)
    aux_loss = e * jnp.sum(fraction_routed * mean_prob)
    return dispatch, combine, aux_loss


def topk_dispatch(router_probs, capacity: int, k: int = 2):
    """GShard-style top-k dispatch/combine with static capacity.

    ``k=1`` delegates to `switch_dispatch` (raw-gate scaling, the Switch
    convention). For ``k>=2``, each token is routed to its k highest
    experts; rank-0 bookings fill expert queues before rank-1 considers
    them (GShard priority), and combine weights are the selected probs
    normalized over the kept ranks. aux is the Switch load-balancing loss
    on rank-0 assignments.
    """
    if k == 1:
        return switch_dispatch(router_probs, capacity)
    t, e = router_probs.shape
    probs_left = router_probs
    masks, gates = [], []
    for _ in range(k):
        idx = jnp.argmax(probs_left, -1)
        m = jax.nn.one_hot(idx, e, dtype=jnp.int32)
        gates.append(jnp.take_along_axis(
            router_probs, idx[:, None], -1)[:, 0])
        masks.append(m)
        probs_left = probs_left * (1 - m.astype(probs_left.dtype))

    dtype = router_probs.dtype
    dispatch = jnp.zeros((t, e, capacity), dtype)
    combine = jnp.zeros_like(dispatch)
    denom = sum(gates) + 1e-9
    offset = jnp.zeros((e,), jnp.int32)   # queue fill from earlier ranks
    for r in range(k):
        m = masks[r]
        pos = (jnp.cumsum(m, axis=0) + offset[None, :]) * m   # 1-based
        offset = offset + jnp.sum(m, axis=0)
        keep = ((pos > 0) & (pos <= capacity)).astype(dtype)
        slot = jax.nn.one_hot(jnp.sum(pos, -1) - 1, capacity, dtype=dtype)
        d_r = (m.astype(dtype) * keep)[:, :, None] * slot[:, None, :]
        dispatch = dispatch + d_r
        combine = combine + d_r * (gates[r] / denom)[:, None, None]

    fraction_routed = jnp.mean(masks[0].astype(dtype), axis=0)
    mean_prob = jnp.mean(router_probs, axis=0)
    aux = e * jnp.sum(fraction_routed * mean_prob)
    return dispatch, combine, aux


class ExpertParallelMLP(nn.Module):
    """Mixture-of-experts FFN with experts sharded over ``axis_name``.

    Use under ``shard_map`` with tokens sharded over the expert axis; this
    shard holds ``experts_per_device`` experts' weights. Total experts =
    ``axis_size * experts_per_device``.

    Parameter-sync contract: the expert tables (``w1``..``b2``) are
    per-shard (init with a rank-folded RNG, like the TP modules), but the
    ``router`` kernel is REPLICATED — give it identical initial values on
    every shard and ``pmean`` its gradient over ``axis_name`` (it is a
    data-parallel parameter; each shard's grad sees local tokens only).
    The tests' ``_stack_expert_params`` shows the layout.

    Returns ``(y, aux_loss)``: the combined expert outputs per local token
    (overflow tokens get zeros, the Switch convention — pair with a
    residual connection) and the load-balancing loss term.
    """

    hidden: int
    experts_per_device: int = 1
    axis_name: str = "expert"
    capacity_factor: float = 1.25
    top_k: int = 1                     # 1 = Switch; 2 = GShard top-2
    act: Callable = nn.gelu
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        n_dev = lax.axis_size(self.axis_name)
        e_tot = n_dev * self.experts_per_device
        t, d = x.shape
        capacity = max(1, int(
            t * self.capacity_factor * self.top_k / e_tot))

        # Router is logically replicated (same weights every shard).
        logits = nn.Dense(e_tot, use_bias=False, name="router",
                          dtype=self.dtype)(x)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        dispatch, combine, aux = topk_dispatch(probs, capacity,
                                               self.top_k)
        dispatch = dispatch.astype(x.dtype)
        combine = combine.astype(x.dtype)

        # [t, e_tot, c] x [t, d] -> [e_tot, c, d], then shuffle so each
        # device holds all shards' tokens for ITS local experts.
        exp_in = jnp.einsum("tec,td->ecd", dispatch, x)
        exp_in = exp_in.reshape(
            n_dev, self.experts_per_device, capacity, d)
        exp_in = lax.all_to_all(
            exp_in, self.axis_name, split_axis=0, concat_axis=0)
        # [n_dev(src), experts_local, c, d] -> [experts_local, n_dev*c, d]
        exp_in = exp_in.transpose(1, 0, 2, 3).reshape(
            self.experts_per_device, n_dev * capacity, d)

        # This shard's experts: one batched column of weights per expert.
        w1 = self.param(
            "w1", nn.initializers.lecun_normal(),
            (self.experts_per_device, d, self.hidden))
        b1 = self.param("b1", nn.initializers.zeros_init(),
                        (self.experts_per_device, self.hidden))
        w2 = self.param(
            "w2", nn.initializers.lecun_normal(),
            (self.experts_per_device, self.hidden, d))
        b2 = self.param("b2", nn.initializers.zeros_init(),
                        (self.experts_per_device, d))
        cdtype = self.dtype or exp_in.dtype
        h = self.act(jnp.einsum(
            "end,edh->enh", exp_in.astype(cdtype), w1.astype(cdtype))
            + b1[:, None, :].astype(cdtype))
        exp_out = jnp.einsum("enh,ehd->end", h, w2.astype(cdtype)) \
            + b2[:, None, :].astype(cdtype)

        # Reverse shuffle back to the token-owning shards.
        exp_out = exp_out.reshape(
            self.experts_per_device, n_dev, capacity, d).transpose(
            1, 0, 2, 3)
        exp_out = lax.all_to_all(
            exp_out, self.axis_name, split_axis=0, concat_axis=0)
        exp_out = exp_out.reshape(e_tot, capacity, d)

        y = jnp.einsum("tec,ecd->td", combine, exp_out.astype(x.dtype))
        return y, aux.astype(jnp.float32)

"""Ulysses-style sequence parallelism: all_to_all head↔sequence resharding.

Beyond-reference capability (SURVEY.md §2.6: the reference predates sequence
parallelism). The complement to ring attention
(chainermn_tpu/parallel/ring_attention.py): instead of rotating KV blocks
around the ring N times, ONE all_to_all redistributes the sharding from
"sequence split, all heads" to "full sequence, heads split", each device
runs ordinary (flash) attention over the whole sequence for its head group,
and a second all_to_all restores the sequence sharding.

Trade-off vs ring: two all_to_alls of activations total (cheap on ICI's
all-to-all bandwidth) instead of N ppermutes of K/V, and the inner compute
is one large flash kernel call (better MXU utilization than N small ones);
but every device must hold the FULL sequence for H/N heads, so the
per-device activation memory is the same as unsharded attention divided by
the axis size only in the head dimension — ring keeps O(L_local) residency
and scales to longer sequences. Use Ulysses while heads are plentiful and
L fits; ring past that.

No custom VJP is needed: ``lax.all_to_all`` is linear (its transpose is the
reverse exchange) and the inner `flash_attention` carries its own VJP.
"""

from __future__ import annotations

from typing import Optional

from jax import lax

from chainermn_tpu.ops.flash_attention import (DEFAULT_BLOCKS,
                                               flash_attention)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False,
                      scale: Optional[float] = None,
                      block_q: Optional[int] = None,
                      block_k: Optional[int] = None,
                      interpret: Optional[bool] = None):
    """Attention over a sequence sharded on ``axis_name``.

    Call inside shard_map: q, k, v are [B, L_local, H, D] per shard with
    the heads dimension intact; H must be divisible by the axis size.
    Returns [B, L_local, H, D] with the same sharding.
    """
    n = lax.axis_size(axis_name)
    h = q.shape[2]
    if h % n != 0:
        raise ValueError(
            f"ulysses_attention needs heads ({h}) divisible by the "
            f"'{axis_name}' axis size ({n}); use ring_flash_attention for "
            "few-head long-sequence cases")

    # [B, L/n, H, D] -> [B, L, H/n, D]: split heads, gather sequence.
    # Device i's shard concatenates in axis order, so the sequence is
    # globally ordered and causal masking needs no offset.
    reshard = lambda x: lax.all_to_all(x, axis_name, split_axis=2,
                                       concat_axis=1, tiled=True)
    o = flash_attention(reshard(q), reshard(k), reshard(v), causal, scale,
                        block_q or DEFAULT_BLOCKS[0],
                        block_k or DEFAULT_BLOCKS[1], interpret)
    # [B, L, H/n, D] -> [B, L/n, H, D]
    return lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)

"""Heterogeneous pipeline stages over the homogeneous 1F1B kernels.

The scheduling kernels in :mod:`chainermn_tpu.parallel.pipeline` move ONE
activation shape around the ring and ONE stacked parameter structure across
shards — the homogeneous-pipeline contract. Real models are not homogeneous:
an LM is embed → N×block → head, with int32 tokens in, [mb, L, D]
activations between blocks, and [mb, L, vocab] logits out, and per-stage
parameter pytrees of different structures.

This module lifts that restriction WITHOUT touching the scheduling kernels,
by compiling heterogeneity away at the edges (reference parity:
MultiNodeChainList composes arbitrary per-rank chains —
chainermn/links/multi_node_chain_list.py, SURVEY.md §2.4 — but sequentially;
here they ride the micro-batched 1F1B schedule):

* **Activation wire**: every inter-stage edge is encoded into one flat
  ``[W]`` buffer (ravel → cast → zero-pad to the widest edge). Decoding
  slices, casts and reshapes — all free (layout-only) in XLA. Integer
  inputs (token ids) round-trip exactly through the float wire for values
  < 2^24.
* **Final stage in the loss** (``head_in_loss``, default for S ≥ 2): the
  last stage's OUTPUT never travels the ring — it dies in the local loss
  on its own device. So the wire is sized by the widest edge that
  actually travels (``avals[0..S-1]``: the inputs of each stage), the
  last stage's ``lax.switch`` branch is the identity, and its real
  compute runs inside the kernels' ``head_params`` loss hook —
  cond-guarded to the owning device, differentiating THIS shard's packed
  parameter slot. For an LM (head output = [mb, L, vocab]) this shrinks
  every ``ppermute`` buffer and every 1F1B activation stash from
  vocab-width to d_model-width (~vocab/d_model ×, e.g. 42× at
  vocab=32k, d=768).
* **Parameter wire**: each stage's param pytree is flattened into a flat
  f32 vector padded to the widest stage, stacked ``[S, P]`` and sharded
  over the stage axis — each device materializes ONLY its own stage's
  (padded) parameters, preserving the pipeline's memory scaling.
  Pad-to-max is OPTIMAL under shard_map's homogeneous-shard rule: every
  scheme in which each device materializes exactly one stage must give
  all devices same-shaped shards, so per-device memory is bounded below
  by max_s P_s, which pad-to-max attains. (Size-class grouping — one
  stack per class — makes every device hold a row of EVERY class:
  Σ_classes P_class ≥ max_s P_s. Strictly worse.) The real escape for
  outlier stages (embed/head tables) is sharding them over a second
  mesh axis — see ``parallel/tensor_parallel.py`` and the TP×PP
  composition in ``examples/pipeline_lm``.
* **Stage dispatch**: one ``lax.switch`` on ``lax.axis_index(axis_name)``
  picks this device's stage function; every branch has the uniform
  signature ``([P] f32, [W] wire) -> [W] wire``, so the kernels see a
  shape-preserving homogeneous ``stage_fn``. ``lax.switch`` is
  differentiable, so the kernels' in-stage remat vjp works unchanged.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.parallel.pipeline import (
    _vma_ref,
    pipeline_1f1b_value_and_grad,
    pipeline_apply,
)
from chainermn_tpu.utils import match_vma


def _aval(x):
    return jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x))


class HeteroPipeline:
    """Codec + dispatch layer turning per-stage (fn, params) pairs into the
    homogeneous wire-format pipeline the scheduling kernels require.

    Args:
      stage_defs: ``[(fn_0, params_0), ..., (fn_{S-1}, params_{S-1})]`` —
        ``fn_s(params_s, x) -> y`` with arbitrary (static) activation
        shapes; stage s+1 consumes stage s's output. Params must be
        inexact-dtype pytrees (they are trained).
      sample_mb: one example micro-batch (array or ShapeDtypeStruct) —
        stage 0's input, e.g. int32 ``[mb, L]`` tokens.
      axis_name: the stage mesh axis (the shard_map axis the kernels run
        over). ``len(stage_defs)`` must equal the axis size at run time.
      wire_dtype: activation wire dtype; default = the widest dtype among
        the edges that travel the ring (``jnp.result_type`` over them).
      int_bound: exclusive upper bound the caller guarantees for values on
        integer edges (token ids, …); the wire must represent every value
        below it exactly or construction fails. Default 2^24 — the f32
        mantissa bound, enough for any real vocabulary.
      head_in_loss: run the final stage inside the kernels' loss hook so
        its output edge never needs a wire slot (see module docstring).
        Default: True whenever S ≥ 2. With False (or S = 1) every edge
        including the final output rides the wire — the round-1 format.
    """

    def __init__(self, stage_defs: Sequence[Tuple[Callable, Any]],
                 sample_mb, axis_name: str, wire_dtype=None,
                 int_bound: int = 2 ** 24, head_in_loss: bool = None):
        self.axis_name = axis_name
        self.fns = [f for f, _ in stage_defs]
        self.params = [p for _, p in stage_defs]
        self.S = len(stage_defs)
        if self.S < 1:
            raise ValueError("need at least one stage")
        if head_in_loss is None:
            head_in_loss = self.S >= 2
        if head_in_loss and self.S < 2:
            raise ValueError("head_in_loss needs S >= 2 (the ring must "
                             "have at least one non-head stage)")
        self.head_in_loss = head_in_loss

        # ---- activation avals along the chain -------------------------
        avals = [_aval(sample_mb) if not isinstance(
            sample_mb, jax.ShapeDtypeStruct) else sample_mb]
        for fn, p in stage_defs:
            out = jax.eval_shape(fn, p, avals[-1])
            if not isinstance(out, jax.ShapeDtypeStruct):
                raise ValueError(
                    "each stage must return a single array; got "
                    f"{jax.tree_util.tree_structure(out)}")
            avals.append(out)
        self.in_avals = avals[:-1]   # stage s consumes in_avals[s]
        self.out_avals = avals[1:]   # stage s produces out_avals[s]

        # edges that ride the ppermute ring: with head_in_loss the final
        # output (avals[-1], e.g. logits) is consumed locally on the last
        # device and never encoded
        ring_avals = avals[:-1] if head_in_loss else avals
        sizes = [int(np.prod(a.shape, initial=1)) for a in ring_avals]
        self.wire_elems = max(sizes)
        if wire_dtype is None:
            wire_dtype = jnp.result_type(*[a.dtype for a in ring_avals])
        self.wire_dtype = jnp.dtype(wire_dtype)
        for a in ring_avals:
            if (jnp.issubdtype(a.dtype, jnp.integer)
                    and jnp.issubdtype(self.wire_dtype, jnp.floating)):
                # int edge riding a float wire: exact only below the
                # mantissa bound (f32 → 2^24 covers any real vocab;
                # f16 → 2^11 and bf16 → 2^8 do not). ``int_bound`` is the
                # caller's declared exclusive upper bound on integer edge
                # values (token ids etc.).
                mant = jnp.finfo(self.wire_dtype).nmant
                if 2 ** (mant + 1) < int_bound:
                    raise ValueError(
                        f"integer activations up to int_bound={int_bound} "
                        f"cannot ride a {self.wire_dtype} wire "
                        f"({mant}-bit mantissa: exact only below "
                        f"{2 ** (mant + 1)}); use wire_dtype=jnp.float32 "
                        "or declare a smaller int_bound")

        # ---- per-stage flat parameter layout --------------------------
        # ravel_pytree handles flatten + unravel-with-dtype-restore; this
        # layer only adds the f32 cast and pad-to-max
        from jax.flatten_util import ravel_pytree

        self._flat_params: List[jnp.ndarray] = []
        self._unravel: List[Callable] = []
        for p in self.params:
            for l in jax.tree_util.tree_leaves(p):
                dt = jnp.result_type(l)
                if (not jnp.issubdtype(dt, jnp.floating)
                        or jnp.dtype(dt).itemsize > 4):
                    raise ValueError(
                        "stage params must be <=32-bit floating-point "
                        f"leaves — the param wire is f32 and would "
                        f"silently truncate {dt}")
            flat, unravel = ravel_pytree(p)
            # remember ravel's own dtype: unravel expects it back
            self._flat_params.append(flat)
            self._unravel.append(unravel)
        self.param_elems = max(
            [f.size for f in self._flat_params], default=1) or 1

    # ---- codecs -------------------------------------------------------

    def encode_act(self, x):
        """ravel → cast → pad to the wire width."""
        flat = jnp.ravel(x).astype(self.wire_dtype)
        return jnp.pad(flat, (0, self.wire_elems - flat.size))

    def decode_act(self, wire, aval):
        n = int(np.prod(aval.shape, initial=1))
        return wire[:n].astype(aval.dtype).reshape(aval.shape)

    def encode_inputs(self, x_microbatches):
        """[M, ...] micro-batches → [M, W] wire buffers (stage 0 feed)."""
        return jax.vmap(self.encode_act)(jnp.asarray(x_microbatches))

    def pack_params(self) -> jnp.ndarray:
        """[S, P] f32 stack — shard over the stage axis (P(axis_name))."""
        return jnp.stack([
            jnp.pad(f.astype(jnp.float32),
                    (0, self.param_elems - f.size))
            for f in self._flat_params
        ])

    def _unflatten(self, s: int, flat):
        f = self._flat_params[s]
        return self._unravel[s](flat[:f.size].astype(f.dtype))

    def unpack_grads(self, flat_grads) -> List[Any]:
        """[S, P] flat gradient stack → per-stage param-pytree grads.

        The parameter wire is f32, so each leaf's gradient comes back as
        the f32 cotangent of the cast — cast to the leaf dtype here.
        """
        out = []
        for s in range(self.S):
            out.append(self._unflatten(s, jnp.asarray(flat_grads)[s]))
        return out

    # ---- in-shard_map pieces ------------------------------------------

    def stage_fn(self, flat_params, wire_h):
        """The homogeneous ``(params, h) -> h`` the kernels schedule:
        switch on this device's stage index. With ``head_in_loss`` the
        final stage's branch is the identity — its input wire flows
        unchanged to the loss hook (forward) and its cotangent flows
        unchanged back onto the ring (backward)."""
        n_ax = lax.axis_size(self.axis_name)  # static at trace time
        if n_ax != self.S:
            raise ValueError(
                f"HeteroPipeline has {self.S} stages but axis "
                f"{self.axis_name!r} spans {n_ax} devices — lax.switch "
                "would silently clamp extra devices onto the last stage")
        n_ring = self.S - 1 if self.head_in_loss else self.S
        branches = []
        for s in range(n_ring):
            def branch(flat, wire, s=s):
                x = self.decode_act(wire, self.in_avals[s])
                y = self.fns[s](self._unflatten(s, flat), x)
                return self.encode_act(y)

            branches.append(branch)
        if self.head_in_loss:
            # identity on the wire; match the compute branches' varying
            # axes (they inherit flat's vma, e.g. under the kernels'
            # eval_shape probe where the wire aval alone is invariant)
            branches.append(lambda flat, wire: match_vma(wire, flat))
        my = lax.axis_index(self.axis_name)
        return lax.switch(my, branches, flat_params, wire_h)

    def wire_loss_fn(self, loss_fn):
        """Wrap ``loss_fn(decoded_final_output, tgt)`` for the kernels.

        head_in_loss: returns ``(head_flat, wire, tgt) -> scalar`` for the
        kernels' ``head_params`` hook — decode the wire as the final
        stage's INPUT, apply the final stage from its flat param slot,
        then the user loss. Otherwise: ``(wire, tgt) -> scalar`` decoding
        the final output directly.
        """
        if self.head_in_loss:
            def f(head_flat, wire_out, tgt):
                return loss_fn(self.head_apply(head_flat, wire_out), tgt)

            return f

        last = self.out_avals[-1]

        def f(wire_out, tgt):
            return loss_fn(self.decode_act(wire_out, last), tgt)

        return f

    def head_apply(self, flat_params, wire):
        """Final stage's forward from its flat param slot, on a decoded
        head-input wire — the driver-side complement of head_in_loss."""
        s = self.S - 1
        x = self.decode_act(wire, self.in_avals[s])
        return self.fns[s](self._unflatten(s, flat_params), x)


def hetero_pipeline_1f1b_value_and_grad(
    pipe: HeteroPipeline,
    loss_fn: Callable,
    packed_params,
    x_microbatches_wire,
    y_microbatches,
):
    """1F1B train step over heterogeneous stages — call INSIDE shard_map.

    Args:
      pipe: the :class:`HeteroPipeline` (built once, outside).
      loss_fn: ``(final_stage_output, target) -> scalar`` on DECODED
        outputs. No collectives over the STAGE axis (with
        ``head_in_loss`` it runs cond-guarded on the final stage's
        device); collectives over orthogonal mesh axes are fine.
      packed_params: THIS shard's ``[P]`` flat stage parameters (shard
        ``pipe.pack_params()`` with ``P(axis_name)`` and strip the leading
        axis in-shard, exactly like ``stack_stage_params``).
      x_microbatches_wire: ``[M, W]`` wire-encoded inputs
        (``pipe.encode_inputs``), replicated.
      y_microbatches: ``[M, ...]`` targets, replicated.

    Returns ``(loss, flat_grads [P])`` — decode grads with
    ``pipe.unpack_grads`` after stacking shards back (out_specs P(axis)).
    With ``head_in_loss`` the final stage's gradient (computed through the
    loss hook) is folded into its device's ``flat_grads`` slot here, so
    the result is identical in shape and meaning either way.
    """
    if not pipe.head_in_loss:
        return pipeline_1f1b_value_and_grad(
            pipe.stage_fn, pipe.wire_loss_fn(loss_fn), packed_params,
            x_microbatches_wire, y_microbatches, pipe.axis_name)

    # the final stage differentiates THIS shard's param slot through the
    # loss hook: only its owner runs the real branch (cond in
    # _head_loss_grads), every other device contributes exact zeros, and
    # the psum'd aux["head_grads"] is masked back onto the owner's slot
    loss, grads, aux = pipeline_1f1b_value_and_grad(
        pipe.stage_fn, pipe.wire_loss_fn(loss_fn), packed_params,
        x_microbatches_wire, y_microbatches, pipe.axis_name,
        head_params=packed_params)
    my = lax.axis_index(pipe.axis_name)
    n = lax.axis_size(pipe.axis_name)
    grads = grads + jnp.where(my == n - 1, aux["head_grads"],
                              jnp.zeros_like(aux["head_grads"]))
    return loss, grads


def hetero_pipeline_apply(pipe: HeteroPipeline, packed_params,
                          x_microbatches_wire):
    """GPipe-style forward over heterogeneous stages — call INSIDE
    shard_map. Returns DECODED final outputs ``[M, *out_avals[-1].shape]``
    (valid on every shard). With ``head_in_loss`` the ring delivers the
    final stage's inputs; its forward then runs cond-guarded on its owner
    device and the result is psum-broadcast."""
    outs = pipeline_apply(pipe.stage_fn, packed_params,
                          x_microbatches_wire, pipe.axis_name)
    final = pipe.out_avals[-1]
    if not pipe.head_in_loss:
        return jax.vmap(lambda w: pipe.decode_act(w, final))(outs)

    my = lax.axis_index(pipe.axis_name)
    n = lax.axis_size(pipe.axis_name)
    # cond branches must agree on varying axes: match the skip zeros to
    # the union of the stage index's and the params' vma (a second mesh
    # axis on the packed params would otherwise diverge the types)
    vref = _vma_ref(my, packed_params)

    def _run(_):
        return jax.vmap(
            lambda w: pipe.head_apply(packed_params, w)
        )(outs).astype(final.dtype)

    def _skip(_):
        return match_vma(
            jnp.zeros((outs.shape[0],) + final.shape, final.dtype), vref)

    ys = lax.cond(my == n - 1, _run, _skip, None)
    return lax.psum(ys, pipe.axis_name)

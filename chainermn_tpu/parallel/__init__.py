from .branching import (
    BranchingPipeline,
    branching_pipeline_apply,
    branching_pipeline_value_and_grad,
)
from .expert_parallel import ExpertParallelMLP, switch_dispatch
from .hetero_pipeline import (
    HeteroPipeline,
    hetero_pipeline_1f1b_value_and_grad,
    hetero_pipeline_apply,
)
from .pipeline import (
    build_interleaved_schedule,
    pipeline_1f1b_value_and_grad,
    pipeline_apply,
    pipeline_interleaved_1f1b_value_and_grad,
    stack_stage_params,
)
from .ring_attention import (
    local_attention_reference,
    ring_attention,
    ring_flash_attention,
)
from .ulysses import ulysses_attention
from .tensor_parallel import (
    ColumnParallelDense,
    RowParallelDense,
    TensorParallelMLP,
    vocab_parallel_cross_entropy,
)

__all__ = [
    "ring_attention",
    "ring_flash_attention",
    "ulysses_attention",
    "local_attention_reference",
    "pipeline_apply",
    "pipeline_1f1b_value_and_grad",
    "pipeline_interleaved_1f1b_value_and_grad",
    "build_interleaved_schedule",
    "stack_stage_params",
    "HeteroPipeline",
    "hetero_pipeline_1f1b_value_and_grad",
    "hetero_pipeline_apply",
    "BranchingPipeline",
    "branching_pipeline_value_and_grad",
    "branching_pipeline_apply",
    "ColumnParallelDense",
    "RowParallelDense",
    "TensorParallelMLP",
    "vocab_parallel_cross_entropy",
    "ExpertParallelMLP",
    "switch_dispatch",
]

"""Branching (DAG) pipeline: per-device stage parameters for tree-shaped
chain graphs.

Reference: chainermn/links/multi_node_chain_list.py (SURVEY.md §2.4) —
``add_link(chain, rank_in, rank_out)`` supports BRANCHING graphs (multiple
``rank_out``, multi-input stages), executed sequentially with blocking MPI
edges. The replicated SPMD executor (``links/chain_list.py .apply``) covers
those semantics but replicates every stage's parameters on every device;
linear chains escape via the 1F1B lowering. This module is the escape for
the branching case — the last reference feature whose big-model form
previously refused to run (VERDICT r3 weak #2).

Design (one device per stage, GPipe fill–drain over micro-batches):

* **Topology**: stages form a DAG in topological order (stage ``s`` runs
  on device ``s``); ``preds[s]`` names its producers. Roots (no preds)
  consume the global micro-batch; exactly ONE sink (the head) feeds the
  loss. ``depth[s]`` = longest path from a root; stages at the same
  depth compute in the same tick on different devices — the parallelism
  a linear schedule can't express.
* **Edges**: each consumer's input slot ``k`` is one ``ppermute`` per
  tick over the pairs ``[(preds[b][k], b) for all b]`` — fan-out is a
  repeated-source pair set, fan-in is multiple slots. An edge whose
  producer is more than one level up (``slack = depth[b] - depth[a] >
  1``: skip connections, uneven branches into a join) parks in a
  per-slot delay line ``[K, max_slack, W]`` rolled each tick; each
  stage's switch branch reads its slot at its own (static) slack index.
* **Wire format**: identical codec discipline to
  :class:`~chainermn_tpu.parallel.hetero_pipeline.HeteroPipeline` —
  activations ravel/cast/pad to the widest TRAVELING edge (the head's
  output never travels: its compute runs in the loss phase, cond-guarded
  on its owner, so vocab-wide logits don't size the wire); per-stage
  params ravel into an f32 ``[S, P]`` stack sharded over the stage axis
  (each device materializes only its own stage — pad-to-max optimality
  argument in hetero_pipeline.py).
* **Schedule**: ``lax.scan`` over ``depth[head] + M`` ticks; stage ``s``
  processes micro-batch ``t - depth[s]``. Backward is autodiff through
  the scan — ``ppermute`` transposes to the reversed edges, reproducing
  the reference's mirror schedule without hand-scheduling. ``remat=True``
  rematerializes each tick in backward (GPipe memory).
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.parallel.pipeline import _vma_ref
from chainermn_tpu.utils import match_vma


def _aval(x):
    if isinstance(x, jax.ShapeDtypeStruct):
        return x
    return jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x))


class BranchingPipeline:
    """Codec + schedule metadata for a DAG of stages (see module doc).

    Args:
      stage_defs: ``[(fn_s, params_s, preds_s), ...]`` in topological
        order. ``fn_s(params_s, *xs) -> y`` (single array out);
        ``preds_s`` is a tuple of earlier stage indices whose outputs are
        ``xs`` in order, or ``()`` for a root consuming the global input.
      sample_mb: one example micro-batch (array or ShapeDtypeStruct) —
        every root's input.
      axis_name: the stage mesh axis; its size must equal ``len(stage_defs)``.
      wire_dtype / int_bound: as in HeteroPipeline (the same exact-int
        constraint applies to integer edges riding a float wire).
    """

    def __init__(self, stage_defs: Sequence[Tuple[Callable, Any, Tuple]],
                 sample_mb, axis_name: str, wire_dtype=None,
                 int_bound: int = 2 ** 24):
        self.axis_name = axis_name
        self.fns = [f for f, _, _ in stage_defs]
        self.params = [p for _, p, _ in stage_defs]
        self.preds: List[Tuple[int, ...]] = [
            tuple(int(i) for i in pr) for _, _, pr in stage_defs]
        self.S = len(stage_defs)
        if self.S < 2:
            raise ValueError("a pipeline needs at least 2 stages")
        for s, pr in enumerate(self.preds):
            for p in pr:
                if not 0 <= p < s:
                    raise ValueError(
                        f"stage {s} consumes stage {p}: predecessors must "
                        "be earlier stages (topological order)")

        consumed = {p for pr in self.preds for p in pr}
        sinks = [s for s in range(self.S) if s not in consumed]
        if len(sinks) != 1:
            raise ValueError(
                f"the DAG must have exactly one output stage (the loss "
                f"consumer); found sinks {sinks}")
        self.head = sinks[0]

        # depth = longest path from a root; same-depth stages overlap
        self.depth = [0] * self.S
        for s in range(self.S):
            if self.preds[s]:
                self.depth[s] = 1 + max(self.depth[p]
                                        for p in self.preds[s])
        if self.depth[self.head] != max(self.depth):
            raise ValueError(
                "the output stage must be the deepest (every stage "
                "must feed it)")
        self.slacks = [
            tuple(self.depth[s] - self.depth[p] for p in self.preds[s])
            for s in range(self.S)
        ]
        self.K = max((len(p) for p in self.preds), default=1) or 1
        self.max_slack = max(
            (sl for sls in self.slacks for sl in sls), default=1)

        # ---- activation avals via an abstract DAG walk ----------------
        sample = _aval(sample_mb)
        self.out_avals: List[jax.ShapeDtypeStruct] = []
        for s in range(self.S):
            ins = ([sample] if not self.preds[s]
                   else [self.out_avals[p] for p in self.preds[s]])
            out = jax.eval_shape(self.fns[s], self.params[s], *ins)
            if not isinstance(out, jax.ShapeDtypeStruct):
                raise ValueError(
                    "each stage must return a single array; stage "
                    f"{s} returned {jax.tree_util.tree_structure(out)}")
            self.out_avals.append(out)
        self.in_avals = [
            tuple([sample] if not self.preds[s]
                  else [self.out_avals[p] for p in self.preds[s]])
            for s in range(self.S)
        ]
        self.sample_aval = sample

        # wire sized by TRAVELING values: every non-head stage's output,
        # plus the root feed (the head's output dies in the loss phase)
        ring_avals = [sample] + [self.out_avals[s] for s in range(self.S)
                                 if s != self.head]
        sizes = [int(np.prod(a.shape, initial=1)) for a in ring_avals]
        self.wire_elems = max(sizes)
        if wire_dtype is None:
            wire_dtype = jnp.result_type(*[a.dtype for a in ring_avals])
        self.wire_dtype = jnp.dtype(wire_dtype)
        for a in ring_avals:
            if (jnp.issubdtype(a.dtype, jnp.integer)
                    and jnp.issubdtype(self.wire_dtype, jnp.floating)):
                mant = jnp.finfo(self.wire_dtype).nmant
                if 2 ** (mant + 1) < int_bound:
                    raise ValueError(
                        f"integer activations up to int_bound={int_bound} "
                        f"cannot ride a {self.wire_dtype} wire "
                        f"({mant}-bit mantissa: exact only below "
                        f"{2 ** (mant + 1)}); use wire_dtype=jnp.float32 "
                        "or declare a smaller int_bound")

        # ---- flat param layout (identical to HeteroPipeline) ----------
        from jax.flatten_util import ravel_pytree

        self._flat_params: List[jnp.ndarray] = []
        self._unravel: List[Callable] = []
        for p in self.params:
            for l in jax.tree_util.tree_leaves(p):
                dt = jnp.result_type(l)
                if (not jnp.issubdtype(dt, jnp.floating)
                        or jnp.dtype(dt).itemsize > 4):
                    raise ValueError(
                        "stage params must be <=32-bit floating-point "
                        f"leaves — the param wire is f32 and would "
                        f"silently truncate {dt}")
            flat, unravel = ravel_pytree(p)
            self._flat_params.append(flat)
            self._unravel.append(unravel)
        self.param_elems = max(
            [f.size for f in self._flat_params], default=1) or 1

        # per-slot ppermute pair lists (slot k: one pair per consumer
        # with in-degree > k — targets unique by construction). A
        # fan-out producer appears as a REPEATED source, which
        # lax.ppermute rejects, so each slot's pairs are greedily
        # partitioned into sub-permutes with unique sources; devices a
        # sub-permute doesn't target receive zeros, so summing the
        # sub-results reassembles the slot's arrivals exactly.
        self.slot_perms: List[List[List[Tuple[int, int]]]] = []
        for k in range(self.K):
            pairs = [(self.preds[b][k], b) for b in range(self.S)
                     if len(self.preds[b]) > k]
            subs: List[List[Tuple[int, int]]] = []
            for pair in pairs:
                for sub in subs:
                    if all(s != pair[0] for s, _ in sub):
                        sub.append(pair)
                        break
                else:
                    subs.append([pair])
            self.slot_perms.append(subs)

    # ---- codecs (wire discipline identical to HeteroPipeline) --------

    def encode_act(self, x):
        flat = jnp.ravel(x).astype(self.wire_dtype)
        return jnp.pad(flat, (0, self.wire_elems - flat.size))

    def decode_act(self, wire, aval):
        n = int(np.prod(aval.shape, initial=1))
        return wire[:n].astype(aval.dtype).reshape(aval.shape)

    def encode_inputs(self, x_microbatches):
        return jax.vmap(self.encode_act)(jnp.asarray(x_microbatches))

    def pack_params(self) -> jnp.ndarray:
        return jnp.stack([
            jnp.pad(f.astype(jnp.float32),
                    (0, self.param_elems - f.size))
            for f in self._flat_params
        ])

    def _unflatten(self, s: int, flat):
        f = self._flat_params[s]
        return self._unravel[s](flat[:f.size].astype(f.dtype))

    def unpack_grads(self, flat_grads) -> List[Any]:
        return [self._unflatten(s, jnp.asarray(flat_grads)[s])
                for s in range(self.S)]

    # ---- in-shard_map pieces ------------------------------------------

    def _stage_branch(self, s: int):
        """Branch s of the dispatch switch: read this stage's inputs from
        its (static) slots/slack indices, compute, encode. The head's
        branch is a zeros wire — its compute runs in the loss phase."""
        if s == self.head:
            # match the compute branches' varying axes: they inherit vma
            # from BOTH the carry (box) and the sharded params (flat)
            return lambda flat, box, feed: match_vma(
                match_vma(jnp.zeros((self.wire_elems,), self.wire_dtype),
                          box), flat)

        def branch(flat, box, feed, s=s):
            if not self.preds[s]:
                xs = [self.decode_act(feed, self.sample_aval)]
            else:
                xs = [
                    self.decode_act(box[k, self.slacks[s][k] - 1],
                                    self.in_avals[s][k])
                    for k in range(len(self.preds[s]))
                ]
            y = self.fns[s](self._unflatten(s, flat), *xs)
            return self.encode_act(y)

        return branch

    def head_inbox(self, box):
        """The head's input wires at their slack indices: [K_head, W]."""
        return jnp.stack([
            box[k, self.slacks[self.head][k] - 1]
            for k in range(len(self.preds[self.head]))
        ])

    def head_apply(self, flat_params, inbox):
        """Head forward from its flat param slot on a stacked inbox."""
        xs = [self.decode_act(inbox[k], self.in_avals[self.head][k])
              for k in range(len(self.preds[self.head]))]
        return self.fns[self.head](
            self._unflatten(self.head, flat_params), *xs)

    def _scan_ticks(self, packed_params, x_wire, remat: bool):
        """The scheduled forward: scan over ticks, returning the head's
        per-micro-batch inbox stash [M, K_head, W] (valid on the head's
        device; garbage elsewhere)."""
        ax = self.axis_name
        n = lax.axis_size(ax)
        if n != self.S:
            raise ValueError(
                f"BranchingPipeline has {self.S} stages but axis {ax!r} "
                f"spans {n} devices")
        my = lax.axis_index(ax)
        m = x_wire.shape[0]
        ticks = self.depth[self.head] + m
        kh = len(self.preds[self.head])

        vref = _vma_ref(my, packed_params)
        box0 = match_vma(
            jnp.zeros((self.K, self.max_slack, self.wire_elems),
                      self.wire_dtype), vref)
        stash0 = match_vma(
            jnp.zeros((m, kh, self.wire_elems), self.wire_dtype), vref)
        branches = [self._stage_branch(s) for s in range(self.S)]
        # device s's micro-batch at tick t is t - depth[s]
        depths = jnp.asarray(self.depth)[my]

        def tick(carry, t):
            box, stash = carry
            mu = t - depths
            feed = lax.dynamic_index_in_dim(
                x_wire, jnp.clip(mu, 0, m - 1), axis=0, keepdims=False)
            y = lax.switch(my, branches, packed_params, box, feed)

            # the head records its inbox for micro-batch mu
            mu_ok = jnp.logical_and(mu >= 0, mu < m)
            record = jnp.logical_and(my == self.head, mu_ok)
            stash = lax.cond(
                record,
                lambda st: lax.dynamic_update_index_in_dim(
                    st, self.head_inbox(box), jnp.clip(mu, 0, m - 1),
                    axis=0),
                lambda st: st,
                stash,
            )

            # move every edge one hop; arrivals land in delay position 0
            # (fan-out = summed unique-source sub-permutes, see __init__)
            arrivals = [
                sum(lax.ppermute(y, ax, sub)
                    for sub in self.slot_perms[k])
                for k in range(self.K)
            ]
            box = jnp.concatenate(
                [jnp.stack(arrivals)[:, None, :],
                 box[:, :-1, :]], axis=1)
            return (box, stash), None

        if remat:
            tick = jax.checkpoint(tick)
        (box, stash), _ = lax.scan(tick, (box0, stash0),
                                   jnp.arange(ticks))
        return stash


def branching_pipeline_value_and_grad(
    pipe: BranchingPipeline,
    loss_fn: Callable,
    packed_params,
    x_microbatches_wire,
    y_microbatches,
    remat: bool = True,
):
    """DAG-pipeline train step — call INSIDE shard_map.

    Args:
      pipe: the :class:`BranchingPipeline` (built once, outside).
      loss_fn: ``(head_output, target) -> scalar`` on DECODED outputs; no
        STAGE-axis collectives (it runs cond-guarded on the head's
        device).
      packed_params: THIS shard's ``[P]`` flat stage parameters (shard
        ``pipe.pack_params()`` with ``P(axis_name)``, strip the axis).
      x_microbatches_wire: ``[M, W]`` wire-encoded root inputs
        (``pipe.encode_inputs``), replicated.
      y_microbatches: ``[M, ...]`` targets, replicated.
      remat: rematerialize each scheduled tick in backward (GPipe
        memory); False stores every tick's activations.

    Returns ``(loss, flat_grads [P])`` — loss is the mean over
    micro-batches; decode grads with ``pipe.unpack_grads`` after
    stacking shards (out_specs ``P(axis_name)``).
    """
    ax = pipe.axis_name
    my = lax.axis_index(ax)

    def f(flat):
        stash = pipe._scan_ticks(flat, x_microbatches_wire, remat)
        vref = _vma_ref(my, flat)

        def _run(_):
            def per_mb(inbox, tgt):
                return loss_fn(pipe.head_apply(flat, inbox), tgt)

            return jnp.mean(
                jax.vmap(per_mb)(stash, y_microbatches)
            ).astype(jnp.float32)

        def _skip(_):
            return match_vma(jnp.zeros((), jnp.float32), vref)

        l = lax.cond(my == pipe.head, _run, _skip, None)
        return lax.psum(l, ax)

    return jax.value_and_grad(f)(packed_params)


def branching_pipeline_apply(pipe: BranchingPipeline, packed_params,
                             x_microbatches_wire):
    """Forward pass over the DAG schedule — call INSIDE shard_map.
    Returns DECODED head outputs ``[M, *head_aval.shape]`` (valid on
    every shard via psum-broadcast)."""
    stash = pipe._scan_ticks(packed_params, x_microbatches_wire,
                             remat=False)
    my = lax.axis_index(pipe.axis_name)
    final = pipe.out_avals[pipe.head]
    vref = _vma_ref(my, packed_params)

    def _run(_):
        return jax.vmap(
            lambda box: pipe.head_apply(packed_params, box)
        )(stash).astype(final.dtype)

    def _skip(_):
        return match_vma(
            jnp.zeros((stash.shape[0],) + final.shape, final.dtype),
            vref)

    ys = lax.cond(my == pipe.head, _run, _skip, None)
    return lax.psum(ys, pipe.axis_name)

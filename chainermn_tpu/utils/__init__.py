"""Small shared utilities."""

from __future__ import annotations

import os


def ensure_platform():
    """Make the JAX_PLATFORMS env var authoritative.

    Some environments install site hooks that re-pin jax's platform on
    import, silently overriding the env var a user set on the command line
    (observed: an example asked for an 8-device CPU mesh and ran on one TPU
    chip instead). Calling this before device queries re-asserts the user's
    choice through jax.config, which wins over the hook.
    """
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        try:
            jax.config.update("jax_platforms", want)
        except Exception:
            pass

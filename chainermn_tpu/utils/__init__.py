"""Small shared utilities."""

from __future__ import annotations

import os


def match_vma(tree, ref):
    """Make ``tree``'s leaves vary on the same manual mesh axes as ``ref``.

    Under shard_map's varying-axis tracking, freshly created constants
    (zeros carries, accumulators) are axis-invariant while scanned/looped
    data varies — lax.scan/fori_loop then reject the carry type mismatch.
    pcast-to-varying aligns them; no-op outside shard_map or when tracking
    is off.
    """
    import jax

    ref_vma = getattr(jax.typeof(ref), "vma", None)
    if not ref_vma:
        return tree

    def fix(l):
        need = tuple(ref_vma - jax.typeof(l).vma)
        return jax.lax.pcast(l, need, to="varying") if need else l

    return jax.tree_util.tree_map(fix, tree)


def ensure_platform():
    """Make the JAX_PLATFORMS env var authoritative.

    Some environments install site hooks that re-pin jax's platform on
    import, silently overriding the env var a user set on the command line
    (observed: an example asked for an 8-device CPU mesh and ran on one TPU
    chip instead). Calling this before device queries re-asserts the user's
    choice through jax.config, which wins over the hook.
    """
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        try:
            jax.config.update("jax_platforms", want)
        except Exception:
            pass

"""schedtune: the AOT overlap-driven collective-schedule search.

Closes the loop the ROADMAP names: dlint DL201/DL203 *measure* overlap;
this module *acts* on them. For each candidate knob setting —
``bucket_bytes``, bucket emission order, ``double_buffering``, reducer
strategy — a caller-supplied ``compile_fn`` produces scheduled HLO
(real AOT compilation of the train step when the TPU compiler plugin
exists, the :mod:`.canned` emulator otherwise), the DL201 overlap
fraction and DL203 permute verdict score the schedule, and the
per-tier :class:`~chainermn_tpu.tuning.topology.Topology` cost model
prices the collectives. The objective is modeled EXPOSED communication
time::

    score = comm_us · (1 − overlap_fraction) + ε · n_buckets

— collectives hidden behind backward compute are free; the ε·buckets
term is a deterministic tie-break toward fewer launches (the flat-first
instinct of ``AutoReducer.choose``). DL203 failures (a pipeline hop
serializing) add the full comm cost as a penalty. No wall clock, no
RNG: the same HLO fixtures always produce the same schedule, which is
what makes the winner storable in the profile DB.

The search is small and exhaustive by design (dozens of candidates,
each scored in microseconds off-TPU) — TACCL-style synthesis over an
explicit topology beats hand-tuned constants without needing a solver.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Dict, List, Optional, Sequence

from chainermn_tpu.tuning.profile_db import SchedulePlan
from chainermn_tpu.tuning.topology import Topology

# NOTE: chainermn_tpu.synthesis is imported lazily inside the functions
# that need it — importing it at module level closes an import cycle
# (synthesis/__init__ pulls the compiler, which pulls collectives,
# which registers the 'synth' strategy back through synthesis).

#: default bucket_bytes sweep (1/4/16/64 MiB — brackets the 4 MiB
#: DEFAULT_DCN_BUCKET_BYTES from both sides, plus the one-bucket regime)
DEFAULT_BUCKET_SWEEP = (1 << 20, 4 << 20, 16 << 20, 64 << 20)
#: the untuned reference configuration (comm/xla.py defaults)
DEFAULT_BUCKET_BYTES = 4 << 20
#: tie-break weight: microseconds charged per collective launch beyond
#: the modeled latency, purely to make equal-exposure choices stable
LAUNCH_EPSILON_US = 1e-3


#: wire formats the lossy sweep tries for the quantized strategy —
#: plain 'int8' is omitted (blockwise strictly dominates it: same wire
#: width, per-256-element scales)
QUANT_WIRE_SWEEP = ("bf16", "int8-block", "int4-block")


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One point in the search space — a knob setting, or (strategy
    ``'synth'``) a whole synthesized program."""

    strategy: str = "flat"
    bucket_bytes: int = DEFAULT_BUCKET_BYTES
    bucket_order: str = "emission"
    double_buffering: bool = False
    #: quantized-wire format; 'f32' (the non-compressing strategies'
    #: only wire) is priced as bf16 when the strategy is 'quantized'.
    #: For 'synth' this mirrors the program's own wire (informational)
    wire_format: str = "f32"
    #: a :class:`chainermn_tpu.synthesis.Program` when strategy is
    #: 'synth' (frozen, so the candidate stays hashable); None for the
    #: fixed-reducer strategies
    program: Any = None


def default_flat_candidate() -> Candidate:
    """What you get today with no tuning: flat psum, 4 MiB buckets,
    pytree-emission order, no staleness."""
    return Candidate()


def default_candidates(topology: Topology,
                       bucket_sweep: Sequence[int] = DEFAULT_BUCKET_SWEEP,
                       lossy: bool = False,
                       allow_stale: bool = False) -> List[Candidate]:
    """The standard grid. ``hierarchical``/``auto`` only enter when the
    topology has an outer tier to exploit; ``quantized`` needs the
    explicit ``lossy`` opt-in and ``double_buffering`` the explicit
    ``allow_stale`` opt-in (both change numerics — a tuner must not)."""
    strategies = ["flat"]
    if topology.inter > 1:
        strategies += ["hierarchical", "auto"]
    if lossy:
        strategies.append("quantized")
    out = []
    for strategy in strategies:
        wires = (QUANT_WIRE_SWEEP if strategy == "quantized"
                 else ("f32",))
        for wf in wires:
            for bb in bucket_sweep:
                for order in ("emission", "size"):
                    out.append(Candidate(strategy, int(bb), order,
                                         False, wf))
                    if allow_stale:
                        out.append(Candidate(strategy, int(bb), order,
                                             True, wf))
    if len(topology.tiers) > 1:
        # program candidates: every enumerator emission, swept over the
        # same buckets/orders (lazy import — see the module-level note)
        from chainermn_tpu.synthesis.sketch import enumerate_programs
        for prog in enumerate_programs(topology, lossy=lossy):
            for bb in bucket_sweep:
                for order in ("emission", "size"):
                    out.append(Candidate("synth", int(bb), order, False,
                                         prog.wire_format, prog))
    return out


def _bucket_payloads(total_bytes: int, bucket_bytes: int) -> List[int]:
    k = max(1, math.ceil(total_bytes / bucket_bytes))
    per, rem = divmod(total_bytes, k)
    return [per + (1 if i < rem else 0) for i in range(k)]


def estimate_comm_us(topology: Topology, candidate: Candidate,
                     total_bytes: int,
                     measured: Optional[Dict] = None) -> float:
    """Per-tier cost-model price of the candidate's collectives (sum
    over buckets). ``auto`` prices each bucket at its best strategy.
    A ``measured`` table ({(strategy, bytes): us}, nearest size wins)
    overrides the model where it has data — the on-TPU sweep path."""

    def one(strategy: str, nbytes: int) -> float:
        if measured:
            pts = [(abs(sz - nbytes), us) for (s, sz), us
                   in measured.items() if s == strategy]
            if pts:
                return min(pts)[1]
        if strategy == "synth":
            from chainermn_tpu.synthesis.sketch import program_cost_us
            return program_cost_us(candidate.program, topology, nbytes)
        if strategy == "quantized":
            wf = (candidate.wire_format
                  if candidate.wire_format != "f32" else "bf16")
            return topology.estimate_us(strategy, nbytes, wire_format=wf)
        return topology.estimate_us(strategy, nbytes)

    total = 0.0
    for nbytes in _bucket_payloads(total_bytes, candidate.bucket_bytes):
        if candidate.strategy == "auto":
            total += min(one("flat", nbytes), one("hierarchical", nbytes))
        else:
            total += one(candidate.strategy, nbytes)
    return total


def bucket_algorithms(topology: Topology, candidate: Candidate,
                      total_bytes: int,
                      measured: Optional[Dict] = None):
    """Per-bucket ``(algorithm, payload_bytes)`` assignment for the
    plan record (``auto`` resolves per bucket, like AutoReducer)."""
    out = []
    for nbytes in _bucket_payloads(total_bytes, candidate.bucket_bytes):
        algo = candidate.strategy
        if algo == "synth" and candidate.program is not None:
            algo = "synth:" + (candidate.program.name or "unnamed")
        if algo == "auto":
            flat = estimate_comm_us(
                topology, Candidate("flat", nbytes), nbytes, measured)
            hier = estimate_comm_us(
                topology, Candidate("hierarchical", nbytes), nbytes,
                measured)
            algo = "flat" if flat <= hier else "hierarchical"
        out.append((algo, int(nbytes)))
    return tuple(out)


def score_candidate(topology: Topology, candidate: Candidate,
                    hlo_text: str, total_bytes: int,
                    measured: Optional[Dict] = None) -> dict:
    """Score one candidate's scheduled HLO (lower is better)."""
    from chainermn_tpu.analysis.hlo_passes import (
        check_pipeline_permute_overlap,
        dp_overlap_fraction,
    )

    frac = dp_overlap_fraction(hlo_text)
    d203 = check_pipeline_permute_overlap(hlo_text)
    n_buckets = max(1, math.ceil(total_bytes / candidate.bucket_bytes))
    comm_us = estimate_comm_us(topology, candidate, total_bytes, measured)
    exposed_us = comm_us * (1.0 - frac)
    permute_penalty_us = (
        comm_us if (d203.get("n_permute_pairs") or d203.get(
            "sync_permutes")) and not d203.get("ok") else 0.0)
    return {
        "candidate": dataclasses.asdict(candidate),
        "overlap_fraction": round(frac, 6),
        "comm_us": round(comm_us, 3),
        "exposed_us": round(exposed_us, 3),
        "permute_penalty_us": round(permute_penalty_us, 3),
        "n_buckets": n_buckets,
        "score": (exposed_us + permute_penalty_us
                  + LAUNCH_EPSILON_US * n_buckets),
    }


@dataclasses.dataclass
class TuningResult:
    """The winner plus the full evidence table."""

    plan: SchedulePlan
    rows: List[dict]
    default: dict  # the untuned flat configuration's score row

    @property
    def improves_overlap(self) -> bool:
        """Strictly higher DL201 overlap fraction than untuned flat —
        the acceptance bar for recording a plan as a win."""
        return (self.plan.overlap_fraction
                > self.default["overlap_fraction"])


def tune(topology: Topology, total_bytes: int,
         compile_fn: Callable[[Candidate], Optional[str]],
         candidates: Optional[Sequence[Candidate]] = None,
         model_key: str = "default",
         measured: Optional[Dict] = None,
         lossy: bool = False,
         allow_stale: bool = False,
         source: str = "canned") -> TuningResult:
    """Run the search: compile + score every candidate, pick the
    minimum (score, declaration order) — fully deterministic.

    ``compile_fn(candidate)`` returns scheduled-HLO text, or ``None``
    to skip a candidate the builder can't express. The untuned default
    flat configuration is always scored too (appended if absent) so
    every :class:`TuningResult` carries the tuned-vs-default delta.
    """
    cands = list(candidates if candidates is not None
                 else default_candidates(topology, lossy=lossy,
                                         allow_stale=allow_stale))
    base = default_flat_candidate()
    if base not in cands:
        cands.append(base)
    rows, scored = [], []
    for idx, cand in enumerate(cands):
        hlo = compile_fn(cand)
        if hlo is None:
            continue
        row = score_candidate(topology, cand, hlo, total_bytes, measured)
        rows.append(row)
        scored.append((row["score"], idx, cand, row))
    if not scored:
        raise ValueError("no candidate compiled — nothing to tune")
    _, _, best, best_row = min(scored, key=lambda t: (t[0], t[1]))
    default_row = next(r for r in rows
                       if r["candidate"] == dataclasses.asdict(base))
    plan = SchedulePlan(
        fingerprint=topology.fingerprint(),
        model_key=model_key,
        strategy=best.strategy,
        bucket_bytes=best.bucket_bytes,
        bucket_order=best.bucket_order,
        double_buffering=best.double_buffering,
        wire_format=best.wire_format,
        overlap_fraction=best_row["overlap_fraction"],
        est_exposed_us=round(best_row["score"], 3),
        source=source,
        buckets=bucket_algorithms(topology, best, total_bytes, measured),
        program=(best.program.to_dict()
                 if best.program is not None else None),
    )
    return TuningResult(plan=plan, rows=rows, default=default_row)


def tune_canned(topology: Topology, total_bytes: int,
                **kwargs) -> TuningResult:
    """The off-TPU entry point: :func:`tune` over the canned
    scheduled-HLO emulator (:mod:`.canned`)."""
    from chainermn_tpu.tuning.canned import canned_compile_fn

    return tune(topology, total_bytes, canned_compile_fn(total_bytes),
                **kwargs)

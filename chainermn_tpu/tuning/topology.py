"""Explicit multi-tier machine topology for schedule tuning.

Replaces the single intra/inter split hard-coded into
``collectives/auto.py``: TACCL (arxiv 2111.04867) and HiCCL (arxiv
2408.05962) both show that a schedule search needs the machine described
as an explicit hierarchy of tiers — each with its own size, launch
latency (alpha) and per-rank bandwidth (beta) — rather than a boolean
"is there a slow tier?". A :class:`Topology` is an ordered tuple of
:class:`Tier` objects, fastest (innermost — ICI ring/torus dims) first,
slowest (DCN) last, plus a deterministic :meth:`~Topology.fingerprint`
that keys the persistent profile DB (:mod:`.profile_db`).

This module is deliberately leaf-level: stdlib only, no jax, no imports
from the rest of ``chainermn_tpu`` — both ``collectives/`` and
``tuning/`` import it without cycles.

Cost model: standard alpha-beta with ring-allreduce byte counts
(``2·b·(k-1)/k`` per rank over a k-ring). The two-tier defaults are the
same v5e-flavored numbers ``collectives.auto.CostModel`` has always
used (ICI ~100 GB/s / ~1 µs, DCN ~25 GB/s / ~100 µs —
docs/scaling_model.md); for two tiers :meth:`Topology.estimate_us` is
algebraically identical to the old model, so the ``auto`` reducer's
crossover structure is unchanged. See docs/tuning.md.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

#: default per-tier parameters (microseconds, GB/s) — order-of-magnitude
#: v5e numbers; override per tier or via measured sweeps (profile DB)
ICI_LATENCY_US = 1.0
ICI_BW_GBPS = 100.0
DCN_LATENCY_US = 100.0
DCN_BW_GBPS = 25.0
#: quantize/dequantize kernel overhead for the quantized-wire strategy
QUANT_OVERHEAD_US = 2.0

#: wire bytes per f32 payload byte, by wire format — values plus the
#: f32 scale sidecar of the blockwise formats (one scale per 256
#: elements = +1/256). MUST stay numerically equal to
#: ``collectives.quantized.wire_ratio`` (this module is stdlib-only so
#: it cannot import the jax-side table; the equality is pinned by
#: tests/tuning_tests/test_wire_cost.py).
WIRE_RATIO = {
    "f32": 1.0,
    "bf16": 0.5,
    "int8": 0.25,
    "int8-block": 0.25 + 1.0 / 256,
    "int4-block": 0.125 + 1.0 / 256,
}


@dataclasses.dataclass(frozen=True)
class Tier:
    """One level of the machine hierarchy: ``size`` ranks connected at
    ``bw_gbps`` per rank with ``latency_us`` launch latency."""

    name: str
    size: int
    latency_us: float
    bw_gbps: float


def _ring_bytes(nbytes: float, k: int) -> float:
    return 2.0 * nbytes * (k - 1) / max(k, 1)


def _xfer_us(nbytes: float, bw_gbps: float) -> float:
    return nbytes / (bw_gbps * 1e3)  # 1 GB/s == 1e3 bytes/us


@dataclasses.dataclass(frozen=True)
class Topology:
    """Ordered multi-tier topology, innermost/fastest tier first.

    ``platform``/``device_kind`` only feed :meth:`fingerprint` — a
    profile measured on one device kind must not silently tune another.
    """

    tiers: Tuple[Tier, ...]
    platform: str = "cpu"
    device_kind: str = ""
    quant_overhead_us: float = QUANT_OVERHEAD_US

    def __post_init__(self):
        if not self.tiers:
            raise ValueError("Topology needs at least one tier")

    # -- shape ----------------------------------------------------------
    @property
    def n(self) -> int:
        out = 1
        for t in self.tiers:
            out *= t.size
        return out

    @property
    def intra(self) -> int:
        return self.tiers[0].size

    @property
    def inter(self) -> int:
        return self.n // self.intra

    def fingerprint(self) -> str:
        """Deterministic key for the profile DB: platform, device kind,
        and the per-tier sizes — everything a schedule choice depends
        on, nothing it doesn't (no hostnames, no PIDs, no timestamps)."""
        kind = (self.device_kind or "generic").lower().replace(" ", "-")
        dims = "+".join(f"{t.name}:{t.size}" for t in self.tiers)
        return f"{self.platform}:{kind}/{dims}"

    # -- construction ---------------------------------------------------
    @classmethod
    def from_comm(cls, comm, intra: Optional[int] = None,
                  ici_latency_us: float = ICI_LATENCY_US,
                  ici_bw_gbps: float = ICI_BW_GBPS,
                  dcn_latency_us: float = DCN_LATENCY_US,
                  dcn_bw_gbps: float = DCN_BW_GBPS,
                  quant_overhead_us: Optional[float] = None) -> "Topology":
        """Describe a communicator's mesh as tiers.

        Same topology-resolution rules as
        ``collectives.hierarchical.HierTopology``: a ≥2-axis mesh (the
        ``('dcn', 'ici')`` factory layout) takes its LAST axis as the
        fast/ICI tier and every preceding axis as a DCN tier; a
        single-axis mesh is factored into ``inter × intra`` with
        ``intra`` defaulting to ``comm.intra_size`` (degenerate: one
        tier when that doesn't divide the axis). Size-1 outer tiers are
        dropped so single-host fingerprints stay stable.
        """
        if quant_overhead_us is None:
            quant_overhead_us = QUANT_OVERHEAD_US
        dev = comm.mesh.devices.flat[0]
        platform = getattr(dev, "platform", "cpu")
        kind = getattr(dev, "device_kind", "") or ""
        axes = comm.axis_names
        if len(axes) >= 2 and intra is None:
            sizes = dict(zip(comm.mesh.axis_names, comm.mesh.devices.shape))
            tiers = [Tier(axes[-1], sizes[axes[-1]],
                          ici_latency_us, ici_bw_gbps)]
            for ax in reversed(axes[:-1]):  # innermost-out
                if sizes[ax] > 1:
                    tiers.append(Tier(ax, sizes[ax],
                                      dcn_latency_us, dcn_bw_gbps))
            return cls(tuple(tiers), platform, kind, quant_overhead_us)
        n = comm.size
        if intra is None:
            intra = comm.intra_size
            if not (1 <= intra <= n and n % intra == 0):
                intra = n  # degenerate: one tier
        if not (1 <= intra <= n and n % intra == 0):
            raise ValueError(
                f"intra {intra} must divide communicator size {n}")
        tiers = [Tier("ici", intra, ici_latency_us, ici_bw_gbps)]
        if n // intra > 1:
            tiers.append(Tier("dcn", n // intra,
                              dcn_latency_us, dcn_bw_gbps))
        return cls(tuple(tiers), platform, kind, quant_overhead_us)

    # -- the cost model -------------------------------------------------
    def estimate_us(self, strategy: str, nbytes: int,
                    wire_format: str = "bf16") -> float:
        """Modeled time for ONE reduction of ``nbytes`` payload.

        ``flat``: one allreduce whose ring crosses the slowest tier.
        ``hierarchical``: the canonical cascade — reduce-scatter +
        all-gather bracketing on every tier but the last, an allreduce
        on the last, each stage carrying ``1/prod(inner sizes)`` of the
        bytes. ``quantized``: flat at ``wire_format``'s wire width
        (:data:`WIRE_RATIO` — beta scales with the actual bytes on the
        wire, so the narrower formats genuinely price cheaper) plus the
        (de)quantize kernel overhead. For a two-tier topology these are
        exactly the ``collectives.auto.CostModel`` formulas; for one
        tier, ``hierarchical`` degenerates to rs + ag on that tier (two
        launches, same formulas). Beyond two tiers the payload keeps
        shrinking at every scatter stage — pricing every outer tier at
        ``nbytes/intra`` (the old behavior) over-charged the slowest
        tier by the product of the middle tier sizes, making 3-tier
        synthesized programs (synthesis/) compare unfairly.
        """
        slow = self.tiers[-1]
        if strategy == "flat":
            return slow.latency_us + _xfer_us(
                _ring_bytes(nbytes, self.n), slow.bw_gbps)
        if strategy == "hierarchical":
            if len(self.tiers) == 1:
                t0 = self.tiers[0]
                return 2 * t0.latency_us + _xfer_us(
                    _ring_bytes(nbytes, t0.size), t0.bw_gbps)  # rs + ag
            t, carried = 0.0, float(nbytes)
            for tier in self.tiers[:-1]:
                # rs + ag bracket the outer stages: two launches, and
                # _ring_bytes' 2x factor covers both directions' bytes
                t += 2 * tier.latency_us + _xfer_us(
                    _ring_bytes(carried, tier.size), tier.bw_gbps)
                carried /= tier.size
            t += slow.latency_us + _xfer_us(
                _ring_bytes(carried, slow.size), slow.bw_gbps)
            return t
        if strategy == "quantized":
            try:
                wire = nbytes * WIRE_RATIO[wire_format]
            except KeyError:
                raise ValueError(
                    f"unknown wire_format {wire_format!r}; expected one "
                    f"of {tuple(WIRE_RATIO)}") from None
            return (slow.latency_us + self.quant_overhead_us
                    + _xfer_us(_ring_bytes(wire, self.n), slow.bw_gbps))
        raise ValueError(f"unknown strategy {strategy!r}")

    def describe(self) -> str:
        return " → ".join(
            f"{t.name}[{t.size}] {t.bw_gbps}GB/s/{t.latency_us}us"
            for t in self.tiers)


def single_tier(n: int, name: str = "ici",
                latency_us: float = ICI_LATENCY_US,
                bw_gbps: float = ICI_BW_GBPS) -> Topology:
    """A one-tier test/CLI convenience topology."""
    return Topology((Tier(name, n, latency_us, bw_gbps),))


def two_tier(intra: int, inter: int) -> Topology:
    """The classic ICI×DCN shape with default parameters."""
    tiers = [Tier("ici", intra, ICI_LATENCY_US, ICI_BW_GBPS)]
    if inter > 1:
        tiers.append(Tier("dcn", inter, DCN_LATENCY_US, DCN_BW_GBPS))
    return Topology(tuple(tiers), platform="tpu")

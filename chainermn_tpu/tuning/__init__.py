"""schedtune — the AOT overlap-driven collective-schedule autotuner.

The feedback loop the ROADMAP asked for: dlint's DL201/DL203 passes
measure how much of the backward window the compiler's schedule
actually uses; this package searches the reducer knob space
(``bucket_bytes``, bucket emission order, ``double_buffering``,
strategy) against those measurements plus an explicit multi-tier
:class:`Topology` cost model, and persists the winner in a per-topology
JSON profile DB that ``create_multi_node_optimizer(tune=...)`` and
``AutoReducer(profile=...)`` consume. The whole search runs off-TPU
(AOT-compiled or canned scheduled HLO); on-TPU ``measure_strategies``
sweeps feed the same DB (``db=``). One CLI: ``tools/schedtune.py``.
See docs/tuning.md.
"""

from chainermn_tpu.tuning.canned import (  # noqa: F401
    canned_compile_fn,
    canned_schedule_hlo,
)
from chainermn_tpu.tuning.profile_db import (  # noqa: F401
    ProfileDB,
    SchedulePlan,
    default_db_path,
    model_key_for,
)
from chainermn_tpu.tuning.topology import (  # noqa: F401
    Tier,
    Topology,
    single_tier,
    two_tier,
)
from chainermn_tpu.tuning.tuner import (  # noqa: F401
    Candidate,
    TuningResult,
    default_candidates,
    default_flat_candidate,
    estimate_comm_us,
    score_candidate,
    tune,
    tune_canned,
)

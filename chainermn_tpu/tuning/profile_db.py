"""The persistent per-topology schedule-profile DB.

One JSON file maps a topology fingerprint (:meth:`Topology.fingerprint`)
to (a) tuned :class:`SchedulePlan` winners keyed by model shape and
(b) measured ``measure_strategies`` sweeps — so a single on-TPU run
permanently improves off-TPU tuning for that machine shape. Consumed by
``AutoReducer(profile=...)`` and
``create_multi_node_optimizer(tune=...)``; written by
``tools/schedtune.py`` and ``measure_strategies(db=...)``.

File layout (version 1)::

    {"version": 1,
     "plans":    {"<fingerprint>": {"<model_key>": {<SchedulePlan>}}},
     "measured": {"<fingerprint>": {"<strategy>:<bytes>": <us>}}}

Loading a profile written for a DIFFERENT fingerprint is the
wrong-machine bug dlint DL107 flags statically and
``create_multi_node_optimizer(tune=...)`` refuses at runtime — a plan
tuned for one machine silently mis-tunes another.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import tempfile
from typing import Any, Dict, Optional, Tuple, Union

from chainermn_tpu.tuning.topology import Topology

#: env override for the default DB location (CI / multi-user hosts)
PROFILE_DB_ENV = "CHAINERMN_TPU_PROFILE_DB"
_DEFAULT_PATH = os.path.join("~", ".cache", "chainermn_tpu",
                             "schedtune.json")


def default_db_path() -> str:
    return os.path.expanduser(
        os.environ.get(PROFILE_DB_ENV) or _DEFAULT_PATH)


def model_key_for(tree) -> str:
    """Deterministic model-shape key: leaf count, total payload bytes,
    and a digest of the (path, shape, dtype) list. Works on concrete or
    abstract (``jax.eval_shape``) pytrees."""
    import jax
    import jax.numpy as jnp

    leaves_kp, _ = jax.tree_util.tree_flatten_with_path(tree)
    rows, total = [], 0
    for kp, leaf in leaves_kp:
        dt = jnp.dtype(getattr(leaf, "dtype", jnp.float32))
        shape = tuple(getattr(leaf, "shape", ()))
        total += int(jnp.size(leaf)) * dt.itemsize
        rows.append(f"{jax.tree_util.keystr(kp)}:{shape}:{dt.name}")
    digest = hashlib.sha1("\n".join(rows).encode()).hexdigest()[:8]
    return f"{len(rows)}l-{total}B-{digest}"


@dataclasses.dataclass(frozen=True)
class SchedulePlan:
    """One tuned collective schedule: the reducer knobs plus the
    evidence that chose them. ``buckets`` is the per-bucket
    ``(algorithm, payload_bytes)`` assignment (informational — the
    reducer re-plans from ``bucket_bytes``/``bucket_order`` at run
    time, which keeps the plan valid across minor model edits)."""

    fingerprint: str
    model_key: str
    strategy: str
    bucket_bytes: int
    bucket_order: str = "emission"
    double_buffering: bool = False
    #: what the quantized wire carries ('f32' = uncompressed; older DB
    #: records omit the key and from_dict's unknown-key filter keeps
    #: them loading with this default)
    wire_format: str = "f32"
    overlap_fraction: float = 0.0
    est_exposed_us: float = 0.0
    #: 'canned' (emulated schedule), 'aot' (real compiled HLO), or
    #: 'measured' (on-TPU sweep contributed to the cost side)
    source: str = "canned"
    buckets: Tuple[Tuple[str, int], ...] = ()
    #: for strategy 'synth': the winning Program as its to_dict() form
    #: ({"name", "tier_sizes", "steps"}) so the plan round-trips through
    #: JSON and ``create_multi_node_optimizer`` can rebuild the exact
    #: reducer; None for the fixed strategies (and in older DB records)
    program: Optional[dict] = None

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["buckets"] = [list(b) for b in self.buckets]
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "SchedulePlan":
        d = dict(d)
        d["buckets"] = tuple(
            (str(a), int(n)) for a, n in d.get("buckets", ()))
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


def _fp(topology: Union[Topology, str]) -> str:
    return (topology if isinstance(topology, str)
            else topology.fingerprint())


class ProfileDB:
    """JSON-file profile store with atomic writes.

    ``path=None`` resolves ``$CHAINERMN_TPU_PROFILE_DB`` then the
    default ``~/.cache/chainermn_tpu/schedtune.json``. A missing or
    unreadable file is an empty DB, never an error — tuning must work
    on a fresh machine.
    """

    def __init__(self, path: Optional[str] = None):
        self.path = os.path.expanduser(path) if path else default_db_path()
        self._data: Dict[str, Any] = {
            "version": 1, "plans": {}, "measured": {}}
        try:
            with open(self.path) as f:
                loaded = json.load(f)
            if isinstance(loaded, dict) and loaded.get("version") == 1:
                self._data.update(loaded)
        except (OSError, ValueError):
            pass

    # -- plans ----------------------------------------------------------
    def put_plan(self, plan: SchedulePlan) -> None:
        self._data["plans"].setdefault(
            plan.fingerprint, {})[plan.model_key] = plan.to_dict()

    def plan_for(self, topology: Union[Topology, str],
                 model_key: Optional[str] = None
                 ) -> Optional[SchedulePlan]:
        """The stored plan for this topology (and model shape).

        ``model_key=None`` accepts a sole stored plan or one stored
        under the ``'default'`` key; ambiguity returns ``None`` rather
        than guessing."""
        entries = self._data["plans"].get(_fp(topology), {})
        if model_key is not None:
            d = entries.get(model_key)
        elif len(entries) == 1:
            d = next(iter(entries.values()))
        else:
            d = entries.get("default")
        return SchedulePlan.from_dict(d) if d else None

    # -- measured sweeps ------------------------------------------------
    def put_measured(self, topology: Union[Topology, str],
                     table: Dict[Tuple[str, int], float]) -> None:
        dst = self._data["measured"].setdefault(_fp(topology), {})
        for (strategy, nbytes), us in table.items():
            dst[f"{strategy}:{int(nbytes)}"] = float(us)

    def measured_for(self, topology: Union[Topology, str]
                     ) -> Dict[Tuple[str, int], float]:
        out: Dict[Tuple[str, int], float] = {}
        for key, us in self._data["measured"].get(_fp(topology),
                                                  {}).items():
            strategy, _, nbytes = key.rpartition(":")
            out[(strategy, int(nbytes))] = float(us)
        return out

    # -- persistence ----------------------------------------------------
    def save(self) -> str:
        """Atomic write (tmp + rename, same publish discipline as the
        checkpointer); returns the path."""
        d = os.path.dirname(self.path) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".schedtune-")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(self._data, f, indent=1, sort_keys=True)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return self.path

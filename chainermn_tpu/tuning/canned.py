"""Canned scheduled-HLO generator: a deterministic emulation of XLA's
latency-hiding schedule for a bucketed data-parallel gradient reduction.

The tuner scores candidates with the REAL DL201/DL203 passes
(:mod:`chainermn_tpu.analysis.hlo_passes`) over scheduled HLO text.
When the TPU compiler plugin is present, that text comes from AOT
compilation of the actual train step (``tools/schedtune.py --aot``).
Off-TPU-plugin machines get this emulator instead: structurally honest
scheduled HLO whose op sequence encodes the schedule consequences of
the knobs —

* **bucket count** ``k = ceil(total_bytes / bucket_bytes)``: the first
  all-reduce can only issue once its bucket's gradients exist, i.e.
  after ``~B/k`` of the ``B`` backward ops (fewer, larger buckets →
  the first collective issues later → less backward left to hide in);
  a single bucket issues after the LAST backward — fully serialized,
  the exact DL201 failure mode.
* **bucket order** ``'size'``: size-sorted emission fills the first
  bucket with the largest (earliest-completing, in the tail-heavy
  transformer/ResNet backward) leaves, issuing the first collective
  one backward op earlier than pytree-emission order.
* **double_buffering**: step t reduces step t-1's grads, so every
  all-reduce issues BEFORE the backward — overlap fraction 1.0 (with
  one-step-stale numerics; the tuner only proposes it when asked).

The emission positions are a model, not a compilation — but the
*scoring path* through ``check_dp_overlap``/``dp_overlap_fraction`` is
byte-for-byte the one real HLO takes, so tuner logic validated here
transfers to ``--aot`` unchanged. Everything is deterministic: same
knobs → same text → same score (no wall clock, no RNG).
"""

from __future__ import annotations

import math
import textwrap

#: backward ops in the emulated module — enough resolution that one
#: bucket of slack moves the overlap fraction by ~1.6%
DEFAULT_N_BACKWARD = 64


def canned_schedule_hlo(n_buckets: int, bucket_order: str = "emission",
                        double_buffering: bool = False,
                        n_backward: int = DEFAULT_N_BACKWARD,
                        staged: bool = False) -> str:
    """Scheduled-HLO text for ``n_buckets`` gradient all-reduces
    interleaved with ``n_backward`` backward fusions (see module doc
    for the placement model).

    ``staged`` models a reduce-scatter-first program (synthesized
    schedules with ``has_scatter``): the first wire step moves only a
    ``1/k``-rank shard instead of the whole bucket, so the scheduler
    can issue it one backward fusion earlier than a monolithic
    all-reduce — the latency floor drops from 3 to 2 emission-order
    ops (2 to 1 size-order)."""
    if n_buckets < 1:
        raise ValueError(f"n_buckets must be >= 1, got {n_buckets}")
    if bucket_order not in ("emission", "size"):
        raise ValueError(f"unknown bucket_order {bucket_order!r}")
    b, k = n_backward, min(n_buckets, n_backward)
    if double_buffering:
        ar_after = [0] * k  # prev-step grads: all issue before backward
    else:
        floor = 2 if staged else 3
        first = max(math.ceil(b / k), floor)
        if bucket_order == "size":
            first = max(first - 1, floor - 1)
        span = max(b - first, 0)
        ar_after = [min(first + (j * span) // k, b) for j in range(k)]

    lines = ["  %p0 = f32[1024]{0} parameter(0)"]
    emitted = 0

    def emit_ars(up_to):
        nonlocal emitted
        while emitted < k and ar_after[emitted] <= up_to:
            j = emitted
            src = f"%bwd{up_to - 1}" if up_to else "%p0"
            lines.append(
                f"  %ar{j} = f32[1024]{{0}} all-reduce-start({src}), "
                "replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%sum, "
                f"metadata={{op_name=\"jit(step)/psum(bucket{j})\"}}")
            emitted += 1

    emit_ars(0)
    for i in range(b):
        src = f"%bwd{i - 1}" if i else "%p0"
        lines.append(
            f"  %bwd{i} = f32[1024]{{0}} fusion({src}), kind=kLoop, "
            "metadata={op_name=\"jit(step)/transpose(jvp(loss))/"
            f"dot_general.{i}\"}}")
        emit_ars(i + 1)
    for j in range(k):
        lines.append(f"  %ard{j} = f32[1024]{{0}} all-reduce-done(%ar{j})")
    lines.append("  ROOT %out = f32[1024]{0} add(%bwd"
                 f"{b - 1}, %ard{k - 1})")
    body = "\n".join(lines)
    return textwrap.dedent("""\
        HloModule canned_step, is_scheduled=true

        %sum (a: f32[], b: f32[]) -> f32[] {
          %a = f32[] parameter(0)
          %b = f32[] parameter(1)
          ROOT %add = f32[] add(%a, %b)
        }

        ENTRY %main (p0: f32[1024]) -> f32[1024] {
        """) + body + "\n}\n"


def canned_compile_fn(total_bytes: int,
                      n_backward: int = DEFAULT_N_BACKWARD):
    """A ``compile_fn`` for :func:`chainermn_tpu.tuning.tuner.tune`
    backed by the emulator: maps a candidate's knobs to scheduled-HLO
    text (the AOT equivalent compiles the real step instead)."""

    def compile_fn(candidate) -> str:
        k = max(1, math.ceil(total_bytes / candidate.bucket_bytes))
        program = getattr(candidate, "program", None)
        staged = bool(program is not None
                      and getattr(program, "has_scatter", False))
        return canned_schedule_hlo(k, candidate.bucket_order,
                                   candidate.double_buffering,
                                   n_backward, staged=staged)

    return compile_fn

"""checkpointing/ — the asynchronous snapshot plane and manifest-driven
elastic resharding.

Two halves of one story — checkpoint cadence cheap enough for
preemption-heavy operation, and snapshots that survive a MESH change,
not just a restart:

* :class:`~chainermn_tpu.checkpointing.async_plane.AsyncSnapshotPlane`
  — a double-buffered snapshot pipeline over the existing
  :class:`~chainermn_tpu.extensions.checkpoint.MultiNodeCheckpointer`:
  the step thread only dispatches a device-side copy and kicks off the
  device→host offload; a background writer serializes, fsyncs,
  SHA-256s, atomically publishes, and pushes to the ring replica. The
  same overlap discipline schedtune applies to collectives
  (docs/tuning.md), applied to checkpoint I/O.
* :mod:`~chainermn_tpu.checkpointing.reshard` — manifest-driven
  resharding: load snapshots written on one mesh onto a DIFFERENT mesh
  shape (changed DP world, changed tile layout, multi-axis TP×DP
  meshes), including the world-stacked flat-bucket EF residual frames
  from ``optimizers/zero.py``. ``resilience/elastic.py`` routes its
  multi-axis plans through here instead of raising
  ``ElasticTopologyError``.

See docs/fault_tolerance.md#checkpoint-cadence for the cookbook and
``tools/ckpt.py`` for the offline inspect/verify/dry-run CLI.
"""

from chainermn_tpu.checkpointing.async_plane import AsyncSnapshotPlane
from chainermn_tpu.checkpointing.reshard import (default_leaf_resharder,
                                                 ef_frame_regroup,
                                                 leaf_coverage,
                                                 manifest_info, mesh_axes,
                                                 reshard_state, saved_axes,
                                                 scan_snapshot_dir)

__all__ = [
    "AsyncSnapshotPlane",
    "default_leaf_resharder",
    "ef_frame_regroup",
    "leaf_coverage",
    "manifest_info",
    "mesh_axes",
    "reshard_state",
    "saved_axes",
    "scan_snapshot_dir",
]

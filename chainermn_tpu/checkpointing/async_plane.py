"""Double-buffered async snapshot pipeline.

The synchronous ``MultiNodeCheckpointer.save`` spends the whole
device-get + serialize + fsync + SHA-256 + rename on the STEP thread —
at a cadence dense enough to survive preemption, that stall dominates
the step. This plane splits the save at the step boundary exactly where
the reference's double buffering split communication:

1. **Step thread** (inside :meth:`AsyncSnapshotPlane.save`): dispatch a
   device-side copy of every leaf (``jnp.copy`` preserves the sharding
   and decouples the snapshot from the caller's next DONATING train
   step — the original buffers may be deleted the moment save returns),
   kick off the device→host offload on the copies
   (``copy_to_host_async``), and enqueue. That is the entire per-step
   stall, measured and exported as ``ckpt/stall_ms``.
2. **Writer thread**: block on the offload (``np.asarray``), then run
   the checkpointer's own atomic publish — tmp + fsync + SHA-256 +
   rename + manifest (``MultiNodeCheckpointer._publish``) — and push
   the fresh file to the ring replica
   (:class:`~chainermn_tpu.resilience.replica.PeerReplicator`), all off
   the critical path.

Backpressure is explicit: the pending queue is bounded
(``max_pending``, default 1 = classic double buffering) and
``backpressure='block'`` stalls save() when the writer falls behind
(bounded host memory, every snapshot published) while ``'skip'`` drops
the NEW snapshot and counts it (bounded stall, sparser cadence under a
slow disk). ``drain(deadline_s=)`` is the barrier the emergency paths
use: :meth:`AsyncSnapshotPlane.emergency_save` drains within a reserved
slice of the SAME preemption grace window
(:func:`~chainermn_tpu.resilience.preemption.reserve_grace` — the drain
budget is subtracted from the emergency-save deadline, never doubled),
and the Trainer's finally-block calls :meth:`close`.

Crash windows: a SIGKILL between offload and publish loses ONLY the
in-flight snapshot — nothing partial is ever visible (the publish is
the checkpointer's tmp+rename), so the consensus election falls back to
the newest fully-verified iteration. The chaos harness widens exactly
that window (``stall_writer``) to prove it.
"""

from __future__ import annotations

import os
import pickle
import queue
import threading
import time
import warnings
from typing import Any, Optional

import numpy as np

import jax
import jax.numpy as jnp

from chainermn_tpu.extensions.checkpoint import (MultiNodeCheckpointer,
                                                 _flatten_state,
                                                 _is_device_sharded,
                                                 _unique_shards)
from chainermn_tpu.resilience import chaos as _chaos
from chainermn_tpu.resilience.preemption import reserve_grace

__all__ = ["AsyncSnapshotPlane"]

#: writer-thread poll period: how quickly an owed replica round (from a
#: skipped save) is noticed when the queue is idle
_POLL_S = 0.05


class AsyncSnapshotPlane:
    """Async snapshot pipeline over a synchronous checkpointer.

    ``plane = AsyncSnapshotPlane(ck)`` then use the plane wherever the
    checkpointer was used on the hot path: as a trainer extension
    (``trainer.extend(plane, trigger=...)``), or via
    :meth:`save` in a manual step loop. Read-side operations
    (:meth:`maybe_load`, :meth:`resume`,
    :meth:`latest_common_iteration`) drain the pipeline first so they
    only ever see published files.

    ``backpressure``: ``'block'`` (default) stalls save() while the
    queue is full — every snapshot is published, the stall is the
    backpressure signal; ``'skip'`` never stalls — a full queue drops
    the NEW snapshot (counted in :attr:`skipped`) and the run keeps its
    step time at the cost of sparser checkpoint cadence.

    ``replicator`` (a
    :class:`~chainermn_tpu.resilience.replica.PeerReplicator` built on
    the same checkpointer) moves the ring push to the writer thread
    too. The exchange is collective, so the plane owes exactly one
    round per :meth:`save` CALL — including skipped ones — keeping
    send/recv counts matched across ranks as long as every rank
    triggers saves at the same cadence (the replicator's existing
    contract). Do NOT also extend the replicator on the trainer.
    """

    def __init__(self, checkpointer: MultiNodeCheckpointer,
                 max_pending: int = 1, backpressure: str = "block",
                 replicator: Optional[Any] = None):
        if backpressure not in ("block", "skip"):
            raise ValueError(
                f"backpressure={backpressure!r}: 'block' (stall save "
                "until the writer catches up) or 'skip' (drop the new "
                "snapshot, count it)")
        if getattr(checkpointer, "async_write", False):
            raise ValueError(
                "AsyncSnapshotPlane owns the write pipeline — build the "
                "checkpointer with async_write=False (double-queueing "
                "through both would reorder publishes)")
        if getattr(checkpointer, "backend", "npz") != "npz":
            raise ValueError(
                "AsyncSnapshotPlane is npz-backend territory (orbax is "
                "natively async — use it directly)")
        if max_pending < 1:
            raise ValueError(f"max_pending must be >= 1: {max_pending}")
        self.ck = checkpointer
        self.backpressure = backpressure
        self.max_pending = max_pending
        self.replicator = replicator
        self._queue: queue.Queue = queue.Queue(maxsize=max_pending)
        self._writer: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._owed_replica = 0
        self._lock = threading.Lock()
        # -- stats (CheckpointReport folds these into observations) ------
        self.published = 0
        self.skipped = 0
        self.stall_ms_last = 0.0
        self.stall_ms_total = 0.0
        self.bytes_last = 0
        self.bytes_total = 0
        self.cadence_last = 0  # iterations since the previous save()
        self._last_iter: Optional[int] = None

    # -- step-thread half -------------------------------------------------

    def save(self, state: Any, iteration: int,
             host_state: Any = None) -> bool:
        """Enqueue a snapshot of ``state`` for ``iteration``; returns
        False when backpressure='skip' dropped it. The only work on this
        thread is the device-side copy dispatch + offload kick — the
        measured stall lands in :attr:`stall_ms_last`."""
        self._raise_pending()
        self._ensure_writer()
        t0 = time.monotonic()
        fn = os.path.join(
            self.ck.path,
            f"snapshot_iter_{iteration}.{self.ck.comm.inter_rank}")
        # chaos: a congested device→host link stretches THIS stall
        _chaos.on_offload(fn, "offload")
        # device-side copy: the caller's next donating step may delete
        # the original buffers the moment we return — the copy keeps its
        # sharding and stays readable after the donation
        snap = jax.tree_util.tree_map(
            lambda l: jnp.copy(l) if isinstance(l, jax.Array) else l,
            state)
        for l in jax.tree_util.tree_leaves(snap):
            if _is_device_sharded(l):
                for s in _unique_shards(l):
                    if hasattr(s.data, "copy_to_host_async"):
                        s.data.copy_to_host_async()
            elif hasattr(l, "copy_to_host_async"):
                l.copy_to_host_async()
        item = (snap, fn, int(iteration), host_state)
        accepted = True
        if self.backpressure == "skip":
            try:
                self._queue.put_nowait(item)
            except queue.Full:
                accepted = False
                self.skipped += 1
        else:
            self._queue.put(item)
        with self._lock:
            if self.replicator is not None:
                # one ring round owed per save CALL (even a skipped one):
                # peers at the same cadence are already counting on it
                self._owed_replica += 1
        if self._last_iter is not None:
            self.cadence_last = int(iteration) - self._last_iter
        self._last_iter = int(iteration)
        self.stall_ms_last = (time.monotonic() - t0) * 1000.0
        self.stall_ms_total += self.stall_ms_last
        return accepted

    # -- writer-thread half -----------------------------------------------

    def _ensure_writer(self):
        if self._writer is not None and self._writer.is_alive():
            return
        self._stop.clear()
        self._writer = threading.Thread(
            target=self._writer_loop,
            name=f"ckpt-plane-{self.ck.name}", daemon=True)
        self._writer.start()
        self._register_atexit()

    def _register_atexit(self):
        if getattr(self, "_atexit_done", False):
            return
        self._atexit_done = True
        import atexit

        def _close_at_exit():
            try:
                self.close()
            except Exception as e:
                warnings.warn(f"async snapshot plane at exit: {e}")

        atexit.register(_close_at_exit)

    def _writer_loop(self):
        while True:
            try:
                item = self._queue.get(timeout=_POLL_S)
            except queue.Empty:
                # idle: settle replica rounds owed by SKIPPED saves (no
                # item ever carried them) so peers' recvs don't starve
                self._run_owed_replica()
                if self._stop.is_set():
                    return
                continue
            try:
                if item is None:
                    return
                snap, fn, iteration, host_state = item
                # chaos: stretch the offload→publish window (the SIGKILL
                # drill lands its kill in here)
                _chaos.on_offload(fn, "writer")
                arrays, _ = _flatten_state(snap)  # blocks on the D2H
                del snap
                arrays["__world__"] = np.int64(self.ck.comm.inter_size)
                if host_state is not None:
                    arrays["__host_state__"] = np.frombuffer(
                        pickle.dumps(host_state,
                                     pickle.HIGHEST_PROTOCOL),
                        np.uint8).copy()
                self.ck._publish(
                    arrays, fn,
                    meta=self.ck._coverage_meta(arrays, iteration))
                self.bytes_last = int(sum(
                    getattr(a, "nbytes", 0) for a in arrays.values()))
                self.bytes_total += self.bytes_last
                self.published += 1
            except BaseException as e:  # surfaced on next save/flush
                self._error = e
            finally:
                self._run_owed_replica()
                self._queue.task_done()

    def _run_owed_replica(self):
        while True:
            with self._lock:
                if self._owed_replica <= 0:
                    return
                self._owed_replica -= 1
            try:
                # drain=False: we ARE the writer thread — the
                # checkpointer queue is not ours and a join here on the
                # item being processed would self-deadlock
                self.replicator.replicate(drain=False)
            except Exception as e:
                # best-effort by design, same as the replicator's store
                warnings.warn(f"async replica push failed: {e}")

    def _raise_pending(self):
        if self._error is not None:
            e, self._error = self._error, None
            raise RuntimeError(
                f"async snapshot publish failed: {e!r}") from e

    # -- barriers ----------------------------------------------------------

    @property
    def pending(self) -> int:
        """Snapshots accepted but not yet published."""
        return int(self._queue.unfinished_tasks)

    def drain(self, deadline_s: Optional[float] = None) -> bool:
        """Wait until every accepted snapshot is published (or failed).
        ``deadline_s`` is an ABSOLUTE monotonic deadline (same convention
        as ``emergency_save``); returns False when it passed with work
        still pending. Never raises — the emergency path must reach its
        own synchronous write regardless."""
        if deadline_s is None:
            self._queue.join()
            return True
        with self._queue.all_tasks_done:
            while self._queue.unfinished_tasks:
                remaining = deadline_s - time.monotonic()
                if remaining <= 0:
                    return False
                self._queue.all_tasks_done.wait(
                    timeout=min(remaining, _POLL_S))
        return True

    def flush(self):
        """Drain fully and raise any deferred publish error."""
        self.drain()
        self._raise_pending()

    def close(self):
        """Drain, settle owed replica rounds, and stop the writer — the
        Trainer's finally-block calls this on every extension."""
        if self._writer is not None and self._writer.is_alive():
            self._queue.join()
            self._stop.set()  # writer exits after settling owed rounds
            self._writer.join()
        self._writer = None
        self._raise_pending()

    # -- trainer integration ----------------------------------------------

    def __call__(self, trainer):
        """Trainer-extension protocol — drop-in for extending the
        checkpointer itself, with the save moved off the step path."""
        host_fn = getattr(trainer.updater, "host_state_dict", None)
        self.save(trainer.updater.state, trainer.updater.iteration,
                  host_state=host_fn() if callable(host_fn) else None)

    def emergency_save(self, trainer,
                       deadline_s: Optional[float] = None):
        """Preemption/crash path: drain the in-flight snapshot within a
        RESERVED slice of the grace window, then run the checkpointer's
        synchronous last-chance save against the original deadline. One
        absolute window covers both phases — the drain budget is
        subtracted from the emergency-save deadline, never doubled."""
        self.drain(reserve_grace(deadline_s))
        return self.ck.emergency_save(trainer, deadline_s=deadline_s)

    # -- read-side passthrough (drain-first) ------------------------------

    def latest_common_iteration(self) -> Optional[int]:
        self.drain()
        return self.ck.latest_common_iteration()

    def maybe_load(self, state: Any, iteration: Optional[int] = None,
                   **kwargs):
        self.drain()
        return self.ck.maybe_load(state, iteration=iteration, **kwargs)

    def resume(self, updater) -> Optional[int]:
        self.drain()
        return self.ck.resume(updater)

    def load_host_state(self, iteration: int) -> Any:
        self.drain()
        return self.ck.load_host_state(iteration)

    def protect(self, iteration: int) -> None:
        self.ck.protect(iteration)

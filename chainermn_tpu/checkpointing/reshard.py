"""Manifest-driven elastic resharding across mesh shapes.

The checkpointer's splice path already restores by GLOBAL INDEX: every
saved shard carries its global slice bounds, so a leaf whose global
shape is unchanged re-scatters onto ANY mesh — fewer devices, more
devices, a different tile layout, a multi-axis TP×DP mesh — by pure
interval arithmetic. What ``resilience/elastic.py`` historically
refused (``ElasticTopologyError``) was everything beyond one DP axis,
because ONE class of leaf really is world-DEPENDENT: the flat-bucket
error-feedback residual stacks from ``optimizers/zero.py``, saved as a
globally-stacked ``(n_ranks, padded)`` frame whose LEADING dimension is
the saving world size.

This module closes that gap with the coverage manifests every publish
now carries (``extensions/checkpoint.py:_coverage_meta`` — saving
world, mesh axes, per-leaf geometry):

* :func:`default_leaf_resharder` — the ``leaf_resharder`` hook
  ``maybe_load`` calls on a global-shape mismatch. It regroups
  world-stacked EF frames between world sizes and refuses anything
  else (a genuine model change still errors loudly).
* :func:`ef_frame_regroup` — the pure regrouping kernel, exposed for
  tests and offline tooling.
* :func:`reshard_state` / :func:`manifest_info` / :func:`saved_axes` /
  :func:`mesh_axes` — the conveniences ``elastic.py`` and
  ``tools/ckpt.py`` plan with.

EF regroup semantics (why it is correct): the reducers average with
``op='mean'``, so the aggregate correction entering each step is the
MEAN over ranks of the per-rank residuals. Shrinking ``n → n/k``
replaces each group of ``k`` rows by their mean (``sum / k`` — exact
for the power-of-two worlds real meshes use), growing ``n → k·n``
duplicates rows (bitwise): both directions preserve
``mean_r e_r`` exactly, so the resumed error feedback injects the same
aggregate correction the old world would have.
"""

from __future__ import annotations

import glob
import os
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from chainermn_tpu.extensions.checkpoint import read_manifest

__all__ = ["default_leaf_resharder", "ef_frame_regroup", "manifest_info",
           "mesh_axes", "reshard_state", "saved_axes"]

_SNAP_RE = re.compile(r"snapshot_iter_(\d+)\.(\d+)$")


def mesh_axes(comm) -> Optional[Dict[str, int]]:
    """The communicator mesh's ``{axis_name: size}`` map (None when the
    communicator has no mesh — e.g. the naive host communicator)."""
    mesh = getattr(comm, "mesh", None)
    if mesh is None:
        return None
    try:
        return {str(a): int(s) for a, s in zip(
            mesh.axis_names, np.shape(mesh.devices))}
    except Exception:  # noqa: BLE001 — metadata is best-effort
        return None


def manifest_info(ck, iteration: int) -> Optional[dict]:
    """The richest coverage manifest any rank published for
    ``iteration`` — primary files first, then ring replicas. Host-side
    JSON only; no array is loaded."""
    best = None
    for d in (ck.path, ck.replica_path):
        if not os.path.isdir(d):
            continue
        for fn in sorted(glob.glob(
                os.path.join(d, f"snapshot_iter_{iteration}.*"))):
            if not _SNAP_RE.search(os.path.basename(fn)):
                continue
            mf = read_manifest(fn)
            if mf is None:
                continue
            if "axes" in mf or "leaves" in mf:
                return mf
            best = best or mf
    return best


def saved_axes(ck, iteration: int) -> Optional[Dict[str, int]]:
    """The SAVING run's mesh axes for ``iteration``, from its coverage
    manifest (None for pre-coverage snapshots)."""
    info = manifest_info(ck, iteration)
    if info is None:
        return None
    axes = info.get("axes")
    return {str(k): int(v) for k, v in axes.items()} if axes else None


def ef_frame_regroup(full: np.ndarray, n_new: int) -> np.ndarray:
    """Regroup a world-stacked ``(n_old, padded)`` EF residual frame
    onto ``n_new`` rows, preserving the cross-rank mean exactly (see
    the module docstring). Requires one row count to divide the other;
    raises ValueError otherwise."""
    full = np.asarray(full)
    if full.ndim != 2:
        raise ValueError(
            f"EF frame must be 2-D (n_ranks, padded): got {full.shape}")
    n_old = full.shape[0]
    if n_old == n_new:
        return full
    if n_old % n_new == 0:
        k = n_old // n_new
        # group mean: pairwise float sums then one exact /k for the
        # power-of-two worlds real meshes use
        out = full.reshape(n_new, k, full.shape[1]).sum(axis=1) / k
        return out.astype(full.dtype, copy=False)
    if n_new % n_old == 0:
        return np.repeat(full, n_new // n_old, axis=0)
    raise ValueError(
        f"cannot regroup an EF frame from {n_old} to {n_new} ranks — "
        "one world size must divide the other (power-of-two meshes "
        "always satisfy this)")


def default_leaf_resharder(i: int, ref, gshape: Tuple[int, ...],
                           fetch_full: Callable[[], np.ndarray]):
    """The ``leaf_resharder`` hook ``maybe_load`` consults when a leaf's
    saved GLOBAL shape differs from the template's.

    Only the world-stacked flat-frame shape is accepted: a 2-D saved
    frame onto a 2-D template with the SAME trailing (padded flat)
    dimension and a divisible leading (rank) dimension — exactly the EF
    residual stacks ``optimizers/zero.py`` builds, whose trailing dim
    is device-count-independent by the quantum padding. Everything else
    returns None, falling through to the checkpointer's different-model
    error. ``fetch_full`` splices the full saved global frame on host —
    EF frames are small (one padded flat vector per rank), so this does
    not breach the no-global-leaf contract for model-sized leaves."""
    tshape = tuple(getattr(ref, "shape", ()) or ())
    if len(gshape) != 2 or len(tshape) != 2 or gshape[1] != tshape[1]:
        return None
    n_old, n_new = int(gshape[0]), int(tshape[0])
    if n_old == n_new:
        return None  # same frame — the splice path handles tile changes
    if n_old % n_new and n_new % n_old:
        return None
    return ef_frame_regroup(np.asarray(fetch_full()), n_new)


def reshard_state(ck, state: Any, iteration: Optional[int] = None,
                  allow_incomplete: bool = False):
    """Restore ``state`` from ``ck``'s snapshots onto the CURRENT mesh,
    resharding as needed: same-shape leaves re-scatter through the
    splice path, world-stacked EF frames regroup through
    :func:`default_leaf_resharder`. Returns ``(state, iteration)`` like
    ``maybe_load``. This is the load half of an elastic resume;
    ``resilience/elastic.py:elastic_resume`` adds the host-side
    rebalancing around it."""
    return ck.maybe_load(state, iteration=iteration,
                         allow_incomplete=allow_incomplete,
                         leaf_resharder=default_leaf_resharder)


# -- offline (no-jax) helpers for tools/ckpt.py ---------------------------

def scan_snapshot_dir(path: str) -> Dict[int, List[str]]:
    """``{iteration: [files]}`` for every snapshot file under ``path``
    and its ``replicas/`` subtree (host-side, no array loads)."""
    out: Dict[int, List[str]] = {}
    for d in (path, os.path.join(path, "replicas")):
        if not os.path.isdir(d):
            continue
        for f in sorted(os.listdir(d)):
            m = _SNAP_RE.match(f)
            fn = os.path.join(d, f)
            if m and not os.path.isdir(fn):
                out.setdefault(int(m.group(1)), []).append(fn)
    return out


def leaf_coverage(files: List[str]) -> Dict[int, dict]:
    """Per-leaf shard-coverage report across a snapshot file set:
    ``{leaf: {gshape, nshards, intervals, covered, volume}}`` where
    ``covered`` is True when the saved shard intervals tile the full
    global volume (disjoint-partition accounting, the same invariant
    ``_SpliceTargets`` enforces at load). Reads only the SMALL manifest
    keys (gshape/nshards/idx) — shard data stays untouched."""
    leaves: Dict[int, dict] = {}
    for fn in files:
        with np.load(fn, allow_pickle=False) as z:
            keys = set(z.files)
            for k in keys:
                m = re.match(r"leaf_(\d+)_nshards$", k)
                if m:
                    i = int(m.group(1))
                    gshape = tuple(
                        int(d) for d in z[f"leaf_{i}_gshape"])
                    rec = leaves.setdefault(i, {
                        "gshape": gshape, "nshards": 0,
                        "intervals": set()})
                    rec["nshards"] += int(z[k])
                    for s in range(int(z[k])):
                        idx = np.asarray(z[f"leaf_{i}_idx{s}"])
                        bounds = tuple(
                            (int(a), int(b) if b != -1 else d)
                            for (a, b), d in zip(idx, gshape))
                        rec["intervals"].add(bounds)
                    continue
                m = re.match(r"leaf_(\d+)$", k)
                if m:
                    i = int(m.group(1))
                    gshape = tuple(int(d) for d in z[k].shape)
                    rec = leaves.setdefault(i, {
                        "gshape": gshape, "nshards": 0,
                        "intervals": set()})
                    rec["intervals"].add(tuple(
                        (0, d) for d in gshape))
    for rec in leaves.values():
        total = int(np.prod(rec["gshape"], dtype=np.int64)) \
            if rec["gshape"] else 1
        vol = sum(
            int(np.prod([b - a for a, b in iv], dtype=np.int64))
            for iv in rec["intervals"])
        rec["volume"] = vol
        # deduplicated intervals: disjoint by construction (they are the
        # saving mesh's shard partition), so covering volume == covered
        rec["covered"] = vol == total
        rec["intervals"] = sorted(rec["intervals"])
    return leaves

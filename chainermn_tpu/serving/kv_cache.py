"""Paged, ring-buffered KV cache + the compiled serving step pair.

The serving cache is the training model's own flax ``cache`` collection,
re-shaped for continuous batching: one PAGE per transformer block, each
page ``k``/``v`` of shape ``[n_slots, capacity, n_kv_heads, d_head]``
plus a per-slot ``idx`` cursor vector ``[n_slots]`` (the decode branch in
``models/transformer.py`` accepts either the scalar cursor ``generate()``
uses or this vector — every row then advances independently).

Ring semantics: the write position for token ``p`` of slot ``s`` is
``p % capacity``; once a slot's stream outgrows its page the oldest
tokens are overwritten and attention degrades to a ``capacity``-token
sliding window (the mask inverts the ring — see the ``kpos`` comment in
the decode branch). Prefer ``pos_emb='rope'`` for streams expected to
wrap (learned positions clip at ``max_len``).

Two compiled entry points, following the SNIPPETS Partitioner shape
(jit with explicit in/out shardings, donated cache buffers):

* ``prefill`` — a fixed-shape cohort ``[S, L_bucket]`` runs the one
  legal multi-token decode apply on a FRESH slab cache, then scatters
  the slab into the page at the cohort's slot ids (a sentinel id of
  ``n_slots`` drops padding rows — ``mode='drop'``). Returns each
  prompt's last-position logits (the first sampled token — TTFT).
* ``decode_step`` — one token for ALL ``n_slots`` slots at once, a
  single ``[n_slots, 1]`` apply against the paged cache. Constant
  shapes by construction: traced once, reused forever (the DL108
  trap this module exists to avoid).

On top of the pair, the multi-token dispatches the engine actually
serves with (ISSUE 10):

* ``decode_k`` — ``k`` decode steps under one ``jax.lax.scan`` with
  on-device sampling (``serving/sampling.py``) feeding each step's
  token to the next, plus per-slot EOS/budget stop masks. One host
  dispatch commits up to ``k`` tokens and transfers ``O(n_slots)``
  int32 ids (4 bytes/token) instead of ``O(n_slots × vocab)`` f32
  logits. Mid-prefill slots ride along PARKED: their cursors are
  pinned to the host-supplied fill level around the scan so decode
  garbage never walks them toward a ring wrap.
* ``prefill_chunk`` — a fixed ``[S, C]`` window of prompt tokens
  written incrementally at each slot's ``pos_offset`` cursor
  (``chunked_prefill=True`` model twin: the slab attends prefix +
  itself under an absolute-position mask). ONE compiled program for
  any prompt length — long prompts stream in without head-of-line
  blocking decode, and chunked == monolithic bitwise (same tokens,
  same cache bytes — tests/serving_tests/test_sampling.py).

Numerics contract (tested bitwise): with ``capacity`` ≥ the full stream
length and ``attention='reference'``, cached decode logits equal the
corresponding full-forward column BITWISE — the decode branch uses
squeezed-q contractions and the same-program prefill kernel to make the
cached path a re-association-free restatement of the training forward.

int8-block page mode (``kv_dtype='int8-block'``, ISSUE 20): pages live
at rest as blockwise int8 codes + f32 scales — the PR 8/11 EQuARX wire
codec moved into the cache itself, with block ``gcd(256, n_kv_heads ·
d_head)`` so every cache column is a whole number of blocks and an
exported slot's flattened codes/scales form a valid ``block_dequantize``
payload (fleet/handoff.py ships them verbatim, without requantizing).
Every compiled program dequantizes ONCE at dispatch entry
(:func:`unpack_cache`) and re-quantizes only the columns the dispatch
actually wrote (:func:`repack_cache`): requantization is not provably
idempotent (the re-derived scale can differ by 1 ulp), so untouched
columns must keep their exact resident bytes. ~3.5–4× more slots per
chip at equal cache memory; accuracy is held to a calibrated
logit-error gate (bench.py ``specdec_gate_ok``) rather than bitwise
parity, and peak transient memory during a dispatch is the f32
working copy — the win is the RESIDENT footprint between dispatches.
No mesh sharding and no ring wrap in this mode — the engine enforces
``prompt + max_new ≤ capacity`` at submit.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from chainermn_tpu.collectives.quantized import QUANT_BLOCK
from chainermn_tpu.models.transformer import bhld_to_blhd_params
from chainermn_tpu.serving.sampling import sample_tokens

__all__ = ["init_cache", "cache_bytes", "cache_spec", "decode_apply",
           "prefill_apply", "decode_k_apply", "prefill_chunk_apply",
           "ServingStep", "KV_PAGE_DTYPES", "page_block", "unpack_cache",
           "repack_cache", "cache_is_quantized"]

#: page storage modes: f32 (resident = compute dtype, bitwise contract)
#: and int8-block (resident = blockwise int8 codes + f32 scales)
KV_PAGE_DTYPES = ("f32", "int8-block")


def _normalize_kv_dtype(kv_dtype: Optional[str]) -> str:
    mode = kv_dtype or "f32"
    if mode not in KV_PAGE_DTYPES:
        raise ValueError(
            f"kv_dtype {kv_dtype!r} not in {KV_PAGE_DTYPES}")
    return mode


def page_block(model) -> int:
    """int8-block block size for this model: ``gcd(256, n_kv_heads ·
    d_head)``. Dividing the per-token row length keeps every cache
    column a whole number of blocks, which is what makes the masked
    per-column requantize in :func:`repack_cache` exact and an exported
    slot's flattened codes/scales a valid ``block_dequantize`` payload
    at this block size (fleet/handoff.py ships them verbatim)."""
    spec = cache_spec(model)
    return math.gcd(QUANT_BLOCK, spec["n_kv_heads"] * spec["d_head"])


def _check_servable(model):
    if model.moe_experts_per_device > 0:
        raise ValueError("serving does not support MoE models: the "
                         "decode path has no expert dispatch")
    if model.tp_axis is not None or getattr(model, "lm_head_tp", False):
        raise ValueError(
            "serving runs the jit decode path; tp_axis/lm_head_tp models "
            "serve without shard_map TP (clone with tp_axis=None, "
            "lm_head_tp=False and gather the weights — head-axis mesh "
            "sharding of the cache covers the TP layout instead)")


def cache_spec(model) -> Dict[str, int]:
    """The numbers the sizing math and page shapes derive from."""
    return dict(
        n_layers=model.n_layers,
        n_kv_heads=model.n_kv_heads or model.n_heads,
        d_head=model.d_model // model.n_heads,
    )


def cache_bytes(model, n_slots: int, capacity: int,
                dtype: Any = None, kv_dtype: Optional[str] = None) -> int:
    """Preallocated RESIDENT cache footprint: ``n_layers · n_slots ·
    capacity · 2 (K and V) · n_kv_heads · d_head · itemsize`` — the
    budget line in docs/serving.md's sizing table. In ``int8-block``
    mode the per-element cost is ``1 + 4/block`` bytes (codes + the
    amortized f32 scale), which is where the ≥3.5× slots-per-chip gain
    comes from."""
    spec = cache_spec(model)
    r = spec["n_kv_heads"] * spec["d_head"]
    cells = spec["n_layers"] * n_slots * capacity * 2 * r
    if _normalize_kv_dtype(kv_dtype) == "int8-block":
        return cells + cells // page_block(model) * 4
    itemsize = jnp.dtype(dtype or model.dtype).itemsize
    return cells * itemsize


def init_cache(model, n_slots: int, capacity: int, dtype: Any = None,
               kv_dtype: Optional[str] = None):
    """Fresh zeroed pages: ``{"block_i": {"k", "v", "idx"}}`` with
    per-slot cursor vectors. The tree is exactly the flax ``cache``
    collection ``model.clone(decode=True)`` declares — supplied values
    override the declared ``max_len`` shapes, which is how ``capacity``
    decouples from ``model.max_len``.

    ``kv_dtype='int8-block'`` swaps each page's ``k``/``v`` leaves for
    ``k_q``/``v_q`` (int8 codes, same shape) + ``k_s``/``v_s`` (f32
    scales, one per block). Scales init to 1.0 — exactly what
    ``block_quantize`` emits for an all-zero block, so a fresh page is
    the quantization of a fresh f32 page."""
    spec = cache_spec(model)
    dt = dtype or model.dtype
    shape = (n_slots, capacity, spec["n_kv_heads"], spec["d_head"])
    if _normalize_kv_dtype(kv_dtype) == "int8-block":
        blk = page_block(model)
        s_shape = (n_slots, capacity,
                   spec["n_kv_heads"] * spec["d_head"] // blk)
        page = lambda: {
            "k_q": jnp.zeros(shape, jnp.int8),
            "k_s": jnp.ones(s_shape, jnp.float32),
            "v_q": jnp.zeros(shape, jnp.int8),
            "v_s": jnp.ones(s_shape, jnp.float32),
            "idx": jnp.zeros((n_slots,), jnp.int32),
        }
    else:
        page = lambda: {
            "k": jnp.zeros(shape, dt),
            "v": jnp.zeros(shape, dt),
            "idx": jnp.zeros((n_slots,), jnp.int32),
        }
    return {f"block_{i}": page() for i in range(spec["n_layers"])}


def _quant_rows(x, block: int):
    """Blockwise-quantize the trailing ``n_kv_heads × d_head`` row of
    ``x`` — the EXACT op sequence of ``collectives.quantized.
    block_quantize`` (same scale formula, same round/clip/astype order)
    applied per block, so flattened codes/scales are byte-identical to
    the wire codec's. Returns ``(codes int8, x.shape)``-shaped codes and
    ``[..., r/block]`` f32 scales."""
    shape = x.shape
    r = shape[-2] * shape[-1]
    b = x.reshape(shape[:-2] + (r // block, block))
    amax = jnp.max(jnp.abs(b), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(x.dtype)
    q = jnp.clip(jnp.round(b / scale[..., None]), -127, 127)
    return q.astype(jnp.int8).reshape(shape), scale


def _dequant_rows(q, scale):
    """Inverse of :func:`_quant_rows`, mirroring ``block_dequantize``'s
    ops (``codes.astype(f32) * scale.astype(f32)``)."""
    shape = q.shape
    blocks = scale.shape[-1]
    b = q.reshape(shape[:-2] + (blocks, -1)).astype(jnp.float32)
    return (b * scale[..., None].astype(jnp.float32)).reshape(shape)


def cache_is_quantized(cache) -> bool:
    """True when ``cache`` holds int8-block pages."""
    return "k_q" in cache["block_0"]


def unpack_cache(cache):
    """PURE: int8-block pages → the f32 ``{"k", "v", "idx"}`` view every
    apply function computes against; identity for f32 pages. Called
    once at dispatch entry — attention reads dequantized values, the
    resident tree between dispatches stays int8."""
    if not cache_is_quantized(cache):
        return cache
    return {name: {"k": _dequant_rows(page["k_q"], page["k_s"]),
                   "v": _dequant_rows(page["v_q"], page["v_s"]),
                   "idx": page["idx"]}
            for name, page in cache.items()}


def repack_cache(old, new, start, count):
    """PURE quantize-on-commit: fold the f32 view ``new`` (an apply
    function's output) back into the resident pages ``old``, re-
    quantizing ONLY the columns the dispatch wrote; identity (returns
    ``new``) for f32 pages.

    ``start`` int32 ``[n_slots]`` — each slot's first written column
    (absolute cursor; the ring position is ``start % capacity``);
    ``count`` — columns written per slot (scalar or ``[n_slots]``; 0
    marks a slot the dispatch did not touch). The mask is exact: a
    column outside its slot's written window keeps its resident bytes
    verbatim, because ``quantize(dequantize(q, s))`` can move the scale
    by 1 ulp — requantizing untouched data would both drift values and
    break the exported-bytes == ``block_quantize`` identity."""
    if not cache_is_quantized(old):
        return new
    n_slots, capacity = old["block_0"]["k_q"].shape[:2]
    start = jnp.asarray(start, jnp.int32)
    count = jnp.broadcast_to(jnp.asarray(count, jnp.int32), (n_slots,))
    blk = (old["block_0"]["k_q"].size
           // old["block_0"]["k_s"].size)
    cols = jnp.arange(capacity, dtype=jnp.int32)[None]
    written = ((cols - start[:, None]) % capacity) < count[:, None]
    out = {}
    for name, page in old.items():
        leaves = {"idx": new[name]["idx"]}
        for kv in ("k", "v"):
            q, s = _quant_rows(new[name][kv], blk)
            leaves[kv + "_q"] = jnp.where(
                written[..., None, None], q, page[kv + "_q"])
            leaves[kv + "_s"] = jnp.where(
                written[..., None], s, page[kv + "_s"])
        out[name] = leaves
    return out


def decode_apply(model, params, cache, tokens):
    """PURE one-token step for every slot: tokens int32 ``[n_slots]`` →
    (logits ``[n_slots, vocab]``, advanced cache). The per-slot cursor
    vector doubles as ``pos_offset`` so learned positional embeddings
    index each slot's own depth."""
    dm = model if model.decode else model.clone(decode=True)
    cursors = cache["block_0"]["idx"]
    logits, upd = dm.apply(
        {"params": params, "cache": cache}, tokens[:, None],
        pos_offset=cursors, mutable=["cache"])
    return logits[:, 0], upd["cache"]


def prefill_apply(model, params, cache, tokens, lengths, slot_ids):
    """PURE cohort prefill: tokens int32 ``[S, L]`` (right-padded),
    lengths ``[S]``, slot_ids ``[S]`` (sentinel ``n_slots`` = padding
    row, dropped by the scatter). Runs the slab forward on a fresh
    ``[S, L]`` cache, scatters K/V into the pages, sets the cursors to
    ``lengths``, and returns (last-real-position logits ``[S, vocab]``,
    new cache)."""
    dm = model if model.decode else model.clone(decode=True)
    s, l = tokens.shape
    capacity = cache["block_0"]["k"].shape[1]
    if l > capacity:
        raise ValueError(
            f"prefill bucket length {l} exceeds page capacity {capacity}")
    spec = cache_spec(model)
    slab0 = {
        f"block_{i}": {
            "k": jnp.zeros((s, l, spec["n_kv_heads"], spec["d_head"]),
                           cache["block_0"]["k"].dtype),
            "v": jnp.zeros((s, l, spec["n_kv_heads"], spec["d_head"]),
                           cache["block_0"]["v"].dtype),
            "idx": jnp.zeros((), jnp.int32),
        } for i in range(spec["n_layers"])
    }
    logits, upd = dm.apply(
        {"params": params, "cache": slab0}, tokens, pos_offset=0,
        mutable=["cache"])
    last = jnp.take_along_axis(
        logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
    sid = jnp.asarray(slot_ids, jnp.int32)
    new_cache = {}
    for name, page in cache.items():
        slab = upd["cache"][name]
        new_cache[name] = {
            # mode='drop': the sentinel slot id (== n_slots) is
            # out of bounds, so padding rows vanish instead of clobbering
            # a live slot
            "k": page["k"].at[sid, :l].set(slab["k"], mode="drop"),
            "v": page["v"].at[sid, :l].set(slab["v"], mode="drop"),
            "idx": page["idx"].at[sid].set(
                jnp.asarray(lengths, jnp.int32), mode="drop"),
        }
    return last, new_cache


def prefill_chunk_apply(model, params, cache, tokens, starts, valid,
                        slot_ids):
    """PURE chunk prefill against the PAGED cache: tokens int32
    ``[S, C]`` (right-padded), starts ``[S]`` (absolute write offsets =
    each slot's current fill), valid ``[S]`` (real tokens in this
    chunk), slot_ids ``[S]`` (sentinel ``n_slots`` = padding row).

    Gathers the cohort's pages, runs the chunk forward with
    ``chunked_prefill=True`` (the slab attends the cached prefix plus
    itself — models/transformer.py), scatters the chunk's K/V back at
    ``[start, start+valid)`` (padding columns and sentinel rows drop),
    advances the cursors to ``start + valid``, and returns
    (last-real-position logits ``[S, vocab]``, new cache). No-wrap
    contract: prompts must fit the page (``prompt_len <= capacity``) —
    the engine enforces it at submit.
    """
    dm = (model if (model.decode and model.chunked_prefill)
          else model.clone(decode=True, chunked_prefill=True))
    s, c = tokens.shape
    n_slots, capacity = cache["block_0"]["k"].shape[:2]
    if c > capacity:
        raise ValueError(
            f"prefill chunk length {c} exceeds page capacity {capacity}")
    sid = jnp.asarray(slot_ids, jnp.int32)
    gid = jnp.clip(sid, 0, n_slots - 1)   # sentinels borrow row 0 (reads
    #                                       only — their writes drop)
    starts = jnp.asarray(starts, jnp.int32)
    valid = jnp.asarray(valid, jnp.int32)
    sub = {name: {"k": page["k"][gid], "v": page["v"][gid], "idx": starts}
           for name, page in cache.items()}
    logits, upd = dm.apply(
        {"params": params, "cache": sub}, tokens, pos_offset=starts,
        mutable=["cache"])
    last = jnp.take_along_axis(
        logits, jnp.clip(valid - 1, 0, c - 1)[:, None, None], axis=1)[:, 0]
    rows_i = jnp.arange(s)[:, None]
    cols = starts[:, None] + jnp.arange(c)[None]
    # padding columns point past the page end → mode='drop' eats them,
    # exactly like the sentinel slot id on the row axis
    cols = jnp.where(jnp.arange(c)[None] < valid[:, None], cols, capacity)
    gather_cols = jnp.clip(cols, 0, capacity - 1)
    new_cache = {}
    for name, page in cache.items():
        uk = upd["cache"][name]["k"][rows_i, gather_cols]
        uv = upd["cache"][name]["v"][rows_i, gather_cols]
        new_cache[name] = {
            "k": page["k"].at[sid[:, None], cols].set(uk, mode="drop"),
            "v": page["v"].at[sid[:, None], cols].set(uv, mode="drop"),
            "idx": page["idx"].at[sid].set(starts + valid, mode="drop"),
        }
    return last, new_cache


def decode_k_apply(model, params, cache, tokens, keys, temps, top_ks,
                   eos_ids, remaining, live, park, k):
    """PURE multi-token decode: ``k`` grid steps under one scan, sampling
    on device each step and feeding the result to the next.

    tokens ``[n]`` int32 (each live slot's latest token); keys
    ``[n, 2]`` uint32 per-slot PRNG state; temps/top_ks ``[n]`` sampling
    knobs (sampling.py encoding); eos_ids ``[n]`` int32 (< 0 → no eos);
    remaining ``[n]`` int32 token budget; live ``[n]`` bool; park
    ``[n]`` int32 — the real fill level of each NON-live slot (mid-
    prefill slots especially), pinned around the scan so the k garbage
    steps those rows ride along for cannot advance their cursors into a
    ring wrap over real prefix tokens.

    Returns ``(toks [n, k] int32 — -1 where the slot was not live,
    last_logits [n, vocab] f32, keys, cache)``. The -1 convention lets
    the host pull ONE int32 array per dispatch: validity is in-band.
    """
    dm = model if model.decode else model.clone(decode=True)
    tokens = jnp.asarray(tokens, jnp.int32)
    live = jnp.asarray(live, bool)
    park = jnp.asarray(park, jnp.int32)
    remaining = jnp.asarray(remaining, jnp.int32)
    eos_ids = jnp.asarray(eos_ids, jnp.int32)
    temps = jnp.asarray(temps, jnp.float32)
    top_ks = jnp.asarray(top_ks, jnp.int32)

    def pin(c):
        return {name: {**page, "idx": jnp.where(live, page["idx"], park)}
                for name, page in c.items()}

    cache = pin(cache)
    zeros = jnp.zeros((tokens.shape[0], dm.vocab), jnp.float32)

    def body(carry, _):
        cache, tok, keys, rem, alive, _last = carry
        logits, cache = decode_apply(dm, params, cache, tok)
        nxt, keys2 = sample_tokens(logits, keys, temps, top_ks)
        # only rows that really sampled consume a key split — the
        # per-request stream position is independent of k and neighbours
        keys = jnp.where(alive[:, None], keys2, keys)
        valid = alive
        rem = rem - valid.astype(jnp.int32)
        hit_eos = (nxt == eos_ids) & (eos_ids >= 0)
        alive = alive & ~hit_eos & (rem > 0)
        tok = jnp.where(valid, nxt, tok)
        out = jnp.where(valid, nxt, jnp.int32(-1))
        return (cache, tok, keys, rem, alive, logits), out

    (cache, _, keys, _, _, last), toks = jax.lax.scan(
        body, (cache, tokens, keys, remaining, live, zeros), None,
        length=k)
    cache = pin(cache)   # non-live cursors back to their real fill
    return toks.T, last, keys, cache


class ServingStep:
    """The compiled prefill/decode pair, owning the paged cache.

    ``decode()`` is jitted ONCE with the cache buffers donated (the page
    updates alias in place — no copy of the multi-GiB cache per token)
    and, when a ``mesh`` is given, explicit NamedShardings: K/V pages
    sharded on the head axis over ``axis`` (the TP layout the training
    mesh uses) whenever ``n_kv_heads`` divides, everything else
    replicated. ``prefill()`` compiles one program per (cohort, bucket)
    shape — bucket lengths are the engine's admission policy; the
    per-shape jit cache plus the trace counters below make recompiles
    observable (``tools/bench_serve.py`` asserts decode traces == 1).
    """

    def __init__(self, model, params, n_slots: int, capacity: int, *,
                 cache_dtype: Any = None, mesh=None, axis: Optional[str] = None,
                 donate: bool = True, kv_dtype: Optional[str] = None):
        _check_servable(model)
        self.kv_dtype = _normalize_kv_dtype(kv_dtype)
        if self.kv_dtype == "int8-block" and mesh is not None:
            raise ValueError(
                "kv_dtype='int8-block' does not compose with mesh-sharded "
                "pages: the blockwise scales span the head axis; serve "
                "int8 pages unsharded or keep f32 pages under the mesh")
        self.src_model = model   # caller's layout: load_params converts from it
        if model.qkv_layout == "bhld":
            params = bhld_to_blhd_params(model, params)
            model = model.clone(qkv_layout="blhd")
        self.model = model
        self.dm = model.clone(decode=True)
        self.dm_chunk = self.dm.clone(chunked_prefill=True)
        self.params = params
        self.n_slots = int(n_slots)
        self.capacity = int(capacity)
        self.cache = init_cache(model, n_slots, capacity, cache_dtype,
                                kv_dtype=self.kv_dtype)
        self.decode_traces = 0
        self.decode_k_traces = 0
        self.prefill_traces: Dict[tuple, int] = {}
        self.prefill_chunk_traces: Dict[tuple, int] = {}
        self._prefill_jits: Dict[tuple, Any] = {}
        self._prefill_sampled_jits: Dict[tuple, Any] = {}
        self._prefill_chunk_jits: Dict[tuple, Any] = {}
        self._decode_k_jits: Dict[int, Any] = {}
        self.last_decode_logits = None   # device [n_slots, vocab] —
        #                                  engine's lazy debug/parity hook
        self._mesh = mesh
        self._axis = axis
        donate_args = (1,) if donate else ()

        def _decode(params, cache, tokens):
            self.decode_traces += 1      # trace-time only: counts compiles
            f32c = unpack_cache(cache)
            start = f32c["block_0"]["idx"]
            logits, f32c = decode_apply(self.dm, params, f32c, tokens)
            return logits, repack_cache(cache, f32c, start, 1)

        kw = {}
        if mesh is not None:
            repl, cache_sh = self._shardings(mesh, axis)
            kw = dict(in_shardings=(repl, cache_sh, repl),
                      out_shardings=(repl, cache_sh))
        self._decode_jit = jax.jit(_decode, donate_argnums=donate_args,
                                   **kw)
        self._donate = donate_args

    def _shardings(self, mesh, axis):
        from jax.sharding import NamedSharding, PartitionSpec as P

        axis = axis or mesh.axis_names[0]
        nax = mesh.shape[axis]
        hkv = cache_spec(self.model)["n_kv_heads"]
        kv_spec = P(None, None, axis, None) if hkv % nax == 0 else P()
        repl = NamedSharding(mesh, P())
        page = {"k": NamedSharding(mesh, kv_spec),
                "v": NamedSharding(mesh, kv_spec),
                "idx": repl}
        cache_sh = {name: dict(page) for name in self.cache}
        return repl, cache_sh

    def _scatter_window(self, slot_ids, starts, counts):
        """Per-SLOT (start, count) written-column windows for a cohort
        scatter — the ``repack_cache`` mask inputs. Sentinel rows
        (``sid == n_slots``) drop out, so their slots' counts stay 0
        and their resident bytes are untouched (mirroring the f32
        path's ``mode='drop'`` exactly)."""
        sid = jnp.asarray(slot_ids, jnp.int32)
        zeros = jnp.zeros((self.n_slots,), jnp.int32)
        start = zeros.at[sid].set(
            jnp.broadcast_to(jnp.asarray(starts, jnp.int32), sid.shape),
            mode="drop")
        count = zeros.at[sid].set(
            jnp.broadcast_to(jnp.asarray(counts, jnp.int32), sid.shape),
            mode="drop")
        return start, count

    def cache_bytes(self) -> int:
        if self.kv_dtype == "int8-block":
            return cache_bytes(self.model, self.n_slots, self.capacity,
                               kv_dtype=self.kv_dtype)
        return cache_bytes(self.model, self.n_slots, self.capacity,
                           self.cache["block_0"]["k"].dtype)

    def cursors(self):
        """Device→host pull of the per-slot fill levels (debug/report)."""
        return jax.device_get(self.cache["block_0"]["idx"])

    def decode(self, tokens):
        """One token for every slot: tokens int ``[n_slots]`` → logits
        ``[n_slots, vocab]`` (f32, on device). Retired/free slots carry
        any token id; their rows are garbage and MUST be ignored — row
        independence keeps them from perturbing live slots (tested
        bitwise)."""
        tokens = jnp.asarray(tokens, jnp.int32)
        logits, self.cache = self._decode_jit(
            self.params, self.cache, tokens)
        return logits

    def prefill(self, tokens, lengths, slot_ids):
        """Cohort prefill (see :func:`prefill_apply`); compiled per
        (S, L) shape with the cache donated, counted in
        ``prefill_traces``."""
        tokens = jnp.asarray(tokens, jnp.int32)
        key = tokens.shape
        if key not in self._prefill_jits:
            def _prefill(params, cache, tokens, lengths, slot_ids,
                         _key=key):
                self.prefill_traces[_key] = (
                    self.prefill_traces.get(_key, 0) + 1)
                f32c = unpack_cache(cache)
                last, f32c = prefill_apply(self.dm, params, f32c, tokens,
                                           lengths, slot_ids)
                start, count = self._scatter_window(slot_ids, 0, _key[1])
                return last, repack_cache(cache, f32c, start, count)

            kw = {}
            if self._mesh is not None:
                repl, cache_sh = self._shardings(self._mesh, self._axis)
                kw = dict(
                    in_shardings=(repl, cache_sh, repl, repl, repl),
                    out_shardings=(repl, cache_sh))
            self._prefill_jits[key] = jax.jit(
                _prefill, donate_argnums=self._donate, **kw)
        logits, self.cache = self._prefill_jits[key](
            self.params, self.cache, tokens,
            jnp.asarray(lengths, jnp.int32),
            jnp.asarray(slot_ids, jnp.int32))
        return logits

    def decode_k(self, tokens, keys, temps, top_ks, eos_ids, remaining,
                 live, park, k: int):
        """``k`` decode steps + on-device sampling in ONE dispatch (see
        :func:`decode_k_apply`). Compiled once per ``k`` with the cache
        donated — ``decode_k_traces`` counts compiles (the DL108
        invariant extends here: any traffic mix at fixed ``k`` runs one
        program). Returns ``(toks [n, k] int32 device, new keys)``;
        the step's final logits stay ON DEVICE in
        ``self.last_decode_logits`` until somebody actually reads them.
        """
        kk = int(k)
        if kk not in self._decode_k_jits:
            def _decode_k(params, cache, tokens, keys, temps, top_ks,
                          eos_ids, remaining, live, park, _k=kk):
                self.decode_k_traces += 1   # trace-time only
                f32c = unpack_cache(cache)
                # every row writes k columns from its PINNED cursor —
                # live rows from idx, ride-along rows from park (their
                # garbage stays beyond their real fill)
                start = jnp.where(jnp.asarray(live, bool),
                                  f32c["block_0"]["idx"],
                                  jnp.asarray(park, jnp.int32))
                toks, last, keys, f32c = decode_k_apply(
                    self.dm, params, f32c, tokens, keys, temps, top_ks,
                    eos_ids, remaining, live, park, _k)
                return toks, last, keys, repack_cache(cache, f32c,
                                                      start, _k)

            kw = {}
            if self._mesh is not None:
                repl, cache_sh = self._shardings(self._mesh, self._axis)
                kw = dict(
                    in_shardings=(repl, cache_sh) + (repl,) * 8,
                    out_shardings=(repl, repl, repl, cache_sh))
            self._decode_k_jits[kk] = jax.jit(
                _decode_k, donate_argnums=self._donate, **kw)
        toks, last, keys, self.cache = self._decode_k_jits[kk](
            self.params, self.cache, jnp.asarray(tokens, jnp.int32),
            keys, jnp.asarray(temps, jnp.float32),
            jnp.asarray(top_ks, jnp.int32),
            jnp.asarray(eos_ids, jnp.int32),
            jnp.asarray(remaining, jnp.int32),
            jnp.asarray(live, bool), jnp.asarray(park, jnp.int32))
        self.last_decode_logits = last
        return toks, keys

    def prefill_sampled(self, tokens, lengths, slot_ids, keys, temps,
                        top_ks):
        """Cohort prefill + on-device first-token sampling: one dispatch
        returns ``(tok [S] int32 device, new keys)`` instead of shipping
        ``[S, vocab]`` logits to the host. Greedy rows are bit-identical
        to ``np.argmax`` over :meth:`prefill`'s logits (sampling.py).
        Compiled per (S, L) shape, counted in ``prefill_traces`` under
        the same (S, L) keys as the logits path — one program per
        bucket either way (the DL108 trace-table assertions carry over
        unchanged)."""
        tokens = jnp.asarray(tokens, jnp.int32)
        key = tokens.shape
        if key not in self._prefill_sampled_jits:
            def _pf(params, cache, tokens, lengths, slot_ids, keys,
                    temps, top_ks, _key=key):
                self.prefill_traces[_key] = (
                    self.prefill_traces.get(_key, 0) + 1)
                f32c = unpack_cache(cache)
                last, f32c = prefill_apply(self.dm, params, f32c,
                                           tokens, lengths, slot_ids)
                start, count = self._scatter_window(slot_ids, 0, _key[1])
                cache = repack_cache(cache, f32c, start, count)
                sid = jnp.asarray(slot_ids, jnp.int32)
                gid = jnp.clip(sid, 0, self.n_slots - 1)
                tok, newk = sample_tokens(last, keys[gid], temps[gid],
                                          top_ks[gid])
                # sentinel rows (sid == n_slots) drop out of the key
                # scatter — their splits never touch a live slot's stream
                keys = keys.at[sid].set(newk, mode="drop")
                return tok, keys, cache

            kw = {}
            if self._mesh is not None:
                repl, cache_sh = self._shardings(self._mesh, self._axis)
                kw = dict(in_shardings=(repl, cache_sh) + (repl,) * 6,
                          out_shardings=(repl, repl, cache_sh))
            self._prefill_sampled_jits[key] = jax.jit(
                _pf, donate_argnums=self._donate, **kw)
        tok, keys, self.cache = self._prefill_sampled_jits[key](
            self.params, self.cache, tokens,
            jnp.asarray(lengths, jnp.int32),
            jnp.asarray(slot_ids, jnp.int32), keys,
            jnp.asarray(temps, jnp.float32),
            jnp.asarray(top_ks, jnp.int32))
        return tok, keys

    def prefill_chunk(self, tokens, starts, valid, slot_ids, final, keys,
                      temps, top_ks):
        """One fixed-shape prompt chunk for up to S slots (see
        :func:`prefill_chunk_apply`), sampling the first token on device
        for rows whose chunk is ``final``. Returns ``(tok [S] int32
        device — -1 for non-final rows, new keys)``. ONE compiled
        program per (S, C) shape regardless of prompt length — counted
        in ``prefill_chunk_traces``."""
        tokens = jnp.asarray(tokens, jnp.int32)
        key = tokens.shape
        if key not in self._prefill_chunk_jits:
            def _pc(params, cache, tokens, starts, valid, slot_ids,
                    final, keys, temps, top_ks, _key=key):
                self.prefill_chunk_traces[_key] = (
                    self.prefill_chunk_traces.get(_key, 0) + 1)
                f32c = unpack_cache(cache)
                last, f32c = prefill_chunk_apply(
                    self.dm_chunk, params, f32c, tokens, starts, valid,
                    slot_ids)
                w_start, w_count = self._scatter_window(
                    slot_ids, starts, valid)
                cache = repack_cache(cache, f32c, w_start, w_count)
                sid = jnp.asarray(slot_ids, jnp.int32)
                gid = jnp.clip(sid, 0, self.n_slots - 1)
                tok, newk = sample_tokens(last, keys[gid], temps[gid],
                                          top_ks[gid])
                # only a COMPLETING chunk consumes its slot's key split:
                # the stream position depends on tokens sampled, never
                # on how many chunks the prompt was carved into
                adv = final & (sid < self.n_slots)
                keys = keys.at[sid].set(
                    jnp.where(adv[:, None], newk, keys[gid]), mode="drop")
                tok = jnp.where(final, tok, jnp.int32(-1))
                return tok, keys, cache

            kw = {}
            if self._mesh is not None:
                repl, cache_sh = self._shardings(self._mesh, self._axis)
                kw = dict(in_shardings=(repl, cache_sh) + (repl,) * 8,
                          out_shardings=(repl, repl, cache_sh))
            self._prefill_chunk_jits[key] = jax.jit(
                _pc, donate_argnums=self._donate, **kw)
        tok, keys, self.cache = self._prefill_chunk_jits[key](
            self.params, self.cache, tokens,
            jnp.asarray(starts, jnp.int32),
            jnp.asarray(valid, jnp.int32),
            jnp.asarray(slot_ids, jnp.int32),
            jnp.asarray(final, bool), keys,
            jnp.asarray(temps, jnp.float32),
            jnp.asarray(top_ks, jnp.int32))
        return tok, keys

    def export_slot(self, slot: int, fill: int) -> Dict[str, Dict[str, Any]]:
        """Pull one slot's populated KV rows to the host: ``{"block_i":
        {"k", "v"}}`` with each leaf ``[fill, n_kv_heads, d_head]`` in
        the cache dtype — the prefill→decode handoff payload
        (fleet/handoff.py). ``fill`` must not exceed the page (a wrapped
        ring has overwritten its prefix; re-prefill instead).

        int8-block pages export RESIDENT form instead: ``{"k_q", "k_s",
        "v_q", "v_s"}`` per block, codes ``[fill, n_kv_heads, d_head]``
        int8 + scales ``[fill, r/block]`` f32. ``fill · r`` is always a
        whole number of ``page_block(model)``-sized blocks, so the
        flattened pair is a valid ``block_dequantize`` payload and
        handoff wire formats 2/4 ship it VERBATIM — no dequantize→
        requantize round trip, zero extra quantization error. (The
        scales are shipped rather than recomputed deliberately: XLA may
        fold the codec's ``amax/127`` divide differently inside a jitted
        commit than the eager wire codec does, so a recompute can be
        1 ulp off the resident bytes.)"""
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} outside [0, {self.n_slots})")
        if not 0 < fill <= self.capacity:
            raise ValueError(
                f"fill {fill} outside (0, capacity={self.capacity}] — a "
                "wrapped slot cannot be exported")
        # Export IS the host pull: handoff serialization runs once per
        # migration, outside the per-token decode loop, and the payload
        # must be host bytes by contract.
        if self.kv_dtype == "int8-block":
            return {  # dlint: disable=DL121 — sanctioned migration pull
                name: {leaf: np.asarray(page[leaf][slot, :fill])
                       for leaf in ("k_q", "k_s", "v_q", "v_s")}
                for name, page in self.cache.items()}
        return {  # dlint: disable=DL121 — sanctioned migration pull
            name: {"k": np.asarray(page["k"][slot, :fill]),
                   "v": np.asarray(page["v"][slot, :fill])}
            for name, page in self.cache.items()}

    def import_slot(self, slot: int, pages, cursor: int) -> None:
        """Inverse of :meth:`export_slot`: write handed-off KV rows into
        ``slot`` and set its cursor to ``cursor``. Raw-format handoffs
        round-trip BITWISE (same dtype, no value transform), so decode
        from an imported slot equals decode on the exporting engine.

        ``pages`` may hold f32 ``{"k", "v"}`` rows or int8-resident
        ``{"k_q", "k_s", "v_q", "v_s"}`` rows, and either lands in
        either page mode: resident→int8 adopts the codes verbatim
        (BITWISE, zero extra quantization error), resident→f32
        dequantizes once, f32→int8 quantizes once (the same single
        quantization a local commit pays)."""
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} outside [0, {self.n_slots})")
        if not 0 < cursor <= self.capacity:
            raise ValueError(
                f"cursor {cursor} outside (0, capacity={self.capacity}]")
        if set(pages) != set(self.cache):
            raise ValueError(
                "handoff pages do not match this model's cache layout: "
                f"got {sorted(pages)}, want {sorted(self.cache)}")
        resident = "k_q" in next(iter(pages.values()))
        blk = page_block(self.model)
        new_cache = {}
        for name, page in self.cache.items():
            if resident:
                rows = {leaf: jnp.asarray(pages[name][leaf])
                        for leaf in ("k_q", "k_s", "v_q", "v_s")}
            else:
                rows = {"k": jnp.asarray(pages[name]["k"]),
                        "v": jnp.asarray(pages[name]["v"])}
                want = (cursor,) + self._row_shape()
                if rows["k"].shape != want or rows["v"].shape != want:
                    raise ValueError(
                        f"handoff rows for {name} have shape "
                        f"{rows['k'].shape}, want {want}")
            if self.kv_dtype == "int8-block":
                if not resident:
                    # f32 rows into int8 pages: ONE quantization — the
                    # same cost a local commit would have paid
                    rows["k_q"], rows["k_s"] = _quant_rows(rows["k"], blk)
                    rows["v_q"], rows["v_s"] = _quant_rows(rows["v"], blk)
                new_cache[name] = {
                    **{leaf: page[leaf].at[slot, :cursor].set(
                        jnp.asarray(rows[leaf], page[leaf].dtype))
                       for leaf in ("k_q", "k_s", "v_q", "v_s")},
                    "idx": page["idx"].at[slot].set(jnp.int32(cursor)),
                }
            else:
                if resident:
                    # int8-resident rows into f32 pages: dequantize once
                    rows["k"] = _dequant_rows(rows["k_q"], rows["k_s"])
                    rows["v"] = _dequant_rows(rows["v_q"], rows["v_s"])
                new_cache[name] = {
                    "k": page["k"].at[slot, :cursor].set(
                        jnp.asarray(rows["k"], page["k"].dtype)),
                    "v": page["v"].at[slot, :cursor].set(
                        jnp.asarray(rows["v"], page["v"].dtype)),
                    "idx": page["idx"].at[slot].set(jnp.int32(cursor)),
                }
        self.cache = new_cache

    def _row_shape(self):
        spec = cache_spec(self.model)
        return (spec["n_kv_heads"], spec["d_head"])

    def load_params(self, params):
        """Swap weights in place (warm restart / rolling update —
        serving/weights.py, fleet/rollout.py). ``params`` is in the
        CALLER's layout, the same one ``__init__`` received; a bhld
        source is converted exactly as construction did. No recompile:
        params are per-call arguments to every jitted program."""
        if self.src_model.qkv_layout == "bhld":
            params = bhld_to_blhd_params(self.src_model, params)
        self.params = params

    def reset(self):
        """Zero every page and cursor (all slots freed)."""
        dt = (None if self.kv_dtype == "int8-block"
              else self.cache["block_0"]["k"].dtype)
        self.cache = init_cache(
            self.model, self.n_slots, self.capacity, dt,
            kv_dtype=self.kv_dtype)

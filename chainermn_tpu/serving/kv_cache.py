"""Paged, ring-buffered KV cache + the compiled serving step pair.

The serving cache is the training model's own flax ``cache`` collection,
re-shaped for continuous batching: one PAGE per transformer block, each
page ``k``/``v`` of shape ``[n_slots, capacity, n_kv_heads, d_head]``
plus a per-slot ``idx`` cursor vector ``[n_slots]`` (the decode branch in
``models/transformer.py`` accepts either the scalar cursor ``generate()``
uses or this vector — every row then advances independently).

Ring semantics: the write position for token ``p`` of slot ``s`` is
``p % capacity``; once a slot's stream outgrows its page the oldest
tokens are overwritten and attention degrades to a ``capacity``-token
sliding window (the mask inverts the ring — see the ``kpos`` comment in
the decode branch). Prefer ``pos_emb='rope'`` for streams expected to
wrap (learned positions clip at ``max_len``).

Two compiled entry points, following the SNIPPETS Partitioner shape
(jit with explicit in/out shardings, donated cache buffers):

* ``prefill`` — a fixed-shape cohort ``[S, L_bucket]`` runs the one
  legal multi-token decode apply on a FRESH slab cache, then scatters
  the slab into the page at the cohort's slot ids (a sentinel id of
  ``n_slots`` drops padding rows — ``mode='drop'``). Returns each
  prompt's last-position logits (the first sampled token — TTFT).
* ``decode_step`` — one token for ALL ``n_slots`` slots at once, a
  single ``[n_slots, 1]`` apply against the paged cache. Constant
  shapes by construction: traced once, reused forever (the DL108
  trap this module exists to avoid).

Numerics contract (tested bitwise): with ``capacity`` ≥ the full stream
length and ``attention='reference'``, cached decode logits equal the
corresponding full-forward column BITWISE — the decode branch uses
squeezed-q contractions and the same-program prefill kernel to make the
cached path a re-association-free restatement of the training forward.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from chainermn_tpu.models.transformer import bhld_to_blhd_params

__all__ = ["init_cache", "cache_bytes", "cache_spec", "decode_apply",
           "prefill_apply", "ServingStep"]


def _check_servable(model):
    if model.moe_experts_per_device > 0:
        raise ValueError("serving does not support MoE models: the "
                         "decode path has no expert dispatch")
    if model.tp_axis is not None or getattr(model, "lm_head_tp", False):
        raise ValueError(
            "serving runs the jit decode path; tp_axis/lm_head_tp models "
            "serve without shard_map TP (clone with tp_axis=None, "
            "lm_head_tp=False and gather the weights — head-axis mesh "
            "sharding of the cache covers the TP layout instead)")


def cache_spec(model) -> Dict[str, int]:
    """The numbers the sizing math and page shapes derive from."""
    return dict(
        n_layers=model.n_layers,
        n_kv_heads=model.n_kv_heads or model.n_heads,
        d_head=model.d_model // model.n_heads,
    )


def cache_bytes(model, n_slots: int, capacity: int,
                dtype: Any = None) -> int:
    """Preallocated cache footprint: ``n_layers · n_slots · capacity ·
    2 (K and V) · n_kv_heads · d_head · itemsize`` — the budget line in
    docs/serving.md's sizing table."""
    spec = cache_spec(model)
    itemsize = jnp.dtype(dtype or model.dtype).itemsize
    return (spec["n_layers"] * n_slots * capacity * 2
            * spec["n_kv_heads"] * spec["d_head"] * itemsize)


def init_cache(model, n_slots: int, capacity: int, dtype: Any = None):
    """Fresh zeroed pages: ``{"block_i": {"k", "v", "idx"}}`` with
    per-slot cursor vectors. The tree is exactly the flax ``cache``
    collection ``model.clone(decode=True)`` declares — supplied values
    override the declared ``max_len`` shapes, which is how ``capacity``
    decouples from ``model.max_len``."""
    spec = cache_spec(model)
    dt = dtype or model.dtype
    page = lambda: {
        "k": jnp.zeros((n_slots, capacity, spec["n_kv_heads"],
                        spec["d_head"]), dt),
        "v": jnp.zeros((n_slots, capacity, spec["n_kv_heads"],
                        spec["d_head"]), dt),
        "idx": jnp.zeros((n_slots,), jnp.int32),
    }
    return {f"block_{i}": page() for i in range(spec["n_layers"])}


def decode_apply(model, params, cache, tokens):
    """PURE one-token step for every slot: tokens int32 ``[n_slots]`` →
    (logits ``[n_slots, vocab]``, advanced cache). The per-slot cursor
    vector doubles as ``pos_offset`` so learned positional embeddings
    index each slot's own depth."""
    dm = model if model.decode else model.clone(decode=True)
    cursors = cache["block_0"]["idx"]
    logits, upd = dm.apply(
        {"params": params, "cache": cache}, tokens[:, None],
        pos_offset=cursors, mutable=["cache"])
    return logits[:, 0], upd["cache"]


def prefill_apply(model, params, cache, tokens, lengths, slot_ids):
    """PURE cohort prefill: tokens int32 ``[S, L]`` (right-padded),
    lengths ``[S]``, slot_ids ``[S]`` (sentinel ``n_slots`` = padding
    row, dropped by the scatter). Runs the slab forward on a fresh
    ``[S, L]`` cache, scatters K/V into the pages, sets the cursors to
    ``lengths``, and returns (last-real-position logits ``[S, vocab]``,
    new cache)."""
    dm = model if model.decode else model.clone(decode=True)
    s, l = tokens.shape
    capacity = cache["block_0"]["k"].shape[1]
    if l > capacity:
        raise ValueError(
            f"prefill bucket length {l} exceeds page capacity {capacity}")
    spec = cache_spec(model)
    slab0 = {
        f"block_{i}": {
            "k": jnp.zeros((s, l, spec["n_kv_heads"], spec["d_head"]),
                           cache["block_0"]["k"].dtype),
            "v": jnp.zeros((s, l, spec["n_kv_heads"], spec["d_head"]),
                           cache["block_0"]["v"].dtype),
            "idx": jnp.zeros((), jnp.int32),
        } for i in range(spec["n_layers"])
    }
    logits, upd = dm.apply(
        {"params": params, "cache": slab0}, tokens, pos_offset=0,
        mutable=["cache"])
    last = jnp.take_along_axis(
        logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
    sid = jnp.asarray(slot_ids, jnp.int32)
    new_cache = {}
    for name, page in cache.items():
        slab = upd["cache"][name]
        new_cache[name] = {
            # mode='drop': the sentinel slot id (== n_slots) is
            # out of bounds, so padding rows vanish instead of clobbering
            # a live slot
            "k": page["k"].at[sid, :l].set(slab["k"], mode="drop"),
            "v": page["v"].at[sid, :l].set(slab["v"], mode="drop"),
            "idx": page["idx"].at[sid].set(
                jnp.asarray(lengths, jnp.int32), mode="drop"),
        }
    return last, new_cache


class ServingStep:
    """The compiled prefill/decode pair, owning the paged cache.

    ``decode()`` is jitted ONCE with the cache buffers donated (the page
    updates alias in place — no copy of the multi-GiB cache per token)
    and, when a ``mesh`` is given, explicit NamedShardings: K/V pages
    sharded on the head axis over ``axis`` (the TP layout the training
    mesh uses) whenever ``n_kv_heads`` divides, everything else
    replicated. ``prefill()`` compiles one program per (cohort, bucket)
    shape — bucket lengths are the engine's admission policy; the
    per-shape jit cache plus the trace counters below make recompiles
    observable (``tools/bench_serve.py`` asserts decode traces == 1).
    """

    def __init__(self, model, params, n_slots: int, capacity: int, *,
                 cache_dtype: Any = None, mesh=None, axis: Optional[str] = None,
                 donate: bool = True):
        _check_servable(model)
        if model.qkv_layout == "bhld":
            params = bhld_to_blhd_params(model, params)
            model = model.clone(qkv_layout="blhd")
        self.model = model
        self.dm = model.clone(decode=True)
        self.params = params
        self.n_slots = int(n_slots)
        self.capacity = int(capacity)
        self.cache = init_cache(model, n_slots, capacity, cache_dtype)
        self.decode_traces = 0
        self.prefill_traces: Dict[tuple, int] = {}
        self._prefill_jits: Dict[tuple, Any] = {}
        self._mesh = mesh
        self._axis = axis
        donate_args = (1,) if donate else ()

        def _decode(params, cache, tokens):
            self.decode_traces += 1      # trace-time only: counts compiles
            return decode_apply(self.dm, params, cache, tokens)

        kw = {}
        if mesh is not None:
            repl, cache_sh = self._shardings(mesh, axis)
            kw = dict(in_shardings=(repl, cache_sh, repl),
                      out_shardings=(repl, cache_sh))
        self._decode_jit = jax.jit(_decode, donate_argnums=donate_args,
                                   **kw)
        self._donate = donate_args

    def _shardings(self, mesh, axis):
        from jax.sharding import NamedSharding, PartitionSpec as P

        axis = axis or mesh.axis_names[0]
        nax = mesh.shape[axis]
        hkv = cache_spec(self.model)["n_kv_heads"]
        kv_spec = P(None, None, axis, None) if hkv % nax == 0 else P()
        repl = NamedSharding(mesh, P())
        page = {"k": NamedSharding(mesh, kv_spec),
                "v": NamedSharding(mesh, kv_spec),
                "idx": repl}
        cache_sh = {name: dict(page) for name in self.cache}
        return repl, cache_sh

    def cache_bytes(self) -> int:
        return cache_bytes(self.model, self.n_slots, self.capacity,
                           self.cache["block_0"]["k"].dtype)

    def cursors(self):
        """Device→host pull of the per-slot fill levels (debug/report)."""
        return jax.device_get(self.cache["block_0"]["idx"])

    def decode(self, tokens):
        """One token for every slot: tokens int ``[n_slots]`` → logits
        ``[n_slots, vocab]`` (f32, on device). Retired/free slots carry
        any token id; their rows are garbage and MUST be ignored — row
        independence keeps them from perturbing live slots (tested
        bitwise)."""
        tokens = jnp.asarray(tokens, jnp.int32)
        logits, self.cache = self._decode_jit(
            self.params, self.cache, tokens)
        return logits

    def prefill(self, tokens, lengths, slot_ids):
        """Cohort prefill (see :func:`prefill_apply`); compiled per
        (S, L) shape with the cache donated, counted in
        ``prefill_traces``."""
        tokens = jnp.asarray(tokens, jnp.int32)
        key = tokens.shape
        if key not in self._prefill_jits:
            def _prefill(params, cache, tokens, lengths, slot_ids,
                         _key=key):
                self.prefill_traces[_key] = (
                    self.prefill_traces.get(_key, 0) + 1)
                return prefill_apply(self.dm, params, cache, tokens,
                                     lengths, slot_ids)

            kw = {}
            if self._mesh is not None:
                repl, cache_sh = self._shardings(self._mesh, self._axis)
                kw = dict(
                    in_shardings=(repl, cache_sh, repl, repl, repl),
                    out_shardings=(repl, cache_sh))
            self._prefill_jits[key] = jax.jit(
                _prefill, donate_argnums=self._donate, **kw)
        logits, self.cache = self._prefill_jits[key](
            self.params, self.cache, tokens,
            jnp.asarray(lengths, jnp.int32),
            jnp.asarray(slot_ids, jnp.int32))
        return logits

    def load_params(self, params):
        """Swap weights in place (warm restart — serving/weights.py)."""
        if self.model.qkv_layout == "bhld":
            params = bhld_to_blhd_params(self.model, params)
        self.params = params

    def reset(self):
        """Zero every page and cursor (all slots freed)."""
        self.cache = init_cache(
            self.model, self.n_slots, self.capacity,
            self.cache["block_0"]["k"].dtype)

"""Continuous-batching engine: iteration-level scheduling over the
paged KV cache.

The engine owns a fixed grid of ``n_slots`` decode slots. Every
``step()`` is one scheduler iteration under a shared per-iteration
TOKEN BUDGET (``EngineConfig.token_budget``; ``None`` → unbounded):

1. **Budget** — the iteration reserves ``len(active) × decode_k``
   tokens for decode first; prefill spends what is left.
2. **Prefill** — either one monolithic same-bucket cohort (the classic
   path: up to ``prefill_cohort`` prompts right-padded to the bucket
   length, sentinel rows filling the fixed shape), or — with
   ``prefill_chunk`` set — fixed-size ``[S, C]`` prompt CHUNKS written
   incrementally at each slot's cursor, so a long prompt streams in
   across iterations instead of head-of-line-blocking every active
   decode slot. A deferral cap (``max_prefill_defer``) guarantees
   prefill still happens under sustained decode pressure, and a wrap
   guard force-finishes any prefill within ``decode_k`` tokens of the
   page end before decode may run again.
3. **Decode** — ONE ``decode_k`` dispatch advances every live slot up
   to ``k`` tokens: sampling runs ON DEVICE (serving/sampling.py, keyed
   by per-slot PRNG state the engine threads), EOS/budget stop masks
   are evaluated in the compiled scan, and the host pulls a single
   ``[n_slots, k]`` int32 array — 4 bytes/token instead of
   ``vocab × 4`` (dlint DL110 polices the old full-logits pull).
4. **Retirement** — slots whose request emitted ``eos_id`` or reached
   its token budget are freed for the next admission.

Prefill and decode co-exist without recompilation — the DL108
invariant: after warmup, serving any traffic mix executes exactly one
compiled ``decode_k`` program plus one prefill program per bucket (or
ONE chunk program total in chunked mode). ``resilience/chaos.py::
on_step`` fires at the top of every iteration, so
``$CHAINERMN_TPU_CHAOS='kill@step=N'`` kills a replica mid-decode — the
supervisor drill in tests/serving_tests.
"""

from __future__ import annotations

import dataclasses
import itertools
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from chainermn_tpu.resilience import chaos
from chainermn_tpu.serving.kv_cache import ServingStep
from chainermn_tpu.serving.reports import ServingReport
from chainermn_tpu.serving.sampling import init_keys, request_key

__all__ = ["Engine", "EngineConfig", "Request", "WeightsVersionSkew",
           "default_buckets"]


class WeightsVersionSkew(ValueError):
    """A handoff/session was minted under a different weights version
    than this engine serves. Adoption is REFUSED — continuing a
    prefill-v2 stream on a decode-v1 replica would silently mix model
    versions inside one output. Callers route the refusal through the
    existing fallbacks: the decode pool re-prefills the stream cleanly
    (fleet/pools.py), the router replays it from seed on a survivor
    (fleet/router.py) — either way the stream is entirely ONE version,
    bitwise against that version's oracle."""


def default_buckets(capacity: int, lo: int = 8) -> Tuple[int, ...]:
    """Power-of-two bucket table up to the page capacity: every prompt
    compiles against one of O(log capacity) prefill shapes."""
    out = []
    b = lo
    while b < capacity:
        out.append(b)
        b *= 2
    out.append(capacity)
    return tuple(out)


@dataclasses.dataclass
class EngineConfig:
    n_slots: int = 4
    capacity: int = 256
    max_new_tokens: int = 64          # default per-request budget
    prefill_cohort: int = 2           # S — cohort width (fixed shape)
    buckets: Optional[Sequence[int]] = None  # None → default_buckets()
    cache_dtype: object = None
    decode_k: int = 4                 # tokens per decode dispatch (the
    #                                   on-device scan length; 1 = the
    #                                   classic one-token step)
    prefill_chunk: Optional[int] = None  # chunk width C; None → the
    #                                      monolithic per-bucket path
    token_budget: Optional[int] = None   # per-iteration token budget
    #                                      shared by decode + prefill;
    #                                      None → unbounded
    max_prefill_defer: int = 4        # iterations prefill may yield to
    #                                   decode before it runs anyway
    kv_dtype: Optional[str] = None    # page storage mode: None/'f32' or
    #                                   'int8-block' (kv_cache.py — int8
    #                                   pages forbid ring wrap, so submit
    #                                   enforces prompt + max_new ≤
    #                                   capacity)

    def bucket_table(self) -> Tuple[int, ...]:
        return (tuple(sorted(self.buckets)) if self.buckets
                else default_buckets(self.capacity))


@dataclasses.dataclass(eq=False)   # identity semantics (prompt is an array)
class Request:
    """One generation stream. ``tokens`` grows as the engine emits;
    terminal states are 'done' (eos or budget) and 'aborted'.

    Sampling happens ON DEVICE (serving/sampling.py): ``temperature``
    ``None``/``0`` → greedy argmax (bit-identical to the old host
    ``np.argmax`` path), ``top_k`` ``None``/``0`` → full vocabulary,
    ``seed`` keys the per-slot PRNG stream — one split per sampled
    token, so a fixed seed replays the same stream under any scheduler
    interleaving.
    """
    request_id: int
    prompt: np.ndarray                # int32 [L]
    max_new_tokens: int
    eos_id: Optional[int] = None
    temperature: Optional[float] = None   # None → greedy argmax
    top_k: Optional[int] = None           # None → full vocab
    seed: int = 0
    tokens: List[int] = dataclasses.field(default_factory=list)
    state: str = "queued"             # queued|running|held|done|aborted
    slot: Optional[int] = None
    prefill_pos: int = 0              # chunked prefill: tokens written
    hold: bool = False                # retire → 'held' (slot kept bound
    #                                   for export_handoff; fleet pools)

    @property
    def finished(self) -> bool:
        return self.state in ("done", "aborted")


class Engine:
    """Single-threaded scheduler core (the thread-safe face is
    ``frontend.Frontend``). ``submit()`` queues, ``step()`` advances one
    iteration, ``run_until_drained()`` loops until idle."""

    def __init__(self, model, params, config: EngineConfig = EngineConfig(),
                 *, mesh=None, axis=None, report: Optional[ServingReport] = None,
                 time_fn=None, weights_version: Optional[str] = None):
        self.config = config
        #: which published weights this engine serves (None = unversioned
        #: — every skew check passes, so pre-rollout fleets are unchanged)
        self.weights_version = weights_version
        if config.decode_k < 1:
            raise ValueError("decode_k must be >= 1")
        if config.prefill_chunk is not None and config.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self.steps = ServingStep(
            model, params, config.n_slots, config.capacity,
            cache_dtype=config.cache_dtype, mesh=mesh, axis=axis,
            kv_dtype=config.kv_dtype)
        self.report = report or (ServingReport(time_fn) if time_fn
                                 else ServingReport())
        self.queue: deque[Request] = deque()
        self.active: Dict[int, Request] = {}          # slot → decoding
        self.prefilling: Dict[int, Request] = {}      # slot → mid-chunk
        self.held: Dict[int, Request] = {}            # slot → awaiting
        #                                               export (handoff)
        self.free_slots: List[int] = list(range(config.n_slots))
        self.cur_tokens = np.zeros(config.n_slots, np.int32)
        # per-slot sampling state, threaded through the compiled
        # programs (sampling.py encoding: temp<=0 greedy, top_k<=0 full)
        self._keys = init_keys(config.n_slots)
        self._temps = np.zeros(config.n_slots, np.float32)
        self._topks = np.zeros(config.n_slots, np.int32)
        self._eos = np.full(config.n_slots, -1, np.int32)
        self._prefill_defer = 0
        self.iteration = 0
        self._ids = itertools.count()
        self._buckets = config.bucket_table()
        if self._buckets[-1] < config.capacity:
            raise ValueError("largest bucket must reach capacity")

    @property
    def last_logits(self) -> Optional[np.ndarray]:
        """Final decode-step logits ``[n_slots, vocab]`` — materialized
        from device ONLY when read (debug/parity hook; the serving hot
        loop itself never pulls them — that's the point of DL110)."""
        dev = self.steps.last_decode_logits
        return None if dev is None else np.asarray(dev)

    # ----------------------------------------------------------------
    # request lifecycle
    # ----------------------------------------------------------------

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_id: Optional[int] = None,
               temperature: Optional[float] = None,
               top_k: Optional[int] = None,
               seed: int = 0, hold: bool = False) -> Request:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if self.config.prefill_chunk is not None:
            # chunked prefill is bucket-free; the page (and the no-wrap
            # chunk contract) is the only length limit
            if prompt.size > self.config.capacity:
                raise ValueError(
                    f"prompt length {prompt.size} exceeds the page "
                    f"capacity ({self.config.capacity})")
        elif prompt.size > self._buckets[-1]:
            raise ValueError(
                f"prompt length {prompt.size} exceeds the largest prefill "
                f"bucket ({self._buckets[-1]})")
        budget = (max_new_tokens if max_new_tokens is not None
                  else self.config.max_new_tokens)
        if (self.steps.kv_dtype == "int8-block"
                and prompt.size + budget > self.config.capacity):
            raise ValueError(
                f"int8-block pages forbid ring wrap: prompt ({prompt.size})"
                f" + max_new_tokens ({budget}) exceeds the page capacity "
                f"({self.config.capacity})")
        req = Request(request_id=next(self._ids), prompt=prompt,
                      max_new_tokens=budget,
                      eos_id=eos_id, temperature=temperature,
                      top_k=top_k, seed=seed, hold=hold)
        self.queue.append(req)
        self.report.record_submit(req.request_id)
        return req

    def _bucket_for(self, length: int) -> int:
        for b in self._buckets:
            if b >= length:
                return b
        raise ValueError(f"no bucket covers prompt length {length}")

    def _install(self, req: Request, slot: int) -> None:
        """Bind a request to a slot: sampling state rows + PRNG key."""
        req.slot = slot
        req.state = "running"
        self._temps[slot] = (req.temperature
                             if req.temperature is not None else 0.0)
        self._topks[slot] = req.top_k if req.top_k is not None else 0
        self._eos[slot] = req.eos_id if req.eos_id is not None else -1
        self._keys = self._keys.at[slot].set(request_key(req.seed))

    def _emit(self, req: Request, token: int) -> None:
        req.tokens.append(int(token))
        self.report.record_token(req.request_id)
        hit_eos = req.eos_id is not None and token == req.eos_id
        if hit_eos or len(req.tokens) >= req.max_new_tokens:
            if req.hold:
                self._hold(req)
            else:
                self._retire(req)
        elif req.slot is not None:
            self.cur_tokens[req.slot] = token

    def _retire(self, req: Request, aborted: bool = False) -> None:
        req.state = "aborted" if aborted else "done"
        if req.slot is not None:
            self.free_slots.append(req.slot)
            self.active.pop(req.slot, None)
            self.prefilling.pop(req.slot, None)
            self.held.pop(req.slot, None)
            req.slot = None
        self.report.record_retire(req.request_id, aborted=aborted)

    def _hold(self, req: Request) -> None:
        """Terminal-by-budget request parks in 'held' instead of
        retiring: the slot stays bound (its KV rows, cursor, and PRNG
        key intact) until ``export_handoff`` + ``release_held`` — the
        prefill side of the disaggregated fleet (fleet/pools.py). The
        conveyor defers the release until the handoff TRANSPORT reports
        a terminal status, so a slot may stay held across many engine
        steps while its bytes are in flight — ``export_handoff`` is a
        pure read precisely so that window is harmless."""
        req.state = "held"
        self.active.pop(req.slot, None)
        self.prefilling.pop(req.slot, None)
        self.held[req.slot] = req

    def release_held(self, req: Request, aborted: bool = False) -> None:
        """Free a held request's slot (after ``export_handoff`` reached
        a terminal outcome — adopted by a peer, or abandoned)."""
        if req.state != "held" or self.held.get(req.slot) is not req:
            raise ValueError(
                f"request {req.request_id} is not held by this engine")
        self._retire(req, aborted=aborted)

    def abort_held(self, req: Request) -> None:
        """Release a held slot whose handoff could NOT be delivered
        (transport attempt budget exhausted): the slot frees cleanly,
        the retire is counted as an abort, and the receiver's clean
        re-prefill owns the stream from here — this engine must not
        keep decoding it."""
        self.release_held(req, aborted=True)

    def export_handoff(self, req: Request) -> dict:
        """Package a HELD request's device state for a decode replica:
        per-block KV rows up to the real fill level, the cursor, the
        post-sampling PRNG key row, the emitted tokens, and the sampling
        knobs. ``fleet/handoff.py`` serializes this dict to a
        manifest-versioned wire blob; raw-format round-trips are
        bitwise, so the importing engine continues the exact stream."""
        if req.state != "held" or self.held.get(req.slot) is not req:
            raise ValueError(
                f"request {req.request_id} is not held by this engine")
        slot = req.slot
        # every emitted token except the newest has been written into
        # the cache (the newest is the decode input still in flight)
        fill = int(req.prompt.size + len(req.tokens) - 1)
        return {
            "pages": self.steps.export_slot(slot, fill),
            "cursor": fill,
            "tokens": list(req.tokens),
            "key": np.asarray(self._keys[slot]),
            "prompt_len": int(req.prompt.size),
            "eos_id": req.eos_id,
            "temperature": req.temperature,
            "top_k": req.top_k,
            "seed": req.seed,
            "weights_version": self.weights_version,
        }

    def export_session(self, req: Request) -> dict:
        """Freeze an ACTIVELY DECODING request at its current token
        boundary and package it for another engine — the decode→decode
        generalization of ``export_handoff``. The slot moves to 'held'
        (decode stops advancing it; ``_decode``'s park pins its cursor),
        so the exported KV rows, PRNG key row, and token history are a
        consistent snapshot no matter how many steps run while the
        bytes are in flight. The dict is ``export_handoff``'s plus the
        remaining-budget field ``max_new_tokens``; ``import_session``
        on the adopting engine continues the stream BITWISE (raw wire),
        because the per-slot key already consumed exactly one split per
        sampled token. Terminal outcomes mirror the prefill conveyor:
        ``release_held`` after the peer adopts, ``abort_held`` if the
        transport gives up (the stream then replays from seed), or
        ``resume_session`` to keep decoding here."""
        if req.state == "held" and self.held.get(req.slot) is req:
            raise ValueError(
                f"request {req.request_id} is a held prefill-handoff "
                "slot — migrate it with export_handoff (the "
                "prefill→decode conveyor); export_session moves "
                "actively DECODING slots")
        if req.slot is not None and self.prefilling.get(req.slot) is req:
            raise ValueError(
                f"request {req.request_id} is mid-prefill — a "
                "partially written slot cannot migrate; let prefill "
                "finish (first token sampled) or re-queue the request "
                "on the destination")
        if req.slot is None or self.active.get(req.slot) is not req:
            raise ValueError(
                f"request {req.request_id} is not actively decoding on "
                f"this engine (state={req.state!r})")
        self.active.pop(req.slot)
        req.state = "held"
        self.held[req.slot] = req
        out = self.export_handoff(req)
        out["max_new_tokens"] = int(req.max_new_tokens)
        return out

    def resume_session(self, req: Request) -> None:
        """Un-freeze a session ``export_session`` held: the slot's KV
        rows, cursor, key, and sampling rows never moved, so decoding
        continues here exactly where it stopped (the migration was
        abandoned before the destination adopted)."""
        if req.state != "held" or self.held.get(req.slot) is not req:
            raise ValueError(
                f"request {req.request_id} is not held by this engine")
        hit_eos = (req.eos_id is not None and req.tokens
                   and req.tokens[-1] == req.eos_id)
        if hit_eos or len(req.tokens) >= req.max_new_tokens:
            raise ValueError(
                f"request {req.request_id} is terminal (a prefill-hold "
                "park, not a frozen session) — release_held it")
        del self.held[req.slot]
        req.state = "running"
        self.active[req.slot] = req
        self.cur_tokens[req.slot] = req.tokens[-1]

    def import_session(self, session: dict, prompt) -> Request:
        """Adopt a migrated decode session (``export_session``'s dict,
        wire-decoded by ``fleet/handoff.py``). The per-request budget
        travels IN the session — the continued stream stops exactly
        where the unmigrated one would have."""
        if "max_new_tokens" not in session:
            raise ValueError(
                "not a decode-session export (no max_new_tokens) — "
                "prefill handoffs are adopted with import_handoff")
        return self.import_handoff(
            session, prompt,
            max_new_tokens=int(session["max_new_tokens"]))

    def import_handoff(self, handoff: dict, prompt,
                       max_new_tokens: Optional[int] = None) -> Request:
        """Adopt an exported slot: bind a free slot, write the KV rows
        and cursor, restore the PRNG key and sampling rows, and resume
        decoding from the handed-off last token. The resumed stream is
        bitwise-identical to the exporting engine continuing (raw wire
        format) — the disaggregation contract bench.py gates."""
        if not self.free_slots:
            raise RuntimeError("no free slot to import a handoff into")
        hv = handoff.get("weights_version")
        if (hv is not None and self.weights_version is not None
                and hv != self.weights_version):
            raise WeightsVersionSkew(
                f"handoff was minted under weights {hv!r} but this "
                f"engine serves {self.weights_version!r} — refusing "
                "the adoption (fall back to a clean re-prefill / "
                "replay-from-seed so the stream stays one version)")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size != int(handoff["prompt_len"]):
            raise ValueError(
                f"handoff prompt_len {handoff['prompt_len']} does not "
                f"match the supplied prompt ({prompt.size})")
        if not handoff["tokens"]:
            raise ValueError("handoff carries no sampled token")
        req = Request(
            request_id=next(self._ids), prompt=prompt,
            max_new_tokens=(max_new_tokens if max_new_tokens is not None
                            else self.config.max_new_tokens),
            eos_id=handoff["eos_id"], temperature=handoff["temperature"],
            top_k=handoff["top_k"], seed=handoff["seed"],
            tokens=list(handoff["tokens"]), state="running")
        self.report.record_submit(req.request_id)
        slot = self.free_slots.pop(0)
        req.slot = slot
        self._temps[slot] = (req.temperature
                             if req.temperature is not None else 0.0)
        self._topks[slot] = req.top_k if req.top_k is not None else 0
        self._eos[slot] = req.eos_id if req.eos_id is not None else -1
        # the handed-off key CONTINUES the stream (one split consumed
        # per sampled token so far) — never re-derive from the seed
        self._keys = self._keys.at[slot].set(
            jnp.asarray(handoff["key"], jnp.uint32))
        # a wire-decoded handoff from an int8-resident source carries
        # the verbatim codes next to the dequantized pages — an int8
        # destination adopts those bytes directly (zero extra
        # quantization error, fleet/handoff.py)
        pages = handoff["pages"]
        if (self.steps.kv_dtype == "int8-block"
                and handoff.get("pages_q8")):
            pages = handoff["pages_q8"]
        self.steps.import_slot(slot, pages, int(handoff["cursor"]))
        last = req.tokens[-1]
        hit_eos = req.eos_id is not None and last == req.eos_id
        if hit_eos or len(req.tokens) >= req.max_new_tokens:
            self._retire(req)              # already terminal at handoff
        else:
            self.cur_tokens[slot] = last
            self.active[slot] = req
        return req

    def abort_all(self, requeue: bool = False) -> List[Request]:
        """Watchdog-bounded teardown: every in-flight request aborts (or
        requeues for a warm restart) and every queued request drains back
        to the caller. Returns the affected requests."""
        hit = []
        inflight = (list(self.active.values())
                    + list(self.prefilling.values())
                    + list(self.held.values()))
        for req in inflight:
            if requeue:
                req.state = "queued"
                req.tokens = []
                req.prefill_pos = 0
                if req.slot is not None:
                    self.free_slots.append(req.slot)
                    self.active.pop(req.slot, None)
                    self.prefilling.pop(req.slot, None)
                    self.held.pop(req.slot, None)
                    req.slot = None
                self.queue.appendleft(req)
            else:
                self._retire(req, aborted=True)
            hit.append(req)
        if not requeue:
            while self.queue:
                req = self.queue.popleft()
                req.state = "aborted"
                self.report.record_retire(req.request_id, aborted=True)
                hit.append(req)
        return hit

    def swap_weights(self, params, weights_version: Optional[str] = None,
                     *, converted: bool = False):
        """Install new weights on a QUIESCENT engine (the SWAP leg of a
        rolling update — fleet/rollout.py). Refused while any request
        is queued, decoding, prefilling, or held: a mid-stream weight
        change would mix model versions inside one output. Drain first
        (``Router.drain`` migrates live sessions to survivors), swap,
        then readmit. No recompile happens — params are per-call
        arguments to every jitted program (``ServingStep.load_params``).

        Returns ``(old_params, old_version)`` — the previous weights in
        the engine's INTERNAL (already layout-converted) form, so a
        failed rollout can walk this replica back with
        ``swap_weights(old_params, old_version, converted=True)``.
        ``converted=True`` skips the caller-layout conversion for
        exactly that round-trip."""
        if self.queue or self.active or self.prefilling or self.held:
            raise RuntimeError(
                "swap_weights requires a drained engine — "
                f"{len(self.queue)} queued, {len(self.active)} active, "
                f"{len(self.prefilling)} prefilling, "
                f"{len(self.held)} held")
        old_params = self.steps.params
        old_version = self.weights_version
        if converted:
            self.steps.params = params
        else:
            self.steps.load_params(params)
        self.weights_version = weights_version
        return old_params, old_version

    # ----------------------------------------------------------------
    # scheduler iterations
    # ----------------------------------------------------------------

    def _max_decode_advance(self) -> int:
        """Cache columns one decode iteration may write per slot — the
        wrap guard's and token budget's reservation unit. The base
        engine advances ``decode_k``; ``speculative.SpeculativeEngine``
        overrides this with its verify width (``spec_k + 1``)."""
        return self.config.decode_k

    def _on_prefill(self, tokens, lengths, slot_ids) -> None:
        """Subclass hook, fired after every monolithic prefill dispatch
        with the cohort's host-side arrays (sentinel rows included).
        ``SpeculativeEngine`` mirrors the prompts into the draft model's
        pages here; the base engine does nothing."""

    def _on_prefill_chunk(self, tokens, starts, valid, slot_ids,
                          final) -> None:
        """Chunked twin of :meth:`_on_prefill` — fired after every
        chunk dispatch with that dispatch's host-side arrays."""

    def _admit(self, avail: float) -> int:
        """One monolithic prefill cohort: same-bucket FIFO prompts into
        free slots, first token sampled on device."""
        if not self.queue or not self.free_slots:
            return 0
        s = self.config.prefill_cohort
        bucket = self._bucket_for(self.queue[0].prompt.size)
        if (bucket > avail and self.active
                and self._prefill_defer < self.config.max_prefill_defer):
            # over budget: let decode keep the iteration, try again next
            # time (the defer cap bounds prefill starvation)
            self._prefill_defer += 1
            return 0
        self._prefill_defer = 0
        cohort: List[Request] = []
        while (self.queue and self.free_slots and len(cohort) < s
               and self._bucket_for(self.queue[0].prompt.size) == bucket):
            req = self.queue.popleft()
            self._install(req, self.free_slots.pop(0))
            self.active[req.slot] = req
            cohort.append(req)
        tokens = np.zeros((s, bucket), np.int32)
        lengths = np.ones(s, np.int32)          # sentinel rows: length 1
        slot_ids = np.full(s, self.steps.n_slots, np.int32)  # sentinel
        for i, req in enumerate(cohort):
            tokens[i, :req.prompt.size] = req.prompt
            lengths[i] = req.prompt.size
            slot_ids[i] = req.slot
        tok, self._keys = self.steps.prefill_sampled(
            tokens, lengths, slot_ids, self._keys, self._temps,
            self._topks)
        self._on_prefill(tokens, lengths, slot_ids)
        first = np.asarray(tok)                 # [S] int32 — ids, never logits
        self.report.record_host_bytes(first.nbytes)
        for i, req in enumerate(cohort):
            self._emit(req, int(first[i]))
        return len(cohort)

    def _advance_prefill_chunks(self, avail: float) -> int:
        """Chunked prefill scheduling: spend the iteration's leftover
        token budget on fixed-size chunk cohorts — in-flight prefills
        first (oldest request first), fresh admissions filling the rest
        of each cohort. Two overrides beat the budget: the WRAP GUARD
        (a prefilling slot within ``decode_k`` of the page end must
        finish before decode's garbage rows can wrap its cursor over
        real prefix tokens) and the livelock guard (if nothing else can
        make progress this iteration, one cohort runs regardless)."""
        cfg = self.config
        c = cfg.prefill_chunk
        s = cfg.prefill_cohort
        admitted = 0
        spent = 0
        dispatched = False
        while True:
            forced = sorted(
                slot for slot, r in self.prefilling.items()
                if (r.prefill_pos + self._max_decode_advance()
                    > self.steps.capacity))
            if not forced:
                if not (self.prefilling
                        or (self.queue and self.free_slots)):
                    break
                if dispatched and cfg.token_budget is None:
                    break       # unbudgeted: one cohort per iteration
                over = spent + c > avail
                starved = self._prefill_defer >= cfg.max_prefill_defer
                if over and not starved and (self.active or dispatched):
                    self._prefill_defer += 1
                    break
            cohort = [(slot, self.prefilling[slot])
                      for slot in forced[:s]]
            for slot, req in sorted(self.prefilling.items(),
                                    key=lambda kv: kv[1].request_id):
                if len(cohort) >= s:
                    break
                if all(slot != s0 for s0, _ in cohort):
                    cohort.append((slot, req))
            while len(cohort) < s and self.queue and self.free_slots:
                req = self.queue.popleft()
                slot = self.free_slots.pop(0)
                self._install(req, slot)
                self.prefilling[slot] = req
                admitted += 1
                cohort.append((slot, req))
            if not cohort:
                break
            self._prefill_defer = 0
            spent += len(cohort) * c
            self._dispatch_chunk(cohort)
            dispatched = True
        return admitted

    def _dispatch_chunk(self, cohort) -> None:
        """One fixed-shape ``[S, C]`` chunk dispatch; completing rows
        sample their first token on device and move to decode."""
        cfg = self.config
        c = cfg.prefill_chunk
        s = cfg.prefill_cohort
        tokens = np.zeros((s, c), np.int32)
        starts = np.zeros(s, np.int32)
        valid = np.ones(s, np.int32)            # sentinel rows: 1 token
        sids = np.full(s, self.steps.n_slots, np.int32)
        final = np.zeros(s, bool)
        for i, (slot, req) in enumerate(cohort):
            pos = req.prefill_pos
            v = min(c, req.prompt.size - pos)
            tokens[i, :v] = req.prompt[pos:pos + v]
            starts[i] = pos
            valid[i] = v
            sids[i] = slot
            final[i] = pos + v == req.prompt.size
        tok, self._keys = self.steps.prefill_chunk(
            tokens, starts, valid, sids, final, self._keys, self._temps,
            self._topks)
        self._on_prefill_chunk(tokens, starts, valid, sids, final)
        first = np.asarray(tok)                 # [S] int32 ids (-1 = not final)
        self.report.record_host_bytes(first.nbytes)
        for i, (slot, req) in enumerate(cohort):
            req.prefill_pos += int(valid[i])
            if final[i]:
                del self.prefilling[slot]
                self.active[slot] = req
                self._emit(req, int(first[i]))

    def _decode(self) -> int:
        """One ``decode_k`` dispatch for the whole grid; the host pulls
        a single ``[n_slots, k]`` int32 array (validity in-band as -1)
        and replays the device's EOS/budget retirement decisions."""
        cfg = self.config
        n = cfg.n_slots
        live = np.zeros(n, bool)
        remaining = np.ones(n, np.int32)
        for slot, req in self.active.items():
            live[slot] = True
            remaining[slot] = req.max_new_tokens - len(req.tokens)
        park = np.zeros(n, np.int32)
        for slot, req in self.prefilling.items():
            park[slot] = req.prefill_pos
        for slot, req in self.held.items():
            # a held slot's rows await export: pin its cursor to the
            # real fill so the ride-along garbage steps can't wrap it
            park[slot] = req.prompt.size + len(req.tokens) - 1
        toks_dev, self._keys = self.steps.decode_k(
            self.cur_tokens, self._keys, self._temps, self._topks,
            self._eos, remaining, live, park, cfg.decode_k)
        toks = np.asarray(toks_dev)             # [n, k] int32 — the ONLY
        #                                         per-token host transfer
        self.report.record_host_bytes(toks.nbytes)
        emitted = 0
        for slot, req in list(self.active.items()):
            for j in range(cfg.decode_k):
                t = int(toks[slot, j])
                if t < 0:
                    break
                self._emit(req, t)
                emitted += 1
                if req.finished:
                    break
        return emitted

    def step(self) -> dict:
        """One scheduler iteration: chaos hook → token budget → prefill
        (chunked or monolithic) → decode_k → retirement. Returns
        counters for the caller's loop policy."""
        chaos.on_step(self.iteration)
        self.iteration += 1
        budget = self.config.token_budget
        avail = (float("inf") if budget is None
                 else budget - len(self.active) * self._max_decode_advance())
        if self.config.prefill_chunk is not None:
            admitted = self._advance_prefill_chunks(avail)
        else:
            admitted = self._admit(avail)
        emitted = self._decode() if self.active else 0
        self.report.record_step(
            len(self.queue),
            (len(self.active) + len(self.prefilling)) / self.config.n_slots)
        return {"admitted": admitted, "emitted": emitted,
                "active": len(self.active), "queued": len(self.queue)}

    def idle(self) -> bool:
        return not self.queue and not self.active and not self.prefilling

    def run_until_drained(self, max_steps: int = 100_000) -> int:
        """Step until no queued or active work remains; returns the
        number of iterations taken."""
        n = 0
        while not self.idle():
            if n >= max_steps:
                raise RuntimeError(
                    f"engine failed to drain within {max_steps} steps")
            # step() syncs internally: one [n_slots, k] int32 pull
            self.step()  # dlint: disable=DL104
            n += 1
        return n

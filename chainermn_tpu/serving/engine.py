"""Continuous-batching engine: iteration-level scheduling over the
paged KV cache.

The engine owns a fixed grid of ``n_slots`` decode slots. Every
``step()`` is one scheduler iteration:

1. **Admission** — if slots are free and requests are queued, one
   prefill cohort runs: up to ``prefill_cohort`` same-bucket prompts,
   right-padded to the bucket length, scattered into free slots
   (sentinel rows fill the cohort — fixed shapes, so the compile count
   is bounded by the bucket table, never by traffic).
2. **Decode** — ONE ``[n_slots]`` decode step advances every live slot
   together. Free slots ride along as garbage rows; row independence
   keeps them from touching live logits (tested bitwise).
3. **Retirement** — slots whose request sampled ``eos_id`` or reached
   its token budget are freed for the next admission.

Prefill and decode therefore co-exist without recompilation — the
DL108 invariant: after warmup, serving any traffic mix executes exactly
one compiled decode program plus one compiled prefill program per
bucket. ``resilience/chaos.py::on_step`` fires at the top of every
iteration, so ``$CHAINERMN_TPU_CHAOS='kill@step=N'`` kills a replica
mid-decode — the supervisor drill in tests/serving_tests.
"""

from __future__ import annotations

import dataclasses
import itertools
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from chainermn_tpu.resilience import chaos
from chainermn_tpu.serving.kv_cache import ServingStep
from chainermn_tpu.serving.reports import ServingReport

__all__ = ["Engine", "EngineConfig", "Request", "default_buckets"]


def default_buckets(capacity: int, lo: int = 8) -> Tuple[int, ...]:
    """Power-of-two bucket table up to the page capacity: every prompt
    compiles against one of O(log capacity) prefill shapes."""
    out = []
    b = lo
    while b < capacity:
        out.append(b)
        b *= 2
    out.append(capacity)
    return tuple(out)


@dataclasses.dataclass
class EngineConfig:
    n_slots: int = 4
    capacity: int = 256
    max_new_tokens: int = 64          # default per-request budget
    prefill_cohort: int = 2           # S — cohort width (fixed shape)
    buckets: Optional[Sequence[int]] = None  # None → default_buckets()
    cache_dtype: object = None

    def bucket_table(self) -> Tuple[int, ...]:
        return (tuple(sorted(self.buckets)) if self.buckets
                else default_buckets(self.capacity))


@dataclasses.dataclass(eq=False)   # identity semantics (prompt is an array)
class Request:
    """One generation stream. ``tokens`` grows as the engine emits;
    terminal states are 'done' (eos or budget) and 'aborted'."""
    request_id: int
    prompt: np.ndarray                # int32 [L]
    max_new_tokens: int
    eos_id: Optional[int] = None
    temperature: Optional[float] = None   # None → greedy argmax
    seed: int = 0
    tokens: List[int] = dataclasses.field(default_factory=list)
    state: str = "queued"             # queued|running|done|aborted
    slot: Optional[int] = None
    _rng: Optional[np.random.Generator] = None

    def sample(self, logits: np.ndarray) -> int:
        if self.temperature is None:
            # first-index ties, same rule as jnp.argmax — greedy engine
            # streams match serial generate() token for token
            return int(np.argmax(logits))
        if self._rng is None:
            self._rng = np.random.default_rng(self.seed)
        z = logits.astype(np.float64) / max(self.temperature, 1e-6)
        z -= z.max()
        p = np.exp(z)
        return int(self._rng.choice(logits.shape[0], p=p / p.sum()))

    @property
    def finished(self) -> bool:
        return self.state in ("done", "aborted")


class Engine:
    """Single-threaded scheduler core (the thread-safe face is
    ``frontend.Frontend``). ``submit()`` queues, ``step()`` advances one
    iteration, ``run_until_drained()`` loops until idle."""

    def __init__(self, model, params, config: EngineConfig = EngineConfig(),
                 *, mesh=None, axis=None, report: Optional[ServingReport] = None,
                 time_fn=None):
        self.config = config
        self.steps = ServingStep(
            model, params, config.n_slots, config.capacity,
            cache_dtype=config.cache_dtype, mesh=mesh, axis=axis)
        self.report = report or (ServingReport(time_fn) if time_fn
                                 else ServingReport())
        self.queue: deque[Request] = deque()
        self.active: Dict[int, Request] = {}          # slot → request
        self.free_slots: List[int] = list(range(config.n_slots))
        self.cur_tokens = np.zeros(config.n_slots, np.int32)
        self.last_logits: Optional[np.ndarray] = None  # debug/parity hook
        self.iteration = 0
        self._ids = itertools.count()
        self._buckets = config.bucket_table()
        if self._buckets[-1] < config.capacity:
            raise ValueError("largest bucket must reach capacity")

    # ----------------------------------------------------------------
    # request lifecycle
    # ----------------------------------------------------------------

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_id: Optional[int] = None,
               temperature: Optional[float] = None,
               seed: int = 0) -> Request:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if prompt.size > self._buckets[-1]:
            raise ValueError(
                f"prompt length {prompt.size} exceeds the largest prefill "
                f"bucket ({self._buckets[-1]})")
        req = Request(request_id=next(self._ids), prompt=prompt,
                      max_new_tokens=(max_new_tokens
                                      if max_new_tokens is not None
                                      else self.config.max_new_tokens),
                      eos_id=eos_id, temperature=temperature, seed=seed)
        self.queue.append(req)
        self.report.record_submit(req.request_id)
        return req

    def _bucket_for(self, length: int) -> int:
        for b in self._buckets:
            if b >= length:
                return b
        raise ValueError(f"no bucket covers prompt length {length}")

    def _emit(self, req: Request, token: int) -> None:
        req.tokens.append(int(token))
        self.report.record_token(req.request_id)
        hit_eos = req.eos_id is not None and token == req.eos_id
        if hit_eos or len(req.tokens) >= req.max_new_tokens:
            self._retire(req)
        elif req.slot is not None:
            self.cur_tokens[req.slot] = token

    def _retire(self, req: Request, aborted: bool = False) -> None:
        req.state = "aborted" if aborted else "done"
        if req.slot is not None:
            self.free_slots.append(req.slot)
            self.active.pop(req.slot, None)
            req.slot = None
        self.report.record_retire(req.request_id, aborted=aborted)

    def abort_all(self, requeue: bool = False) -> List[Request]:
        """Watchdog-bounded teardown: every in-flight request aborts (or
        requeues for a warm restart) and every queued request drains back
        to the caller. Returns the affected requests."""
        hit = []
        for req in list(self.active.values()):
            if requeue:
                req.state = "queued"
                req.tokens = []
                if req.slot is not None:
                    self.free_slots.append(req.slot)
                    self.active.pop(req.slot, None)
                    req.slot = None
                self.queue.appendleft(req)
            else:
                self._retire(req, aborted=True)
            hit.append(req)
        if not requeue:
            while self.queue:
                req = self.queue.popleft()
                req.state = "aborted"
                self.report.record_retire(req.request_id, aborted=True)
                hit.append(req)
        return hit

    # ----------------------------------------------------------------
    # scheduler iterations
    # ----------------------------------------------------------------

    def _admit(self) -> int:
        """One prefill cohort: same-bucket FIFO prompts into free slots."""
        if not self.queue or not self.free_slots:
            return 0
        s = self.config.prefill_cohort
        bucket = self._bucket_for(self.queue[0].prompt.size)
        cohort: List[Request] = []
        while (self.queue and self.free_slots and len(cohort) < s
               and self._bucket_for(self.queue[0].prompt.size) == bucket):
            req = self.queue.popleft()
            req.slot = self.free_slots.pop(0)
            req.state = "running"
            self.active[req.slot] = req
            cohort.append(req)
        tokens = np.zeros((s, bucket), np.int32)
        lengths = np.ones(s, np.int32)          # sentinel rows: length 1
        slot_ids = np.full(s, self.steps.n_slots, np.int32)  # sentinel
        for i, req in enumerate(cohort):
            tokens[i, :req.prompt.size] = req.prompt
            lengths[i] = req.prompt.size
            slot_ids[i] = req.slot
        logits = np.asarray(self.steps.prefill(tokens, lengths, slot_ids))
        for i, req in enumerate(cohort):
            self._emit(req, req.sample(logits[i]))
        return len(cohort)

    def step(self) -> dict:
        """One scheduler iteration: chaos hook → admission → decode →
        retirement. Returns counters for the caller's loop policy."""
        chaos.on_step(self.iteration)
        self.iteration += 1
        admitted = self._admit()
        emitted = 0
        if self.active:
            logits = np.asarray(self.steps.decode(self.cur_tokens))
            self.last_logits = logits
            for slot, req in list(self.active.items()):
                self._emit(req, req.sample(logits[slot]))
                emitted += 1
        self.report.record_step(
            len(self.queue),
            len(self.active) / self.config.n_slots)
        return {"admitted": admitted, "emitted": emitted,
                "active": len(self.active), "queued": len(self.queue)}

    def idle(self) -> bool:
        return not self.queue and not self.active

    def run_until_drained(self, max_steps: int = 100_000) -> int:
        """Step until no queued or active work remains; returns the
        number of iterations taken."""
        n = 0
        while not self.idle():
            if n >= max_steps:
                raise RuntimeError(
                    f"engine failed to drain within {max_steps} steps")
            # step() syncs internally: np.asarray pulls every logit row
            self.step()  # dlint: disable=DL104
            n += 1
        return n

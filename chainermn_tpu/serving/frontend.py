"""Thread-safe serving front end: ``submit(prompt) -> Future``.

One background thread owns the :class:`~chainermn_tpu.serving.engine.
Engine` (the engine itself is single-threaded by design); callers from
any thread enqueue work through a mailbox and block on standard
``concurrent.futures.Future`` objects. Two resilience hooks, both
reused from the training fleet:

* **Deadline-bounded waits** — ``result()`` slices its wait into
  ``RpcPolicy.probe_ms`` probes (the same fail-fast shape as the
  host-plane RPCs in ``comm/object_plane.py``), so a wedged replica is
  noticed in O(probe), not O(timeout). The total budget defaults to
  ``RpcPolicy.timeout_ms``.
* **Watchdog-bounded abortion** — every scheduler iteration polls the
  process watchdog (``resilience/watchdog.py``); on a declared-dead
  peer the engine aborts all in-flight requests and their futures fail
  with ``JobAbortedError`` within one iteration + one probe slice,
  instead of hanging until the client gives up.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Optional

from chainermn_tpu.resilience.policy import RpcPolicy, policy
from chainermn_tpu.resilience.watchdog import current_watchdog

__all__ = ["Frontend", "DeadlineExceeded", "AdmissionRejected"]


class DeadlineExceeded(TimeoutError):
    """The deadline-bounded wait ran out of budget (the replica may
    still be alive — the request is NOT cancelled)."""


class AdmissionRejected(RuntimeError):
    """Queue-depth backpressure: the submission was REFUSED before any
    engine state changed. ``retry_after_ms`` is the server's hint —
    re-submit after that long (the ``RpcPolicy`` backoff base, so a
    polite client and the RPC retry ladder pace identically). Raised by
    ``Frontend.submit`` (one engine, ``max_queue_depth``) and
    ``fleet.Router.submit`` (every live replica over its bound)."""

    def __init__(self, msg: str, retry_after_ms: int):
        super().__init__(msg)
        self.retry_after_ms = int(retry_after_ms)


class Frontend:
    """Wraps an Engine in a mailbox + scheduler thread.

    Use as a context manager; ``close()`` drains the mailbox, stops the
    thread, and aborts whatever is still in flight.
    """

    _IDLE_WAIT_S = 0.005     # mailbox poll while the engine is idle

    def __init__(self, engine, *, rpc_policy: Optional[RpcPolicy] = None,
                 watchdog=None, max_queue_depth: Optional[int] = None):
        self.engine = engine
        self._policy = rpc_policy
        self._watchdog = watchdog
        self.max_queue_depth = max_queue_depth
        self._mail: _queue.Queue = _queue.Queue()
        self._futures = {}           # request_id → Future
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="serving-frontend",
                                        daemon=True)
        self._thread.start()

    # ----------------------------------------------------------------
    # client face (any thread)
    # ----------------------------------------------------------------

    def submit(self, prompt, **kw) -> Future:
        """Queue one generation request; the Future resolves to the
        engine's Request (``.tokens`` holds the emitted ids). Keyword
        arguments pass straight through to ``Engine.submit`` — per-
        request ``max_new_tokens``, ``eos_id``, and the on-device
        sampling knobs ``temperature``/``top_k``/``seed``
        (serving/sampling.py).

        With ``max_queue_depth`` set, a submission that would push the
        backlog (mailbox + engine queue) past the bound raises
        :class:`AdmissionRejected` with a ``retry_after_ms`` hint
        instead of growing an unbounded queue — load sheds at the door,
        not as a timeout ten layers later."""
        if self._stop.is_set():
            raise RuntimeError("frontend is closed")
        if self.max_queue_depth is not None:
            depth = self._mail.qsize() + len(self.engine.queue)
            if depth >= self.max_queue_depth:
                pol = self._policy or policy()
                raise AdmissionRejected(
                    f"queue depth {depth} at the bound "
                    f"({self.max_queue_depth}); retry after "
                    f"{pol.backoff_base_ms} ms",
                    retry_after_ms=pol.backoff_base_ms)
        fut: Future = Future()
        self._mail.put((prompt, kw, fut))
        return fut

    def result(self, future: Future, timeout_ms: Optional[int] = None):
        """Deadline-bounded wait, sliced at ``probe_ms`` for fail-fast:
        a dead scheduler thread or tripped watchdog surfaces on the next
        probe instead of after the full budget."""
        pol = self._policy or policy()
        budget_ms = timeout_ms if timeout_ms is not None else pol.timeout_ms
        deadline = time.monotonic() + budget_ms / 1e3
        while True:
            left = deadline - time.monotonic()
            if left <= 0:
                raise DeadlineExceeded(
                    f"no result within {budget_ms} ms "
                    f"(probe={pol.probe_ms} ms)")
            try:
                return future.result(
                    timeout=min(pol.probe_ms / 1e3, left))
            except FutureTimeout:
                if not self._thread.is_alive() and not future.done():
                    raise RuntimeError(
                        "serving scheduler thread died with the request "
                        "in flight")

    def drain(self, timeout_ms: Optional[int] = None) -> None:
        """Block until the engine has no queued or active work."""
        pol = self._policy or policy()
        budget_ms = timeout_ms if timeout_ms is not None else pol.timeout_ms
        deadline = time.monotonic() + budget_ms / 1e3
        while time.monotonic() < deadline:
            with self._lock:
                if self._mail.empty() and self.engine.idle():
                    return
            time.sleep(self._IDLE_WAIT_S)
        raise DeadlineExceeded(f"engine not drained within {budget_ms} ms")

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=30)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ----------------------------------------------------------------
    # scheduler thread
    # ----------------------------------------------------------------

    def _poll_watchdog(self):
        from chainermn_tpu.comm.object_plane import JobAbortedError

        wd = self._watchdog or current_watchdog()
        if wd is None:
            return
        try:
            wd.check()
        except JobAbortedError as e:
            # bounded abortion: fail EVERYTHING in flight now — clients
            # see the peer loss within one probe slice, never a hang
            with self._lock:
                hit = {r.request_id for r in self.engine.abort_all()}
                for rid in list(self._futures):
                    if rid in hit:
                        fut, _req = self._futures.pop(rid)
                        if not fut.done():
                            fut.set_exception(JobAbortedError(str(e)))

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._poll_watchdog()
            worked = False
            try:
                while True:
                    prompt, kw, fut = self._mail.get_nowait()
                    with self._lock:
                        try:
                            req = self.engine.submit(prompt, **kw)
                            self._futures[req.request_id] = (fut, req)
                        except Exception as e:  # bad request, not fatal
                            fut.set_exception(e)
                    worked = True
            except _queue.Empty:
                pass
            with self._lock:
                if not self.engine.idle():
                    # Engine.step() syncs internally (one [n_slots, k]
                    # int32 token pull — sampling stays on device)
                    self.engine.step()  # dlint: disable=DL104
                    worked = True
                    for rid, (fut, req) in list(self._futures.items()):
                        if req.finished:
                            self._futures.pop(rid)
                            if not fut.done():
                                fut.set_result(req)
            if not worked:
                time.sleep(self._IDLE_WAIT_S)
        # teardown: nothing new is accepted; in-flight work aborts and
        # never-admitted mailbox entries fail too (close() may beat the
        # drain loop to a freshly submitted request)
        with self._lock:
            self.engine.abort_all()
            for rid, (fut, req) in list(self._futures.items()):
                self._futures.pop(rid)
                if not fut.done():
                    fut.set_exception(
                        RuntimeError("frontend closed mid-request"))
            try:
                while True:
                    _prompt, _kw, fut = self._mail.get_nowait()
                    if not fut.done():
                        fut.set_exception(
                            RuntimeError("frontend closed mid-request"))
            except _queue.Empty:
                pass

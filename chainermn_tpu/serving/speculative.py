"""Speculative decoding: a draft model proposes, the target verifies —
tokens-per-dispatch without giving up bitwise streams.

``decode_k`` already amortizes dispatch overhead by committing ``k``
tokens per host round trip, but every token still costs one full
TARGET-model forward. Speculative decoding (ISSUE 20) splits the work:
a small draft ``TransformerLM`` — its own paged KV slots, always f32 —
proposes ``spec_k`` tokens per round, and the target model verifies all
of them in ONE batched chunk forward. Each round is exactly two
dispatches for the whole slot grid:

1. **Propose** (:func:`propose_apply`, one draft program): a width-2
   catch-up chunk writes the tokens the draft cache is missing (the
   current token; plus the previous round's bonus token after a full
   accept), its last-position logits sample the first draft ``d_1``,
   and a ``lax.scan`` of ``spec_k - 1`` draft decode steps samples
   ``d_2 .. d_spec_k``. Sampling uses a SHADOW copy of the target's
   per-slot PRNG rows (:func:`~.sampling.draft_shadow_keys`): the same
   key values, at the same stream positions, the target will use —
   that alignment is what makes sampled-mode acceptance nonzero. The
   shadow is discarded; the drafts never leave the device.

2. **Verify** (:func:`verify_apply`, one target program): a chunked
   forward of ``[cur, d_1 .. d_spec_k]`` at each slot's fill level
   returns per-position logits ``L_0 .. L_spec_k``, where ``L_j`` is
   BITWISE the logits non-speculative decode would compute at that
   stream position (chunked == monolithic == squeezed-q decode — the
   pinned parity chain in models/transformer.py, ``attention=
   'reference'``, no ring wrap). An on-device acceptance scan then
   samples ``s_j`` from ``L_j`` with the REAL key rows — advancing a
   row's key only when it actually emits, the one-split-per-sampled-
   token contract — and emits the longest accepted prefix
   (``s_j == d_{j+1}``) plus one more target-sampled token: the
   CORRECTION on the first mismatch, or the BONUS ``s_spec_k`` after a
   full accept. EOS/budget stop masks mirror ``decode_k_apply``
   exactly. The host pulls ONE ``[n_slots, spec_k + 1]`` int32 array.

Because every emitted token is sampled by the TARGET from bitwise-
oracle logits with the oracle's own key stream, accepted streams are
bitwise-identical to non-speculative decode — greedy and sampled, at
every scheduler shape (tests/serving_tests/test_speculative.py). The
draft only decides how far a round advances (1 to ``spec_k + 1``
tokens), never what gets emitted.

Garbage discipline: rejected-draft K/V beyond the accepted prefix stays
in the target pages, but the next round's verify window starts at the
new fill and rewrites every such column before any mask can read it
(the chunk writes all of its columns before attending, and both
attention masks stop at the query row). Ride-along rows (mid-prefill,
held) ride with their cursors parked at their real fill, exactly like
``decode_k`` — their garbage lands at-or-beyond fill and is clipped at
the page end (the chunk branch drops, never wraps).

No-wrap contract: ``submit`` enforces ``prompt + max_new + spec_k <=
capacity`` — the verify chunk's absolute-position mask (and the parity
chain above) has no ring semantics, and the margin keeps the draft's
own pages from wrapping too.

Host-transfer honesty: a round moves ``4 · (spec_k + 1)`` bytes per
slot for 1..``spec_k + 1`` emitted tokens, so the ≤ 8 bytes/token
decode gate (DL110/bench.py) holds only at healthy acceptance rates;
``ServingReport.acceptance_rate`` / ``tokens_per_dispatch`` are the
observability for exactly that (reports.py).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from chainermn_tpu.models.transformer import bhld_to_blhd_params
from chainermn_tpu.serving.engine import Engine, EngineConfig
from chainermn_tpu.serving.kv_cache import (
    _check_servable,
    decode_apply,
    init_cache,
    prefill_apply,
    prefill_chunk_apply,
    repack_cache,
    unpack_cache,
)
from chainermn_tpu.serving.sampling import draft_shadow_keys, sample_tokens

__all__ = ["DraftStep", "SpeculativeEngine", "propose_apply",
           "verify_apply"]


def propose_apply(dm, dm_chunk, params, cache, prev, cur, valid, starts,
                  keys, temps, top_ks, live, park, spec_k: int):
    """PURE draft proposal for the whole grid: one catch-up chunk + a
    ``spec_k - 1``-step decode scan, fused into one program.

    prev/cur ``[n]`` int32 — the previous round's bonus token (used only
    where ``valid == 2``) and each slot's current token; valid ``[n]``
    (1 normally, 2 after a full accept — the bonus token was proposed
    but never written to the draft pages); starts ``[n]`` = fill -
    (valid - 1); keys ``[n, 2]`` — the TARGET's key rows, shadow-copied
    here and discarded; live/park as in ``decode_k_apply``.

    Returns ``(drafts [n, spec_k] int32 — ON DEVICE, new draft cache)``.
    The draft cache invariant this maintains: between rounds a live
    slot's pages hold exactly the stream positions ``[0, fill)`` — the
    same invariant the target pages keep — so the catch-up never needs
    more than width 2.
    """
    n = cur.shape[0]
    cur = jnp.asarray(cur, jnp.int32)
    prev = jnp.asarray(prev, jnp.int32)
    valid = jnp.asarray(valid, jnp.int32)
    starts = jnp.asarray(starts, jnp.int32)
    live = jnp.asarray(live, bool)
    park = jnp.asarray(park, jnp.int32)
    two = valid == 2
    chunk = jnp.stack([jnp.where(two, prev, cur),
                       jnp.where(two, cur, 0)], axis=1)
    last, cache = prefill_chunk_apply(
        dm_chunk, params, cache, chunk, starts, valid,
        jnp.arange(n, dtype=jnp.int32))
    shadow = draft_shadow_keys(keys)
    d1, shadow = sample_tokens(last, shadow, temps, top_ks)

    def body(carry, _):
        cache, tok, shadow = carry
        logits, cache = decode_apply(dm, params, cache, tok)
        nxt, shadow = sample_tokens(logits, shadow, temps, top_ks)
        return (cache, nxt, shadow), nxt

    (cache, _, _), rest = jax.lax.scan(
        body, (cache, d1, shadow), None, length=spec_k - 1)
    drafts = jnp.concatenate([d1[:, None], rest.T], axis=1)
    # ride-along rows: cursors back to their real fill, like decode_k
    cache = {name: {**page, "idx": jnp.where(live, page["idx"], park)}
             for name, page in cache.items()}
    return drafts, cache


def verify_apply(dm_chunk, params, cache, cur, drafts, keys, temps,
                 top_ks, eos_ids, remaining, live, park, spec_k: int):
    """PURE target verification + acceptance for the whole grid.

    One chunked forward of ``[cur, d_1 .. d_spec_k]`` (width ``spec_k +
    1``) at ``starts = fill`` yields per-position logits; the
    acceptance scan samples ``s_j`` from position ``j`` with the real
    key rows and emits while ``s_j == d_{j+1}``, then one correction or
    bonus token. Key rows advance ONLY on emission — one split per
    sampled token, the same contract as ``decode_k_apply`` — and the
    EOS/budget masks mirror its stop logic token for token.

    Returns ``(emitted [n, spec_k+1] int32 — -1 past each row's stop,
    new keys, new cache)``. Live cursors land at ``fill + emitted``;
    ride-along rows stay parked. ``cache`` must be the f32 view
    (callers unpack/repack int8 pages around this).
    """
    n = cur.shape[0]
    w = spec_k + 1
    cur = jnp.asarray(cur, jnp.int32)
    drafts = jnp.asarray(drafts, jnp.int32)
    live = jnp.asarray(live, bool)
    park = jnp.asarray(park, jnp.int32)
    eos_ids = jnp.asarray(eos_ids, jnp.int32)
    remaining = jnp.asarray(remaining, jnp.int32)
    temps = jnp.asarray(temps, jnp.float32)
    top_ks = jnp.asarray(top_ks, jnp.int32)
    start = jnp.where(live, cache["block_0"]["idx"], park)
    chunk = jnp.concatenate([cur[:, None], drafts], axis=1)
    # the pages ARE the batch (every slot rides along), so unlike
    # prefill_chunk_apply no gather/scatter detour is needed: the chunk
    # branch writes each column at its absolute position (clip-drop at
    # the page end) before attention reads it
    sub = {name: {"k": page["k"], "v": page["v"], "idx": start}
           for name, page in cache.items()}
    logits, upd = dm_chunk.apply(
        {"params": params, "cache": sub}, chunk, pos_offset=start,
        mutable=["cache"])
    new_cache = upd["cache"]

    lo = jnp.moveaxis(logits, 1, 0)                       # [w, n, vocab]
    nxt_draft = jnp.concatenate(
        [drafts, jnp.full((n, 1), -1, jnp.int32)], axis=1)
    dn = nxt_draft.T                                      # [w, n]: d_{j+1}
    is_bonus = jnp.arange(w) == w - 1

    def body(carry, xs):
        keys, rem, alive, accepting, m = carry
        lj, dj, bonus = xs
        s, keys2 = sample_tokens(lj, keys, temps, top_ks)
        emit = accepting & alive
        # only emitting rows consume a split — the key stream position
        # stays a pure function of tokens sampled, as everywhere else
        keys = jnp.where(emit[:, None], keys2, keys)
        rem = rem - emit.astype(jnp.int32)
        hit_eos = (s == eos_ids) & (eos_ids >= 0)
        alive = alive & ~(emit & (hit_eos | (rem <= 0)))
        accepting = accepting & alive & ~bonus & (s == dj)
        out = jnp.where(emit, s, jnp.int32(-1))
        return (keys, rem, alive, accepting,
                m + emit.astype(jnp.int32)), out

    init = (keys, remaining, live, live, jnp.zeros((n,), jnp.int32))
    (keys, _, _, _, m), outs = jax.lax.scan(body, init, (lo, dn, is_bonus))
    emitted = outs.T
    idx = jnp.where(live, start + m, park)
    new_cache = {name: {**page, "idx": idx}
                 for name, page in new_cache.items()}
    return emitted, keys, new_cache


class DraftStep:
    """The draft model's compiled programs + paged cache (always f32 —
    the draft's logits only pick how far a round advances, so its pages
    never justify quantization complexity). Mirrors the target's
    admission writes (:meth:`mirror_prefill` / :meth:`mirror_chunk`,
    logits discarded) and runs the fused proposal (:meth:`propose`).
    One compiled program per shape, counted — the DL108 discipline."""

    def __init__(self, model, params, n_slots: int, capacity: int, *,
                 donate: bool = True):
        _check_servable(model)
        self.src_model = model
        if model.qkv_layout == "bhld":
            params = bhld_to_blhd_params(model, params)
            model = model.clone(qkv_layout="blhd")
        self.model = model
        self.dm = model.clone(decode=True)
        self.dm_chunk = self.dm.clone(chunked_prefill=True)
        self.params = params
        self.n_slots = int(n_slots)
        self.capacity = int(capacity)
        self.cache = init_cache(model, n_slots, capacity)
        self.propose_traces = 0
        self.mirror_traces: Dict[tuple, int] = {}
        self._mirror_jits: Dict[tuple, Any] = {}
        self._propose_jits: Dict[int, Any] = {}
        self._donate = (1,) if donate else ()

    def mirror_prefill(self, tokens, lengths, slot_ids) -> None:
        """Write a monolithic prefill cohort's prompts into the draft
        pages (same slab/scatter as the target's prefill; the draft's
        first-token logits are discarded — the target samples)."""
        tokens = jnp.asarray(tokens, jnp.int32)
        key = ("prefill",) + tokens.shape
        if key not in self._mirror_jits:
            def _mp(params, cache, tokens, lengths, slot_ids, _key=key):
                self.mirror_traces[_key] = (
                    self.mirror_traces.get(_key, 0) + 1)
                _, cache = prefill_apply(self.dm, params, cache, tokens,
                                         lengths, slot_ids)
                return cache

            self._mirror_jits[key] = jax.jit(
                _mp, donate_argnums=self._donate)
        self.cache = self._mirror_jits[key](
            self.params, self.cache, tokens,
            jnp.asarray(lengths, jnp.int32),
            jnp.asarray(slot_ids, jnp.int32))

    def mirror_chunk(self, tokens, starts, valid, slot_ids) -> None:
        """Chunked twin of :meth:`mirror_prefill`."""
        tokens = jnp.asarray(tokens, jnp.int32)
        key = ("chunk",) + tokens.shape
        if key not in self._mirror_jits:
            def _mc(params, cache, tokens, starts, valid, slot_ids,
                    _key=key):
                self.mirror_traces[_key] = (
                    self.mirror_traces.get(_key, 0) + 1)
                _, cache = prefill_chunk_apply(
                    self.dm_chunk, params, cache, tokens, starts, valid,
                    slot_ids)
                return cache

            self._mirror_jits[key] = jax.jit(
                _mc, donate_argnums=self._donate)
        self.cache = self._mirror_jits[key](
            self.params, self.cache, tokens,
            jnp.asarray(starts, jnp.int32),
            jnp.asarray(valid, jnp.int32),
            jnp.asarray(slot_ids, jnp.int32))

    def propose(self, prev, cur, valid, starts, keys, temps, top_ks,
                live, park, spec_k: int):
        """One fused proposal dispatch (see :func:`propose_apply`);
        compiled once per ``spec_k``, counted in ``propose_traces``.
        Returns drafts ``[n, spec_k]`` ON DEVICE."""
        kk = int(spec_k)
        if kk not in self._propose_jits:
            def _pp(params, cache, prev, cur, valid, starts, keys,
                    temps, top_ks, live, park, _k=kk):
                self.propose_traces += 1    # trace-time only
                return propose_apply(self.dm, self.dm_chunk, params,
                                     cache, prev, cur, valid, starts,
                                     keys, temps, top_ks, live, park,
                                     _k)

            self._propose_jits[kk] = jax.jit(
                _pp, donate_argnums=self._donate)
        drafts, self.cache = self._propose_jits[kk](
            self.params, self.cache, jnp.asarray(prev, jnp.int32),
            jnp.asarray(cur, jnp.int32), jnp.asarray(valid, jnp.int32),
            jnp.asarray(starts, jnp.int32), keys,
            jnp.asarray(temps, jnp.float32),
            jnp.asarray(top_ks, jnp.int32),
            jnp.asarray(live, bool), jnp.asarray(park, jnp.int32))
        return drafts

    def load_params(self, params) -> None:
        """Swap draft weights (rolling update of the draft/target pair;
        same conversion contract as ``ServingStep.load_params``)."""
        if self.src_model.qkv_layout == "bhld":
            params = bhld_to_blhd_params(self.src_model, params)
        self.params = params

    def reset(self) -> None:
        self.cache = init_cache(self.model, self.n_slots, self.capacity)


class SpeculativeEngine(Engine):
    """The continuous-batching engine with speculative rounds replacing
    ``decode_k`` dispatches. Scheduling, admission, chunked prefill,
    token budgets, holds, and exports are all inherited — a round
    reserves ``spec_k + 1`` cache columns per slot
    (:meth:`_max_decode_advance`), and the admission hooks mirror every
    prompt write into the draft pages so the draft is always exactly
    one token behind the target.

    ``cfg.decode_k`` is ignored: the verify width is ``spec_k + 1``.
    Works with f32 or int8-block target pages (``cfg.kv_dtype``); the
    draft pages are always f32. Exports (handoff/session) read target
    state only, so a speculative replica hands off to any engine;
    imports mirror the adopted prefix into the draft pages in fixed-
    width chunks before the next round."""

    #: draft-prefix mirror chunk width for imports when the engine
    #: isn't running chunked prefill (one compiled mirror shape)
    _IMPORT_MIRROR_CHUNK = 32

    def __init__(self, model, params, draft_model, draft_params,
                 config: EngineConfig = EngineConfig(), *,
                 spec_k: int = 4, report=None, time_fn=None,
                 weights_version: Optional[str] = None):
        if spec_k < 1:
            raise ValueError("spec_k must be >= 1")
        super().__init__(model, params, config, report=report,
                         time_fn=time_fn, weights_version=weights_version)
        if draft_model.vocab != model.vocab:
            raise ValueError(
                f"draft vocab {draft_model.vocab} != target vocab "
                f"{model.vocab} — proposals would not be sampleable "
                "by the target")
        self.spec_k = int(spec_k)
        self.draft = DraftStep(draft_model, draft_params,
                               config.n_slots, config.capacity)
        n = config.n_slots
        # full-accept bookkeeping: after a round that emitted
        # spec_k + 1 tokens, the bonus token was never written to the
        # draft pages — the next catch-up chunk is width 2
        self._spec_full = np.zeros(n, bool)
        self._spec_prev = np.zeros(n, np.int32)
        self.verify_traces = 0
        self._verify_jit = None

    # -- scheduling integration ---------------------------------------

    def _max_decode_advance(self) -> int:
        return self.spec_k + 1

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               **kwargs):
        prompt_arr = np.asarray(prompt, np.int32).reshape(-1)
        budget = (max_new_tokens if max_new_tokens is not None
                  else self.config.max_new_tokens)
        if prompt_arr.size + budget + self.spec_k > self.config.capacity:
            raise ValueError(
                "speculative decode forbids ring wrap: prompt "
                f"({prompt_arr.size}) + max_new_tokens ({budget}) + "
                f"spec_k ({self.spec_k}) exceeds the page capacity "
                f"({self.config.capacity}) — the verify chunk and the "
                "draft pages both need the absolute-position no-wrap "
                "margin")
        return super().submit(prompt, max_new_tokens, **kwargs)

    def _install(self, req, slot: int) -> None:
        super()._install(req, slot)
        self._spec_full[slot] = False
        self._spec_prev[slot] = 0

    def _on_prefill(self, tokens, lengths, slot_ids) -> None:
        self.draft.mirror_prefill(tokens, lengths, slot_ids)

    def _on_prefill_chunk(self, tokens, starts, valid, slot_ids,
                          final) -> None:
        self.draft.mirror_chunk(tokens, starts, valid, slot_ids)

    def import_handoff(self, handoff: dict, prompt,
                       max_new_tokens: Optional[int] = None):
        req = super().import_handoff(handoff, prompt,
                                     max_new_tokens=max_new_tokens)
        if req.slot is not None:    # terminal handoffs retired already
            if (req.prompt.size + req.max_new_tokens + self.spec_k
                    > self.config.capacity):
                self._retire(req, aborted=True)
                raise ValueError(
                    "adopted session does not fit the speculative "
                    f"no-wrap margin (prompt {req.prompt.size} + budget "
                    f"{req.max_new_tokens} + spec_k {self.spec_k} > "
                    f"capacity {self.config.capacity})")
            # the draft pages must hold the adopted stream's positions
            # [0, fill) before the next round's catch-up
            prefix = np.concatenate(
                [req.prompt, np.asarray(req.tokens[:-1], np.int32)])
            self._mirror_prefix(req.slot, prefix)
            self._spec_full[req.slot] = False
            self._spec_prev[req.slot] = 0
        return req

    def _mirror_prefix(self, slot: int, prefix: np.ndarray) -> None:
        c = self.config.prefill_chunk or min(self._IMPORT_MIRROR_CHUNK,
                                             self.config.capacity)
        pos = 0
        while pos < prefix.size:
            v = int(min(c, prefix.size - pos))
            tokens = np.zeros((1, c), np.int32)
            tokens[0, :v] = prefix[pos:pos + v]
            self.draft.mirror_chunk(
                tokens, np.array([pos], np.int32),
                np.array([v], np.int32), np.array([slot], np.int32))
            pos += v

    # -- the speculative round ----------------------------------------

    def _verify(self, cur, drafts, remaining, live, park):
        if self._verify_jit is None:
            steps = self.steps
            w = self.spec_k + 1

            def _vf(params, cache, cur, drafts, keys, temps, top_ks,
                    eos, rem, live, park):
                self.verify_traces += 1     # trace-time only
                f32c = unpack_cache(cache)
                start = jnp.where(jnp.asarray(live, bool),
                                  f32c["block_0"]["idx"],
                                  jnp.asarray(park, jnp.int32))
                emitted, keys, f32c = verify_apply(
                    steps.dm_chunk, params, f32c, cur, drafts, keys,
                    temps, top_ks, eos, rem, live, park, self.spec_k)
                # the chunk branch clip-DROPS columns past the page end
                # (never wraps), so the int8 commit window clips too
                count = jnp.clip(steps.capacity - start, 0, w)
                return emitted, keys, repack_cache(cache, f32c, start,
                                                   count)

            self._verify_jit = jax.jit(_vf, donate_argnums=(1,))
        emitted, keys, self.steps.cache = self._verify_jit(
            self.steps.params, self.steps.cache,
            jnp.asarray(cur, jnp.int32), drafts, self._keys,
            jnp.asarray(self._temps, jnp.float32),
            jnp.asarray(self._topks, jnp.int32),
            jnp.asarray(self._eos, jnp.int32),
            jnp.asarray(remaining, jnp.int32),
            jnp.asarray(live, bool), jnp.asarray(park, jnp.int32))
        return emitted, keys

    def _decode(self) -> int:
        """One speculative ROUND for the whole grid (propose + verify,
        two dispatches) in place of the base engine's one ``decode_k``
        dispatch; the host pulls a single ``[n_slots, spec_k + 1]``
        int32 array and replays the device's emissions."""
        cfg = self.config
        n = cfg.n_slots
        w = self.spec_k + 1
        live = np.zeros(n, bool)
        remaining = np.ones(n, np.int32)
        fills = np.zeros(n, np.int32)
        for slot, req in self.active.items():
            live[slot] = True
            remaining[slot] = req.max_new_tokens - len(req.tokens)
            fills[slot] = req.prompt.size + len(req.tokens) - 1
        park = np.zeros(n, np.int32)
        for slot, req in self.prefilling.items():
            park[slot] = req.prefill_pos
        for slot, req in self.held.items():
            park[slot] = req.prompt.size + len(req.tokens) - 1
        valid = np.where(live & self._spec_full, 2, 1).astype(np.int32)
        starts = np.where(live, fills - (valid - 1), park)
        drafts = self.draft.propose(
            self._spec_prev, self.cur_tokens, valid, starts, self._keys,
            self._temps, self._topks, live, park, self.spec_k)
        emitted_dev, self._keys = self._verify(
            self.cur_tokens, drafts, remaining, live, park)
        toks = np.asarray(emitted_dev)      # [n, spec_k+1] int32 — the
        #                                     round's ONLY host pull
        self.report.record_host_bytes(toks.nbytes)
        emitted = 0
        for slot, req in list(self.active.items()):
            m = 0
            for j in range(w):
                t = int(toks[slot, j])
                if t < 0:
                    break
                self._emit(req, t)
                m += 1
                emitted += 1
                if req.finished:
                    break
            # the round's last token is always target-sampled
            # (correction, bonus, or terminal) → accepted = m - 1
            self.report.record_spec_round(self.spec_k, max(m - 1, 0), m)
            self._spec_full[slot] = m == w
            if m == w:
                self._spec_prev[slot] = int(toks[slot, w - 2])
        return emitted

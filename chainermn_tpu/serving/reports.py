"""ServingReport — the inference-side sibling of ``training/reports.py``.

The training reports observe a step loop; this one observes a request
lifecycle: admission → first token (TTFT) → per-token cadence →
retirement, plus the scheduler-level signals (queue depth, slot
occupancy) that tell an operator whether the fleet is sized right.

Everything is recorded as plain floats against an injectable clock
(``time_fn``) so tests drive it deterministically; ``summary()`` folds
the raw samples into the JSON block ``tools/bench_serve.py`` and the
``bench.py`` serving section emit. Field reference: docs/serving.md.
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional

__all__ = ["ServingReport", "ReceivedServingReport", "percentile",
           "REPORT_WIRE_VERSION"]

#: version tag on every serialized report envelope — bump on any change
#: to the ``raw()`` schema so a mixed-version fleet fails loudly instead
#: of merging mis-shaped telemetry
#: (1 → 2: speculative-decoding counters — draft_tokens_proposed/
#: accepted, spec_dispatches, spec_tokens_emitted)
REPORT_WIRE_VERSION = 2


def percentile(samples: List[float], q: float) -> float:
    """Nearest-rank percentile (no numpy dependency at import time; the
    sample counts here never justify interpolation)."""
    if not samples:
        return float("nan")
    xs = sorted(samples)
    k = max(0, min(len(xs) - 1, int(round(q / 100.0 * (len(xs) - 1)))))
    return float(xs[k])


class ServingReport:
    """Aggregates one serving process's request/scheduler telemetry.

    Engine calls the ``record_*`` hooks; ``summary()`` is cheap enough
    to call per scrape. All latencies are reported in milliseconds,
    throughput in tokens/s over the observed wall span.
    """

    PERCENTILES = (50, 90, 95, 99)

    def __init__(self, time_fn=time.monotonic):
        self._time = time_fn
        self._t0: Optional[float] = None
        self._t_last: Optional[float] = None
        self.submitted = 0
        self.completed = 0
        self.aborted = 0
        self.tokens_emitted = 0
        self.host_bytes = 0           # device→host bytes on the emit path
        # speculative decoding (serving/speculative.py): per-slot round
        # counters — acceptance_rate and tokens_per_dispatch in summary()
        self.draft_tokens_proposed = 0
        self.draft_tokens_accepted = 0
        self.spec_dispatches = 0      # one per (slot, round) pair
        self.spec_tokens_emitted = 0
        self.ttft_s: List[float] = []
        self.token_gap_s: List[float] = []
        self.queue_depth_samples: List[int] = []
        self.occupancy_samples: List[float] = []
        self._last_token_t: Dict[int, float] = {}
        self._submit_t: Dict[int, float] = {}

    # ----------------------------------------------------------------
    # engine hooks
    # ----------------------------------------------------------------

    def record_submit(self, request_id: int) -> None:
        now = self._time()
        if self._t0 is None:
            self._t0 = now
        self._t_last = now
        self.submitted += 1
        self._submit_t[request_id] = now

    def record_token(self, request_id: int) -> None:
        now = self._time()
        self._t_last = now
        self.tokens_emitted += 1
        prev = self._last_token_t.get(request_id)
        if prev is None:
            sub = self._submit_t.get(request_id)
            if sub is not None:
                self.ttft_s.append(now - sub)
        else:
            self.token_gap_s.append(now - prev)
        self._last_token_t[request_id] = now

    def record_retire(self, request_id: int, aborted: bool = False) -> None:
        self._t_last = self._time()
        if aborted:
            self.aborted += 1
        else:
            self.completed += 1
        self._last_token_t.pop(request_id, None)
        self._submit_t.pop(request_id, None)

    def record_step(self, queue_depth: int, occupancy: float) -> None:
        self.queue_depth_samples.append(int(queue_depth))
        self.occupancy_samples.append(float(occupancy))

    def record_host_bytes(self, nbytes: int) -> None:
        """Device→host transfer on the token-emit path (the engine calls
        this per dispatch with the pulled array's ``nbytes``). With
        on-device sampling this is int32 token ids only — the
        ``host_bytes_per_token`` summary key is the observable DL110
        exists to keep small (bench.py gates decode traffic at
        ≤ 8 bytes/token; the old full-logits pull was ``vocab × 4``)."""
        self.host_bytes += int(nbytes)

    def record_spec_round(self, proposed: int, accepted: int,
                          emitted: int) -> None:
        """One speculative round for ONE slot (the engine calls this per
        live slot per propose+verify round): ``proposed`` draft tokens
        went into the verify chunk, ``accepted`` matched the target's
        own samples, and ``emitted`` tokens entered the stream
        (``accepted + 1`` normally — the round's last token is always
        target-sampled: correction, bonus, or terminal). The ratios an
        operator sizes the draft model by — ``acceptance_rate`` and
        ``tokens_per_dispatch`` — fold out of these in ``summary()``."""
        self.draft_tokens_proposed += int(proposed)
        self.draft_tokens_accepted += int(accepted)
        self.spec_dispatches += 1
        self.spec_tokens_emitted += int(emitted)

    # ----------------------------------------------------------------
    # output
    # ----------------------------------------------------------------

    def raw(self) -> dict:
        """The UNREDUCED telemetry: raw sample lists + counters + the
        observed wall span. This is the only honest input to cross-
        replica aggregation — ``fleet.FleetReport.merge`` pools these
        and takes percentiles over the pooled samples, because a mean of
        per-replica p99s is not a fleet p99 (and a mean of per-replica
        ``host_bytes_per_token`` ratios mis-weights unequal replicas)."""
        span = ((self._t_last - self._t0)
                if self._t0 is not None and self._t_last is not None
                else 0.0)
        return {
            "ttft_s": list(self.ttft_s),
            "token_gap_s": list(self.token_gap_s),
            "queue_depth_samples": list(self.queue_depth_samples),
            "occupancy_samples": list(self.occupancy_samples),
            "submitted": self.submitted,
            "completed": self.completed,
            "aborted": self.aborted,
            "tokens_emitted": self.tokens_emitted,
            "host_bytes": self.host_bytes,
            "draft_tokens_proposed": self.draft_tokens_proposed,
            "draft_tokens_accepted": self.draft_tokens_accepted,
            "spec_dispatches": self.spec_dispatches,
            "spec_tokens_emitted": self.spec_tokens_emitted,
            "wall_s": span,
        }

    def _dist_ms(self, samples: List[float]) -> Dict[str, float]:
        out = {f"p{q}": percentile(samples, q) * 1e3
               for q in self.PERCENTILES}
        out["mean"] = (sum(samples) / len(samples) * 1e3 if samples
                       else float("nan"))
        out["n"] = len(samples)
        return out

    def summary(self) -> dict:
        span = ((self._t_last - self._t0)
                if self._t0 is not None and self._t_last is not None
                else 0.0)
        occ = self.occupancy_samples
        qd = self.queue_depth_samples
        return {
            "requests": {"submitted": self.submitted,
                         "completed": self.completed,
                         "aborted": self.aborted},
            "tokens_emitted": self.tokens_emitted,
            "tokens_per_s": (self.tokens_emitted / span if span > 0
                             else float("nan")),
            "host_bytes_per_token": (self.host_bytes / self.tokens_emitted
                                     if self.tokens_emitted
                                     else float("nan")),
            # speculative decoding: fraction of draft proposals the
            # target's own samples confirmed, and how many tokens a
            # (slot, round) pair advances — > 1 is the whole point
            "acceptance_rate": (self.draft_tokens_accepted
                                / self.draft_tokens_proposed
                                if self.draft_tokens_proposed
                                else float("nan")),
            "tokens_per_dispatch": (self.spec_tokens_emitted
                                    / self.spec_dispatches
                                    if self.spec_dispatches
                                    else float("nan")),
            "draft_tokens_proposed": self.draft_tokens_proposed,
            "draft_tokens_accepted": self.draft_tokens_accepted,
            "ttft_ms": self._dist_ms(self.ttft_s),
            # inter-token latency — the standard serving-benchmark name
            # for the same per-request token-gap distribution
            "itl_ms": self._dist_ms(self.token_gap_s),
            "token_latency_ms": self._dist_ms(self.token_gap_s),
            "queue_depth": {"mean": (sum(qd) / len(qd) if qd
                                     else float("nan")),
                            "max": max(qd) if qd else 0},
            "slot_occupancy": {"mean": (sum(occ) / len(occ) if occ
                                        else float("nan")),
                               "max": max(occ) if occ else 0.0},
            "wall_s": span,
        }

    def json(self) -> str:
        return json.dumps(self.summary(), sort_keys=True)

    # ----------------------------------------------------------------
    # wire serialization (cross-process fleet merge)
    # ----------------------------------------------------------------

    def to_wire(self) -> dict:
        """Version-tagged, JSON-safe envelope of :meth:`raw` — the form
        a cross-process replica ships its telemetry home in (fleet_lm
        ``--hosts`` report files). Everything in ``raw()`` is ints and
        floats, and Python's float repr round-trips exactly through
        ``json.dumps``/``loads``, so the pooled-percentile merge on the
        far side sees bit-identical samples."""
        return {"version": REPORT_WIRE_VERSION, "kind": "serving_report",
                "raw": self.raw()}

    @staticmethod
    def from_wire(wire: dict) -> "ReceivedServingReport":
        """Rehydrate a :meth:`to_wire` envelope (version-checked) into
        an object ``FleetReport.merge`` accepts alongside live ones."""
        if not isinstance(wire, dict) or wire.get("kind") != "serving_report":
            raise ValueError(
                f"not a serving_report envelope: {type(wire).__name__}")
        if wire.get("version") != REPORT_WIRE_VERSION:
            raise ValueError(
                f"serving_report wire version {wire.get('version')!r} "
                f"!= {REPORT_WIRE_VERSION} (mixed-version fleet?)")
        return ReceivedServingReport(wire["raw"])


class ReceivedServingReport:
    """A peer replica's telemetry, deserialized from the wire: exposes
    the same :meth:`raw` surface ``FleetReport.merge`` pools, nothing
    else (a received report cannot record new events)."""

    def __init__(self, raw: dict):
        missing = [k for k in ("ttft_s", "token_gap_s",
                               "queue_depth_samples", "occupancy_samples",
                               "submitted", "completed", "aborted",
                               "tokens_emitted", "host_bytes",
                               "draft_tokens_proposed",
                               "draft_tokens_accepted",
                               "spec_dispatches", "spec_tokens_emitted",
                               "wall_s")
                   if k not in raw]
        if missing:
            raise ValueError(
                f"serving_report raw block missing keys: {missing}")
        self._raw = {k: (list(v) if isinstance(v, list) else v)
                     for k, v in raw.items()}

    def raw(self) -> dict:
        return {k: (list(v) if isinstance(v, list) else v)
                for k, v in self._raw.items()}

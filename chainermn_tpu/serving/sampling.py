"""On-device token sampling — the piece that lets decode stop shipping
logits across the host boundary.

The host-side loop this replaces (``np.asarray`` of the full
``[n_slots, vocab]`` logits + a Python ``np.argmax``/softmax per slot —
dlint DL110's target) moved the one array that grows with vocabulary
over PCIe once per generated token. Here sampling compiles INTO the
decode program: :func:`sample_tokens` is pure jax, takes the per-slot
PRNG keys/temperatures/top-k the engine threads as state, and returns
int32 token ids — so a ``decode_k`` dispatch transfers ``O(n_slots)``
ids instead of ``O(n_slots × vocab)`` floats (gated ≤ 8 bytes/token in
bench.py).

Encoding conventions (the engine's ``None`` → array mapping):

* ``temperature <= 0``  → greedy ``jnp.argmax`` (first-index ties —
  bit-identical to the host ``np.argmax`` path it replaces);
* ``top_k <= 0``        → no truncation (full vocabulary);
* keys are RAW uint32 ``[n, 2]`` PRNG keys (``jax.random.PRNGKey``
  layout) so they scan/scatter as plain arrays.

Determinism contract: one key split per SAMPLED token, per slot —
independent of ``decode_k``, chunk size, or neighbouring traffic — so a
fixed per-request ``seed`` replays the same stream under any scheduler
interleaving (tested in tests/serving_tests/test_sampling.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["request_key", "init_keys", "split_keys", "sample_tokens",
           "draft_shadow_keys"]


def request_key(seed: int):
    """Raw uint32 ``[2]`` key for one request (set into the engine's
    per-slot key matrix at admission)."""
    return jax.random.PRNGKey(seed)


def init_keys(n: int):
    """The engine's resting key state: ``[n, 2]`` zeros (free slots
    sample garbage rows nobody reads — row independence, as everywhere
    in the serving grid)."""
    return jnp.zeros((n, 2), jnp.uint32)


def split_keys(keys):
    """Per-row key split: ``[n, 2]`` → (advanced keys, subkeys)."""
    both = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
    return both[:, 0], both[:, 1]


def sample_tokens(logits, keys, temperature, top_k):
    """One sampled token per row, entirely on device.

    logits ``[n, vocab]`` f32; keys ``[n, 2]`` uint32; temperature
    ``[n]`` f32 (``<= 0`` → greedy); top_k ``[n]`` int32 (``<= 0`` → full
    vocab). Returns ``(tokens [n] int32, new_keys [n, 2])``.

    Every row consumes exactly one split — greedy rows too — so the key
    stream position depends only on how many tokens a slot has sampled,
    never on its neighbours' sampling modes. Callers freeze keys for
    rows that didn't really sample (dead/pad rows) with a ``where`` on
    the returned keys.
    """
    logits = logits.astype(jnp.float32)
    n, v = logits.shape
    temperature = jnp.asarray(temperature, jnp.float32)
    top_k = jnp.asarray(top_k, jnp.int32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    new_keys, sub = split_keys(keys)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    # top-k truncation with a TRACED k: sort descending, gather the
    # k-th value per row, mask everything strictly below it (same
    # >=-kth tie rule as generate()'s static-k lax.top_k path)
    kth_idx = jnp.clip(top_k - 1, 0, v - 1)
    srt = -jnp.sort(-scaled, axis=-1)
    kth = jnp.take_along_axis(srt, kth_idx[:, None], axis=-1)
    truncated = jnp.where(scaled >= kth, scaled, -jnp.inf)
    scaled = jnp.where((top_k > 0)[:, None], truncated, scaled)
    sampled = jax.vmap(jax.random.categorical)(sub, scaled).astype(jnp.int32)

    tokens = jnp.where(temperature > 0, sampled, greedy)
    return tokens, new_keys


def draft_shadow_keys(keys):
    """SHADOW copy of the target's per-slot keys for a speculative
    draft pass (serving/speculative.py).

    The draft model proposes tokens by sampling with the SAME key
    values, at the same stream positions, that the target will use to
    verify — that alignment is what makes the Gumbel-max categorical
    draws coincide whenever draft and target logits are close, so
    sampled-mode acceptance is nonzero. The shadow is discarded after
    every speculative round: only the verify pass advances the REAL key
    rows, exactly one split per emitted token, which is what keeps
    accepted streams bitwise-identical to non-speculative decode and
    keeps migration/replay contracts intact.

    A draft-sampled token must NEVER be committed from this shadow
    stream without a verify pass blessing it — dlint DL125
    (draft-target-key-confusion) flags exactly that dataflow.
    """
    return jnp.asarray(keys, jnp.uint32).copy()

"""Warm-weight plane for serving replicas.

A restarted serving replica must come back HOT: re-initializing (or
re-downloading) weights inside the restart window is what turns one
preemption into a visible outage. This module is the
``PeerReplicator``-style snapshot path applied to inference weights:

* ``publish_weights`` — atomic publish (tmp + fsync + rename, exactly
  the checkpoint plane's discipline) of a params pytree plus a
  ``{"format": 1, "sha256", "bytes"}`` manifest sidecar, the same
  manifest grammar ``extensions/checkpoint.py`` emits, so fleet tooling
  verifies both planes with one code path. ``wire_format='int8-block' |
  'int4-block'`` publishes through the SAME blockwise codec the
  quantized collectives use (``collectives.quantized.block_quantize``,
  docs/collectives.md#quantized-wire-formats): each large float leaf is
  stored as ``<key>::q`` codes plus ``<key>::scale`` per-256-element
  scales, the manifest (format 2) records the codec and per-leaf
  shape/dtype, and ``load_weights`` dequantizes transparently — a warm
  restart pulls ~4× (int8) / ~8× (int4) less over the replica plane.
* ``load_weights`` — manifest-verified load; a corrupt or torn file is
  REFUSED (never half-loaded into a serving process), and candidates
  are tried newest-first across the primary path and any replica
  directories (``<dir>/replicas/*`` — where PeerReplicator drops peer
  snapshots), so losing the local disk still warm-starts from a peer.
* ``pull_weights`` — the in-process fast path: fetch the params from a
  live peer over the communicator object plane (``bcast_obj``), for
  replicas joining while the fleet is up.
* ``encode_weights`` / ``decode_weights`` — the diskless wire form of
  the same (manifest, payload) pair, for the rolling-update relay
  (``fleet/rollout.py``): the publisher encodes a snapshot ONCE, ships
  it replica-to-replica in SHA-chunked frames, and every receiver
  re-verifies the full-payload manifest before a single byte reaches a
  serving process.
* ``load_snapshot_weights`` — warm-reload straight from the TRAINING
  checkpoint directory: the async snapshot plane
  (``checkpointing/async_plane.py``) publishes ``snapshot_iter_<N>``
  files under the same manifest grammar, so a serving replica can come
  back hot from the newest verified training snapshot without a
  separate weight-publish step (the ``leaf_{i}``/``leaf_{i}_s<k>``
  shard keys are reassembled against a template pytree).
"""

from __future__ import annotations

import glob
import hashlib
import io
import json
import os
from typing import Any, List, Optional, Tuple

import numpy as np

__all__ = ["publish_weights", "load_weights", "pull_weights",
           "weight_candidates", "load_snapshot_weights",
           "snapshot_candidates", "encode_weights", "decode_weights",
           "WeightsError"]

_MANIFEST_FORMAT = 1
#: format 2 = blockwise-quantized payload; the manifest's ``codec`` key
#: records wire_format/block plus per-leaf shape/dtype/size
_MANIFEST_FORMAT_QUANT = 2
_ACCEPTED_FORMATS = (_MANIFEST_FORMAT, _MANIFEST_FORMAT_QUANT)


class WeightsError(RuntimeError):
    """No verifiable weight snapshot could be loaded."""


def _flatten(params) -> dict:
    import jax

    flat = {}
    leaves_with_path = jax.tree_util.tree_flatten_with_path(params)[0]
    for path, leaf in leaves_with_path:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def _encode_quantized(flat: dict, wire_format: str) -> Tuple[dict, dict]:
    """Blockwise-encode the large float leaves of a flat param dict with
    the collectives' codec. Returns ``(encoded_flat, codec_manifest)``.
    Small leaves (< one quant block) and non-float leaves pass through
    raw — the scale sidecar would dominate them."""
    from chainermn_tpu.collectives.quantized import (QUANT_BLOCK,
                                                     block_quantize)

    if wire_format not in ("int8-block", "int4-block"):
        raise ValueError(
            f"publish_weights wire_format={wire_format!r}: only the "
            "blockwise storage codecs ('int8-block', 'int4-block') "
            "apply to weights at rest")
    enc, leaves = {}, {}
    for k, arr in flat.items():
        if arr.dtype.kind == "f" and arr.size >= QUANT_BLOCK:
            q, s = block_quantize(arr.reshape(-1), wire_format)
            enc[k + "::q"] = np.asarray(q)
            enc[k + "::scale"] = np.asarray(s, dtype=np.float32)
            leaves[k] = {"shape": list(arr.shape),
                         "dtype": arr.dtype.name,
                         "size": int(arr.size)}
        else:
            enc[k] = arr
    codec = {"wire_format": wire_format, "block": QUANT_BLOCK,
             "leaves": leaves}
    return enc, codec


def _decode_quantized(flat: dict, manifest: dict) -> dict:
    from chainermn_tpu.collectives.quantized import block_dequantize

    codec = manifest.get("codec") or {}
    wf = codec.get("wire_format")
    blk = int(codec.get("block", 256))
    leaves = codec.get("leaves", {})
    out = {}
    for k, v in flat.items():
        if k.endswith("::scale"):
            continue
        if k.endswith("::q"):
            base = k[: -len("::q")]
            meta = leaves.get(base)
            if meta is None:
                raise WeightsError(
                    f"quantized snapshot has no codec entry for {base!r}")
            deq = np.asarray(block_dequantize(
                v, flat[base + "::scale"], int(meta["size"]), wf,
                np.dtype(meta["dtype"]), blk))
            out[base] = deq.reshape(meta["shape"])
        else:
            out[k] = v
    return out


def encode_weights(params, wire_format: Optional[str] = None,
                   weights_version: Optional[str] = None
                   ) -> Tuple[dict, bytes]:
    """Serialize ``params`` to ``(manifest, payload)`` without touching
    disk — the wire form of :func:`publish_weights`, for the rollout
    relay (``fleet/rollout.py``) that ships a snapshot replica-to-
    replica. Same manifest grammar (format 1 raw / format 2 blockwise-
    quantized via ``wire_format``); ``weights_version`` stamps the
    manifest so receivers can fence version skew. ``decode_weights``
    is the inverse and REFUSES a payload that fails the manifest's
    SHA-256."""
    flat = _flatten(params)
    codec = None
    if wire_format not in (None, "f32"):
        flat, codec = _encode_quantized(flat, wire_format)
    buf = io.BytesIO()
    np.savez(buf, **flat)
    data = buf.getvalue()
    manifest = {"format": (_MANIFEST_FORMAT_QUANT if codec
                           else _MANIFEST_FORMAT),
                "sha256": hashlib.sha256(data).hexdigest(),
                "bytes": len(data)}
    if codec:
        manifest["codec"] = codec
    if weights_version is not None:
        manifest["weights_version"] = str(weights_version)
    return manifest, data


def decode_weights(manifest: dict, data: bytes, like: Any = None):
    """Verify + deserialize a payload produced by
    :func:`encode_weights`. The manifest's byte count and SHA-256 gate
    the load — torn or corrupt bytes raise :class:`WeightsError`, never
    half-load. With ``like`` the flat keys are folded back into the
    template pytree; otherwise a flat ``{path: array}`` dict is
    returned (quantized payloads are dequantized either way)."""
    if manifest.get("format") not in _ACCEPTED_FORMATS:
        raise WeightsError(
            f"unknown weight manifest format {manifest.get('format')!r}")
    if (len(data) != manifest.get("bytes")
            or hashlib.sha256(data).hexdigest()
            != manifest.get("sha256")):
        raise WeightsError(
            "weight payload does not match its manifest "
            "(torn or corrupt bytes)")
    with np.load(io.BytesIO(data)) as z:
        flat = {k: z[k] for k in z.files}
    if manifest.get("format") == _MANIFEST_FORMAT_QUANT:
        flat = _decode_quantized(flat, manifest)
    if like is None:
        return flat
    return _unflatten_like(like, flat)


def publish_weights(params, path: str,
                    wire_format: Optional[str] = None,
                    weights_version: Optional[str] = None) -> dict:
    """Atomically write ``params`` (any pytree of arrays) to ``path``
    (.npz) with a SHA-256 manifest sidecar ``path + '.json'``. Returns
    the manifest. The rename is the commit point: readers only ever see
    a complete, verified file.

    ``wire_format``: ``None``/``'f32'`` store raw arrays (format 1);
    ``'int8-block'``/``'int4-block'`` store blockwise codes + scales
    (format 2) through the collectives' codec — ``load_weights``
    dequantizes transparently from the manifest-recorded scales.
    ``weights_version`` (optional) is recorded in the manifest, so a
    restart can tell WHICH version its local snapshot verifies as
    (the rollout controller's convergence contract)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    manifest, data = encode_weights(params, wire_format=wire_format,
                                    weights_version=weights_version)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    mtmp = path + ".json.tmp"
    with open(mtmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    os.replace(mtmp, path + ".json")
    return manifest


def _verify(path: str) -> Optional[dict]:
    """The verified manifest, or ``None`` when the snapshot is missing,
    torn, or from an unknown format."""
    mf = path + ".json"
    if not (os.path.exists(path) and os.path.exists(mf)):
        return None
    try:
        with open(mf) as f:
            manifest = json.load(f)
        if manifest.get("format") not in _ACCEPTED_FORMATS:
            return None
        with open(path, "rb") as f:
            data = f.read()
        ok = (len(data) == manifest.get("bytes")
              and hashlib.sha256(data).hexdigest()
              == manifest.get("sha256"))
        return manifest if ok else None
    except (OSError, ValueError):
        return None


def weight_candidates(path: str) -> List[str]:
    """The primary snapshot plus any peer replicas
    (``<dir>/replicas/*/<name>``), newest mtime first."""
    cands = [path]
    d, name = os.path.split(os.path.abspath(path))
    cands += glob.glob(os.path.join(d, "replicas", "*", name))
    cands = [c for c in cands if os.path.exists(c)]
    return sorted(cands, key=lambda c: os.path.getmtime(c), reverse=True)


def load_weights(path: str,
                 like: Any = None) -> Tuple[dict, str]:
    """Load the newest VERIFIED snapshot reachable from ``path``.
    Returns ``(params, source_path)``. With ``like`` (a template
    pytree), the flat npz keys are folded back into the template's
    structure; otherwise a flat ``{path: array}`` dict is returned.
    Blockwise-quantized snapshots (manifest format 2) are dequantized
    from the manifest-recorded scales transparently. Corrupt candidates
    are skipped (torn writes, bad sha); raises :class:`WeightsError`
    when nothing verifies."""
    for cand in weight_candidates(path):
        manifest = _verify(cand)
        if manifest is None:
            continue
        with np.load(cand) as z:
            flat = {k: z[k] for k in z.files}
        if manifest.get("format") == _MANIFEST_FORMAT_QUANT:
            flat = _decode_quantized(flat, manifest)
        if like is None:
            return flat, cand
        return _unflatten_like(like, flat), cand
    raise WeightsError(
        f"no verified weight snapshot at {path!r} or its replicas")


def _unflatten_like(like, flat: dict):
    import jax
    import jax.numpy as jnp

    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(like)
    leaves = []
    for path, leaf in leaves_with_path:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        if key not in flat:
            raise WeightsError(f"snapshot is missing parameter {key!r}")
        arr = flat[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise WeightsError(
                f"snapshot shape mismatch for {key!r}: "
                f"{arr.shape} vs {np.shape(leaf)}")
        leaves.append(jnp.asarray(arr))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def snapshot_candidates(ckpt_dir: str,
                        iteration: Optional[int] = None) -> List[str]:
    """Training-snapshot files under a checkpoint directory (primaries
    plus ``replicas/``), filtered to ``iteration`` when given, sorted
    newest iteration first (rank order within an iteration). No
    verification here — :func:`load_snapshot_weights` verifies each
    candidate's manifest before touching it."""
    import re

    pat = re.compile(r"snapshot_iter_(\d+)\.(\d+)$")
    found = []
    for d in (ckpt_dir, os.path.join(ckpt_dir, "replicas")):
        if not os.path.isdir(d):
            continue
        for f in os.listdir(d):
            m = pat.match(f)
            fn = os.path.join(d, f)
            if not m or os.path.isdir(fn):
                continue
            it = int(m.group(1))
            if iteration is not None and it != iteration:
                continue
            found.append((it, int(m.group(2)), fn))
    found.sort(key=lambda t: (-t[0], t[1]))
    return [fn for _, _, fn in found]


def load_snapshot_weights(ckpt_dir: str, like: Any,
                          iteration: Optional[int] = None):
    """Warm-reload serving weights from the newest VERIFIED training
    snapshot under ``ckpt_dir`` (the async snapshot plane's output —
    same manifest grammar as :func:`publish_weights`, so the same
    verification applies). ``like`` is the params template pytree; the
    snapshot's ``leaf_{i}`` arrays and ``leaf_{i}_s<k>`` shard pieces
    are reassembled against it BY FLATTEN ORDER — pass the exact
    subtree that was saved (for training states that bundle optimizer
    state, save/publish the params subtree for serving, or use
    ``fsdp_gather_params`` first). Returns ``(params, source_path)``;
    raises :class:`WeightsError` when nothing verifies or the template
    does not match."""
    import jax
    import jax.numpy as jnp

    last_err = None
    for cand in snapshot_candidates(ckpt_dir, iteration=iteration):
        if _verify(cand) is None:
            continue
        try:
            with np.load(cand, allow_pickle=False) as z:
                keys = set(z.files)
                leaves, treedef = jax.tree_util.tree_flatten(like)
                out = []
                for i, ref in enumerate(leaves):
                    if f"leaf_{i}" in keys:
                        arr = z[f"leaf_{i}"]
                    elif f"leaf_{i}_nshards" in keys:
                        gshape = tuple(int(d)
                                       for d in z[f"leaf_{i}_gshape"])
                        n = int(z[f"leaf_{i}_nshards"])
                        first = z[f"leaf_{i}_s0"]
                        arr = np.empty(gshape, first.dtype)
                        vol = 0
                        for k in range(n):
                            idx = np.asarray(z[f"leaf_{i}_idx{k}"])
                            sl = tuple(
                                slice(int(a),
                                      int(b) if b != -1 else d)
                                for (a, b), d in zip(idx, gshape))
                            arr[sl] = z[f"leaf_{i}_s{k}"]
                            vol += int(np.prod(
                                [s.stop - s.start for s in sl],
                                initial=1))
                        if vol != int(np.prod(gshape, initial=1)):
                            raise WeightsError(
                                f"snapshot {cand} holds only part of "
                                f"leaf {i} ({vol} of "
                                f"{int(np.prod(gshape, initial=1))} "
                                "elements) — a multi-process sharded "
                                "snapshot; gather before publishing")
                    else:
                        raise WeightsError(
                            f"snapshot {cand} has no leaf {i} — the "
                            "template does not match the saved pytree "
                            "(per-rank sharded snapshots need every "
                            "rank's file; this loader reads ONE file)")
                    if tuple(arr.shape) != tuple(np.shape(ref)):
                        raise WeightsError(
                            f"snapshot leaf {i} shape {arr.shape} vs "
                            f"template {np.shape(ref)}")
                    out.append(jnp.asarray(arr))
            return jax.tree_util.tree_unflatten(treedef, out), cand
        except WeightsError as e:
            last_err = e  # try the next candidate (older/replica)
            continue
    raise last_err or WeightsError(
        f"no verified training snapshot under {ckpt_dir!r}")


def pull_weights(comm, params: Optional[Any], root: int = 0):
    """Fetch warm weights from a live peer: rank ``root`` contributes
    its params, everyone receives them (object-plane broadcast — the
    joining replica never touches disk)."""
    return comm.bcast_obj(params, root=root)

"""chainermn_tpu.serving — continuous-batching inference over the
training mesh, resilience, and reporting layers.

Layered exactly like the training side: ``kv_cache`` is the compiled
numerics core (paged ring cache + jit prefill/decode with donation),
``engine`` is the single-threaded scheduler (slots, admission,
retirement), ``frontend`` is the thread-safe client face (futures,
RpcPolicy deadlines, watchdog-bounded aborts), ``reports`` is the
telemetry sibling of ``training/reports.py``, and ``weights`` is the
warm-restart snapshot plane. See docs/serving.md.
"""

from chainermn_tpu.serving.engine import (Engine, EngineConfig, Request,
                                          default_buckets)
from chainermn_tpu.serving.frontend import DeadlineExceeded, Frontend
from chainermn_tpu.serving.kv_cache import (ServingStep, cache_bytes,
                                            cache_spec, decode_apply,
                                            init_cache, prefill_apply)
from chainermn_tpu.serving.reports import ServingReport
from chainermn_tpu.serving.weights import (WeightsError, load_weights,
                                           publish_weights, pull_weights,
                                           weight_candidates)

__all__ = [
    "Engine", "EngineConfig", "Request", "default_buckets",
    "Frontend", "DeadlineExceeded",
    "ServingStep", "cache_bytes", "cache_spec", "decode_apply",
    "init_cache", "prefill_apply",
    "ServingReport",
    "WeightsError", "load_weights", "publish_weights", "pull_weights",
    "weight_candidates",
]

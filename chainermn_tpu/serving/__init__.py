"""chainermn_tpu.serving — continuous-batching inference over the
training mesh, resilience, and reporting layers.

Layered exactly like the training side: ``kv_cache`` is the compiled
numerics core (paged ring cache + jit prefill/decode_k/chunk programs
with donation), ``sampling`` is the on-device token sampler those
programs compile in, ``engine`` is the single-threaded scheduler
(slots, token-budget admission, retirement), ``frontend`` is the
thread-safe client face (futures,
RpcPolicy deadlines, watchdog-bounded aborts), ``speculative`` is the
draft-propose/target-verify engine subclass, ``reports`` is the
telemetry sibling of ``training/reports.py``, and ``weights`` is the
warm-restart snapshot plane. See docs/serving.md.
"""

from chainermn_tpu.serving.engine import (Engine, EngineConfig, Request,
                                          default_buckets)
from chainermn_tpu.serving.frontend import (AdmissionRejected,
                                            DeadlineExceeded, Frontend)
from chainermn_tpu.serving.kv_cache import (ServingStep, cache_bytes,
                                            cache_spec, decode_apply,
                                            decode_k_apply, init_cache,
                                            prefill_apply,
                                            prefill_chunk_apply)
from chainermn_tpu.serving.reports import ServingReport
from chainermn_tpu.serving.sampling import (draft_shadow_keys, init_keys,
                                            request_key, sample_tokens,
                                            split_keys)
from chainermn_tpu.serving.speculative import DraftStep, SpeculativeEngine
from chainermn_tpu.serving.weights import (WeightsError, load_weights,
                                           publish_weights, pull_weights,
                                           weight_candidates)

__all__ = [
    "Engine", "EngineConfig", "Request", "default_buckets",
    "Frontend", "DeadlineExceeded", "AdmissionRejected",
    "ServingStep", "cache_bytes", "cache_spec", "decode_apply",
    "decode_k_apply", "init_cache", "prefill_apply",
    "prefill_chunk_apply",
    "ServingReport",
    "DraftStep", "SpeculativeEngine",
    "draft_shadow_keys", "init_keys", "request_key", "sample_tokens",
    "split_keys",
    "WeightsError", "load_weights", "publish_weights", "pull_weights",
    "weight_candidates",
]

"""Self-contained datasets for examples and tests.

The build environment has no network egress, so the examples ship with
deterministic synthetic stand-ins shaped exactly like the reference's
datasets (MNIST 28×28 grayscale/10 classes, CIFAR 32×32×3/100 classes).
Real data drops in unchanged: anything indexable as (image, label) works.
"""

from __future__ import annotations

import numpy as np


class ArrayDataset:
    """Pairs of (x, y) arrays, indexable like the reference's TupleDataset."""

    def __init__(self, xs: np.ndarray, ys: np.ndarray):
        assert len(xs) == len(ys)
        self.xs = xs
        self.ys = ys

    def __len__(self):
        return len(self.xs)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return list(zip(self.xs[i], self.ys[i]))
        return self.xs[i], self.ys[i]


def synthetic_mnist(n: int = 4096, seed: int = 0):
    """Class-separable synthetic MNIST: each class has a fixed random
    prototype plus noise, so a model can actually learn (loss decreases,
    accuracy rises) — unlike pure-noise data. Prototypes are seed-independent
    so train/test splits (different seeds) share the same classes."""
    protos = np.random.RandomState(12345).rand(10, 28, 28).astype(np.float32)
    rng = np.random.RandomState(seed)
    ys = rng.randint(0, 10, size=n).astype(np.int32)
    xs = protos[ys] + 0.3 * rng.randn(n, 28, 28).astype(np.float32)
    return ArrayDataset(xs.astype(np.float32), ys)


def synthetic_cifar(n: int = 4096, n_classes: int = 100, seed: int = 0):
    protos = np.random.RandomState(54321).rand(
        n_classes, 32, 32, 3).astype(np.float32)
    rng = np.random.RandomState(seed)
    ys = rng.randint(0, n_classes, size=n).astype(np.int32)
    xs = protos[ys] + 0.3 * rng.randn(n, 32, 32, 3).astype(np.float32)
    return ArrayDataset(xs.astype(np.float32), ys)


def synthetic_translation(n: int = 2048, src_vocab: int = 1000,
                          tgt_vocab: int = 1000, max_len: int = 24,
                          seed: int = 0):
    """Variable-length 'translation' pairs: the target is a deterministic
    transform of the source (reversal mod vocab), so seq2seq training has
    signal. Mirrors the reference's WMT En-De usage shape (lists of int
    arrays of varying length)."""
    rng = np.random.RandomState(seed)
    data = []
    for _ in range(n):
        ln = rng.randint(4, max_len)
        src = rng.randint(3, src_vocab, size=ln).astype(np.int32)
        tgt = ((src[::-1] + 7) % (tgt_vocab - 3) + 3).astype(np.int32)
        data.append((src, tgt))

    class _Seq:
        def __len__(self):
            return len(data)

        def __getitem__(self, i):
            return data[i]

    return _Seq()

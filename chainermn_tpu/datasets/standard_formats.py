"""Readers/writers for the standard on-disk dataset formats the reference's
examples consume: IDX (MNIST ``train-images-idx3-ubyte``) and the CIFAR
binary batch layout.

The reference's MNIST/CIFAR examples parse real dataset files (upstream
``examples/mnist/train_mnist.py`` via ``chainer.datasets.get_mnist`` — the
LeCun IDX format; CIFAR via the binary batches). This environment has no
network egress, so the writers here produce byte-identical layouts locally
and the examples *parse* them — the executed input path is always the real
format parser, never an in-memory synthetic array.

IDX format (the canonical spec, as written by the original MNIST files)::

    [0x00 0x00] [dtype code] [ndim]      -- 4-byte magic, big-endian
    ndim x uint32 big-endian dimensions
    row-major payload, big-endian for multi-byte dtypes

dtype codes: 0x08 uint8, 0x09 int8, 0x0B int16, 0x0C int32, 0x0D float32,
0x0E float64.

CIFAR binary (per record, no header, fixed-size records)::

    CIFAR-10  : [label u8]               [3072 bytes: 3x32x32 channel-major]
    CIFAR-100 : [coarse u8] [fine u8]    [3072 bytes: 3x32x32 channel-major]

Files: CIFAR-10 ``data_batch_{1..5}.bin`` + ``test_batch.bin``; CIFAR-100
``train.bin`` + ``test.bin``.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Tuple

import numpy as np

from chainermn_tpu.datasets.toy import ArrayDataset

_IDX_DTYPES = {
    0x08: np.dtype(np.uint8),
    0x09: np.dtype(np.int8),
    0x0B: np.dtype(">i2"),
    0x0C: np.dtype(">i4"),
    0x0D: np.dtype(">f4"),
    0x0E: np.dtype(">f8"),
}
_IDX_CODES = {
    np.dtype(np.uint8): 0x08,
    np.dtype(np.int8): 0x09,
    np.dtype(np.int16): 0x0B,
    np.dtype(np.int32): 0x0C,
    np.dtype(np.float32): 0x0D,
    np.dtype(np.float64): 0x0E,
}


def _open_maybe_gz(path: str):
    """The distributed MNIST files are gzipped (``*-ubyte.gz``); accept
    both the unpacked and the gzipped form transparently."""
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    return open(path, "rb")


def load_idx(path: str) -> np.ndarray:
    """Parse one IDX file (optionally ``.gz``) into a native-endian array."""
    with _open_maybe_gz(path) as f:
        magic = f.read(4)
        if len(magic) != 4 or magic[0] != 0 or magic[1] != 0:
            raise ValueError(
                f"{path}: not an IDX file (magic starts "
                f"{magic[:2].hex() if magic else '<empty>'}, expected 0000)")
        code, ndim = magic[2], magic[3]
        if code not in _IDX_DTYPES:
            raise ValueError(
                f"{path}: unknown IDX dtype code 0x{code:02x}")
        dims_raw = f.read(4 * ndim)
        if len(dims_raw) != 4 * ndim:
            raise ValueError(f"{path}: truncated IDX dimension header")
        dims = struct.unpack(f">{ndim}I", dims_raw)
        dtype = _IDX_DTYPES[code]
        count = int(np.prod(dims, initial=1))
        payload = f.read(count * dtype.itemsize)
        if len(payload) != count * dtype.itemsize:
            raise ValueError(
                f"{path}: truncated IDX payload ({len(payload)} bytes, "
                f"expected {count * dtype.itemsize} for shape {dims})")
        arr = np.frombuffer(payload, dtype=dtype).reshape(dims)
        # native-endian copy (frombuffer views are read-only big-endian)
        return arr.astype(dtype.newbyteorder("="), copy=True)


def save_idx(path: str, arr: np.ndarray) -> None:
    """Write ``arr`` in IDX layout (big-endian payload, spec-exact)."""
    arr = np.asarray(arr)
    code = _IDX_CODES.get(np.dtype(arr.dtype.name))
    if code is None:
        raise ValueError(f"dtype {arr.dtype} has no IDX code")
    if arr.ndim > 255:
        raise ValueError("IDX ndim is a single byte")
    with open(path, "wb") as f:
        f.write(bytes([0, 0, code, arr.ndim]))
        f.write(struct.pack(f">{arr.ndim}I", *arr.shape))
        f.write(np.ascontiguousarray(
            arr, dtype=arr.dtype.newbyteorder(">")).tobytes())


def _find_idx(data_dir: str, stem: str) -> str:
    """Resolve ``stem`` under ``data_dir`` accepting the two distributed
    spellings (``-idx3-ubyte`` / ``.idx3-ubyte``) and optional ``.gz``."""
    for name in (stem, stem + ".gz",
                 stem.replace("-idx", ".idx"),
                 stem.replace("-idx", ".idx") + ".gz"):
        p = os.path.join(data_dir, name)
        if os.path.exists(p):
            return p
    raise FileNotFoundError(
        f"{data_dir}: no {stem}[.gz] (expected the standard MNIST file "
        "names; generate locally with examples/mnist/make_mnist_dataset.py)")


def load_mnist(data_dir: str, train: bool = True,
               normalize: bool = True) -> ArrayDataset:
    """Load an MNIST-layout directory (``train-images-idx3-ubyte`` etc.,
    plain or gzipped) into an :class:`ArrayDataset` of
    (float32 [28,28] in [0,1], int32 label) pairs — the reference's
    ``get_mnist`` output shape."""
    prefix = "train" if train else "t10k"
    images = load_idx(_find_idx(data_dir, f"{prefix}-images-idx3-ubyte"))
    labels = load_idx(_find_idx(data_dir, f"{prefix}-labels-idx1-ubyte"))
    if images.ndim != 3:
        raise ValueError(
            f"images file has ndim={images.ndim}, expected 3 (N, H, W)")
    if labels.ndim != 1 or len(labels) != len(images):
        raise ValueError(
            f"labels/images mismatch: {labels.shape} vs {images.shape}")
    xs = images.astype(np.float32)
    if normalize:
        xs /= 255.0
    return ArrayDataset(xs, labels.astype(np.int32))


def save_mnist(data_dir: str, xs: np.ndarray, ys: np.ndarray,
               train: bool = True, gz: bool = False) -> None:
    """Write (uint8 images [N,28,28], labels [N]) as standard MNIST IDX
    files under ``data_dir``."""
    os.makedirs(data_dir, exist_ok=True)
    prefix = "train" if train else "t10k"
    ipath = os.path.join(data_dir, f"{prefix}-images-idx3-ubyte")
    lpath = os.path.join(data_dir, f"{prefix}-labels-idx1-ubyte")
    save_idx(ipath, np.asarray(xs, np.uint8))
    save_idx(lpath, np.asarray(ys, np.uint8))
    if gz:
        for p in (ipath, lpath):
            with open(p, "rb") as src, gzip.open(p + ".gz", "wb") as dst:
                dst.write(src.read())
            os.remove(p)


_CIFAR_REC = 3 * 32 * 32  # channel-major pixel payload per record


def _parse_cifar_records(raw: bytes, label_bytes: int, path: str
                         ) -> Tuple[np.ndarray, np.ndarray]:
    rec = label_bytes + _CIFAR_REC
    if len(raw) == 0 or len(raw) % rec != 0:
        raise ValueError(
            f"{path}: size {len(raw)} is not a multiple of the "
            f"{rec}-byte record ({label_bytes} label byte(s) + 3072 pixels)")
    a = np.frombuffer(raw, np.uint8).reshape(-1, rec)
    # fine label is the LAST label byte (CIFAR-100: [coarse, fine])
    labels = a[:, label_bytes - 1].astype(np.int32)
    # channel-major [3,32,32] -> NHWC
    imgs = a[:, label_bytes:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return imgs, labels


def load_cifar(data_dir: str, n_classes: int = 100, train: bool = True,
               normalize: bool = True) -> ArrayDataset:
    """Load a CIFAR binary-layout directory into an :class:`ArrayDataset`
    of (float32 NHWC [32,32,3] in [0,1], int32 fine label) pairs.

    ``n_classes=100`` reads ``train.bin``/``test.bin`` (2 label bytes per
    record, fine label used); ``n_classes=10`` reads
    ``data_batch_{1..5}.bin``/``test_batch.bin`` (1 label byte)."""
    if n_classes == 100:
        files = ["train.bin"] if train else ["test.bin"]
        label_bytes = 2
        optional = set()
    elif n_classes == 10:
        files = ([f"data_batch_{i}.bin" for i in range(1, 6)]
                 if train else ["test_batch.bin"])
        label_bytes = 1
        # the real distribution always has all five train batches; a
        # small locally-generated set may hold fewer (save_cifar skips
        # empty parts), so only batch 1 is mandatory
        optional = set(files[1:]) if train else set()
    else:
        raise ValueError(f"n_classes must be 10 or 100, got {n_classes}")
    imgs, labels = [], []
    for name in files:
        path = os.path.join(data_dir, name)
        if not os.path.exists(path):
            if name in optional:
                continue
            raise FileNotFoundError(
                f"{path}: missing CIFAR-{n_classes} binary batch "
                "(generate locally with examples/cifar/"
                "make_cifar_dataset.py)")
        with open(path, "rb") as f:
            i, l = _parse_cifar_records(f.read(), label_bytes, path)
        imgs.append(i)
        labels.append(l)
    xs = np.concatenate(imgs).astype(np.float32)
    if normalize:
        xs /= 255.0
    return ArrayDataset(xs, np.concatenate(labels))


def save_cifar(data_dir: str, xs: np.ndarray, ys: np.ndarray,
               n_classes: int = 100, train: bool = True,
               coarse: np.ndarray = None) -> None:
    """Write (uint8 NHWC images, fine labels) as CIFAR binary batches.

    CIFAR-100 records carry [coarse, fine] label bytes; ``coarse``
    defaults to ``fine // 5`` (the real file's 20 superclasses also
    partition the 100 classes 5-to-1)."""
    os.makedirs(data_dir, exist_ok=True)
    xs = np.asarray(xs, np.uint8)
    ys = np.asarray(ys, np.uint8)
    pix = xs.transpose(0, 3, 1, 2).reshape(len(xs), _CIFAR_REC)
    if n_classes == 100:
        if coarse is None:
            coarse = ys // 5
        recs = np.concatenate(
            [np.asarray(coarse, np.uint8)[:, None], ys[:, None], pix],
            axis=1)
        files = {("train.bin" if train else "test.bin"): recs}
    elif n_classes == 10:
        recs = np.concatenate([ys[:, None], pix], axis=1)
        if train:
            if len(recs) == 0:
                raise ValueError("cannot save an empty CIFAR-10 set")
            # skip empty parts for tiny locally-generated sets (a 0-byte
            # batch file would fail the loader's record-size check);
            # load_cifar treats batches 2..5 as optional accordingly
            parts = [p for p in np.array_split(recs, 5) if len(p)]
            files = {f"data_batch_{i + 1}.bin": p
                     for i, p in enumerate(parts)}
        else:
            files = {"test_batch.bin": recs}
    else:
        raise ValueError(f"n_classes must be 10 or 100, got {n_classes}")
    for name, r in files.items():
        with open(os.path.join(data_dir, name), "wb") as f:
            f.write(r.tobytes())

"""Folder-of-images dataset: the reference ImageNet input path.

Reference parity: upstream ``examples/imagenet/train_imagenet.py``
(SURVEY.md §3.1) trains from a labeled-image list via
``chainer.datasets.LabeledImageDataset`` + a ``PreprocessedDataset``
wrapper doing random-crop/center-crop (+ optional hflip) per sample. This
module is the same contract on the standard on-disk layout
(``root/<class_name>/*.jpg``): REAL image files decoded per access (PIL),
composing with ``scatter_dataset``/``SubDataset``, the iterators, and the
trainer exactly like any other dataset.

Decode throughput note: JPEG decode is host-CPU work. On a many-core host
it hides behind the device step via the prefetch loader; this repo's
1-core environment decodes ~10^2 img/s, so the PERF benches keep their
on-device synthetic feed (bench.py) and this path carries the
correctness/parity story — the same split the reference makes between
its benchmark harness and its example scripts.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import numpy as np

_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


class ImageFolderDataset:
    """``root/<class>/<image>`` → ``(float32 [H, W, 3] in [0, 1], int32)``.

    Args:
      root: dataset directory; each subdirectory is one class (sorted
        subdirectory names define the label ids, torchvision/keras
        convention).
      image_size: output side length (square crop).
      train: True → resize shorter side to ``resize_to`` then RANDOM crop
        + horizontal flip (upstream PreprocessedDataset's train branch);
        False → deterministic center crop, no flip.
      resize_to: shorter-side resize before cropping (default
        ``image_size * 256 // 224``, the classic 256→224 recipe).
      mean / std: optional per-channel normalization applied after the
        [0, 1] scaling.
      seed: base seed for the per-access crop/flip randomness; access
        ``i`` uses ``seed + i`` epoch-independently, so distributed
        shards stay reproducible without shared RNG state.
    """

    def __init__(self, root: str, image_size: int = 224,
                 train: bool = True, resize_to: Optional[int] = None,
                 mean: Optional[Sequence[float]] = None,
                 std: Optional[Sequence[float]] = None, seed: int = 0):
        from PIL import Image  # noqa: F401 — fail here, not per sample

        if not os.path.isdir(root):
            raise FileNotFoundError(f"dataset root {root!r} is not a "
                                    "directory")
        self.root = root
        self.image_size = int(image_size)
        self.resize_to = int(resize_to if resize_to is not None
                             else image_size * 256 // 224)
        if self.resize_to < self.image_size:
            raise ValueError(
                f"resize_to ({self.resize_to}) must be >= image_size "
                f"({self.image_size})")
        self.train = train
        self.mean = None if mean is None else np.asarray(
            mean, np.float32).reshape(1, 1, 3)
        self.std = None if std is None else np.asarray(
            std, np.float32).reshape(1, 1, 3)
        self.seed = seed

        self.classes = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))
        if not self.classes:
            raise ValueError(f"no class subdirectories under {root!r}")
        self._samples: list = []
        for label, cls in enumerate(self.classes):
            cdir = os.path.join(root, cls)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith(_EXTS):
                    self._samples.append((os.path.join(cdir, fn), label))
        if not self._samples:
            raise ValueError(f"no image files under {root!r} "
                             f"(extensions {_EXTS})")

    def __len__(self) -> int:
        return len(self._samples)

    def _load(self, path: str) -> np.ndarray:
        from PIL import Image

        with Image.open(path) as im:
            im = im.convert("RGB")
            w, h = im.size
            scale = self.resize_to / min(w, h)
            if scale != 1.0:
                im = im.resize((max(self.image_size, round(w * scale)),
                                max(self.image_size, round(h * scale))),
                               Image.BILINEAR)
            return np.asarray(im, np.uint8)

    def __getitem__(self, i: int) -> Tuple[np.ndarray, np.int32]:
        path, label = self._samples[int(i)]
        img = self._load(path)
        h, w = img.shape[:2]
        c = self.image_size
        if self.train:
            rng = np.random.RandomState(
                (self.seed + int(i)) % (2 ** 31 - 1))
            top = rng.randint(0, h - c + 1)
            left = rng.randint(0, w - c + 1)
            img = img[top:top + c, left:left + c]
            if rng.randint(2):
                img = img[:, ::-1]
        else:
            top, left = (h - c) // 2, (w - c) // 2
            img = img[top:top + c, left:left + c]
        x = np.ascontiguousarray(img, np.float32) / 255.0
        if self.mean is not None:
            x = x - self.mean
        if self.std is not None:
            x = x / self.std
        return x, np.int32(label)


def write_image_folder(root: str, n_classes: int, per_class: int,
                       image_size: int = 256, seed: int = 0,
                       fmt: str = "JPEG") -> int:
    """Write a REAL folder-of-JPEG dataset (class-correlated content so
    models can learn from it) — the local stand-in for downloading
    ImageNet in this no-egress environment; the reading path treats it
    exactly like the real thing. Returns the number of files written."""
    from PIL import Image

    protos = np.random.RandomState(seed + 99).rand(
        n_classes, image_size, image_size, 3)
    rng = np.random.RandomState(seed)
    n = 0
    for c in range(n_classes):
        cdir = os.path.join(root, f"class_{c:04d}")
        os.makedirs(cdir, exist_ok=True)
        for j in range(per_class):
            img = protos[c] + 0.25 * rng.randn(image_size, image_size, 3)
            arr = (np.clip(img, 0, 1) * 255).astype(np.uint8)
            ext = "jpg" if fmt.upper() == "JPEG" else fmt.lower()
            Image.fromarray(arr).save(
                os.path.join(cdir, f"img_{j:05d}.{ext}"), fmt.upper())
            n += 1
    return n

"""Dataset scattering across the process plane.

Reference: chainermn/datasets/scatter_dataset.py (SURVEY.md §2.5, §3.4; mount
empty — module path citation). Root shuffles a global index permutation,
splits the dataset into ``size`` contiguous sub-datasets, and ships each
shard as pickled ≤256 MB chunks over MPI; ``create_empty_dataset`` stubs
ranks that hold no data.

TPU-native mapping: ranks-that-load-data are *processes* (hosts), not chips —
device-level sharding happens per global batch inside the compiled step. So
``scatter_dataset`` splits across ``comm.inter_size`` and ships shards over
the host object plane (chunked KV-store transport, the analog of the MPI
chunking); single-process programs get the whole (shuffled) dataset, which is
exactly the single-controller contract. Variable-length Python samples
(seq2seq) are supported — the object plane pickles anything.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from chainermn_tpu.comm.base import CommunicatorBase


class SubDataset:
    """A view of ``dataset`` at ``order[start:stop]`` (reference:
    chainer.datasets.SubDataset semantics, local rebuild)."""

    def __init__(self, dataset, order: Sequence[int]):
        self._dataset = dataset
        self._order = np.asarray(order, dtype=np.int64)

    def __len__(self):
        return len(self._order)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self._dataset[int(j)] for j in self._order[i]]
        return self._dataset[int(self._order[i])]


def split_indices(
    n: int,
    k: int,
    shuffle: bool = False,
    seed: Optional[int] = None,
    force_equal_length: bool = True,
):
    """Root's index plan: permutation of ``range(n)`` split into ``k`` parts.

    ``force_equal_length`` pads the tail shards by wrapping (reference
    behavior keeping every rank's epoch the same length).
    """
    order = np.arange(n)
    if shuffle:
        rng = np.random.RandomState(seed)
        rng.shuffle(order)
    if force_equal_length:
        per = -(-n // k)  # ceil
        padded = np.resize(order, per * k)  # wraps around, reference-style
        return [padded[r * per:(r + 1) * per] for r in range(k)]
    base = n // k
    rem = n % k
    out, at = [], 0
    for r in range(k):
        ln = base + (1 if r < rem else 0)
        out.append(order[at:at + ln])
        at += ln
    return out


def scatter_dataset(
    dataset,
    comm: CommunicatorBase,
    shuffle: bool = False,
    root: int = 0,
    seed: Optional[int] = None,
    max_buf_len: int = 256 * 1024 * 1024,
    force_equal_length: bool = True,
    shared_storage: bool = True,
):
    """Split ``dataset`` across the process plane; return this process's shard.

    Single-process: the whole dataset (shuffled view if requested) — device
    sharding is the compiled step's job. Multi-process, ``shared_storage=
    True`` (default): the root computes the index plan and scatters index
    arrays (cheap) — every process reaches the same storage, the common
    TPU-pod case. ``shared_storage=False``: reference semantics
    (chainermn/datasets/scatter_dataset.py, SURVEY.md §3.4) — the root
    materializes each shard's actual SAMPLES and ships them pickled over
    the chunked object plane; non-root processes may pass ``dataset=None``
    and receive a materialized :class:`ListDataset`. Variable-length
    Python samples (seq2seq) ship fine — the plane pickles anything.
    ``max_buf_len`` bounds the per-message chunk the root materializes and
    ships (samples are accumulated into a chunk until their summed pickled
    size reaches the bound — robust to highly variable sample sizes; the
    reference's 256 MB default); the transport further slices each message
    at the KV-store bound.
    """
    k = comm.inter_size
    if k == 1:
        # one process: it is the root whatever `root` says
        my = split_indices(len(dataset), k, shuffle, seed,
                           force_equal_length)[0]
        return SubDataset(dataset, my)
    if shared_storage:
        if comm.inter_rank == root:
            plans = split_indices(len(dataset), k, shuffle, seed,
                                  force_equal_length)
        else:
            plans = None
        my = comm.scatter_obj(plans, root=root)
        return SubDataset(dataset, my)
    # payload shipping: the root streams each shard in ≤max_buf_len chunks
    # (reference scatter_dataset.py behavior) — one chunk materialized at a
    # time, so root memory stays bounded by dataset + one chunk instead of
    # 2-3x the dataset
    _SCATTER_TAG = 0x5CA77E0
    if comm.inter_rank == root:
        import pickle

        plans = split_indices(len(dataset), k, shuffle, seed,
                              force_equal_length)
        for r in range(k):
            if r == root:
                continue
            # ship pre-pickled samples, flushing whenever the RUNNING
            # pickled size reaches max_buf_len — a first-sample size
            # estimate breaks the root-memory bound on datasets with
            # highly variable sample sizes. None terminates the stream.
            buf, sz = [], 0
            for i in plans[r]:
                b = pickle.dumps(dataset[int(i)], pickle.HIGHEST_PROTOCOL)
                buf.append(b)
                sz += len(b)
                if sz >= max_buf_len:
                    comm.send_obj(buf, dest=r, tag=_SCATTER_TAG)
                    buf, sz = [], 0
            if buf:
                comm.send_obj(buf, dest=r, tag=_SCATTER_TAG)
            comm.send_obj(None, dest=r, tag=_SCATTER_TAG)
        return ListDataset(dataset[int(i)] for i in plans[root])
    import pickle

    samples = []
    while True:
        part = comm.recv_obj(src=root, tag=_SCATTER_TAG)
        if part is None:
            break
        samples.extend(pickle.loads(b) for b in part)
    return ListDataset(samples)


class ListDataset:
    """Received-payload shard: samples materialized on this process
    (reference: the unpickled sub-dataset a non-root rank receives from
    chainermn/datasets/scatter_dataset.py's chunked MPI scatter)."""

    def __init__(self, samples):
        self._samples = list(samples)

    def __len__(self):
        return len(self._samples)

    def __getitem__(self, i):
        return self._samples[i]


class _EmptyDataset:
    def __len__(self):
        return 0

    def __getitem__(self, i):
        raise IndexError("empty dataset")


def create_empty_dataset(dataset=None):
    """Stub dataset for processes that hold no data (reference:
    create_empty_dataset in chainermn/datasets/__init__.py)."""
    return _EmptyDataset()


# real on-disk ingestion (reference examples' input paths)
from chainermn_tpu.datasets.bpe import (  # noqa: E402
    BPETokenizer,
    train_bpe,
    train_bpe_file,
)
from chainermn_tpu.datasets.image_folder import (  # noqa: E402
    ImageFolderDataset,
    write_image_folder,
)
from chainermn_tpu.datasets.standard_formats import (  # noqa: E402
    load_cifar,
    load_idx,
    load_mnist,
    save_cifar,
    save_idx,
    save_mnist,
)

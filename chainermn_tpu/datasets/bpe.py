"""Byte-level BPE tokenizer: the reference seq2seq vocabulary path.

Reference parity: upstream ``examples/seq2seq/seq2seq.py`` (SURVEY.md
§3.4) builds word vocabularies from WMT text files and encodes source/
target corpora before scattering them. This is the same role with the
modern construction — byte-level BPE (GPT-2 style base alphabet of all
256 bytes, so ANY unicode text round-trips exactly, no UNK) trained
locally on the corpus it will encode.

Pure Python on purpose: training is a one-shot preprocessing step
(pair-count + merge loop over a word-frequency table, the original BPE
algorithm), not hot-path work. Encoded corpora are arrays; the hot path
never touches the tokenizer.
"""

from __future__ import annotations

import json
import os
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# specials come FIRST so pad=0 matches the models' masking convention
PAD, BOS, EOS = 0, 1, 2
_N_SPECIAL = 3


class BPETokenizer:
    """Byte-level BPE: ids [0, 3) are PAD/BOS/EOS, [3, 259) the raw
    bytes, and beyond that one id per learned merge, in merge order."""

    def __init__(self, merges: Sequence[Tuple[int, int]]):
        self.merges: List[Tuple[int, int]] = [tuple(m) for m in merges]
        # rank of each pair = merge priority (lower merges first)
        self._rank: Dict[Tuple[int, int], int] = {
            m: i for i, m in enumerate(self.merges)}
        # id of the token a pair merges into
        self._pair_id: Dict[Tuple[int, int], int] = {
            m: _N_SPECIAL + 256 + i for i, m in enumerate(self.merges)}

    @property
    def vocab_size(self) -> int:
        return _N_SPECIAL + 256 + len(self.merges)

    # -- codec ----------------------------------------------------------

    def _merge_word(self, ids: List[int]) -> List[int]:
        """Apply merges in rank order (classic BPE greedy loop)."""
        while len(ids) > 1:
            best = None
            best_rank = None
            for pair in zip(ids, ids[1:]):
                r = self._rank.get(pair)
                if r is not None and (best_rank is None or r < best_rank):
                    best, best_rank = pair, r
            if best is None:
                break
            out = []
            i = 0
            while i < len(ids):
                if (i + 1 < len(ids)
                        and (ids[i], ids[i + 1]) == best):
                    out.append(self._pair_id[best])
                    i += 2
                else:
                    out.append(ids[i])
                    i += 1
            ids = out
        return ids

    def encode(self, text: str, bos: bool = False,
               eos: bool = False) -> List[int]:
        ids: List[int] = [BOS] if bos else []
        for word in _split_words(text):
            ids.extend(self._merge_word(
                [b + _N_SPECIAL for b in word]))
        if eos:
            ids.append(EOS)
        return ids

    def decode(self, ids: Iterable[int]) -> str:
        bs = bytearray()
        for i in ids:
            i = int(i)
            if i < _N_SPECIAL:
                continue
            bs.extend(self._bytes_of(i))
        return bs.decode("utf-8", errors="replace")

    def _bytes_of(self, tok: int) -> bytes:
        if tok < _N_SPECIAL + 256:
            return bytes([tok - _N_SPECIAL])
        a, b = self.merges[tok - _N_SPECIAL - 256]
        return self._bytes_of(a) + self._bytes_of(b)

    # -- persistence (one JSON file: the vocabulary artifact) -----------

    def save(self, path: str) -> None:
        # write-temp-then-rename: concurrent processes polling
        # os.path.exists never observe a partially written vocabulary
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"format": "chainermn_tpu-bpe-v1",
                       "merges": self.merges}, f)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "BPETokenizer":
        with open(path) as f:
            d = json.load(f)
        if d.get("format") != "chainermn_tpu-bpe-v1":
            raise ValueError(f"{path}: not a chainermn_tpu BPE file")
        return cls([tuple(m) for m in d["merges"]])


def _split_words(text: str) -> List[bytes]:
    """Whitespace-boundary pre-split (merges never cross words), each
    word carrying its leading space — the byte-level convention that
    makes decode a pure concatenation."""
    words: List[bytes] = []
    cur = bytearray()
    for ch in text.encode("utf-8"):
        if ch in (0x20, 0x0A, 0x09, 0x0D):  # space-ish starts a new word
            if cur:
                words.append(bytes(cur))
            cur = bytearray([ch])
        else:
            cur.append(ch)
    if cur:
        words.append(bytes(cur))
    return words


def train_bpe(corpus: Iterable[str], vocab_size: int,
              max_lines: Optional[int] = None,
              cache_path: Optional[str] = None) -> BPETokenizer:
    """Learn merges from text lines until ``vocab_size`` is reached.

    The original BPE training loop over a word-frequency table: count
    adjacent pairs weighted by word frequency, merge the most frequent,
    repeat. ``vocab_size`` counts specials + 256 byte tokens + merges.
    ``cache_path``: load the vocabulary from this JSON if present, save
    it there after training otherwise (atomic rename — safe against
    concurrent processes sharing the cache).
    """
    if cache_path and os.path.exists(cache_path):
        return BPETokenizer.load(cache_path)
    if vocab_size < _N_SPECIAL + 256:
        raise ValueError(
            f"vocab_size must be >= {_N_SPECIAL + 256} "
            "(specials + byte alphabet)")
    freq: Counter = Counter()
    for ln, line in enumerate(corpus):
        if max_lines is not None and ln >= max_lines:
            break
        for w in _split_words(line):
            freq[w] += 1
    # words as tuples of current token ids
    words: Dict[Tuple[int, ...], int] = {
        tuple(b + _N_SPECIAL for b in w): c for w, c in freq.items()}

    merges: List[Tuple[int, int]] = []
    next_id = _N_SPECIAL + 256
    while next_id < vocab_size:
        pairs: Counter = Counter()
        for w, c in words.items():
            for pair in zip(w, w[1:]):
                pairs[pair] += c
        if not pairs:
            break
        # deterministic tie-break: max count, then smallest pair ids
        best = min(pairs.items(), key=lambda kv: (-kv[1], kv[0]))[0]
        if pairs[best] < 2:
            break
        merges.append(best)
        new_words: Dict[Tuple[int, ...], int] = {}
        for w, c in words.items():
            out: List[int] = []
            i = 0
            while i < len(w):
                if i + 1 < len(w) and (w[i], w[i + 1]) == best:
                    out.append(next_id)
                    i += 2
                else:
                    out.append(w[i])
                    i += 1
            t = tuple(out)
            new_words[t] = new_words.get(t, 0) + c
        words = new_words
        next_id += 1
    tok = BPETokenizer(merges)
    if cache_path:
        tok.save(cache_path)
    return tok


def train_bpe_file(path: str, vocab_size: int,
                   cache_path: Optional[str] = None) -> BPETokenizer:
    """Train on a text file, with a JSON vocabulary cache keyed only by
    the caller's chosen path (the reference caches its WMT vocab pickles
    the same way)."""
    if cache_path and os.path.exists(cache_path):
        return BPETokenizer.load(cache_path)
    with open(path, encoding="utf-8") as f:
        return train_bpe(f, vocab_size, cache_path=cache_path)

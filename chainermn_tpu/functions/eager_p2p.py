"""Eager differentiable point-to-point communication.

Reference: chainermn/functions/point_to_point_communication.py (SURVEY.md
§2.3, §7 hard-part #1). There, ``send``/``recv`` run EAGERLY mid-forward
under define-by-run autograd — blocking MPI calls with data-dependent
Python control flow between them — and each Function's ``backward`` runs
the reverse transport.

The compiled path (:mod:`chainermn_tpu.functions.point_to_point`) covers
the traced world with ``ppermute``. This module covers the reference's
*eager* world: ``eager_send``/``eager_recv`` are ``jax.custom_vjp``
functions whose forward is an **ordered** ``io_callback`` into the
driver-level object-plane transport (``comm.send``/``comm.recv`` —
device→host→KV-store→peer), and whose backward runs the REVERSE
transport on a dedicated gradient channel: ``eager_send``'s vjp receives
the output-gradient from the destination, ``eager_recv``'s vjp sends the
incoming gradient back to the source. A reference script that
differentiates through an eager send loop now has a working path.

Contracts carried over from the reference (they are transport truths,
not API accidents):

- **Global order discipline.** Every process must issue its sends/recvs
  in a globally consistent order, or the transports deadlock — same
  contract as MPI (SURVEY.md §3.3). Autodiff replays the reverse order
  in backward, so a consistent forward order implies a consistent
  backward order (the reference's mirror schedule).
- **Known shapes.** ``eager_recv`` needs ``shape``/``dtype`` spelled out
  (or a ``like=`` example): a traced program cannot negotiate avals at
  runtime the way the reference's `_MessageType` header exchange did.
- **Cross-process only.** Same-process shards exchange data inside the
  compiled program (``chainermn_tpu.functions.send/recv``); the eager
  channel raises for same-process endpoints, like ``comm.send`` itself.
- **Anchoring (functional-autodiff deviation, enforced).** Chainer's
  define-by-run backward visits EVERY node reachable from the loss, so
  a Recv always sends its gradient back even when the receiving rank
  has no parameters behind it. JAX's transpose only walks paths from
  differentiated INPUTS to outputs — a received value used purely as
  data (``loss = f(my_params, h)`` where ``h`` came off the wire) is a
  constant w.r.t. ``my_params`` and its vjp would silently never run,
  deadlocking the sender's backward. ``eager_recv`` therefore requires
  ``anchor=``: any value on your differentiation path (a parameter, a
  prior delegate token); the transfer is threaded through it so
  backward provably visits the reverse transport.

Works both fully eagerly (``jax.grad`` of a host-level function — the
callbacks fire during trace/execute) and inside ``jit`` (the callbacks
become host round-trips at execution time; keep them off hot paths).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

_GRAD_NS = "eagergrad"


def _grad_tag(tag) -> str:
    """Backward messages ride their own ordered channel so a reverse
    transfer can never interleave with forward messages of the same
    tag."""
    return f"{_GRAD_NS}.{tag}"


def _io_callback(fn, result_shape, *args):
    from jax.experimental import io_callback

    return io_callback(fn, result_shape, *args, ordered=True)


@functools.lru_cache(maxsize=None)
def _send_fn(comm, dest: int, tag, avals):
    """Build (and cache) the custom_vjp send for one (comm, dest, tag,
    aval-signature). The aval signature is closed over so the backward
    knows the gradient's shapes without carrying residual arrays."""

    shapes = tuple(jax.ShapeDtypeStruct(s, d) for (s, d) in avals)

    @jax.custom_vjp
    def _send(*leaves):
        def _do(*concrete):
            comm.send(list(concrete), dest, tag=tag)
            return jnp.zeros((), jnp.float32)

        return _io_callback(_do, jax.ShapeDtypeStruct((), jnp.float32),
                            *leaves)

    def _fwd(*leaves):
        return _send(*leaves), None

    def _bwd(_, g_token):
        del g_token  # the real gradient comes from the peer

        def _do():
            gl = comm.recv(dest, tag=_grad_tag(tag))
            return tuple(jnp.asarray(g) for g in gl)

        return _io_callback(_do, shapes)

    _send.defvjp(_fwd, _bwd)
    return _send


@functools.lru_cache(maxsize=None)
def _recv_fn(comm, src: int, tag, avals):
    shapes = tuple(jax.ShapeDtypeStruct(s, d) for (s, d) in avals)

    @jax.custom_vjp
    def _recv(anchor):
        del anchor  # differentiation-path anchor; value unused

        def _do():
            got = comm.recv(src, tag=tag)
            return tuple(jnp.asarray(g) for g in got)

        return _io_callback(_do, shapes)

    def _fwd(anchor):
        return _recv(anchor), jnp.zeros_like(anchor)

    def _bwd(zero, gs):
        def _do(*concrete):
            comm.send(list(concrete), src, tag=_grad_tag(tag))
            return jnp.zeros((), jnp.float32)

        tok = _io_callback(_do, jax.ShapeDtypeStruct((), jnp.float32),
                           *gs)
        # the anchor's cotangent is numerically zero, but runs through
        # the transport's token so the send cannot be pruned
        return (zero + (tok * 0.0).astype(zero.dtype),)

    _recv.defvjp(_fwd, _bwd)
    return _recv


def _aval_sig(tree):
    leaves = jax.tree_util.tree_leaves(tree)
    return tuple(
        (tuple(jnp.shape(l)), jnp.result_type(l)) for l in leaves)


def eager_send(x, communicator, rank: int, tag=0):
    """Differentiable eager send of pytree ``x`` to ``rank``.

    Returns a scalar *delegate token* carrying the autograd edge — tie it
    into your local loss (add it, or via
    :func:`~chainermn_tpu.functions.pseudo_connect`-style summation) so
    backward visits the transfer; its forward value is 0.0. In backward,
    the matching ``eager_recv``'s vjp on the peer sends the output
    gradient back and this token's vjp delivers it to ``x``'s producers.
    """
    leaves, treedef = jax.tree_util.tree_flatten(x)
    fn = _send_fn(communicator, int(rank), tag, _aval_sig(x))
    return fn(*leaves)


def eager_recv(communicator, rank: int, shape=None, dtype=None,
               like=None, anchor=None, tag=0):
    """Differentiable eager receive from ``rank``.

    Declare the incoming value: either ``shape``+``dtype`` for a single
    array or ``like=`` an example pytree (only shapes/dtypes are read).

    ``anchor`` (REQUIRED for gradients to flow): any array on your
    differentiation path — a parameter, an upstream activation, or the
    token from a prior :func:`eager_send`. The transfer is threaded
    through it so ``jax.grad`` provably visits the vjp (which sends the
    incoming gradient back to ``rank`` on a dedicated channel); its
    value is not read and its cotangent contribution is zero. Without
    an anchor the receive is FORWARD-ONLY — fine for eval/serving
    loops, but differentiating around it silently treats the received
    value as a constant (JAX transposes only input→output paths) and
    the sending rank's backward will deadlock waiting for a gradient
    that never comes. MIGRATION.md covers the pattern.
    """
    if like is None:
        if shape is None or dtype is None:
            raise ValueError(
                "eager_recv needs the incoming aval: pass shape= and "
                "dtype=, or like= an example pytree (the reference's "
                "runtime _MessageType negotiation has no traced-world "
                "equivalent)")
        like = jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))
    leaves, treedef = jax.tree_util.tree_flatten(like)
    fn = _recv_fn(communicator, int(rank), tag, _aval_sig(like))
    anchor = jnp.zeros((), jnp.float32) if anchor is None \
        else jnp.asarray(anchor)
    out = fn(anchor)
    return jax.tree_util.tree_unflatten(treedef, list(out))

"""Differentiable collective communication functions.

Reference: chainermn/functions/collective_communication.py (SURVEY.md §2.3;
mount empty — module path citation): Chainer Functions for allgather (bwd:
reduce-scatter), alltoall (bwd: alltoall), bcast (bwd: gather+sum at root),
gather/scatter (bwd: each other).

JAX's collectives are already differentiable with exactly these transposes —
``all_gather`` ↔ ``psum_scatter``, ``all_to_all`` self-transposes, ``psum``'s
transpose broadcasts — so these wrappers only add the reference's API shape
(communicator-first signatures) on top of the in-graph comm ops. All of them
must be called inside a jitted/shard_map program on the communicator's mesh.
"""

from __future__ import annotations

import jax
from jax import lax


def allgather(communicator, x):
    """Every shard receives every shard's ``x``, stacked on axis 0.
    Backward: reduce-scatter of the output gradient."""
    return communicator.allgather(x)


def alltoall(communicator, x):
    """Chunk-exchange: shard r's chunk s goes to shard s's slot r.
    ``x``'s leading axis must be divisible by the communicator size.
    Backward: the reverse alltoall."""
    return communicator.alltoall(x)


def bcast(communicator, x, root: int = 0):
    """Broadcast ``x`` from shard ``root`` to all shards.
    Backward: gradient psum arriving at root."""
    return communicator.bcast(x, root=root)


def gather(communicator, x, root: int = 0):
    """Gather every shard's ``x``. In uniform SPMD the gathered stack is
    materialized on every shard (the root distinction is a host-side
    concern). Backward: scatter."""
    return communicator.gather(x, root=root)


def scatter(communicator, x, root: int = 0):
    """Each shard takes its own slice of the (replicated) stacked ``x``.
    Backward: gather."""
    return communicator.scatter(x, root=root)


def allreduce(communicator, x, op: str = "sum"):
    """All-reduce (not in the reference's functions module — it exposes this
    only at the communicator level — included for orthogonality)."""
    return communicator.allreduce(x, op)

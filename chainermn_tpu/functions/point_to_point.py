"""Differentiable point-to-point communication.

Reference: chainermn/functions/point_to_point_communication.py (SURVEY.md
§2.3; mount empty — module path citation). There, ``send`` is a Chainer
Function whose forward does a blocking MPI send and returns a *delegate
variable* (a dummy output carrying the autograd edge), and ``recv``'s
backward sends the gradient back — deadlock-free only if every rank issues
its calls in a globally consistent order.

TPU-native redesign: a transfer is a compiled ``lax.ppermute`` (XLA
collective-permute over ICI) executed by *all* shards of a ``shard_map``
program. JAX's ppermute is already differentiable — its transpose is the
reversed permutation — so the reference's hand-written reverse-communication
backward falls out of autodiff, and the runtime-deadlock class is eliminated:
the schedule is fixed at trace time.

The delegate-variable pattern survives as :class:`DelegateVariable`, a pytree
carrying the in-flight value between the ``send`` and ``recv`` calls, so
reference-shaped code (``phi = send(x, comm, dest); ...; y = recv(comm, src,
delegate_variable=phi)``) works unchanged inside the traced program.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


@jax.tree_util.register_pytree_node_class
class DelegateVariable:
    """Carries an in-flight transferred value between send() and recv().

    Reference: the dummy output of Send keeping the send on the backward
    graph (point_to_point_communication.py). Here it simply holds the
    ppermuted array (valid on the destination shard, zeros elsewhere), so
    data dependence — and therefore the reverse transfer in backward — is
    explicit.
    """

    def __init__(self, data, src: int, dest: int):
        self.data = data
        self.src = src
        self.dest = dest

    def tree_flatten(self):
        return (self.data,), (self.src, self.dest)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux[0], aux[1])


def transfer(x, communicator, edges: Sequence[Tuple[int, int]]):
    """Move shard-local values along ``edges`` = [(src_rank, dst_rank), ...].

    Every shard executes this (SPMD). A shard that is a dst in ``edges``
    receives the src's value; all other shards receive zeros. Lowered to one
    XLA collective-permute; differentiable (transpose = reversed edges).
    Multi-axis communicators (e.g. the multi-process ``('dcn', 'ici')``
    mesh) permute over the linearized rank space, so chain-list stages may
    span the DCN seam.

    Rank-order subtlety: edge ranks use the COMMUNICATOR's linearization
    (``comm.axis_index`` — row-major over ``comm.axis_names``), but
    ``lax.ppermute``'s lowering sorts each replica group, interpreting
    indices in MESH axis order. When a communicator was built with axes
    out of mesh order, the edges are remapped — without this the permute
    silently routes to the wrong shards.
    """
    axes = tuple(communicator.axis_names)
    mesh_order = tuple(a for a in communicator.mesh.axis_names if a in axes)
    if axes != mesh_order:
        shape = dict(communicator.mesh.shape)
        sizes = [shape[a] for a in axes]

        def remap(r: int) -> int:
            coords = {}
            for a, s in zip(reversed(axes), reversed(sizes)):
                coords[a] = r % s
                r //= s
            out = 0
            for a in mesh_order:
                out = out * shape[a] + coords[a]
            return out

        edges = [(remap(s), remap(d)) for (s, d) in edges]
    return jax.tree_util.tree_map(
        lambda l: lax.ppermute(l, axes, list(edges)), x
    )


def send(x, communicator, rank: int, *, self_rank: Optional[int] = None,
         tag: int = 0) -> DelegateVariable:
    """Send ``x`` from shard ``self_rank`` to shard ``rank``.

    Single-controller SPMD note: the reference infers the sender from the
    calling process; in a compiled uniform program the sender must be named
    statically — pass ``self_rank`` (MultiNodeChainList does this for you).
    Returns a :class:`DelegateVariable` to hand to :func:`recv`.
    """
    if self_rank is None:
        raise ValueError(
            "send() inside a compiled SPMD program needs the sending rank "
            "spelled out: send(x, comm, dest, self_rank=src)"
        )
    moved = transfer(x, communicator, [(self_rank, rank)])
    return DelegateVariable(moved, src=self_rank, dest=rank)


def recv(communicator, rank: int, delegate_variable: Optional[DelegateVariable] = None,
         tag: int = 0):
    """Receive the value sent from shard ``rank``.

    Pass the matching :class:`DelegateVariable` from :func:`send`. The
    returned array is the sent value on the destination shard (zeros on
    others — uniform SPMD); gradients flow back through the reversed
    collective-permute automatically.
    """
    if delegate_variable is None:
        raise ValueError(
            "recv() in the compiled SPMD world consumes the DelegateVariable "
            "returned by the matching send(); free-standing recv has no "
            "eager channel to read from"
        )
    if delegate_variable.src != rank:
        raise ValueError(
            f"recv(rank={rank}) does not match delegate sent from "
            f"rank {delegate_variable.src}"
        )
    return delegate_variable.data


def pseudo_connect(delegate_variable: DelegateVariable, *actual_variables):
    """Merge a delegate's graph edge into real variables.

    Reference: chainermn/functions/pseudo_connect.py — keeps a dangling
    send's backward alive when its output is unused. Functional autodiff
    makes data dependence explicit, so this adds a symbolic zero tying the
    delegate into the returned value(s): backward will traverse the transfer.
    """
    def tie(v):
        zero = jnp.zeros((), v.dtype)
        for leaf in jax.tree_util.tree_leaves(delegate_variable.data):
            zero = zero + jnp.sum(leaf * 0).astype(v.dtype)
        return v + zero

    if not actual_variables:
        return delegate_variable
    out = tuple(tie(v) for v in actual_variables)
    return out[0] if len(out) == 1 else out

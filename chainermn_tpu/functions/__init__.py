from .collective import allgather, allreduce, alltoall, bcast, gather, scatter
from .eager_p2p import eager_recv, eager_send
from .point_to_point import DelegateVariable, pseudo_connect, recv, send, transfer

__all__ = [
    "allgather",
    "allreduce",
    "alltoall",
    "bcast",
    "gather",
    "scatter",
    "send",
    "recv",
    "transfer",
    "pseudo_connect",
    "DelegateVariable",
    "eager_send",
    "eager_recv",
]

from .trainer import StandardUpdater, Trainer
from .reports import LogReport, PrintReport

__all__ = ["Trainer", "StandardUpdater", "LogReport", "PrintReport"]

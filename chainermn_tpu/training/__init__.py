from .trainer import StandardUpdater, Trainer
from .reports import LogReport, PrintReport
from .profiling import Profile

__all__ = ["Trainer", "StandardUpdater", "LogReport", "PrintReport",
           "Profile"]

"""Profiler integration.

The reference has no profiler of its own (SURVEY.md §5 — upstream practice
was Chainer TimerHook + nvprof). Here ``jax.profiler`` gives per-collective
and per-op device timing natively; this extension captures a trace window
viewable in TensorBoard/Perfetto/XProf.
"""

from __future__ import annotations

import jax


class Profile:
    """Trainer extension: capture a jax.profiler trace for iterations
    [start, stop). Attach with trigger=(1, 'iteration')::

        trainer.extend(Profile('prof_dir', start=3, stop=8),
                       trigger=(1, 'iteration'))

    Skips the first iterations so compilation stays out of the trace.
    """

    def __init__(self, logdir: str, start: int = 3, stop: int = 8):
        assert stop > start
        self.logdir = logdir
        self.start = start
        self.stop = stop
        self._active = False

    def __call__(self, trainer=None):
        it = trainer.updater.iteration
        if not self._active and it >= self.start and it < self.stop:
            jax.profiler.start_trace(self.logdir)
            self._active = True
        elif self._active and it >= self.stop:
            jax.profiler.stop_trace()
            self._active = False

    def close(self):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False

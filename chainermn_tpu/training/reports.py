"""Log/print reporting extensions (reference: Chainer's LogReport /
PrintReport, attached rank-0-only in every ChainerMN example)."""

from __future__ import annotations

import json
import os
from typing import List, Optional


class LogReport:
    """Accumulates trainer observations; optionally writes JSON lines."""

    def __init__(self, path: Optional[str] = None):
        self.log: List[dict] = []
        self.path = path

    def __call__(self, trainer):
        obs = dict(trainer.observation)
        self.log.append(obs)
        if self.path:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(self.path, "a") as f:
                f.write(json.dumps(obs) + "\n")


class PrintReport:
    def __init__(self, keys: List[str]):
        self.keys = keys
        self._header_done = False

    def __call__(self, trainer):
        if not self._header_done:
            print("  ".join(f"{k:>14}" for k in self.keys), flush=True)
            self._header_done = True
        row = []
        for k in self.keys:
            v = trainer.observation.get(k, float("nan"))
            row.append(f"{v:>14.6g}" if isinstance(v, float) else f"{v:>14}")
        print("  ".join(row), flush=True)

"""Log/print reporting extensions (reference: Chainer's LogReport /
PrintReport, attached rank-0-only in every ChainerMN example)."""

from __future__ import annotations

import json
import os
from typing import List, Optional


class LogReport:
    """Accumulates trainer observations; optionally writes JSON lines."""

    def __init__(self, path: Optional[str] = None):
        self.log: List[dict] = []
        self.path = path

    def __call__(self, trainer):
        obs = dict(trainer.observation)
        self.log.append(obs)
        if self.path:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(self.path, "a") as f:
                f.write(json.dumps(obs) + "\n")


class ReductionReport:
    """Surfaces the gradient-reduction plan (docs/collectives.md).

    Attach like LogReport. On the first call it prints the reducer's
    per-bucket plan once — algorithm, payload bytes, wire bytes — and on
    every call it folds the aggregate totals into
    ``trainer.observation`` (``comm/bytes``, ``comm/wire_bytes``,
    ``comm/wire_compression``, ``comm/strategy``) so
    LogReport/PrintReport pick them up. ``wire_bytes`` is the EXACT
    per-step wire footprint — for the blockwise formats it includes the
    f32 scale sidecar (``quantized_wire_bytes``), so the compression
    ratio is honest, not the nominal dtype ratio.

    ``reducer`` is a :class:`~chainermn_tpu.collectives.GradReducer`;
    ``grads_template`` any pytree with the gradient leaves' shapes and
    dtypes (the params tree works). The plan is host-side metadata — no
    device computation happens here.
    """

    def __init__(self, reducer, grads_template, quiet: bool = False):
        self.reducer = reducer
        self.rows = [] if reducer is None else reducer.plan(grads_template)
        self.quiet = quiet
        self._printed = False

    @property
    def total_bytes(self) -> int:
        return sum(r["bytes"] for r in self.rows)

    @property
    def total_wire_bytes(self) -> int:
        return sum(r["wire_bytes"] for r in self.rows)

    @property
    def wire_compression(self) -> float:
        """``wire_bytes / payload_bytes`` — 1.0 uncompressed, ~0.254
        for int8-block, ~0.129 for int4-block (scale sidecar included)."""
        total = self.total_bytes
        return self.total_wire_bytes / total if total else 1.0

    def __call__(self, trainer):
        if self.reducer is None:
            return
        if not self._printed and not self.quiet:
            for line in self.reducer.describe_rows(self.rows):
                print(line, flush=True)
            self._printed = True
        trainer.observation["comm/bytes"] = self.total_bytes
        trainer.observation["comm/wire_bytes"] = self.total_wire_bytes
        trainer.observation["comm/wire_compression"] = round(
            self.wire_compression, 6)
        trainer.observation["comm/strategy"] = self.reducer.name


class TuningReport:
    """Surfaces the schedtune-chosen collective schedule
    (docs/tuning.md) beside :class:`ReductionReport`.

    ``plan`` is a :class:`~chainermn_tpu.tuning.profile_db.SchedulePlan`
    or anything carrying one as ``.plan`` (a tuned
    ``create_multi_node_optimizer`` result works directly). On the
    first call it prints the chosen schedule once; on every call it
    folds ``tuning/overlap_frac``, ``tuning/bucket_bytes``, and
    ``tuning/strategy`` into ``trainer.observation`` so bench runs log
    what the tuner picked. No-op when there is no plan (untuned runs
    stay byte-identical in their logs).
    """

    def __init__(self, plan, quiet: bool = False):
        self.plan = getattr(plan, "plan", plan)
        self.quiet = quiet
        self._printed = False

    def __call__(self, trainer):
        plan = self.plan
        if plan is None:
            return
        if not self._printed and not self.quiet:
            db = " +double_buffering" if plan.double_buffering else ""
            wf = getattr(plan, "wire_format", "f32")
            db = (f" wire={wf}" if wf != "f32" else "") + db
            print(
                f"schedtune: {plan.strategy} "
                f"bucket_bytes={plan.bucket_bytes:,} "
                f"order={plan.bucket_order}{db} "
                f"overlap_frac={plan.overlap_fraction:.4f} "
                f"[{plan.source}] ({plan.fingerprint})", flush=True)
            self._printed = True
        trainer.observation["tuning/overlap_frac"] = plan.overlap_fraction
        trainer.observation["tuning/bucket_bytes"] = plan.bucket_bytes
        trainer.observation["tuning/strategy"] = plan.strategy


class CheckpointReport:
    """Surfaces the async snapshot plane's pipeline stats
    (docs/fault_tolerance.md#checkpoint-cadence) beside LogReport.

    ``plane`` is a
    :class:`~chainermn_tpu.checkpointing.AsyncSnapshotPlane`. On the
    first call it prints the pipeline configuration once; on every call
    it folds ``ckpt/stall_ms`` (the step-thread save stall —
    the number the async plane exists to shrink), ``ckpt/bytes``,
    ``ckpt/cadence`` (iterations between saves), ``ckpt/pending``,
    ``ckpt/published``, and ``ckpt/skipped`` (backpressure drops) into
    ``trainer.observation`` so LogReport/PrintReport and bench runs
    pick them up. Host-side counters only — nothing here touches the
    device or the writer thread.
    """

    def __init__(self, plane, quiet: bool = False):
        self.plane = plane
        self.quiet = quiet
        self._printed = False

    def __call__(self, trainer):
        p = self.plane
        if not self._printed and not self.quiet:
            print(f"ckpt plane: backpressure="
                  f"{getattr(p, 'backpressure', 'sync')} "
                  f"max_pending={getattr(p, 'max_pending', 0)} "
                  f"replicator="
                  f"{'on' if getattr(p, 'replicator', None) else 'off'}",
                  flush=True)
            self._printed = True
        obs = trainer.observation
        obs["ckpt/stall_ms"] = round(
            float(getattr(p, "stall_ms_last", 0.0)), 3)
        obs["ckpt/bytes"] = int(getattr(p, "bytes_last", 0))
        obs["ckpt/cadence"] = int(getattr(p, "cadence_last", 0))
        obs["ckpt/pending"] = int(getattr(p, "pending", 0))
        obs["ckpt/published"] = int(getattr(p, "published", 0))
        obs["ckpt/skipped"] = int(getattr(p, "skipped", 0))


class PrintReport:
    def __init__(self, keys: List[str]):
        self.keys = keys
        self._header_done = False

    def __call__(self, trainer):
        if not self._header_done:
            print("  ".join(f"{k:>14}" for k in self.keys), flush=True)
            self._header_done = True
        row = []
        for k in self.keys:
            v = trainer.observation.get(k, float("nan"))
            row.append(f"{v:>14.6g}" if isinstance(v, float) else f"{v:>14}")
        print("  ".join(row), flush=True)

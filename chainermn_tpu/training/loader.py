"""Prefetching data loader backed by the native runtime.

The reference's hot loop pays host time for iterator.next() + concat +
to_gpu every step (SURVEY.md §3.1). This loader overlaps batch assembly with
device compute: a C++ worker thread gathers the next batch's rows into a
reusable buffer (native/chainermn_native.cpp) while the current step runs,
and the Python side only wraps the finished buffer as a numpy view. Falls
back to synchronous numpy assembly without the native lib.
"""

from __future__ import annotations

from collections import deque
from typing import Iterator, Optional

import ctypes

import numpy as np

from chainermn_tpu.ops import native


class PrefetchingLoader:
    """Iterate (x_batch, y_batch) over array data with native prefetch.

    Args:
      xs, ys: the full data arrays (first axis indexes samples).
      batch_size: rows per batch.
      shuffle/seed/epochs: epoch order control (epochs=None → infinite).
      depth: prefetch depth (buffers in flight).
    """

    def __init__(self, xs: np.ndarray, ys: np.ndarray, batch_size: int,
                 shuffle: bool = True, seed: Optional[int] = None,
                 epochs: Optional[int] = None, depth: int = 2,
                 n_threads: int = 4):
        self.xs = np.ascontiguousarray(xs)
        self.ys = np.ascontiguousarray(ys)
        if batch_size > len(self.xs):
            # _indices would otherwise yield nothing and, with
            # epochs=None, spin forever re-shuffling an empty schedule
            raise ValueError(
                f"batch_size {batch_size} exceeds dataset size "
                f"{len(self.xs)}")
        self.batch_size = batch_size
        self._shuffle = shuffle
        self._rng = np.random.RandomState(seed)
        self._epochs = epochs
        self._depth = depth
        self._n_threads = n_threads
        self.epoch = 0
        self.is_new_epoch = False
        self._native = native.get_lib()
        self._handle = None
        if self._native is not None:
            xrow = self.xs.dtype.itemsize * int(
                np.prod(self.xs.shape[1:], initial=1))
            yrow = self.ys.dtype.itemsize * int(
                np.prod(self.ys.shape[1:], initial=1))
            self._handle = self._native.cmn_loader_create(
                self.xs.ctypes.data, self.ys.ctypes.data, xrow, yrow,
                batch_size, depth, n_threads)
        self._outstanding = 0
        self._index_iter = self._indices()
        # epochs-completed value for each submitted-but-not-yet-returned
        # batch, FIFO — ``self.epoch`` must track the batch the caller
        # RECEIVES, not how far ahead the prefetcher has drained the
        # index generator
        self._pending_epochs: deque = deque()

    def _indices(self) -> Iterator[tuple]:
        """Yields (epochs_completed_after_this_batch, index_array)."""
        n = len(self.xs)
        ep = 0
        while self._epochs is None or ep < self._epochs:
            order = np.arange(n, dtype=np.int64)
            if self._shuffle:
                self._rng.shuffle(order)
            starts = list(range(0, n - self.batch_size + 1, self.batch_size))
            for j, at in enumerate(starts):
                done = ep + 1 if j == len(starts) - 1 else ep
                yield done, order[at:at + self.batch_size]
            ep += 1

    def _submit_one(self) -> bool:
        try:
            ep, idx = next(self._index_iter)
        except StopIteration:
            return False
        idx = np.ascontiguousarray(idx, dtype=np.int64)
        self._native.cmn_loader_submit(
            self._handle,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(idx))
        self._pending_epochs.append(ep)
        self._outstanding += 1
        return True

    def __iter__(self):
        return self

    def __next__(self):
        if self._handle is None:
            # numpy fallback: synchronous assembly
            ep, idx = next(self._index_iter)  # StopIteration ends iteration
            batch = (native.gather_rows(self.xs, idx),
                     native.gather_rows(self.ys, idx))
            self.is_new_epoch = ep > self.epoch
            self.epoch = ep
            return batch
        while self._outstanding < self._depth:
            if not self._submit_one():
                break
        if self._outstanding == 0:
            raise StopIteration
        xptr = ctypes.c_void_p()
        yptr = ctypes.c_void_p()
        buf = self._native.cmn_loader_next(
            self._handle, ctypes.byref(xptr), ctypes.byref(yptr))
        self._outstanding -= 1
        bs = self.batch_size
        x = np.ctypeslib.as_array(
            ctypes.cast(xptr, ctypes.POINTER(ctypes.c_uint8)),
            shape=(bs * self.xs.dtype.itemsize
                   * int(np.prod(self.xs.shape[1:], initial=1)),),
        ).view(self.xs.dtype).reshape((bs,) + self.xs.shape[1:])
        y = np.ctypeslib.as_array(
            ctypes.cast(yptr, ctypes.POINTER(ctypes.c_uint8)),
            shape=(bs * self.ys.dtype.itemsize
                   * int(np.prod(self.ys.shape[1:], initial=1)),),
        ).view(self.ys.dtype).reshape((bs,) + self.ys.shape[1:])
        # copy out so the buffer can be recycled immediately; the gather
        # itself (the expensive part) already happened off-thread
        x, y = x.copy(), y.copy()
        self._native.cmn_loader_release(self._handle, buf)
        ep = self._pending_epochs.popleft()
        self.is_new_epoch = ep > self.epoch
        self.epoch = ep
        return x, y

    next = __next__

    def close(self):
        if self._handle is not None:
            self._native.cmn_loader_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

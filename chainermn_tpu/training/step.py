"""Train/eval step factories: the framework's compiled hot path.

Reference hot loop (SURVEY.md §3.1): forward/backward, pack grads, NCCL
allreduce, unpack, optimizer update — four host-driven phases. Here the whole
iteration is ONE compiled XLA program over the mesh: loss/grad, gradient
all-reduce (vma-aware psum), optimizer update, and metric reduction, with
XLA overlapping the collective against adjacent compute (what the
reference's double-buffering thread did by hand).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax, shard_map
from jax.sharding import PartitionSpec as P


def _accepts_train(model) -> bool:
    import inspect

    try:
        sig = inspect.signature(type(model).__call__)
    except (TypeError, ValueError):
        return False
    return "train" in sig.parameters


def classifier_loss(model, params, x, y, train: bool = True,
                    mutable=None, extra_vars=None, rngs=None):
    """Softmax cross-entropy + accuracy for an (x, y) classifier.

    The ``train`` flag is forwarded whenever the model's ``__call__``
    declares it (dropout/BN models), independent of whether mutable
    collections exist.
    """
    variables = {"params": params, **(extra_vars or {})}
    kwargs = {}
    if _accepts_train(model):
        kwargs["train"] = train
    if mutable and train:
        logits, new_vars = model.apply(variables, x, mutable=list(mutable),
                                       rngs=rngs, **kwargs)
    else:
        logits = model.apply(variables, x, rngs=rngs, **kwargs)
        new_vars = {}
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
    acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
    return loss, (acc, new_vars)


def make_data_parallel_train_step(
    model,
    optimizer: optax.GradientTransformation,
    comm,
    loss_fn: Optional[Callable] = None,
    mutable: Optional[Tuple[str, ...]] = None,
    donate: bool = True,
    grad_accum: int = 1,
    remat: Any = False,
    with_rng: bool = False,
    scan_steps: int = 1,
):
    """Build the jitted data-parallel train step.

    ``state = (params, opt_state)`` or ``(params, opt_state, extra_vars)``
    when ``mutable`` names flax variable collections (e.g. BN
    ``('batch_stats',)`` — their new values are pmean-synced across replicas,
    the reference's MultiNodeBatchNormalization/AllreducePersistent
    semantics). The optimizer should already wrap the communicator
    (create_multi_node_optimizer); a plain optax optimizer also works when
    autodiff inserts the psum (default shard_map mode).

    ``with_rng=True`` changes the step signature to
    ``step(state, x, y, rng)`` and threads per-shard dropout keys into the
    loss (``rng`` is one PRNGKey; each shard folds in its mesh position, and
    each micro-batch its index, so masks decorrelate). Required for models
    with dropout — without it the loss runs rng-less and flax raises.

    ``scan_steps=K`` compiles K optimizer steps into ONE XLA program via
    ``lax.scan``: the step signature becomes ``step(state, xs, ys)`` where
    ``xs``/``ys`` carry a leading K axis (one batch per inner step) and the
    returned metrics gain a leading K axis. One dispatch per K steps — on
    hosts with a high per-dispatch floor (e.g. a tunneled chip) this is the
    difference between measuring dispatch latency and measuring the device.

    ``grad_accum=N`` splits each shard's batch into N micro-batches and
    accumulates gradients over a ``lax.scan`` — same optimizer math as the
    full batch at 1/N the activation memory (micro-batch moments differ for
    BN, as in every framework). ``remat`` rematerializes the forward during
    backward (``True`` for full remat, or a ``jax.checkpoint`` policy, e.g.
    ``jax.checkpoint_policies.dots_with_no_batch_dims_saveable``) — the
    HBM-for-FLOPs trade the task's hardware notes call for.
    """
    lf = loss_fn or classifier_loss
    mesh = comm.mesh
    axes = comm.axis_names
    dspec = P(axes if len(axes) > 1 else axes[0])

    # A stateful GradReducer (quantized with error feedback) threads
    # per-rank residuals through the optimizer state: their stacked
    # (comm.size, ...) leaves are sharded over the comm axis and
    # (un)stacked around the update — everything else about the step is
    # identical, and the stateless path below compiles the exact same
    # program as before this knob existed.
    reducer = getattr(optimizer, "grad_reducer", None)
    stateful_reducer = bool(getattr(reducer, "stateful", False))
    if stateful_reducer:
        from chainermn_tpu.optimizers import _ReducerWrappedState

    def local_step(state, x, y, rng=None):
        if mutable:
            params, opt_state, extra = state
        else:
            params, opt_state = state
            extra = None
        if stateful_reducer:
            # per-rank residuals arrive stacked-with-leading-1; drop to
            # the rank-local view the reducer works in
            opt_state = _ReducerWrappedState(
                opt_state.inner,
                jax.tree_util.tree_map(lambda r: r[0], opt_state.reducer))

        if rng is not None:
            # decorrelate dropout masks across shards
            for a in axes:
                rng = jax.random.fold_in(rng, lax.axis_index(a))

        if with_rng:
            def f(p, x, y, extra, r):
                return lf(model, p, x, y, train=True, mutable=mutable,
                          extra_vars=extra, rngs={"dropout": r})
        else:
            def f(p, x, y, extra, r):
                return lf(model, p, x, y, train=True, mutable=mutable,
                          extra_vars=extra)

        if remat:
            policy = None if remat is True else remat
            f = jax.checkpoint(f, policy=policy)

        if grad_accum > 1:
            b = x.shape[0]
            assert b % grad_accum == 0, (
                f"per-shard batch {b} not divisible by grad_accum "
                f"{grad_accum}")
            xm = x.reshape((grad_accum, b // grad_accum) + x.shape[1:])
            ym = y.reshape((grad_accum, b // grad_accum) + y.shape[1:])

            def one(extra_c, xi, yi, i):
                # per-micro-batch dropout key
                r = None if rng is None else jax.random.fold_in(rng, i)
                (loss, (acc, new_vars)), g = jax.value_and_grad(
                    f, has_aux=True)(params, xi, yi, extra_c, r)
                new_extra = (
                    {k: new_vars[k] for k in mutable} if mutable else extra_c
                )
                return g, loss, acc, new_extra

            def micro(carry, xyi):
                g_acc, loss_acc, acc_acc, extra_c = carry
                g, loss, acc, new_extra = one(extra_c, *xyi)
                g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
                return (g_acc, loss_acc + loss, acc_acc + acc,
                        new_extra), None

            # The first micro-batch runs outside the scan so the carry is
            # initialized with each component's TRUE varying-axis type:
            # grads w.r.t. replicated params arrive already psummed
            # (axis-invariant) under vma tracking — casting a zeros carry to
            # varying here would make allreduce_grad re-reduce them (an N x
            # gradient), while leaving it invariant breaks BN state (varying).
            g0, l0, a0, e0 = one(extra, xm[0], ym[0], 0)
            (g_sum, loss_sum, acc_sum, new_extra), _ = lax.scan(
                micro, (g0, l0, a0, e0),
                (xm[1:], ym[1:], jnp.arange(1, grad_accum)))
            grads = jax.tree_util.tree_map(
                lambda g: g / grad_accum, g_sum)
            loss = loss_sum / grad_accum
            acc = acc_sum / grad_accum
            new_vars = new_extra if mutable else {}
        else:
            (loss, (acc, new_vars)), grads = jax.value_and_grad(
                f, has_aux=True)(params, x, y, extra, rng)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        if stateful_reducer:
            opt_state = _ReducerWrappedState(
                opt_state.inner,
                jax.tree_util.tree_map(lambda r: r[None],
                                       opt_state.reducer))
        params = optax.apply_updates(params, updates)
        metrics = {
            "main/loss": lax.pmean(loss, axes),
            "main/accuracy": lax.pmean(acc, axes),
        }
        if mutable:
            # replica-consistent persistent state (BN running stats)
            new_extra = jax.tree_util.tree_map(
                lambda v: lax.pmean(v, axes)
                if jax.typeof(v).vma else v,
                new_vars,
            )
            return (params, opt_state, new_extra), metrics
        return (params, opt_state), metrics

    if scan_steps > 1:
        single = local_step

        def local_step(state, xs, ys, rng=None):
            def body(state, ixy):
                i, x, y = ixy
                r = None if rng is None else jax.random.fold_in(rng, i)
                return single(state, x, y, r)

            return lax.scan(
                body, state, (jnp.arange(scan_steps), xs, ys))

        # batch axis moves to dim 1 under the leading scan axis
        batch_spec = P(None, axes if len(axes) > 1 else axes[0])
    else:
        batch_spec = dspec

    n_state = 3 if mutable else 2
    if not stateful_reducer:
        in_specs = ((P(),) * n_state, batch_spec, batch_spec)
        if with_rng:
            in_specs = in_specs + (P(),)  # the PRNGKey, replicated
        step = jax.jit(
            shard_map(
                local_step,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=((P(),) * n_state, P()),
            ),
            donate_argnums=(0,) if donate else (),
        )
        return step

    # Stateful reducer: the opt-state specs depend on the state's
    # structure (which leaves are residuals), so compile lazily per
    # treedef — the make_expert_parallel_train_step pattern.
    lead_spec = P(axes if len(axes) > 1 else axes[0])

    def build(state):
        opt_state = state[1]
        if not isinstance(opt_state, _ReducerWrappedState):
            raise ValueError(
                "optimizer carries a stateful grad_reducer but the "
                "opt_state is not reducer-wrapped; initialize with "
                "optimizer.init(params) (outside jit) so the residual "
                "state exists")
        ospecs = _ReducerWrappedState(
            jax.tree_util.tree_map(lambda _: P(), opt_state.inner),
            jax.tree_util.tree_map(lambda _: lead_spec,
                                   opt_state.reducer),
        )
        state_specs = ((P(), ospecs, P()) if mutable else (P(), ospecs))
        in_specs = (state_specs, batch_spec, batch_spec)
        if with_rng:
            in_specs = in_specs + (P(),)
        return jax.jit(
            shard_map(
                local_step,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=(state_specs, P()),
            ),
            donate_argnums=(0,) if donate else (),
        )

    compiled = {}

    def step(state, *args):
        key = jax.tree_util.tree_structure(state)
        if key not in compiled:
            compiled[key] = build(state)
        return compiled[key](state, *args)

    return step


def _is_expert_path(path, expert_key: str) -> bool:
    """True for per-shard expert tables. The router lives under the MoE
    module too but is data-parallel (replicated; see ExpertParallelMLP's
    parameter-sync contract), so it is explicitly excluded."""
    parts = [str(getattr(k, "key", k)) for k in path]
    return (any(expert_key in p for p in parts)
            and not any("router" in p for p in parts))


def init_expert_parallel_state(model, comm, rng, sample, optimizer,
                               expert_key: str = "moe"):
    """Initialize a model containing expert-parallel layers.

    Expert leaves (param path containing ``expert_key``) are per-shard:
    each mesh shard initializes its own experts (rank-folded RNG) and the
    global array concatenates them over the comm axis (sharded ``P(ax)``).
    Every other leaf is replicated — shard 0's init wins.

    Returns ``(state, param_specs)`` where ``state = (params, opt_state)``
    and ``param_specs`` is the PartitionSpec pytree
    (make_expert_parallel_train_step needs it).
    """
    mesh = comm.mesh
    ax = comm.axis_names[0]

    def init_fn(toks):
        r = jax.random.fold_in(rng, lax.axis_index(ax))
        params = model.init(r, toks)["params"]

        def fix(path, leaf):
            if _is_expert_path(path, expert_key):
                return leaf                       # this shard's experts
            return lax.all_gather(leaf, ax)[0]    # replicate shard 0's init

        return jax.tree_util.tree_map_with_path(fix, params)

    # structure discovery pass (shapes only — out_specs don't matter here)
    abs_params = jax.eval_shape(
        shard_map(init_fn, mesh=mesh, in_specs=(P(),), out_specs=P(),
                  check_vma=False),
        sample,
    )
    param_specs = jax.tree_util.tree_map_with_path(
        lambda path, _: P(ax) if _is_expert_path(path, expert_key) else P(),
        abs_params,
    )
    params = jax.jit(shard_map(
        init_fn, mesh=mesh, in_specs=(P(),), out_specs=param_specs,
        check_vma=False,
    ))(sample)
    opt_state = jax.jit(optimizer.init)(params)  # shardings follow params
    return (params, opt_state), param_specs


def make_expert_parallel_train_step(
    model,
    optimizer: optax.GradientTransformation,
    comm,
    param_specs,
    loss_fn: Optional[Callable] = None,
    expert_key: str = "moe",
    donate: bool = True,
):
    """Train step for models with expert-parallel (MoE) layers.

    Shared parameters are data-parallel (replicated; their gradients are
    globally reduced by shard_map's replication typing — do NOT wrap the
    optimizer in create_multi_node_optimizer here, that would re-reduce).
    Expert parameters stay sharded over the comm axis: each shard owns and
    updates its experts; their gradients already aggregate every shard's
    tokens through the all_to_all transpose, so no collective touches them.

    ``param_specs`` comes from init_expert_parallel_state. ``optimizer`` is
    a PLAIN optax transformation.
    """
    lf = loss_fn or classifier_loss
    mesh = comm.mesh
    axes = comm.axis_names
    dspec = P(axes if len(axes) > 1 else axes[0])

    def local_step(state, x, y):
        params, opt_state = state

        def f(p):
            loss, (acc, _) = lf(model, p, x, y, train=True)
            # global-mean objective; expert grads flow through the
            # all_to_all transpose, shared grads through replication typing
            return lax.pmean(loss, axes), acc

        (loss, acc), grads = jax.value_and_grad(f, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics = {
            "main/loss": loss,
            "main/accuracy": lax.pmean(acc, axes),
        }
        return (params, opt_state), metrics

    def opt_spec_like(tree):
        """Specs over an opt-state pytree: leaves on an expert path are
        sharded, the rest (incl. step counters) replicated."""
        # same single-axis sharding as param_specs (axes[0]) — a multi-axis
        # spec here would disagree with the params' local shapes
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: P(axes[0])
            if _is_expert_path(path, expert_key) and getattr(leaf, "ndim", 0)
            else P(),
            tree,
        )

    def build(state):
        params, opt_state = state
        opt_specs = opt_spec_like(opt_state)
        return jax.jit(
            shard_map(
                local_step,
                mesh=mesh,
                in_specs=((param_specs, opt_specs), dspec, dspec),
                out_specs=(((param_specs, opt_specs)), P()),
            ),
            donate_argnums=(0,) if donate else (),
        )

    compiled = {}

    def step(state, x, y):
        key = jax.tree_util.tree_structure(state)
        if key not in compiled:
            compiled[key] = build(state)
        return compiled[key](state, x, y)

    return step


def make_eval_step(model, comm, loss_fn: Optional[Callable] = None,
                   extra_vars_in_state: bool = False):
    """Jitted eval step: (state, x, y) -> metrics dict (pmean-reduced)."""
    lf = loss_fn or classifier_loss
    mesh = comm.mesh
    axes = comm.axis_names
    dspec = P(axes if len(axes) > 1 else axes[0])

    def local_eval(state, x, y):
        params = state[0]
        extra = state[2] if extra_vars_in_state else None
        loss, (acc, _) = lf(model, params, x, y, train=False,
                            mutable=None, extra_vars=extra)
        return {
            "validation/main/loss": lax.pmean(loss, axes),
            "validation/main/accuracy": lax.pmean(acc, axes),
        }

    n_state = 3 if extra_vars_in_state else 2
    return jax.jit(
        shard_map(
            local_eval,
            mesh=mesh,
            in_specs=((P(),) * n_state, dspec, dspec),
            out_specs=P(),
        )
    )

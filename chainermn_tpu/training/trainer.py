"""Minimal trainer/updater loop.

The reference delegates its training loop to Chainer's
``Trainer``/``StandardUpdater`` and integrates via extensions (SURVEY.md
§3.1). This standalone rebuild ships a lean equivalent: an updater that
feeds global batches (sharded over the communicator's mesh axis) into one
jitted train step, and a trainer with interval-triggered extensions — enough
to run every reference example shape (log/print/eval/snapshot at triggers,
rank-0-only reporting convention).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


def default_converter(batch):
    """List of (x, y) pairs → stacked arrays (the reference's concat_examples)."""
    xs = np.stack([b[0] for b in batch])
    ys = np.stack([b[1] for b in batch])
    return xs, ys


class StandardUpdater:
    """Pulls a batch, shards it over the data axis, runs the jitted step.

    ``step_fn(state, *batch_arrays) -> (state, metrics_dict)`` must already
    be jitted (with the collective ops compiled in — see
    create_multi_node_optimizer). ``state`` is any pytree the caller owns.
    """

    def __init__(self, iterator, step_fn: Callable, state: Any, comm,
                 converter: Callable = default_converter):
        self.iterator = iterator
        self.step_fn = step_fn
        self.state = state
        self.comm = comm
        self.converter = converter
        self.iteration = 0
        self.last_metrics: Dict[str, float] = {}
        axes = comm.axis_names
        self._data_sharding = NamedSharding(
            comm.mesh, P(axes if len(axes) > 1 else axes[0])
        )

    @property
    def epoch(self):
        return getattr(self.iterator, "epoch", 0)

    @property
    def is_new_epoch(self):
        return getattr(self.iterator, "is_new_epoch", False)

    def shard_batch(self, arrays):
        n = self.comm.size
        if jax.process_count() > 1:
            # each process feeds its LOCAL rows; assemble the global
            # sharded array without any host ever holding the full batch
            n_local = jax.local_device_count()
            for a in arrays:
                if hasattr(a, "shape") and a.shape and (
                        a.shape[0] % n_local != 0):
                    raise ValueError(
                        f"per-process batch size {a.shape[0]} is not "
                        f"divisible by this process's {n_local} local "
                        "devices — every process must feed a local row "
                        f"count that is a multiple of {n_local} (and all "
                        "processes must feed the same count, or "
                        "make_array_from_process_local_data will raise a "
                        "shape error)"
                    )
            return tuple(
                jax.make_array_from_process_local_data(
                    self._data_sharding, np.asarray(a))
                for a in arrays
            )
        for a in arrays:
            if hasattr(a, "shape") and a.shape and a.shape[0] % n != 0:
                raise ValueError(
                    f"global batch size {a.shape[0]} is not divisible by the "
                    f"{n} devices of the data axis — pick a batch size that "
                    f"is a multiple of {n}"
                )
        return tuple(
            jax.device_put(a, self._data_sharding) for a in arrays
        )

    def update(self):
        batch = next(self.iterator)
        arrays = self.converter(batch)
        arrays = self.shard_batch(arrays)
        self.state, metrics = self.step_fn(self.state, *arrays)
        self.last_metrics = metrics
        self.iteration += 1


class _Entry:
    def __init__(self, ext, trigger, name):
        self.ext = ext
        self.n, self.unit = trigger
        self.name = name
        self._last_epoch = 0
        self.closed = False

    def due(self, updater) -> bool:
        if self.unit == "iteration":
            return updater.iteration % self.n == 0
        if self.unit == "epoch":
            if updater.is_new_epoch and updater.epoch % self.n == 0:
                return True
            return False
        raise ValueError(f"unknown trigger unit {self.unit!r}")


class Trainer:
    """Runs the updater until the stop trigger, firing extensions.

    Reference convention preserved: attach reporting extensions only on the
    master (``if comm.rank == 0: trainer.extend(...)``) — metric reduction
    happens in-graph or via the multi-node evaluator, not here.
    """

    def __init__(self, updater: StandardUpdater,
                 stop_trigger: Tuple[int, str] = (1, "epoch"),
                 out: str = "result"):
        self.updater = updater
        self.stop_n, self.stop_unit = stop_trigger
        self.out = out
        self._extensions = []
        self.observation: Dict[str, float] = {}

    def extend(self, extension, trigger: Tuple[int, str] = (1, "epoch"),
               name: Optional[str] = None):
        self._extensions.append(_Entry(extension, trigger, name))

    def _stopped(self) -> bool:
        if self.stop_unit == "epoch":
            return self.updater.epoch >= self.stop_n
        return self.updater.iteration >= self.stop_n

    def _materialize_observation(self, start):
        # float() blocks on the device — do it only when someone will read
        # the numbers, so async dispatch keeps the device pipeline full.
        # update (not replace): extension-published keys (validation/...)
        # stay visible until their next refresh
        self.observation.update(
            {k: float(v) for k, v in self.updater.last_metrics.items()}
        )
        self.observation["iteration"] = self.updater.iteration
        self.observation["epoch"] = self.updater.epoch
        self.observation["elapsed_time"] = time.time() - start

    def run(self):
        if any(e.closed for e in self._extensions):
            # a prior run() finalized extensions holding external
            # resources; silently skipping (or re-firing) them would lose
            # data — resuming needs a fresh Trainer
            raise RuntimeError(
                "this Trainer already ran and finalized its extensions; "
                "construct a new Trainer (re-attaching extensions) to "
                "resume")
        start = time.time()
        try:
            while not self._stopped():
                try:
                    self.updater.update()
                except StopIteration:
                    break  # non-repeating iterator exhausted
                due = [e for e in self._extensions if e.due(self.updater)]
                if due:
                    self._materialize_observation(start)
                    for e in due:
                        e.ext(self)
            self._materialize_observation(start)
        finally:
            # finalize extensions that hold external resources (an open
            # jax.profiler trace, checkpoint writers) even when the run ends
            # before their stop condition or raises
            for e in self._extensions:
                if e.closed:
                    continue  # a prior run() already released it
                close = getattr(e.ext, "close", None)
                if callable(close):
                    e.closed = True
                    try:
                        close()
                    except Exception:
                        import traceback

                        traceback.print_exc()

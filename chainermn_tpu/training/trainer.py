"""Minimal trainer/updater loop.

The reference delegates its training loop to Chainer's
``Trainer``/``StandardUpdater`` and integrates via extensions (SURVEY.md
§3.1). This standalone rebuild ships a lean equivalent: an updater that
feeds global batches (sharded over the communicator's mesh axis) into one
jitted train step, and a trainer with interval-triggered extensions — enough
to run every reference example shape (log/print/eval/snapshot at triggers,
rank-0-only reporting convention).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


def default_converter(batch):
    """List of (x, y) pairs → stacked arrays (the reference's concat_examples)."""
    xs = np.stack([b[0] for b in batch])
    ys = np.stack([b[1] for b in batch])
    return xs, ys


class StandardUpdater:
    """Pulls a batch, shards it over the data axis, runs the jitted step.

    ``step_fn(state, *batch_arrays) -> (state, metrics_dict)`` must already
    be jitted (with the collective ops compiled in — see
    create_multi_node_optimizer). ``state`` is any pytree the caller owns.
    """

    def __init__(self, iterator, step_fn: Callable, state: Any, comm,
                 converter: Callable = default_converter):
        self.iterator = iterator
        self.step_fn = step_fn
        self.state = state
        self.comm = comm
        self.converter = converter
        self.iteration = 0
        self.last_metrics: Dict[str, float] = {}
        axes = comm.axis_names
        self._data_sharding = NamedSharding(
            comm.mesh, P(axes if len(axes) > 1 else axes[0])
        )

    @property
    def epoch(self):
        return getattr(self.iterator, "epoch", 0)

    @property
    def is_new_epoch(self):
        return getattr(self.iterator, "is_new_epoch", False)

    def shard_batch(self, arrays):
        n = self.comm.size
        if jax.process_count() > 1:
            # each process feeds its LOCAL rows; assemble the global
            # sharded array without any host ever holding the full batch
            n_local = jax.local_device_count()
            for a in arrays:
                if hasattr(a, "shape") and a.shape and (
                        a.shape[0] % n_local != 0):
                    raise ValueError(
                        f"per-process batch size {a.shape[0]} is not "
                        f"divisible by this process's {n_local} local "
                        "devices — every process must feed a local row "
                        f"count that is a multiple of {n_local} (and all "
                        "processes must feed the same count, or "
                        "make_array_from_process_local_data will raise a "
                        "shape error)"
                    )
            return tuple(
                jax.make_array_from_process_local_data(
                    self._data_sharding, np.asarray(a))
                for a in arrays
            )
        for a in arrays:
            if hasattr(a, "shape") and a.shape and a.shape[0] % n != 0:
                raise ValueError(
                    f"global batch size {a.shape[0]} is not divisible by the "
                    f"{n} devices of the data axis — pick a batch size that "
                    f"is a multiple of {n}"
                )
        return tuple(
            jax.device_put(a, self._data_sharding) for a in arrays
        )

    def update(self):
        batch = next(self.iterator)
        arrays = self.converter(batch)
        arrays = self.shard_batch(arrays)
        self.state, metrics = self.step_fn(self.state, *arrays)
        self.last_metrics = metrics
        self.iteration += 1

    # -- full-state resume (docs/fault_tolerance.md) --------------------

    def host_state_dict(self) -> Dict[str, Any]:
        """Host-side training position for checkpoints: iteration count,
        iterator position/epoch/RNG, and the global NumPy RNG (augment
        pipelines draw from it). Everything here is small and picklable;
        the device pytree (``self.state``) is snapshotted separately."""
        it_state = getattr(self.iterator, "state_dict", None)
        return {
            "iteration": self.iteration,
            "iterator": it_state() if callable(it_state) else None,
            "np_random": np.random.get_state(),
        }

    def load_host_state(self, host: Dict[str, Any]) -> None:
        """Restore :meth:`host_state_dict` output — the resumed run draws
        the exact next batch the interrupted run would have."""
        self.iteration = int(host.get("iteration", self.iteration))
        it_state = host.get("iterator")
        restore = getattr(self.iterator, "load_state_dict", None)
        if it_state is not None and callable(restore):
            restore(it_state)
        if host.get("np_random") is not None:
            np.random.set_state(host["np_random"])


class _Entry:
    def __init__(self, ext, trigger, name):
        self.ext = ext
        self.n, self.unit = trigger
        self.name = name
        self._last_epoch = 0
        self.closed = False

    def due(self, updater) -> bool:
        if self.unit == "iteration":
            return updater.iteration % self.n == 0
        if self.unit == "epoch":
            if updater.is_new_epoch and updater.epoch % self.n == 0:
                return True
            return False
        raise ValueError(f"unknown trigger unit {self.unit!r}")


class Trainer:
    """Runs the updater until the stop trigger, firing extensions.

    Reference convention preserved: attach reporting extensions only on the
    master (``if comm.rank == 0: trainer.extend(...)``) — metric reduction
    happens in-graph or via the multi-node evaluator, not here.

    Resilience (docs/fault_tolerance.md): with ``handle_preemption=True``
    (default) the run installs a SIGTERM/SIGINT flag handler and polls it
    every step — a preemption triggers an emergency checkpoint on every
    extension that offers ``emergency_save`` (the multi-node
    checkpointer), then a clean loop exit with ``trainer.preempted`` set.
    Any exception escaping the step loop also gets the last-chance
    checkpoint before extensions are finalized, so partial-epoch progress
    survives crashes. The chaos harness's step hook and the peer-death
    watchdog (``$CHAINERMN_TPU_WATCHDOG``) ride the same per-step poll.
    """

    def __init__(self, updater: StandardUpdater,
                 stop_trigger: Tuple[int, str] = (1, "epoch"),
                 out: str = "result", handle_preemption: bool = True):
        self.updater = updater
        self.stop_n, self.stop_unit = stop_trigger
        self.out = out
        self.handle_preemption = handle_preemption
        self.preempted = False
        self._extensions = []
        self.observation: Dict[str, float] = {}

    def extend(self, extension, trigger: Tuple[int, str] = (1, "epoch"),
               name: Optional[str] = None):
        self._extensions.append(_Entry(extension, trigger, name))

    def _stopped(self) -> bool:
        if self.stop_unit == "epoch":
            return self.updater.epoch >= self.stop_n
        return self.updater.iteration >= self.stop_n

    def _materialize_observation(self, start):
        # float() blocks on the device — do it only when someone will read
        # the numbers, so async dispatch keeps the device pipeline full.
        # update (not replace): extension-published keys (validation/...)
        # stay visible until their next refresh
        self.observation.update(
            {k: float(v) for k, v in self.updater.last_metrics.items()}
        )
        self.observation["iteration"] = self.updater.iteration
        self.observation["epoch"] = self.updater.epoch
        self.observation["elapsed_time"] = time.time() - start

    def _emergency_checkpoint(self, deadline_s=None) -> bool:
        """Fire ``emergency_save`` on every extension offering it (the
        multi-node checkpointer). Failures are printed, not raised — this
        runs on the way OUT of a dying/preempted run, where a save error
        must not mask the original exit path."""
        fired = False
        for e in self._extensions:
            fn = getattr(e.ext, "emergency_save", None)
            if callable(fn):
                try:
                    fn(self, deadline_s=deadline_s)
                    fired = True
                except Exception:
                    import traceback

                    traceback.print_exc()
        return fired

    def exit_code(self) -> int:
        """Process exit status under the supervisor contract
        (resilience/supervisor.py): :data:`PREEMPTED_EXIT_CODE` (143)
        after a preempted run — the supervisor restarts it for free —
        else 0. Train scripts: ``sys.exit(trainer.exit_code())``, or
        wrap the whole main in
        :func:`chainermn_tpu.resilience.supervisor.main_exit_code`
        (which also maps ``JobAbortedError`` to the aborted code)."""
        from chainermn_tpu.resilience.preemption import PREEMPTED_EXIT_CODE

        return PREEMPTED_EXIT_CODE if self.preempted else 0

    def run(self):
        if any(e.closed for e in self._extensions):
            # a prior run() finalized extensions holding external
            # resources; silently skipping (or re-firing) them would lose
            # data — resuming needs a fresh Trainer
            raise RuntimeError(
                "this Trainer already ran and finalized its extensions; "
                "construct a new Trainer (re-attaching extensions) to "
                "resume")
        from chainermn_tpu.resilience import chaos, preemption, watchdog

        guard = None
        if self.handle_preemption:
            guard = preemption.install_preemption_handler()
        wd = watchdog.maybe_start_watchdog()
        start = time.time()
        try:
            try:
                while not self._stopped():
                    # chaos first: an injected SIGTERM at this step is
                    # visible to the preemption poll on the next line
                    chaos.on_step(self.updater.iteration)
                    if wd is not None:
                        wd.check()
                    if guard is not None and guard.requested:
                        self.preempted = True
                        self._emergency_checkpoint(guard.grace_deadline())
                        break
                    try:
                        self.updater.update()
                    except StopIteration:
                        break  # non-repeating iterator exhausted
                    due = [e for e in self._extensions
                           if e.due(self.updater)]
                    if due:
                        self._materialize_observation(start)
                        for e in due:
                            e.ext(self)
                self._materialize_observation(start)
            except BaseException:
                # last-chance checkpoint: partial-epoch progress survives
                # any exception leaving the step loop (the consensus
                # election picks it up on restart); then re-raise
                self._emergency_checkpoint()
                raise
        finally:
            if guard is not None:
                guard.uninstall()
            # finalize extensions that hold external resources (an open
            # jax.profiler trace, checkpoint writers) even when the run ends
            # before their stop condition or raises
            for e in self._extensions:
                if e.closed:
                    continue  # a prior run() already released it
                close = getattr(e.ext, "close", None)
                if callable(close):
                    e.closed = True
                    try:
                        close()
                    except Exception:
                        import traceback

                        traceback.print_exc()

"""Evaluator: run an eval step over a validation iterator.

Wrap with chainermn_tpu.create_multi_node_evaluator for the reference's
cross-process metric averaging (device-level averaging is already in-graph).
"""

from __future__ import annotations

from typing import Callable, Dict

import numpy as np


class Evaluator:
    def __init__(self, iterator_factory: Callable, eval_step: Callable,
                 updater, converter=None):
        from .trainer import default_converter

        self._make_it = iterator_factory
        self._eval_step = eval_step
        self._updater = updater
        self._converter = converter or default_converter

    def __call__(self, trainer=None) -> Dict[str, float]:
        it = self._make_it()
        sums: Dict[str, float] = {}
        n = 0
        for batch in it:
            arrays = self._converter(batch)
            arrays = self._updater.shard_batch(arrays)
            metrics = self._eval_step(self._updater.state, *arrays)
            for k, v in metrics.items():
                sums[k] = sums.get(k, 0.0) + float(v)
            n += 1
        out = {k: v / max(1, n) for k, v in sums.items()}
        if trainer is not None:
            trainer.observation.update(out)
        return out

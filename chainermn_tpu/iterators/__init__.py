"""Iterators: serial base + multi-node wrappers.

Reference: chainermn/iterators/ (SURVEY.md §2.5; mount empty — module path
citation): ``create_multi_node_iterator`` has the master rank iterate and
broadcast each batch (for data that cannot be scattered);
``create_synchronized_iterator`` seeds every rank's RNG identically so ranks
draw the same batches. The serial iterator itself came from Chainer; a local
equivalent lives here so the framework is standalone.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from chainermn_tpu.comm.base import CommunicatorBase


class SerialIterator:
    """Epoch-aware batch iterator (local rebuild of the Chainer contract:
    ``next()``, ``epoch``, ``is_new_epoch``, ``reset()``)."""

    def __init__(self, dataset, batch_size: int, repeat: bool = True,
                 shuffle: bool = True, seed: Optional[int] = None):
        self.dataset = dataset
        self.batch_size = batch_size
        self._repeat = repeat
        self._shuffle = shuffle
        self._rng = np.random.RandomState(seed)
        self.reset()

    def reset(self):
        self.epoch = 0
        self.is_new_epoch = False
        self._at = 0
        self._order = self._new_order()

    def _new_order(self):
        order = np.arange(len(self.dataset))
        if self._shuffle:
            self._rng.shuffle(order)
        return order

    def __iter__(self):
        return self

    def __next__(self):
        n = len(self.dataset)
        if self._at >= n:
            if not self._repeat and self.epoch >= 1:
                raise StopIteration
        batch_idx = self._order[self._at:self._at + self.batch_size]
        self._at += self.batch_size
        self.is_new_epoch = self._at >= n
        if self.is_new_epoch:
            self.epoch += 1
            if self._repeat:
                short = self.batch_size - len(batch_idx)
                self._order = self._new_order()
                self._at = 0
                if short:
                    batch_idx = np.concatenate([batch_idx, self._order[:short]])
                    self._at = short
            elif len(batch_idx) == 0:
                raise StopIteration
        return [self.dataset[int(i)] for i in batch_idx]

    next = __next__

    @property
    def epoch_detail(self):
        return self.epoch + self._at / max(1, len(self.dataset))

    # -- full-state resume (docs/fault_tolerance.md) --------------------

    def state_dict(self) -> dict:
        """Position + shuffling-RNG snapshot: restoring it continues the
        epoch on the exact next batch, with the same future shuffles —
        unlike the reference's restart semantics, which replayed the
        epoch from its beginning with a fresh shuffle."""
        return {
            "epoch": self.epoch,
            "is_new_epoch": self.is_new_epoch,
            "at": self._at,
            "order": np.asarray(self._order).copy(),
            "rng": self._rng.get_state(),
        }

    def load_state_dict(self, state: dict) -> None:
        order = np.asarray(state["order"])
        if len(order) != len(self.dataset):
            raise ValueError(
                f"iterator state is for a dataset of {len(order)} samples, "
                f"this iterator holds {len(self.dataset)} — resuming would "
                "index out of range or silently skip data")
        self.epoch = int(state["epoch"])
        self.is_new_epoch = bool(state["is_new_epoch"])
        self._at = int(state["at"])
        self._order = order
        self._rng.set_state(state["rng"])

    def set_position(self, at: int, epoch: int = 0) -> None:
        """Jump to sample offset ``at`` within ``epoch``, with a freshly
        shuffled order — the elastic shrink-to-fit rebalance
        (resilience/elastic.py): after resharding onto a different world
        size the saved per-shard position no longer maps 1:1, so the
        resumed run continues APPROXIMATELY (epoch counters and overall
        progress preserved; the exact next batch is not — unlike
        :meth:`load_state_dict`, which is exact but shape-preserving)."""
        n = len(self.dataset)
        self.epoch = int(epoch)
        self.is_new_epoch = False
        self._at = int(at) % n if n else 0
        self._order = self._new_order()


def create_multi_node_iterator(actual_iterator, communicator: CommunicatorBase,
                               rank_master: int = 0):
    """Master process iterates; every process receives the master's batch.

    Reference: chainermn/iterators/multi_node_iterator.py. Here the batch
    rides the host object plane; with one process it is a passthrough.
    """
    if communicator.inter_size == 1:
        return actual_iterator
    return _MultiNodeIterator(actual_iterator, communicator, rank_master)


class _MultiNodeIterator:
    """Every process's view of the master's iterator: ``epoch``,
    ``is_new_epoch`` and ``epoch_detail`` ride the broadcast payload, so
    trigger logic (LogReport intervals, epoch-end hooks) agrees across
    processes by construction."""

    def __init__(self, iterator, comm, rank_master):
        self._it = iterator
        self._comm = comm
        self._master = rank_master
        self.epoch = getattr(iterator, "epoch", 0)
        self.is_new_epoch = getattr(iterator, "is_new_epoch", False)
        self.epoch_detail = getattr(iterator, "epoch_detail", 0.0)

    def __iter__(self):
        return self

    def __next__(self):
        if self._comm.inter_rank == self._master:
            try:
                batch = self._it.next()
                payload = (batch, self._it.epoch, self._it.is_new_epoch,
                           getattr(self._it, "epoch_detail", None), False)
            except StopIteration:
                payload = (None, None, None, None, True)
            payload = self._comm.bcast_obj(payload, root=self._master)
        else:
            payload = self._comm.bcast_obj(None, root=self._master)
        batch, epoch, is_new_epoch, epoch_detail, stop = payload
        if stop:
            # keep the last valid epoch counters; callers may read them
            raise StopIteration
        self.epoch, self.is_new_epoch = epoch, is_new_epoch
        self.epoch_detail = epoch_detail
        return batch

    next = __next__

    def state_dict(self) -> dict:
        """Per-rank resume state: the master saves its inner iterator's
        full position; every rank saves the shared epoch counters (the
        broadcast keeps them in agreement, so any rank's copy is the
        job's)."""
        inner = getattr(self._it, "state_dict", None)
        return {
            "epoch": self.epoch,
            "is_new_epoch": self.is_new_epoch,
            "epoch_detail": self.epoch_detail,
            "inner": inner() if (callable(inner)
                                 and self._comm.inter_rank == self._master)
            else None,
        }

    def load_state_dict(self, state: dict) -> None:
        inner = state.get("inner")
        restore = getattr(self._it, "load_state_dict", None)
        if inner is not None and callable(restore):
            restore(inner)
        self.epoch = state["epoch"]
        self.is_new_epoch = state["is_new_epoch"]
        self.epoch_detail = state["epoch_detail"]


def create_synchronized_iterator(actual_iterator, communicator: CommunicatorBase):
    """Synchronize shuffling RNGs so every process draws identical batches.

    Reference: chainermn/iterators/_synchronized_iterator.py — the root's
    seed is broadcast and every rank reseeds its iterator with it.
    """
    seed = communicator.bcast_obj(
        int(np.random.RandomState().randint(0, 2**31 - 1)), root=0
    )
    if isinstance(actual_iterator, SerialIterator):
        actual_iterator._rng = np.random.RandomState(seed)
        actual_iterator.reset()
    return actual_iterator

"""HandoffTransport — how a prefill→decode handoff actually travels.

``fleet/pools.py`` decides *when* a populated KV slot moves; this module
decides *how* the bytes get there and what happens when the wire lies.
Two implementations of one contract:

* :class:`InProcessTransport` — the single-process queue pair the
  original conveyor used, now with the same framing/verification
  discipline as the real wire (so the tier-1 fault matrix runs without
  spawning processes, and an optional ``wire_delay_ms`` models DCN
  latency for the bench's overlap gate).
* :class:`ObjectPlaneTransport` — ships frames between processes over
  any object plane exposing ``send_obj``/``try_recv_obj`` (the
  jax.distributed coordinator KV store via
  :class:`~chainermn_tpu.comm.object_plane.ObjectPlane`, or the
  restart-tolerant :class:`~chainermn_tpu.comm.object_plane.
  FsObjectPlane` the supervised cross-host drill uses).

The reliability protocol (both implementations):

* **frames** — each handoff travels as ``{seq, stream_id, manifest,
  blob}``. The sender assigns a monotonic per-channel sequence number;
  the manifest already carries ``bytes`` + ``sha256`` over the blob, so
  the receiver verifies every frame before it can touch an engine:
  truncation fails the length check, corruption fails the digest,
  duplication is fenced by the resolved-stream set, and reordering is
  detected by the sequence gap (and is harmless — adoption is keyed by
  stream, not arrival order).
* **NACK → bounded re-send → clean re-prefill** — a frame that fails
  verification is NACKed; the sender re-sends up to ``max_attempts``
  with the :class:`~chainermn_tpu.resilience.policy.RpcPolicy` jittered
  backoff between attempts. A receiver that has NACKed the same
  sequence number ``max_attempts`` times gives up: it acks ``failed``
  and surfaces the stream for a clean re-prefill. Either side giving up
  resolves the stream, so a late/duplicate frame can never poison a
  decode slot afterwards (the *fence*).
* **every blocking receive is bounded** — ack waits use
  ``RpcPolicy.handoff_ack_ms()`` per attempt, receiver polls take an
  explicit ``timeout_ms``; nothing in this module can wait forever on a
  dead peer (the DL117 contract this module is the clean exemplar for).

Chaos: every delivery attempt passes through ``chaos.on_wire`` —
``drop_handoff`` / ``delay_handoff`` / ``dup_handoff`` /
``corrupt_handoff`` tear at exactly this layer, which is how the drill
proves the protocol above is not decorative.
"""

from __future__ import annotations

import pickle
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from chainermn_tpu.resilience import chaos
from chainermn_tpu.resilience.policy import RpcPolicy, policy

__all__ = ["TransportError", "Arrival", "InProcessTransport",
           "ObjectPlaneTransport", "LoopbackPlane", "PairedTransport",
           "HANDOFF_DATA_TAG", "HANDOFF_ACK_TAG"]

#: object-plane tags for the two handoff channels (data and acks ride
#: separate p2p channels so a slow blob never blocks an ack read)
HANDOFF_DATA_TAG = 7001
HANDOFF_ACK_TAG = 7002

#: terminal ack statuses a sender can observe for one frame
_ACK_STATUSES = ("adopted", "duplicate", "failed")


class TransportError(RuntimeError):
    """The transport itself is broken (not a per-frame defect)."""


class Arrival:
    """One verified receiver-side outcome. ``manifest is None`` means
    the frame could not be delivered intact within the attempt budget —
    the caller must answer with a clean re-prefill (the blob never
    touches an engine). ``defects`` is then the per-attempt defect
    history (every ``_frame_defect`` reason this frame's seq
    accumulated), so the fallback log can say WHY the wire failed
    instead of just that it did."""

    __slots__ = ("stream_id", "manifest", "blob", "defects")

    def __init__(self, stream_id: int, manifest: Optional[dict],
                 blob: Optional[bytes],
                 defects: Tuple[str, ...] = ()):
        self.stream_id = int(stream_id)
        self.manifest = manifest
        self.blob = blob
        self.defects = tuple(defects)

    @property
    def failed(self) -> bool:
        return self.manifest is None


def _frame_defect(manifest: dict, blob: bytes) -> Optional[str]:
    """Cheap wire-level verification (the manifest vouches for the
    blob): returns a reason string for a torn/corrupt frame, or None.
    This is the SAME check ``decode_handoff`` re-runs before touching
    an engine — verified twice, adopted once."""
    import hashlib
    try:
        want = int(manifest["bytes"])
        if len(blob) != want:
            return f"truncated: {len(blob)} bytes, manifest says {want}"
        if hashlib.sha256(blob).hexdigest() != manifest["sha256"]:
            return "corrupt: sha256 mismatch"
    except Exception as e:  # broken manifest structure → same contract
        return f"undecodable manifest: {type(e).__name__}: {e}"
    return None


class _ReceiverState:
    """Sequence/fence bookkeeping shared by both transports."""

    def __init__(self, max_attempts: int):
        self.max_attempts = max_attempts
        self.resolved: set = set()          # stream_ids fenced off
        self.expect_seq = 0                 # next frame seq (stats only)
        self.nacks: Dict[int, int] = {}     # seq → failed deliveries
        self.defects: Dict[int, List[str]] = {}  # seq → defect history
        self.stats = {"delivered": 0, "duplicates": 0, "nacked": 0,
                      "reordered": 0, "failed": 0, "chunk_nacked": 0}

    def admit(self, seq: int, stream_id: int, manifest: dict,
              blob: bytes) -> Tuple[str, Optional[Arrival]]:
        """Classify one raw frame. Returns ``(ack_status, arrival)``
        where ack_status is ``adopted``/``duplicate``/``failed`` or
        ``nack``; arrival is non-None for adopted and failed."""
        if stream_id in self.resolved:
            self.stats["duplicates"] += 1
            return "duplicate", None
        if seq != self.expect_seq:
            # a gap (sender moved on / restarted) or a late re-send:
            # harmless either way — adoption is keyed by stream id, the
            # counter only tracks that reordering was SEEN
            self.stats["reordered"] += 1
        defect = _frame_defect(manifest, blob)
        if defect is None:
            self.expect_seq = max(self.expect_seq, seq + 1)
            self.resolved.add(stream_id)
            self.stats["delivered"] += 1
            self.defects.pop(seq, None)
            return "adopted", Arrival(stream_id, manifest, blob)
        self.defects.setdefault(seq, []).append(defect)
        bad = self.nacks.get(seq, 0) + 1
        self.nacks[seq] = bad
        if bad >= self.max_attempts:
            # give up on the wire for this frame: fence the stream and
            # hand it back for a clean re-prefill — with the full
            # defect history attached, so the fallback log names the
            # wire's failure mode instead of just the outcome
            self.expect_seq = max(self.expect_seq, seq + 1)
            self.resolved.add(stream_id)
            self.stats["failed"] += 1
            return "failed", Arrival(stream_id, None, None,
                                     defects=tuple(
                                         self.defects.pop(seq, ())))
        self.stats["nacked"] += 1
        if isinstance(manifest, dict) and manifest.get("format") == 5 \
                and manifest.get("kind") == "chunk":
            # a streamed chunk re-sends alone — the counter the
            # fleet-report gate uses to prove per-chunk granularity
            self.stats["chunk_nacked"] += 1
        return "nack", None


class InProcessTransport:
    """The queue pair, with real framing: sender and receiver faces of
    one object, safe to drive from the conveyor's worker thread (send)
    and step thread (poll) concurrently.

    ``wire_delay_ms`` sleeps each delivery attempt — canned DCN latency
    for the bench's overlap gate and the backpressure tests; real
    latency comes from a real plane. ``backoff`` enables the RpcPolicy
    jittered sleep between re-sends (off by default: an in-process
    retry has nobody to wait for, and the fault matrix stays fast)."""

    def __init__(self, max_attempts: int = 4,
                 pol: Optional[RpcPolicy] = None,
                 wire_delay_ms: float = 0.0, backoff: bool = False,
                 chaos_kind: str = "handoff"):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.policy = pol or policy()
        self.max_attempts = max_attempts
        self.wire_delay_ms = float(wire_delay_ms)
        self.backoff = backoff
        #: which chaos wire faults target this transport's traffic —
        #: "handoff" (corrupt_handoff) or "rollout"
        #: (corrupt_rollout_chunk); generic drop/delay/dup hit both
        self.chaos_kind = chaos_kind
        self._lock = threading.Lock()
        self._recv = _ReceiverState(max_attempts)
        self._arrivals: deque = deque()
        self._send_seq = 0
        self.stats = {"sent": 0, "attempts": 0, "dropped": 0,
                      "send_failed": 0}
        #: defect history of the most recent ``failed`` send (why the
        #: wire failed, not just that it did)
        self.last_send_defects: Tuple[str, ...] = ()

    # -- sender face -----------------------------------------------------

    def send(self, stream_id: int, manifest: dict, blob: bytes) -> str:
        """Deliver one handoff; returns the terminal ack status
        (``adopted``/``duplicate``/``failed``). Bounded: at most
        ``max_attempts`` delivery attempts, each re-rolled through the
        chaos wire, then the stream is fenced and surfaced for a clean
        re-prefill — this call cannot spin forever."""
        with self._lock:
            seq = self._send_seq
            self._send_seq += 1
            self.stats["sent"] += 1
        for attempt in range(self.max_attempts):
            self.stats["attempts"] += 1
            verdict, wire = chaos.on_wire(blob, kind=self.chaos_kind)
            if self.wire_delay_ms:
                time.sleep(self.wire_delay_ms / 1000.0)
            if verdict == "drop":
                self.stats["dropped"] += 1
                status = None              # nothing arrived: like a lost
            else:                          # frame, the "ack" times out
                status = self._deliver(seq, stream_id, manifest, wire)
                if verdict == "dup":
                    dup = self._deliver(seq, stream_id, manifest, wire)
                    status = status if status in _ACK_STATUSES else dup
            if status in _ACK_STATUSES:
                return status
            if self.backoff and attempt + 1 < self.max_attempts:
                time.sleep(
                    self.policy.backoff_ms(attempt) / 1000.0)
        # attempts exhausted with no intact delivery: fence + fallback
        with self._lock:
            self.stats["send_failed"] += 1
            defects = tuple(self._recv.defects.pop(seq, ())) or (
                f"no intact delivery in {self.max_attempts} attempts "
                "(frames dropped in flight)",)
            self.last_send_defects = defects
            if stream_id not in self._recv.resolved:
                self._recv.resolved.add(stream_id)
                self._recv.stats["failed"] += 1
                self._arrivals.append(
                    Arrival(stream_id, None, None, defects=defects))
        return "failed"

    def _deliver(self, seq: int, stream_id: int, manifest: dict,
                 blob: bytes) -> Optional[str]:
        with self._lock:
            status, arrival = self._recv.admit(seq, stream_id,
                                               manifest, blob)
            if arrival is not None:
                self._arrivals.append(arrival)
        return status if status in _ACK_STATUSES else None

    # -- receiver face ---------------------------------------------------

    def poll(self, timeout_ms: int = 0) -> List[Arrival]:
        """Drain verified arrivals (non-blocking; the in-process wire
        has no latency for a timeout to cover)."""
        del timeout_ms
        out = []
        with self._lock:
            while self._arrivals:
                out.append(self._arrivals.popleft())
        return out

    def resolve(self, stream_id: int) -> None:
        """Fence a stream the caller resolved out-of-band (deadline
        fallback): later frames for it drop as duplicates."""
        with self._lock:
            self._recv.resolved.add(stream_id)

    @property
    def receiver_stats(self) -> dict:
        with self._lock:
            return dict(self._recv.stats)

    def close(self) -> None:
        pass


class ObjectPlaneTransport:
    """Handoff frames over a cross-process object plane.

    One instance per directed (sender, receiver) pair; the sender host
    calls :meth:`send`, the receiver host calls :meth:`poll` — the same
    faces as :class:`InProcessTransport`, so ``fleet/pools.py`` and
    ``tools/fleet_lm.py`` are transport-agnostic.

    ``plane`` needs three methods (both
    :class:`~chainermn_tpu.comm.object_plane.ObjectPlane` and
    :class:`~chainermn_tpu.comm.object_plane.FsObjectPlane` qualify):

    * ``send_obj(obj, dest, tag)`` — publish one object;
    * ``try_recv_obj(src, tag, timeout_ms)`` — bounded receive that
      raises ``TimeoutError`` WITHOUT consuming the channel position,
      so a poll can come back later;
    * ``process_index`` — this host's rank.

    If the plane also exposes ``gc(src, tag)`` (FsObjectPlane), the
    transport calls it after each resolved frame/ack so a long drill
    does not accumulate one file per frame on disk.

    Restart tolerance: adoption is keyed by ``stream_id``, not by
    sequence number, so a restarted sender (fresh seq counter, replayed
    streams) is answered with ``duplicate`` acks for everything the
    receiver already resolved — the fenced re-queue the SIGKILL drill
    pins."""

    def __init__(self, plane, peer: int, *,
                 max_attempts: int = 4,
                 pol: Optional[RpcPolicy] = None,
                 data_tag: int = HANDOFF_DATA_TAG,
                 ack_tag: int = HANDOFF_ACK_TAG,
                 chaos_kind: str = "handoff"):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.plane = plane
        self.peer = int(peer)
        self.policy = pol or policy()
        self.max_attempts = max_attempts
        self.data_tag = data_tag
        self.ack_tag = ack_tag
        self.chaos_kind = chaos_kind     # see InProcessTransport
        self._recv = _ReceiverState(max_attempts)
        self._send_seq = 0
        self._acks: Dict[int, str] = {}     # seq → status (sender side)
        self._nack_reasons: Dict[int, List[str]] = {}  # seq → defects
        self.stats = {"sent": 0, "attempts": 0, "ack_timeouts": 0,
                      "send_failed": 0}
        #: defect history of the most recent ``failed`` send — the
        #: receiver's NACK reasons plus local ack timeouts, so the
        #: fallback log can say WHY the wire failed
        self.last_send_defects: Tuple[str, ...] = ()

    # -- sender face -----------------------------------------------------

    def send(self, stream_id: int, manifest: dict, blob: bytes) -> str:
        """Ship one handoff frame and wait for its ack. Bounded end to
        end: ``max_attempts`` attempts, each with an
        ``RpcPolicy.handoff_ack_ms()`` ack deadline and a jittered
        backoff before the re-send; exhaustion returns ``failed`` (the
        receiver's own give-up or deadline fallback re-prefills)."""
        seq = self._send_seq
        self._send_seq += 1
        self.stats["sent"] += 1
        frame = {"kind": "handoff", "seq": seq, "stream_id": int(stream_id),
                 "manifest": manifest}
        for attempt in range(self.max_attempts):
            self.stats["attempts"] += 1
            verdict, wire = chaos.on_wire(blob, kind=self.chaos_kind)
            if verdict != "drop":
                self.plane.send_obj(dict(frame, blob=wire), self.peer,
                                    tag=self.data_tag)
                if verdict == "dup":
                    self.plane.send_obj(dict(frame, blob=wire), self.peer,
                                        tag=self.data_tag)
            status = self._await_ack(seq)
            if status in _ACK_STATUSES:
                self._gc_plane(self.ack_tag)
                self._nack_reasons.pop(seq, None)
                return status
            if attempt + 1 < self.max_attempts:
                time.sleep(self.policy.backoff_ms(attempt) / 1000.0)
        self.stats["send_failed"] += 1
        self.last_send_defects = tuple(self._nack_reasons.pop(seq, ()))
        return "failed"

    def _gc_plane(self, tag: int) -> None:
        """Prune consumed frame files on planes that support it
        (FsObjectPlane) — a long drill must not accumulate one file
        per frame forever. Best-effort: a racing unlink is not an
        error, and memory planes simply have no ``gc``."""
        gc = getattr(self.plane, "gc", None)
        if gc is None:
            return
        try:
            gc(self.peer, tag=tag)
        except OSError:
            pass

    def _await_ack(self, seq: int) -> Optional[str]:
        """Wait (bounded) for the ack of frame ``seq``. Acks arrive in
        channel order; entries for older frames are recorded and
        skipped, a missing ack within the budget returns None (the
        caller re-sends)."""
        cached = self._acks.pop(seq, None)
        if cached is not None:
            return cached
        budget_ms = self.policy.handoff_ack_ms()
        deadline = time.monotonic() + budget_ms / 1000.0
        while True:
            left_ms = (deadline - time.monotonic()) * 1000.0
            if left_ms <= 0:
                self.stats["ack_timeouts"] += 1
                self._nack_reasons.setdefault(seq, []).append(
                    f"no ack within {int(budget_ms)} ms "
                    "(frame or ack lost in flight)")
                return None
            try:
                ack = self.plane.try_recv_obj(
                    self.peer, tag=self.ack_tag,
                    timeout_ms=max(1, int(min(left_ms,
                                              self.policy.probe_ms))))
            except TimeoutError:
                continue                      # bounded by the deadline
            if not isinstance(ack, dict) or "seq" not in ack:
                continue                      # unintelligible: ignore
            if ack.get("kind") == "nack" and int(ack["seq"]) == seq:
                self._nack_reasons.setdefault(seq, []).append(
                    str(ack.get("reason", "receiver NACK")))
                return None                   # damaged in flight: re-send
            if ack.get("kind") == "ack":
                if int(ack["seq"]) == seq:
                    return str(ack.get("status", "adopted"))
                # an ack for another frame (late ack after our earlier
                # timeout): remember it for that frame's caller
                self._acks[int(ack["seq"])] = str(
                    ack.get("status", "adopted"))

    # -- receiver face ---------------------------------------------------

    def poll(self, timeout_ms: int = 0) -> List[Arrival]:
        """Drain frames available within ``timeout_ms``: verify, ack or
        NACK each, and return the verified arrivals. Every wait is an
        explicit bounded ``try_recv_obj``; an empty wire returns an
        empty list rather than blocking."""
        out: List[Arrival] = []
        deadline = time.monotonic() + max(0, timeout_ms) / 1000.0
        while True:
            left_ms = (deadline - time.monotonic()) * 1000.0
            wait_ms = max(1, int(min(max(left_ms, 0),
                                     self.policy.probe_ms)))
            try:
                frame = self.plane.try_recv_obj(
                    self.peer, tag=self.data_tag, timeout_ms=wait_ms)
            except TimeoutError:
                if time.monotonic() >= deadline:
                    return out
                continue
            arrival = self._admit_frame(frame)
            if arrival is not None:
                out.append(arrival)
            if time.monotonic() >= deadline:
                return out

    def _admit_frame(self, frame) -> Optional[Arrival]:
        if not isinstance(frame, dict) or frame.get("kind") != "handoff":
            return None                      # garbage on the channel
        try:
            seq = int(frame["seq"])
            stream_id = int(frame["stream_id"])
            manifest = frame["manifest"]
            blob = frame["blob"]
        except Exception:
            return None
        status, arrival = self._recv.admit(seq, stream_id, manifest, blob)
        if status == "nack":
            hist = self._recv.defects.get(seq) or ["frame defect"]
            self.plane.send_obj({"kind": "nack", "seq": seq,
                                 "reason": hist[-1]}, self.peer,
                                tag=self.ack_tag)
        else:
            self.plane.send_obj({"kind": "ack", "seq": seq,
                                 "status": status}, self.peer,
                                tag=self.ack_tag)
            self._gc_plane(self.data_tag)
        return arrival

    def resolve(self, stream_id: int) -> None:
        """Fence a stream resolved out-of-band (the receiver's deadline
        fallback re-prefilled it): any later frame for it is answered
        ``duplicate`` and dropped."""
        self._recv.resolved.add(stream_id)

    @property
    def receiver_stats(self) -> dict:
        return dict(self._recv.stats)

    def close(self) -> None:
        pass


class PairedTransport:
    """Two :class:`ObjectPlaneTransport` endpoints glued into the
    single-object transport interface ``DisaggregatedFleet`` expects.

    A real object plane has one process per end, so the sender face
    and the receiver face of a channel live in different transports.
    When one process holds BOTH ends — the bench's localhost-socket
    drill, the tier-1 socket harness — this adapter routes ``send``
    to the sender-side transport and ``poll``/``resolve`` to the
    receiver-side one, while forwarding the stats surfaces
    (``stats``, ``receiver_stats``, ``last_send_defects``, ``plane``)
    the fleet's wire-health accounting reads."""

    def __init__(self, sender: ObjectPlaneTransport,
                 receiver: ObjectPlaneTransport):
        self.sender = sender
        self.receiver = receiver
        self.plane = sender.plane

    def send(self, stream_id: int, manifest: dict, blob: bytes) -> str:
        return self.sender.send(stream_id, manifest, blob)

    def poll(self, timeout_ms: int = 0) -> List[Arrival]:
        return self.receiver.poll(timeout_ms=timeout_ms)

    def resolve(self, stream_id: int) -> None:
        self.receiver.resolve(stream_id)

    @property
    def stats(self) -> dict:
        return self.sender.stats

    @property
    def receiver_stats(self) -> dict:
        return self.receiver.receiver_stats

    @property
    def last_send_defects(self):
        return self.sender.last_send_defects

    def close(self) -> None:
        pass


class LoopbackPlane:
    """An in-memory object plane (``send_obj``/``try_recv_obj``) wiring
    two :class:`ObjectPlaneTransport` endpoints inside one process —
    the tier-1 harness for the full cross-process protocol (acks,
    NACKs, re-sends, fences) without spawning processes. Channels are
    keyed exactly like the real plane's (src, dst, tag) triples."""

    def __init__(self, n: int = 2):
        self.process_count = n
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._chan: Dict[Tuple[int, int, int], deque] = {}

    def endpoint(self, index: int) -> "_LoopbackEndpoint":
        return _LoopbackEndpoint(self, index)


class _LoopbackEndpoint:
    def __init__(self, plane: LoopbackPlane, index: int):
        self._plane = plane
        self.process_index = int(index)
        self.process_count = plane.process_count

    def send_obj(self, obj: Any, dest: int, tag: int = 0) -> None:
        # pickle round-trip: the frame crosses a byte boundary exactly
        # like the real plane (no shared mutable state leaks across)
        data = pickle.dumps(obj)
        with self._plane._cond:
            self._plane._chan.setdefault(
                (self.process_index, int(dest), int(tag)),
                deque()).append(data)
            self._plane._cond.notify_all()

    def try_recv_obj(self, src: int, tag: int = 0,
                     timeout_ms: Optional[int] = None) -> Any:
        deadline = time.monotonic() + (timeout_ms or 0) / 1000.0
        key = (int(src), self.process_index, int(tag))
        with self._plane._cond:
            while True:
                q = self._plane._chan.get(key)
                if q:
                    return pickle.loads(q.popleft())
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"no object on channel {key} within "
                        f"{timeout_ms} ms")
                self._plane._cond.wait(timeout=left)

"""KVHandoff — the prefill→decode wire codec for disaggregated serving.

A prefill replica finishes a prompt (``prefill_chunk`` to completion,
first token sampled on device) and must move the populated slot to a
decode replica: per-block KV rows ``[fill, n_kv_heads, d_head]``, the
cursor, the post-sampling PRNG key, the emitted token(s), and the
sampling knobs — exactly what ``Engine.export_handoff`` packages. This
module turns that dict into ``(manifest, blob)`` and back:

* the **blob** is the concatenated C-order bytes of every array — no
  container framing, so wire accounting is exact (``manifest["bytes"]``
  is what actually crosses the interconnect, the number the bench gate
  prices);
* the **manifest** is a JSON-able dict under the same versioned grammar
  as ``serving/weights.py``: ``format`` 1 (raw) or 2 (blockwise
  quantized) for prefill handoffs, 3/4 for decode→decode SESSION
  migrations (same payload grammar plus the remaining ``max_new_tokens``
  budget in ``meta``), ``sha256`` + ``bytes`` over the blob, an
  ``arrays`` table (name/dtype/shape/offset), a ``codec`` block for the
  quantized formats, and the scalar ``meta`` (cursor, tokens, knobs).

Wire formats:

* ``f32`` (format 1) — raw cache bytes. Decode from an imported slot is
  BITWISE the exporting engine continuing; the fleet's raw-format
  streams therefore pin exactly to single-engine ``generate()``.
* ``int8-block`` (format 2) — each KV leaf through the collectives'
  per-256-element blockwise codec (``collectives.quantized``,
  EQuARX): int8 codes + one f32 scale per block, ~0.254× the raw f32
  bytes (``wire_ratio``). Logit error after the handoff is bounded by
  the per-block scale — calibrated in tests/fleet_tests.

When the SOURCE pages are already int8-resident (``kv_dtype=
"int8-block"`` engines, serving/kv_cache.py), the quantized formats
(2/4/5) ship the resident codes and scales VERBATIM — no dequantize →
requantize round trip, so the wire bytes are exactly the page bytes the
source engine was serving from and the handoff adds ZERO quantization
error on top of the at-rest codec. The codec leaf is marked
``resident`` so an int8 destination adopts the codes byte-for-byte
(``pages_q8`` in the decoded dict) while an f32 destination gets the
one inherent dequantization. Raw formats (1/3) from a resident source
dequantize once at encode — the raw wire grammar stays f32 bytes.

Decode REFUSES anything it cannot verify — unknown format, byte-count
mismatch (truncation), digest mismatch (corruption), or a structurally
broken manifest all raise :class:`HandoffError` — so a damaged handoff
becomes a clean re-prefill on the decode pool, never a poisoned slot.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Tuple

import numpy as np

__all__ = ["HandoffError", "encode_handoff", "decode_handoff",
           "handoff_payload_bytes", "HANDOFF_FORMAT_RAW",
           "HANDOFF_FORMAT_QUANT", "HANDOFF_FORMAT_SESSION_RAW",
           "HANDOFF_FORMAT_SESSION_QUANT", "HANDOFF_FORMAT_STREAMED",
           "HANDOFF_WIRE_FORMATS", "encode_handoff_streamed",
           "decode_handoff_streamed", "streamed_wire_bytes",
           "streamed_chunk_sid", "streamed_parent_sid",
           "CHUNKS_PER_STREAM"]

HANDOFF_FORMAT_RAW = 1
HANDOFF_FORMAT_QUANT = 2
# decode→decode session migration (Engine.export_session): the same
# array payload plus the remaining-budget meta — a distinct format id
# so a mixed-version fleet REFUSES instead of silently dropping the
# budget (decode_handoff's unknown-format contract)
HANDOFF_FORMAT_SESSION_RAW = 3
HANDOFF_FORMAT_SESSION_QUANT = 4
# chunked/streamed prefill handoff (TACCL/GC3 chunk pipelining applied
# to the handoff path): per-layer KV frames shipped as they are ready
# plus a closing manifest committing to every chunk's digest. A
# monolithic ``decode_handoff`` REFUSES format 5 (it cannot verify a
# blob it only holds a piece of) — use ``decode_handoff_streamed``.
HANDOFF_FORMAT_STREAMED = 5
_ACCEPTED_FORMATS = (HANDOFF_FORMAT_RAW, HANDOFF_FORMAT_QUANT,
                     HANDOFF_FORMAT_SESSION_RAW,
                     HANDOFF_FORMAT_SESSION_QUANT)
_QUANT_FORMATS = (HANDOFF_FORMAT_QUANT, HANDOFF_FORMAT_SESSION_QUANT)
_SESSION_FORMATS = (HANDOFF_FORMAT_SESSION_RAW,
                    HANDOFF_FORMAT_SESSION_QUANT)

#: wire formats encode_handoff accepts (f32 = raw bytes, bitwise)
HANDOFF_WIRE_FORMATS = ("f32", "int8-block")

#: meta keys every manifest must carry (decode validates the set);
#: session formats additionally carry ``max_new_tokens``. The OPTIONAL
#: ``weights_version`` meta (all formats 1–5) stamps which published
#: weights minted the KV rows: importers refuse a mismatch
#: (``Engine.import_handoff`` → ``WeightsVersionSkew``) so a rolling
#: update never mixes model versions inside one stream; manifests
#: without the field (pre-rollout encoders) stay loadable.
_META_KEYS = ("cursor", "tokens", "prompt_len", "eos_id", "temperature",
              "top_k", "seed")


class HandoffError(RuntimeError):
    """The handoff could not be verified/decoded — re-prefill instead."""


def _dtype(name: str):
    try:
        return np.dtype(name)
    except TypeError:
        import jax.numpy as jnp
        return jnp.dtype(name)     # ml_dtypes names (bfloat16, ...)


class _Packer:
    def __init__(self):
        self.arrays: List[Dict[str, Any]] = []
        self.chunks: List[bytes] = []
        self.offset = 0

    def put(self, name: str, arr) -> None:
        arr = np.ascontiguousarray(arr)
        raw = arr.tobytes()
        self.arrays.append({"name": name, "dtype": arr.dtype.name,
                            "shape": list(arr.shape),
                            "offset": self.offset, "nbytes": len(raw)})
        self.chunks.append(raw)
        self.offset += len(raw)


def _pack_page(pk: "_Packer", block: str, page: dict, wire_format: str,
               codec_leaves: Dict[str, dict]) -> int:
    """Pack one KV block's leaves (shared by the monolithic and
    streamed encoders). Returns the blockwise-codec block size the
    quantized leaves actually use: the at-rest page block for resident
    sources (codes/scales shipped verbatim), else ``QUANT_BLOCK``."""
    from chainermn_tpu.collectives.quantized import (QUANT_BLOCK,
                                                     block_dequantize,
                                                     block_quantize)
    blk = QUANT_BLOCK
    resident = "k_q" in page
    for leaf in ("k", "v"):
        name = f"{block}/{leaf}"
        if resident:
            q = np.ascontiguousarray(np.asarray(page[leaf + "_q"],
                                                np.int8))
            s = np.ascontiguousarray(np.asarray(page[leaf + "_s"],
                                                np.float32))
            blk = q.size // s.size
            if wire_format == "f32":
                # the raw grammar is f32 bytes: the source's ONE
                # inherent dequantization happens at encode
                arr = np.asarray(block_dequantize(
                    q.reshape(-1), s.reshape(-1), q.size, "int8-block",
                    np.float32, blk)).reshape(q.shape)
                pk.put(name, arr)
            else:
                # already quantized at rest: the wire IS the page —
                # codes and scales verbatim, zero extra error
                pk.put(name + "::q", q.reshape(-1))
                pk.put(name + "::scale", s.reshape(-1))
                codec_leaves[name] = {"shape": list(q.shape),
                                      "dtype": "float32",
                                      "size": int(q.size),
                                      "resident": True}
        else:
            arr = np.asarray(page[leaf])
            if wire_format == "f32":
                pk.put(name, arr)
            else:
                q, s = block_quantize(arr.reshape(-1), wire_format)
                pk.put(name + "::q", np.asarray(q))
                pk.put(name + "::scale", np.asarray(s, np.float32))
                codec_leaves[name] = {"shape": list(arr.shape),
                                      "dtype": arr.dtype.name,
                                      "size": int(arr.size)}
    return blk


def encode_handoff(handoff: dict,
                   wire_format: str = "f32") -> Tuple[dict, bytes]:
    """Serialize ``Engine.export_handoff``'s dict. Returns
    ``(manifest, blob)``; the manifest alone decides whether the blob is
    trustworthy at the other end."""
    if wire_format not in HANDOFF_WIRE_FORMATS:
        raise ValueError(
            f"unknown handoff wire_format {wire_format!r} — known: "
            + ", ".join(HANDOFF_WIRE_FORMATS))
    pk = _Packer()
    codec_leaves: Dict[str, dict] = {}
    blk = None
    for block in sorted(handoff["pages"]):
        blk = _pack_page(pk, block, handoff["pages"][block],
                         wire_format, codec_leaves)
    pk.put("key", np.asarray(handoff["key"], np.uint32))
    blob = b"".join(pk.chunks)
    # a dict carrying max_new_tokens is a decode-session export
    # (Engine.export_session); plain prefill handoffs keep format 1/2
    session = "max_new_tokens" in handoff
    if wire_format == "f32":
        fmt = HANDOFF_FORMAT_SESSION_RAW if session else HANDOFF_FORMAT_RAW
    else:
        fmt = (HANDOFF_FORMAT_SESSION_QUANT if session
               else HANDOFF_FORMAT_QUANT)
    meta = ({k: handoff[k] for k in _META_KEYS if k != "cursor"}
            | {"cursor": int(handoff["cursor"])})
    if session:
        meta["max_new_tokens"] = int(handoff["max_new_tokens"])
    if handoff.get("weights_version") is not None:
        meta["weights_version"] = str(handoff["weights_version"])
    manifest: Dict[str, Any] = {
        "format": fmt,
        "bytes": len(blob),
        "sha256": hashlib.sha256(blob).hexdigest(),
        "arrays": pk.arrays,
        "meta": meta,
    }
    if wire_format != "f32":
        from chainermn_tpu.collectives.quantized import QUANT_BLOCK
        manifest["codec"] = {"wire_format": wire_format,
                             "block": (blk if blk is not None
                                       else QUANT_BLOCK),
                             "leaves": codec_leaves}
    return manifest, blob


def handoff_payload_bytes(manifest: dict) -> int:
    """Exact wire bytes of the encoded handoff (the blob length the
    manifest vouches for — what the bench gate prices)."""
    return int(manifest["bytes"])


def decode_handoff(manifest: dict, blob: bytes) -> dict:
    """Verify + decode back to the ``Engine.import_handoff`` dict.

    Raises :class:`HandoffError` on ANY defect — unknown format, torn
    blob, digest mismatch, or a manifest missing its structure. Callers
    (fleet/pools.py) answer with a clean re-prefill."""
    try:
        fmt = manifest["format"]
        if fmt not in _ACCEPTED_FORMATS:
            raise HandoffError(
                f"unknown handoff manifest format {fmt!r} — accepted: "
                f"{_ACCEPTED_FORMATS}")
        if len(blob) != int(manifest["bytes"]):
            raise HandoffError(
                f"truncated handoff: blob is {len(blob)} bytes, "
                f"manifest says {manifest['bytes']}")
        digest = hashlib.sha256(blob).hexdigest()
        if digest != manifest["sha256"]:
            raise HandoffError("corrupt handoff: sha256 mismatch")
        flat: Dict[str, np.ndarray] = {}
        for ent in manifest["arrays"]:
            dt = _dtype(ent["dtype"])
            raw = blob[ent["offset"]:ent["offset"] + ent["nbytes"]]
            flat[ent["name"]] = np.frombuffer(
                raw, dtype=dt).reshape(ent["shape"])
        meta = manifest["meta"]
        pages: Dict[str, Dict[str, np.ndarray]] = {}
        pages_q8: Dict[str, Dict[str, np.ndarray]] = {}
        if fmt not in _QUANT_FORMATS:
            for name, arr in flat.items():
                if name == "key":
                    continue
                block, leaf = name.rsplit("/", 1)
                pages.setdefault(block, {})[leaf] = arr
        else:
            from chainermn_tpu.collectives.quantized import \
                block_dequantize
            codec = manifest["codec"]
            blk = int(codec.get("block", 256))
            for base, spec in codec["leaves"].items():
                deq = np.asarray(block_dequantize(
                    flat[base + "::q"], flat[base + "::scale"],
                    int(spec["size"]), codec["wire_format"],
                    _dtype(spec["dtype"]), blk))
                block, leaf = base.rsplit("/", 1)
                pages.setdefault(block, {})[leaf] = deq.reshape(
                    spec["shape"])
                if spec.get("resident"):
                    # verbatim source page bytes: an int8-resident
                    # destination adopts these directly (zero extra
                    # quantization error), f32 destinations use the
                    # dequantized ``pages``
                    shape = list(spec["shape"])
                    pages_q8.setdefault(block, {})[leaf + "_q"] = (
                        flat[base + "::q"].reshape(shape))
                    pages_q8.setdefault(block, {})[leaf + "_s"] = (
                        flat[base + "::scale"].reshape(shape[0], -1))
        out = {
            "pages": pages,
            "cursor": int(meta["cursor"]),
            "tokens": list(meta["tokens"]),
            "key": flat["key"],
            "prompt_len": int(meta["prompt_len"]),
            "eos_id": meta["eos_id"],
            "temperature": meta["temperature"],
            "top_k": meta["top_k"],
            "seed": meta["seed"],
            "weights_version": meta.get("weights_version"),
        }
        if pages_q8:
            out["pages_q8"] = pages_q8
        if fmt in _SESSION_FORMATS:
            # the remaining-budget meta is what MAKES it a session; a
            # session manifest without it is structurally broken
            out["max_new_tokens"] = int(meta["max_new_tokens"])
        return out
    except HandoffError:
        raise
    except Exception as e:   # broken manifest structure → same contract
        raise HandoffError(
            f"undecodable handoff manifest: {type(e).__name__}: {e}"
        ) from e


# -- format 5: streamed (chunked) handoffs --------------------------------

#: chunk stream-id address space per parent stream (a handoff with more
#: KV blocks than this cannot be streamed — encode refuses)
CHUNKS_PER_STREAM = 4096


def streamed_chunk_sid(stream_id: int, index: int) -> int:
    """Transport stream id for chunk ``index`` of ``stream_id``.

    Chunk frames ride the SAME transport protocol as whole handoffs —
    per-frame SHA verify, NACK → bounded re-send, duplicate fencing —
    so each needs its own id. Client stream ids are non-negative
    (``itertools.count``/request ids), so the chunk space is the
    negative integers: collision-free by sign, and invertible."""
    if not 0 <= index < CHUNKS_PER_STREAM:
        raise ValueError(f"chunk index {index} outside "
                         f"[0, {CHUNKS_PER_STREAM})")
    return -(int(stream_id) * CHUNKS_PER_STREAM + index + 1)


def streamed_parent_sid(chunk_sid: int) -> Tuple[int, int]:
    """Invert :func:`streamed_chunk_sid` → ``(stream_id, index)``."""
    if chunk_sid >= 0:
        raise ValueError(f"{chunk_sid} is not a chunk stream id")
    flat = -int(chunk_sid) - 1
    return flat // CHUNKS_PER_STREAM, flat % CHUNKS_PER_STREAM


def encode_handoff_streamed(
        handoff: dict, wire_format: str = "f32",
) -> Tuple[List[Tuple[dict, bytes]], dict, bytes]:
    """Serialize one handoff as independently verifiable per-layer
    frames: returns ``(chunks, closing_manifest, closing_blob)`` where
    ``chunks[i] = (chunk_manifest, chunk_blob)`` carries one KV block's
    leaves and the closing manifest carries the scalar meta, the PRNG
    key, and a ``chunks`` table committing to every chunk's byte count
    and digest — so a receiver can prove it assembled exactly the
    handoff the sender encoded, and a corrupt chunk costs one chunk's
    re-send, not the whole blob's."""
    if wire_format not in HANDOFF_WIRE_FORMATS:
        raise ValueError(
            f"unknown handoff wire_format {wire_format!r} — known: "
            + ", ".join(HANDOFF_WIRE_FORMATS))
    if "max_new_tokens" in handoff:
        raise ValueError("session exports migrate whole (format 3/4); "
                         "streaming is for prefill handoffs")
    blocks = sorted(handoff["pages"])
    if len(blocks) > CHUNKS_PER_STREAM:
        raise ValueError(f"{len(blocks)} KV blocks exceed the streamed "
                         f"chunk space ({CHUNKS_PER_STREAM})")
    chunks: List[Tuple[dict, bytes]] = []
    table: List[Dict[str, Any]] = []
    for i, block in enumerate(blocks):
        pk = _Packer()
        codec_leaves: Dict[str, dict] = {}
        blk = _pack_page(pk, block, handoff["pages"][block],
                         wire_format, codec_leaves)
        blob = b"".join(pk.chunks)
        digest = hashlib.sha256(blob).hexdigest()
        man: Dict[str, Any] = {
            "format": HANDOFF_FORMAT_STREAMED, "kind": "chunk",
            "layer": block, "index": i,
            "bytes": len(blob), "sha256": digest, "arrays": pk.arrays,
        }
        if wire_format != "f32":
            man["codec"] = {"wire_format": wire_format,
                            "block": blk, "leaves": codec_leaves}
        chunks.append((man, blob))
        table.append({"layer": block, "index": i,
                      "bytes": len(blob), "sha256": digest})
    pk = _Packer()
    pk.put("key", np.asarray(handoff["key"], np.uint32))
    closing_blob = b"".join(pk.chunks)
    meta = ({k: handoff[k] for k in _META_KEYS if k != "cursor"}
            | {"cursor": int(handoff["cursor"])})
    if handoff.get("weights_version") is not None:
        meta["weights_version"] = str(handoff["weights_version"])
    closing: Dict[str, Any] = {
        "format": HANDOFF_FORMAT_STREAMED, "kind": "closing",
        "bytes": len(closing_blob),
        "sha256": hashlib.sha256(closing_blob).hexdigest(),
        "arrays": pk.arrays, "meta": meta, "chunks": table,
        "wire_format": wire_format,
    }
    return chunks, closing, closing_blob


def streamed_wire_bytes(closing_manifest: dict) -> int:
    """Exact wire bytes of the whole streamed handoff: the closing blob
    plus every chunk the closing table commits to (the streamed sibling
    of :func:`handoff_payload_bytes`, same bench-gate pricing role)."""
    return int(closing_manifest["bytes"]) + sum(
        int(c["bytes"]) for c in closing_manifest["chunks"])


def decode_handoff_streamed(closing_manifest: dict, closing_blob: bytes,
                            chunks: List[Tuple[dict, bytes]]) -> dict:
    """Verify + assemble streamed frames back to the
    ``Engine.import_handoff`` dict.

    Every chunk must verify against BOTH its own manifest and the
    closing table's commitment (byte count + digest + layer name) —
    transport-level SHA checks already rejected torn frames, but only
    the closing table proves the SET of chunks is complete and is THIS
    handoff's (a chunk swapped in from another stream has a valid
    self-manifest and still fails the table). Any defect raises
    :class:`HandoffError`: the caller re-prefills, never adopts."""
    try:
        if closing_manifest.get("format") != HANDOFF_FORMAT_STREAMED \
                or closing_manifest.get("kind") != "closing":
            raise HandoffError(
                "not a streamed closing manifest: format="
                f"{closing_manifest.get('format')!r} "
                f"kind={closing_manifest.get('kind')!r}")
        if len(closing_blob) != int(closing_manifest["bytes"]):
            raise HandoffError(
                f"truncated closing frame: {len(closing_blob)} bytes, "
                f"manifest says {closing_manifest['bytes']}")
        if hashlib.sha256(closing_blob).hexdigest() \
                != closing_manifest["sha256"]:
            raise HandoffError("corrupt closing frame: sha256 mismatch")
        table = closing_manifest["chunks"]
        if len(chunks) != len(table):
            raise HandoffError(
                f"incomplete stream: {len(chunks)} chunks arrived, "
                f"closing manifest commits to {len(table)}")
        by_index: Dict[int, Tuple[dict, bytes]] = {}
        for man, blob in chunks:
            if man.get("format") != HANDOFF_FORMAT_STREAMED \
                    or man.get("kind") != "chunk":
                raise HandoffError(
                    f"not a streamed chunk manifest: {man.get('kind')!r}")
            by_index[int(man["index"])] = (man, blob)
        pages: Dict[str, Dict[str, np.ndarray]] = {}
        pages_q8: Dict[str, Dict[str, np.ndarray]] = {}
        for ent in table:
            idx = int(ent["index"])
            if idx not in by_index:
                raise HandoffError(f"missing chunk {idx} "
                                   f"(layer {ent['layer']!r})")
            man, blob = by_index[idx]
            if (man["layer"] != ent["layer"]
                    or len(blob) != int(ent["bytes"])
                    or hashlib.sha256(blob).hexdigest() != ent["sha256"]
                    or man["sha256"] != ent["sha256"]):
                raise HandoffError(
                    f"chunk {idx} (layer {ent['layer']!r}) does not "
                    "match the closing manifest's commitment")
            flat: Dict[str, np.ndarray] = {}
            for a in man["arrays"]:
                raw = blob[a["offset"]:a["offset"] + a["nbytes"]]
                flat[a["name"]] = np.frombuffer(
                    raw, dtype=_dtype(a["dtype"])).reshape(a["shape"])
            codec = man.get("codec")
            if codec is None:
                for name, arr in flat.items():
                    block, leaf = name.rsplit("/", 1)
                    pages.setdefault(block, {})[leaf] = arr
            else:
                from chainermn_tpu.collectives.quantized import \
                    block_dequantize
                blk = int(codec.get("block", 256))
                for base, spec in codec["leaves"].items():
                    deq = np.asarray(block_dequantize(
                        flat[base + "::q"], flat[base + "::scale"],
                        int(spec["size"]), codec["wire_format"],
                        _dtype(spec["dtype"]), blk))
                    block, leaf = base.rsplit("/", 1)
                    pages.setdefault(block, {})[leaf] = deq.reshape(
                        spec["shape"])
                    if spec.get("resident"):
                        shape = list(spec["shape"])
                        pages_q8.setdefault(block, {})[leaf + "_q"] = (
                            flat[base + "::q"].reshape(shape))
                        pages_q8.setdefault(block, {})[leaf + "_s"] = (
                            flat[base + "::scale"].reshape(shape[0], -1))
        meta = closing_manifest["meta"]
        key = None
        for a in closing_manifest["arrays"]:
            if a["name"] == "key":
                raw = closing_blob[a["offset"]:a["offset"] + a["nbytes"]]
                key = np.frombuffer(raw, dtype=_dtype(a["dtype"])
                                    ).reshape(a["shape"])
        if key is None:
            raise HandoffError("closing manifest carries no PRNG key")
        out = {
            "pages": pages,
            "cursor": int(meta["cursor"]),
            "tokens": list(meta["tokens"]),
            "key": key,
            "prompt_len": int(meta["prompt_len"]),
            "eos_id": meta["eos_id"],
            "temperature": meta["temperature"],
            "top_k": meta["top_k"],
            "seed": meta["seed"],
            "weights_version": meta.get("weights_version"),
        }
        if pages_q8:
            out["pages_q8"] = pages_q8
        return out
    except HandoffError:
        raise
    except Exception as e:   # broken manifest structure → same contract
        raise HandoffError(
            f"undecodable streamed handoff: {type(e).__name__}: {e}"
        ) from e

"""FleetReport — honest cross-replica aggregation + fleet counters.

Aggregating per-replica ``ServingReport`` summaries the lazy way is
WRONG in two specific, quantifiable ways:

* **percentiles do not average.** The mean of per-replica p99s is not
  the fleet p99 — a single slow replica's tail disappears into the
  average. ``merge`` therefore pools the RAW samples (``ServingReport.
  raw()``) and takes nearest-rank percentiles over the pooled list, so
  every token gap and TTFT sample carries exactly its own weight.
* **ratios do not average.** ``host_bytes_per_token`` is a quotient;
  the mean of per-replica quotients weights a replica that served 10
  tokens the same as one that served 10k. ``merge`` computes
  ``sum(host_bytes) / sum(tokens_emitted)`` — token-weighted by
  construction — and the pooled ``itl_ms`` distribution is likewise
  token-weighted because each gap sample IS one token.

The fleet-level counters (admission rejections, re-queues after a
replica death, handoffs by wire format and their exact wire bytes,
handoff fallbacks) live here because no single engine can see them —
they are properties of the routing layer. ``summary()`` emits the JSON
block ``tools/fleet_lm.py`` and the ``bench.py`` fleet gate read.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List

from chainermn_tpu.serving.reports import ServingReport, percentile

__all__ = ["FleetReport"]


def _dist_ms(samples: List[float]) -> Dict[str, float]:
    out = {f"p{q}": percentile(samples, q) * 1e3
           for q in ServingReport.PERCENTILES}
    out["mean"] = (sum(samples) / len(samples) * 1e3 if samples
                   else float("nan"))
    out["n"] = len(samples)
    return out


class FleetReport:
    """Routing-layer counters + pooled-sample replica aggregation."""

    def __init__(self):
        self.rejected = 0             # AdmissionRejected at the router
        self.requeued = 0             # requests moved off a dead replica
        self.replicas_dead = 0
        self.replicas_drained = 0     # Router.drain decommissions
        self.handoffs = 0
        self.handoff_fallbacks = 0    # HandoffError → clean re-prefill
        self.handoff_wire_bytes: Dict[str, int] = {}   # wire_format → B
        self.migrations = 0           # decode sessions adopted by a peer
        self.migration_fallbacks = 0  # migrate failed → replay from seed
        self.migration_wire_bytes: Dict[str, int] = {}  # wire_format → B
        # transport wire health (PR 18 socket plane + streamed chunks)
        self.transport_retransmits = 0   # delivery attempts beyond 1st
        self.transport_reconnects = 0    # socket-plane redials
        self.transport_dup_fenced = 0    # frames answered `duplicate`
        self.streamed_chunk_nacks = 0    # format-5 chunk-only re-sends
        # rolling weight updates (fleet/rollout.py)
        self.rollouts_completed = 0      # fleet fully on the new version
        self.rollouts_rolled_back = 0    # failed mid-walk → back to v1
        self.canary_failures = 0         # canary miscompare → abort
        self.rollout_wire_bytes = 0      # relay bytes shipped (all hops)
        # speculative decoding (serving/speculative.py) — fleet-level
        # tallies a host folds out of its engines' ServingReports so
        # acceptance travels with the routing counters
        self.draft_tokens_proposed = 0
        self.draft_tokens_accepted = 0
        self.spec_dispatches = 0
        self.spec_tokens_emitted = 0

    # ----------------------------------------------------------------
    # router / pool hooks
    # ----------------------------------------------------------------

    def record_rejected(self) -> None:
        self.rejected += 1

    def record_requeue(self, n: int = 1) -> None:
        self.requeued += int(n)

    def record_replica_dead(self) -> None:
        self.replicas_dead += 1

    def record_handoff(self, wire_format: str, nbytes: int) -> None:
        self.handoffs += 1
        self.handoff_wire_bytes[wire_format] = (
            self.handoff_wire_bytes.get(wire_format, 0) + int(nbytes))

    def record_fallback(self) -> None:
        self.handoff_fallbacks += 1

    def record_drained(self) -> None:
        self.replicas_drained += 1

    def record_migration(self, wire_format: str, nbytes: int) -> None:
        """One decode session adopted by a peer; ``nbytes`` is the
        exact encoded blob length that crossed the wire."""
        self.migrations += 1
        self.migration_wire_bytes[wire_format] = (
            self.migration_wire_bytes.get(wire_format, 0) + int(nbytes))

    def record_migration_fallback(self) -> None:
        """A migration that could not complete (transport budget, no
        free destination slot, undecodable frame) — the session fell
        back to the PR 11 replay-from-seed path."""
        self.migration_fallbacks += 1

    def record_rollout_completed(self) -> None:
        """Every replica serves the new version (rollout SUCCEEDED)."""
        self.rollouts_completed += 1

    def record_rollout_rolled_back(self) -> None:
        """A rollout failed mid-walk (persistent relay corruption, a
        mid-swap death, ...) and every already-swapped replica walked
        back to v1 through the same drain path."""
        self.rollouts_rolled_back += 1

    def record_canary_failure(self) -> None:
        """The canary's bitwise prompt replay miscompared against the
        v2 oracle — the rollout aborted with zero traffic moved."""
        self.canary_failures += 1

    def record_rollout_wire(self, nbytes: int) -> None:
        """Relay bytes shipped for a rollout (chunk payloads, every
        hop) — the bench gate prices publisher egress against this."""
        self.rollout_wire_bytes += int(nbytes)

    def record_transport(self, sender_stats: dict = (),
                         receiver_stats: dict = (),
                         plane_stats: dict = ()) -> None:
        """Fold one transport's lifetime counters into the fleet
        tallies: retransmits (attempts beyond each frame's first),
        reconnects (socket plane redials), duplicate-fenced frames,
        and streamed-chunk NACKs. Call once per transport at the end
        of its run (the stats are lifetime totals, not deltas)."""
        s = dict(sender_stats or {})
        r = dict(receiver_stats or {})
        p = dict(plane_stats or {})
        self.transport_retransmits += max(
            0, int(s.get("attempts", 0)) - int(s.get("sent", 0)))
        self.transport_reconnects += int(p.get("reconnects", 0))
        self.transport_dup_fenced += int(r.get("duplicates", 0))
        self.streamed_chunk_nacks += int(r.get("chunk_nacked", 0))

    def record_spec(self, proposed: int, accepted: int,
                    emitted: int, dispatches: int = 1) -> None:
        """Fold a replica's speculative-round tallies into the fleet
        counters (a host typically calls this once per engine with the
        ``ServingReport`` totals, ``dispatches=spec_dispatches``)."""
        self.draft_tokens_proposed += int(proposed)
        self.draft_tokens_accepted += int(accepted)
        self.spec_dispatches += int(dispatches)
        self.spec_tokens_emitted += int(emitted)

    # ----------------------------------------------------------------
    # wire serialization (cross-process fleet merge)
    # ----------------------------------------------------------------

    #: bump on any change to the counter schema below
    #: (2: migration/drain counters — PR 17 session migration;
    #:  3: transport wire-health counters — PR 18 socket plane;
    #:  4: rolling-update counters — PR 19 versioned rollout;
    #:  5: speculative-decoding counters — PR 20 draft/verify rounds)
    WIRE_VERSION = 5

    def to_wire(self) -> dict:
        """Version-tagged JSON-safe envelope of the fleet counters —
        a cross-process host ships this home next to its
        ``ServingReport.to_wire()`` blocks; the merging side rebuilds
        with :meth:`from_wire` and folds hosts together with
        :meth:`absorb`. Round-trip is exact (ints only)."""
        return {"version": self.WIRE_VERSION, "kind": "fleet_report",
                "counters": {
                    "rejected": self.rejected,
                    "requeued": self.requeued,
                    "replicas_dead": self.replicas_dead,
                    "replicas_drained": self.replicas_drained,
                    "handoffs": self.handoffs,
                    "handoff_fallbacks": self.handoff_fallbacks,
                    "handoff_wire_bytes": dict(self.handoff_wire_bytes),
                    "migrations": self.migrations,
                    "migration_fallbacks": self.migration_fallbacks,
                    "migration_wire_bytes": dict(
                        self.migration_wire_bytes),
                    "transport_retransmits": self.transport_retransmits,
                    "transport_reconnects": self.transport_reconnects,
                    "transport_dup_fenced": self.transport_dup_fenced,
                    "streamed_chunk_nacks": self.streamed_chunk_nacks,
                    "rollouts_completed": self.rollouts_completed,
                    "rollouts_rolled_back": self.rollouts_rolled_back,
                    "canary_failures": self.canary_failures,
                    "rollout_wire_bytes": self.rollout_wire_bytes,
                    "draft_tokens_proposed": self.draft_tokens_proposed,
                    "draft_tokens_accepted": self.draft_tokens_accepted,
                    "spec_dispatches": self.spec_dispatches,
                    "spec_tokens_emitted": self.spec_tokens_emitted,
                }}

    @classmethod
    def from_wire(cls, wire: dict) -> "FleetReport":
        if not isinstance(wire, dict) or wire.get("kind") != "fleet_report":
            raise ValueError(
                f"not a fleet_report envelope: {type(wire).__name__}")
        if wire.get("version") != cls.WIRE_VERSION:
            raise ValueError(
                f"fleet_report wire version {wire.get('version')!r} "
                f"!= {cls.WIRE_VERSION} (mixed-version fleet?)")
        c = wire["counters"]
        out = cls()
        out.rejected = int(c["rejected"])
        out.requeued = int(c["requeued"])
        out.replicas_dead = int(c["replicas_dead"])
        out.replicas_drained = int(c["replicas_drained"])
        out.handoffs = int(c["handoffs"])
        out.handoff_fallbacks = int(c["handoff_fallbacks"])
        out.handoff_wire_bytes = {str(k): int(v) for k, v
                                  in c["handoff_wire_bytes"].items()}
        out.migrations = int(c["migrations"])
        out.migration_fallbacks = int(c["migration_fallbacks"])
        out.migration_wire_bytes = {str(k): int(v) for k, v
                                    in c["migration_wire_bytes"].items()}
        out.transport_retransmits = int(c["transport_retransmits"])
        out.transport_reconnects = int(c["transport_reconnects"])
        out.transport_dup_fenced = int(c["transport_dup_fenced"])
        out.streamed_chunk_nacks = int(c["streamed_chunk_nacks"])
        out.rollouts_completed = int(c["rollouts_completed"])
        out.rollouts_rolled_back = int(c["rollouts_rolled_back"])
        out.canary_failures = int(c["canary_failures"])
        out.rollout_wire_bytes = int(c["rollout_wire_bytes"])
        out.draft_tokens_proposed = int(c["draft_tokens_proposed"])
        out.draft_tokens_accepted = int(c["draft_tokens_accepted"])
        out.spec_dispatches = int(c["spec_dispatches"])
        out.spec_tokens_emitted = int(c["spec_tokens_emitted"])
        return out

    def absorb(self, other: "FleetReport") -> None:
        """Fold another host's counters into this report (merge of the
        routing-layer tallies; the sample-level merge stays in
        :meth:`merge`, fed by each host's serving reports)."""
        self.rejected += other.rejected
        self.requeued += other.requeued
        self.replicas_dead += other.replicas_dead
        self.replicas_drained += other.replicas_drained
        self.handoffs += other.handoffs
        self.handoff_fallbacks += other.handoff_fallbacks
        for fmt, nbytes in other.handoff_wire_bytes.items():
            self.handoff_wire_bytes[fmt] = (
                self.handoff_wire_bytes.get(fmt, 0) + int(nbytes))
        self.migrations += other.migrations
        self.migration_fallbacks += other.migration_fallbacks
        for fmt, nbytes in other.migration_wire_bytes.items():
            self.migration_wire_bytes[fmt] = (
                self.migration_wire_bytes.get(fmt, 0) + int(nbytes))
        self.transport_retransmits += other.transport_retransmits
        self.transport_reconnects += other.transport_reconnects
        self.transport_dup_fenced += other.transport_dup_fenced
        self.streamed_chunk_nacks += other.streamed_chunk_nacks
        self.rollouts_completed += other.rollouts_completed
        self.rollouts_rolled_back += other.rollouts_rolled_back
        self.canary_failures += other.canary_failures
        self.rollout_wire_bytes += other.rollout_wire_bytes
        self.draft_tokens_proposed += other.draft_tokens_proposed
        self.draft_tokens_accepted += other.draft_tokens_accepted
        self.spec_dispatches += other.spec_dispatches
        self.spec_tokens_emitted += other.spec_tokens_emitted

    # ----------------------------------------------------------------
    # aggregation
    # ----------------------------------------------------------------

    @staticmethod
    def merge(reports: Iterable[ServingReport]) -> dict:
        """Fold N replicas' raw telemetry into one fleet summary.

        Pools raw samples for every distribution (so percentiles are
        exact over the fleet, not averaged-of-averages) and computes
        ratio metrics from summed numerators/denominators (so
        ``host_bytes_per_token`` and ``itl_ms`` are weighted by actual
        token counts). The fleet wall span is the max replica span —
        replicas run concurrently, so spans overlap rather than add."""
        raws = [r.raw() for r in reports]
        ttft: List[float] = []
        gaps: List[float] = []
        qd: List[int] = []
        occ: List[float] = []
        submitted = completed = aborted = tokens = host_bytes = 0
        proposed = accepted = dispatches = spec_tokens = 0
        span = 0.0
        for raw in raws:
            ttft.extend(raw["ttft_s"])
            gaps.extend(raw["token_gap_s"])
            qd.extend(raw["queue_depth_samples"])
            occ.extend(raw["occupancy_samples"])
            submitted += raw["submitted"]
            completed += raw["completed"]
            aborted += raw["aborted"]
            tokens += raw["tokens_emitted"]
            host_bytes += raw["host_bytes"]
            # speculative ratios, like host_bytes_per_token, only merge
            # honestly from summed numerators/denominators
            proposed += raw.get("draft_tokens_proposed", 0)
            accepted += raw.get("draft_tokens_accepted", 0)
            dispatches += raw.get("spec_dispatches", 0)
            spec_tokens += raw.get("spec_tokens_emitted", 0)
            span = max(span, raw["wall_s"])
        return {
            "replicas": len(raws),
            "requests": {"submitted": submitted, "completed": completed,
                         "aborted": aborted},
            "tokens_emitted": tokens,
            "tokens_per_s": tokens / span if span > 0 else float("nan"),
            "host_bytes_per_token": (host_bytes / tokens if tokens
                                     else float("nan")),
            "acceptance_rate": (accepted / proposed if proposed
                                else float("nan")),
            "tokens_per_dispatch": (spec_tokens / dispatches if dispatches
                                    else float("nan")),
            "draft_tokens_proposed": proposed,
            "draft_tokens_accepted": accepted,
            "ttft_ms": _dist_ms(ttft),
            "itl_ms": _dist_ms(gaps),
            "queue_depth": {"mean": (sum(qd) / len(qd) if qd
                                     else float("nan")),
                            "max": max(qd) if qd else 0},
            "slot_occupancy": {"mean": (sum(occ) / len(occ) if occ
                                        else float("nan")),
                               "max": max(occ) if occ else 0.0},
            "wall_s": span,
        }

    def summary(self, reports: Iterable[ServingReport] = ()) -> dict:
        out = self.merge(reports)
        out["fleet"] = {
            "rejected": self.rejected,
            "requeued": self.requeued,
            "replicas_dead": self.replicas_dead,
            "replicas_drained": self.replicas_drained,
            "handoffs": self.handoffs,
            "handoff_fallbacks": self.handoff_fallbacks,
            "handoff_wire_bytes": dict(self.handoff_wire_bytes),
            "migrations": self.migrations,
            "migration_fallbacks": self.migration_fallbacks,
            "migration_wire_bytes": dict(self.migration_wire_bytes),
            "transport": {
                "retransmits": self.transport_retransmits,
                "reconnects": self.transport_reconnects,
                "dup_fenced": self.transport_dup_fenced,
                "chunk_nacks": self.streamed_chunk_nacks,
            },
            "rollouts": {
                "completed": self.rollouts_completed,
                "rolled_back": self.rollouts_rolled_back,
                "canary_failures": self.canary_failures,
                "wire_bytes": self.rollout_wire_bytes,
            },
            "speculative": {
                "draft_tokens_proposed": self.draft_tokens_proposed,
                "draft_tokens_accepted": self.draft_tokens_accepted,
                "spec_dispatches": self.spec_dispatches,
                "spec_tokens_emitted": self.spec_tokens_emitted,
                "acceptance_rate": (
                    self.draft_tokens_accepted
                    / self.draft_tokens_proposed
                    if self.draft_tokens_proposed else float("nan")),
                "tokens_per_dispatch": (
                    self.spec_tokens_emitted / self.spec_dispatches
                    if self.spec_dispatches else float("nan")),
            },
        }
        return out

    def json(self, reports: Iterable[ServingReport] = ()) -> str:
        return json.dumps(self.summary(reports), sort_keys=True)

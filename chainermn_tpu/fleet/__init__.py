"""chainermn_tpu.fleet — many serving engines, one front door.

Two composition patterns over ``serving.Engine``:

* **Replicated** (``router.Router``): N identical engines behind a
  load-aware, session-affine router with queue-depth backpressure and
  heartbeat-driven replica health — a dead replica's in-flight work
  re-queues onto survivors with client futures intact.
* **Disaggregated** (``pools.DisaggregatedFleet``): a prefill pool
  runs ``prefill_chunk`` to completion and hands populated KV slots to
  a decode pool through the manifest-versioned ``handoff`` codec (raw
  f32 — bitwise — or blockwise int8 at ~0.254× the wire bytes), over a
  ``transport`` (in-process queue pair, or seq/SHA-framed object-plane
  frames between real processes) — synchronously or on the async
  conveyor's bounded worker queue.

``reports.FleetReport`` aggregates per-replica telemetry honestly
(pooled-sample percentiles, token-weighted ratios); ``health.
FleetHealth`` is the per-replica liveness verdict. See docs/serving.md.
"""

from chainermn_tpu.fleet.handoff import (HANDOFF_WIRE_FORMATS,
                                         HandoffError, decode_handoff,
                                         encode_handoff,
                                         handoff_payload_bytes)
from chainermn_tpu.fleet.health import FleetHealth
from chainermn_tpu.fleet.pools import (DecodePool, DisaggregatedFleet,
                                       PrefillPool, Stream)
from chainermn_tpu.fleet.reports import FleetReport
from chainermn_tpu.fleet.router import EngineReplica, Router
from chainermn_tpu.fleet.transport import (Arrival, InProcessTransport,
                                           LoopbackPlane,
                                           ObjectPlaneTransport,
                                           TransportError)

__all__ = [
    "HandoffError", "encode_handoff", "decode_handoff",
    "handoff_payload_bytes", "HANDOFF_WIRE_FORMATS",
    "FleetHealth", "FleetReport",
    "Stream", "PrefillPool", "DecodePool", "DisaggregatedFleet",
    "EngineReplica", "Router",
    "TransportError", "Arrival", "InProcessTransport",
    "ObjectPlaneTransport", "LoopbackPlane",
]

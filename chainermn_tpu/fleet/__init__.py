"""chainermn_tpu.fleet — many serving engines, one front door.

Two composition patterns over ``serving.Engine``:

* **Replicated** (``router.Router``): N identical engines behind a
  load-aware, session-affine router with queue-depth backpressure and
  heartbeat-driven replica health — a dead replica's in-flight work
  re-queues onto survivors with client futures intact.
* **Disaggregated** (``pools.DisaggregatedFleet``): m prefill pools
  run ``prefill_chunk`` to completion and hand populated KV slots to
  n decode pools (least-depth destination choice with the saturated-
  survivor precheck) through the manifest-versioned ``handoff`` codec
  — raw f32 (bitwise), blockwise int8 at ~0.254× the wire bytes, or
  the streamed format-5 per-layer chunk frames — over a ``transport``
  (in-process queue pair, or seq/SHA-framed object-plane frames
  between real processes, including the TCP
  ``comm.socket_plane.SocketObjectPlane``) — synchronously or on the
  async conveyor's bounded worker queue.

``reports.FleetReport`` aggregates per-replica telemetry honestly
(pooled-sample percentiles, token-weighted ratios) plus the transport
wire-health counters; ``health.FleetHealth`` is the per-replica
liveness verdict. See docs/serving.md.
"""

from chainermn_tpu.fleet.handoff import (HANDOFF_WIRE_FORMATS,
                                         HandoffError, decode_handoff,
                                         decode_handoff_streamed,
                                         encode_handoff,
                                         encode_handoff_streamed,
                                         handoff_payload_bytes,
                                         streamed_chunk_sid,
                                         streamed_parent_sid,
                                         streamed_wire_bytes)
from chainermn_tpu.fleet.health import FleetHealth
from chainermn_tpu.fleet.pools import (DecodePool, DisaggregatedFleet,
                                       PrefillPool, Stream,
                                       StreamAssembler)
from chainermn_tpu.fleet.reports import FleetReport
from chainermn_tpu.fleet.rollout import (DEFAULT_CHUNK_BYTES,
                                         RolloutController, RolloutError)
from chainermn_tpu.fleet.router import EngineReplica, Router
from chainermn_tpu.fleet.transport import (Arrival, InProcessTransport,
                                           LoopbackPlane,
                                           ObjectPlaneTransport,
                                           PairedTransport,
                                           TransportError)

__all__ = [
    "HandoffError", "encode_handoff", "decode_handoff",
    "encode_handoff_streamed", "decode_handoff_streamed",
    "streamed_wire_bytes", "streamed_chunk_sid", "streamed_parent_sid",
    "handoff_payload_bytes", "HANDOFF_WIRE_FORMATS",
    "FleetHealth", "FleetReport",
    "Stream", "PrefillPool", "DecodePool", "DisaggregatedFleet",
    "StreamAssembler",
    "EngineReplica", "Router",
    "RolloutController", "RolloutError", "DEFAULT_CHUNK_BYTES",
    "TransportError", "Arrival", "InProcessTransport",
    "ObjectPlaneTransport", "LoopbackPlane", "PairedTransport",
]

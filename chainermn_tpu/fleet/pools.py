"""Disaggregated prefill/decode pools over two serving engines.

The disaggregation argument (DistServe/Splitwise, PAPERS.md): prefill
is compute-bound and bursty, decode is memory-bandwidth-bound and
steady — co-locating them makes every long prompt a head-of-line stall
for every active decode stream. Here the split is explicit:

* :class:`PrefillPool` owns an engine that runs ``prefill_chunk`` to
  completion with ``max_new_tokens=1, hold=True`` — the first token is
  sampled on device and the finished slot PARKS (``Engine.held``: KV
  rows, cursor, and post-split PRNG key stay bound) instead of
  retiring. The slot stays held until the TRANSPORT reports a terminal
  status for its handoff (deferred release), so an aborted transfer
  can still fall back cleanly.
* :class:`DecodePool` owns an engine that adopts exported slots
  (``Engine.import_handoff``) and decodes them to termination.
* :class:`DisaggregatedFleet` is the conveyor between them. Every held
  prefill slot is exported, serialized through the
  :mod:`~chainermn_tpu.fleet.handoff` codec (``wire_format`` — ``f32``
  raw or ``int8-block``), and shipped over a
  :mod:`~chainermn_tpu.fleet.transport` — seq-numbered, SHA-verified
  frames with NACK → bounded re-send, the layer the wire-level chaos
  faults (drop/delay/dup/corrupt/truncate) tear at.

Two conveyor disciplines:

* **synchronous** (default) — ``step()`` does export → send → place
  inline; the step thread pays every wire millisecond. Simple, and the
  bitwise reference the async path is checked against.
* **asynchronous** (``async_conveyor=True``) — encode+send move onto a
  bounded worker queue (the ``AsyncSnapshotPlane`` double-buffer
  discipline, checkpointing/async_plane.py) so the wire overlaps
  decode steps. Engine calls — export, release, import — STAY on the
  step thread (the engine is not thread-safe and ``_decode`` iterates
  ``held``); only serialization and transport ride the worker.
  ``backpressure="block"`` stalls the step thread when ``max_pending``
  transfers are queued; ``"skip"`` leaves the slot held and retries
  next step (counted in ``stats["skipped"]``). ``drain(deadline_s=)``
  bounds shutdown; worker errors surface on the next ``step()``.

Contracts the tests pin: raw-format streams are BITWISE-identical to
the single-engine path (export → import is exact f32 bytes and the PRNG
key continues, never re-derives) in BOTH conveyor modes; a handoff the
transport cannot deliver intact within its attempt budget falls back to
a CLEAN re-prefill of the full prompt on the decode engine — same seed,
so the one-split-per-token contract replays the identical stream — and
never a poisoned slot.
"""

from __future__ import annotations

import collections
import itertools
import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from chainermn_tpu.fleet.handoff import (HANDOFF_FORMAT_STREAMED,
                                         HandoffError, decode_handoff,
                                         decode_handoff_streamed,
                                         encode_handoff,
                                         encode_handoff_streamed,
                                         streamed_chunk_sid,
                                         streamed_parent_sid,
                                         streamed_wire_bytes)
from chainermn_tpu.fleet.reports import FleetReport
from chainermn_tpu.fleet.transport import InProcessTransport
from chainermn_tpu.serving.engine import WeightsVersionSkew

__all__ = ["Stream", "PrefillPool", "DecodePool", "DisaggregatedFleet",
           "StreamAssembler"]


class Stream:
    """One client stream crossing the prefill→decode boundary. The
    terminal ``tokens`` list is the SAME sequence a single engine's
    ``generate()`` would emit for this prompt/seed (bitwise under the
    raw wire format)."""

    def __init__(self, stream_id: int, prompt, max_new_tokens: int,
                 kw: dict):
        self.stream_id = stream_id
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.kw = dict(kw)            # eos_id / temperature / top_k / seed
        self.tokens: List[int] = []
        self.state = "queued"         # queued|prefill|decode|done
        self.fell_back = False        # handoff failed → re-prefilled
        self.fallback_reason: Optional[str] = None  # why the wire failed

    @property
    def finished(self) -> bool:
        return self.state == "done"


class PrefillPool:
    """Prefill-side engine wrapper: prompts in, held slots out."""

    def __init__(self, engine):
        self.engine = engine
        self._by_id: Dict[int, Stream] = {}   # request_id → stream

    def submit(self, stream: Stream) -> None:
        req = self.engine.submit(stream.prompt, max_new_tokens=1,
                                 hold=True, **stream.kw)
        self._by_id[req.request_id] = stream
        stream.state = "prefill"

    def depth(self) -> int:
        """Streams submitted here and not yet released — the
        least-depth signal for prefill-pool choice."""
        return len(self._by_id)

    def step(self) -> bool:
        """Advance iff there is prefill work (held slots alone are not
        work — they pin their cursors and wait for export)."""
        if self.engine.idle():
            return False
        self.engine.step()
        return True

    def ready(self) -> List[Tuple[Stream, object]]:
        """Held (stream, request) pairs awaiting export, oldest first."""
        reqs = sorted(self.engine.held.values(),
                      key=lambda r: r.request_id)
        return [(self._by_id[r.request_id], r) for r in reqs]

    def export(self, req) -> dict:
        """Export one held slot (a pure read of device state). The slot
        STAYS held until :meth:`release` — deferred so the conveyor can
        wait for the transport's terminal status and, on an aborted
        transfer, release with the abort accounted."""
        return self.engine.export_handoff(req)

    def release(self, req, aborted: bool = False) -> None:
        """Release a held slot whose handoff reached a terminal
        transport status (``adopted``/``duplicate`` → clean retire;
        ``failed`` → aborted retire, the receiver re-prefills)."""
        if aborted:
            self.engine.abort_held(req)
        else:
            self.engine.release_held(req)
        self._by_id.pop(req.request_id, None)


class DecodePool:
    """Decode-side engine wrapper: adopts handoffs, drains streams."""

    def __init__(self, engine):
        self.engine = engine
        self._inflight: List[Tuple[object, Stream]] = []

    def has_room(self) -> bool:
        return bool(self.engine.free_slots)

    def depth(self) -> int:
        """Streams this pool is currently responsible for — the
        router's least-depth placement signal, applied to decode-pool
        choice in the m×n conveyor."""
        return len(self._inflight)

    def place(self, stream: Stream, handoff: dict) -> None:
        """Adopt a VERIFIED handoff: the imported slot resumes the
        exporting engine's exact stream."""
        req = self.engine.import_handoff(
            handoff, stream.prompt, max_new_tokens=stream.max_new_tokens)
        stream.state = "decode"
        self._inflight.append((req, stream))

    def fallback(self, stream: Stream,
                 reason: Optional[str] = None) -> None:
        """Handoff failed verification or delivery → CLEAN re-prefill
        of the full prompt on this engine. Same seed, so the per-token
        key-split contract replays the identical stream; the suspect
        bytes never touch a slot. ``reason`` is the wire's defect
        history (transport NACK reasons / codec error) so the fallback
        log says WHY, not just that it happened."""
        req = self.engine.submit(stream.prompt,
                                 max_new_tokens=stream.max_new_tokens,
                                 **stream.kw)
        stream.state = "decode"
        stream.fell_back = True
        stream.fallback_reason = reason or "delivery failed"
        self._inflight.append((req, stream))

    def step(self) -> bool:
        worked = False
        if not self.engine.idle():
            self.engine.step()
            worked = True
        still = []
        for req, stream in self._inflight:
            if req.finished:
                stream.tokens = list(req.tokens)
                stream.state = "done"
            else:
                still.append((req, stream))
        self._inflight = still
        return worked


class StreamAssembler:
    """Receiver-side reassembly of streamed (format-5) handoffs.

    Chunk frames ride the transport under their own (negative) stream
    ids — per-frame SHA verify, NACK/re-send, and duplicate fencing all
    apply per chunk — and park here until the closing frame commits the
    stream. ``decode_handoff_streamed`` then proves the set against the
    closing table; a chunk that never survived its delivery budget is
    simply missing at assembly time, which fails verification and
    becomes a clean re-prefill — chunk-level loss can never poison a
    decode slot, and its defect history rides along for the log."""

    def __init__(self) -> None:
        self.chunks: Dict[int, Dict[int, Tuple[dict, bytes]]] = {}
        self.defects: Dict[int, List[str]] = {}

    def add_chunk(self, arrival) -> None:
        """File one chunk arrival under its parent stream."""
        sid, idx = streamed_parent_sid(arrival.stream_id)
        if arrival.failed:
            why = "; ".join(arrival.defects) or "delivery failed"
            self.defects.setdefault(sid, []).append(
                f"chunk {idx}: {why}")
            return
        self.chunks.setdefault(sid, {})[idx] = (arrival.manifest,
                                                arrival.blob)

    def take(self, sid: int) -> Tuple[List[Tuple[dict, bytes]],
                                      List[str]]:
        """Pop everything held for ``sid``: ``(chunks_in_index_order,
        defect_notes)``. Called exactly once per closing frame (or on
        the stream's failure), so fenced streams leave no residue."""
        held = self.chunks.pop(sid, {})
        return ([held[i] for i in sorted(held)],
                self.defects.pop(sid, []))


class DisaggregatedFleet:
    """The conveyor: submit → prefill → handoff transport → decode.

    ``wire_format`` picks the handoff codec (``"f32"`` raw/bitwise,
    ``"int8-block"`` quantized at ~0.254× the wire bytes); ``report``
    accumulates the fleet counters (handoffs, wire bytes by format,
    fallbacks) that ``bench.py``'s fleet gate reads; ``transport``
    defaults to an :class:`~chainermn_tpu.fleet.transport.
    InProcessTransport` (pass one with ``wire_delay_ms`` to model DCN
    latency, or wire the pools across processes via
    ``tools/fleet_lm.py --hosts``).

    **m×n pools** — both engine arguments accept a single engine or a
    list. Every prefill pool feeds every decode pool: the destination
    for each handoff is chosen at transfer time by the router's
    least-depth logic over the decode pools, with the saturated-
    survivor precheck — when NO decode pool has a free slot the slot
    stays held (``stats["deferred"]``) instead of shipping bytes that
    would have nowhere to adopt. One transport per decode pool
    (``transport`` may be a matching list); arrivals adopt on the pool
    whose transport delivered them.

    **streamed handoffs** (``streamed=True``) — each handoff ships as
    format-5 per-layer chunk frames plus a closing manifest
    (:func:`~chainermn_tpu.fleet.handoff.encode_handoff_streamed`).
    Every chunk is its own transport frame — SHA-verified, NACKed, and
    re-sent independently, so a corrupt chunk costs one chunk's
    re-send — and the receiver's :class:`StreamAssembler` holds them
    until the closing frame proves the set. Any gap fails assembly and
    falls back to a clean re-prefill.

    With ``async_conveyor=True`` the encode+send leg runs on a worker
    thread behind a bounded queue — see the module docstring for the
    threading discipline and backpressure semantics. ``stats`` then
    separates ``stall_ms_total`` (step-thread time lost to the
    conveyor) from ``transfer_ms_total`` (worker wall-time on the
    wire); their ratio is :attr:`overlap_fraction`. The synchronous
    conveyor books every transfer millisecond as stall — by
    construction its overlap is 0.
    """

    _POLL_S = 0.05

    def __init__(self, prefill_engine, decode_engine, *,
                 wire_format: str = "f32",
                 report: Optional[FleetReport] = None,
                 transport=None,
                 async_conveyor: bool = False,
                 max_pending: int = 2,
                 backpressure: str = "block",
                 streamed: bool = False):
        if backpressure not in ("block", "skip"):
            raise ValueError(
                f"backpressure must be 'block' or 'skip': {backpressure!r}")
        pre = (list(prefill_engine)
               if isinstance(prefill_engine, (list, tuple))
               else [prefill_engine])
        dec = (list(decode_engine)
               if isinstance(decode_engine, (list, tuple))
               else [decode_engine])
        if not pre or not dec:
            raise ValueError("need at least one engine per side")
        self.prefills = [PrefillPool(e) for e in pre]
        self.decodes = [DecodePool(e) for e in dec]
        # the 1×1 aliases older callers (and half the tests) use
        self.prefill = self.prefills[0]
        self.decode = self.decodes[0]
        self.wire_format = wire_format
        self.streamed = bool(streamed)
        self.report = report or FleetReport()
        if transport is None:
            self.transports = [InProcessTransport() for _ in self.decodes]
        elif isinstance(transport, (list, tuple)):
            if len(transport) != len(self.decodes):
                raise ValueError(
                    f"{len(transport)} transports for "
                    f"{len(self.decodes)} decode pools")
            self.transports = list(transport)
        else:
            if len(self.decodes) != 1:
                raise ValueError("a single transport needs a single "
                                 "decode pool — pass one per pool")
            self.transports = [transport]
        self.transport = self.transports[0]
        self.async_conveyor = bool(async_conveyor)
        self.backpressure = backpressure
        self._ids = itertools.count()
        self.streams: List[Stream] = []
        self._by_sid: Dict[int, Stream] = {}
        self._asm = StreamAssembler()
        self._pending_place: list = []   # (decode_idx, Arrival) buffered
        self.stats = {"transfers": 0, "skipped": 0, "deferred": 0,
                      "streamed_chunks": 0,
                      "stall_ms_total": 0.0, "transfer_ms_total": 0.0}
        if self.async_conveyor:
            self._q: queue.Queue = queue.Queue(max(1, int(max_pending)))
            # sid → (owning prefill pool, held req)
            self._inflight: Dict[int, Tuple[PrefillPool, object]] = {}
            self._done: collections.deque = collections.deque()
            self._error: Optional[BaseException] = None
            self._stop = threading.Event()
            self._worker = threading.Thread(
                target=self._run_worker, name="fleet-conveyor", daemon=True)
            self._worker.start()

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               **kw) -> Stream:
        mnt = (max_new_tokens if max_new_tokens is not None
               else self.prefill.engine.config.max_new_tokens)
        stream = Stream(next(self._ids), prompt, mnt, kw)
        self.streams.append(stream)
        self._by_sid[stream.stream_id] = stream
        # least-depth over the prefill pools (ties break by index)
        pool = min(enumerate(self.prefills),
                   key=lambda e: (e[1].depth(), e[0]))[1]
        pool.submit(stream)
        return stream

    # -- destination choice (m×n) ----------------------------------------

    def _pick_dest(self) -> Optional[int]:
        """Least-depth decode pool WITH a free slot (ties break by
        index — deterministic, like the router's ``_pick_dest``).
        ``None`` means every pool is saturated: the saturated-survivor
        precheck — shipping bytes now would leave them with nowhere to
        adopt, so the held slot defers until someone drains."""
        cands = [(pool.depth(), di)
                 for di, pool in enumerate(self.decodes)
                 if pool.has_room()]
        if not cands:
            return None
        return min(cands)[1]

    def _send_handoff(self, di: int, sid: int, handoff: dict) -> str:
        """Encode + ship one handoff on ``transports[di]``; returns
        the terminal status of the frame that commits the stream.

        Streamed mode ships each KV block as its own transport frame
        (chunk stream ids) — verified, NACKed, and re-sent per chunk —
        then the closing frame under the real stream id. A chunk that
        exhausts its budget is NOT fatal here: the receiver's assembly
        check catches the gap at adoption and re-prefills cleanly."""
        transport = self.transports[di]
        if not self.streamed:
            manifest, blob = encode_handoff(handoff, self.wire_format)
            self.report.record_handoff(self.wire_format, len(blob))
            return transport.send(sid, manifest, blob)
        chunks, closing, closing_blob = encode_handoff_streamed(
            handoff, self.wire_format)
        self.report.record_handoff(self.wire_format,
                                   streamed_wire_bytes(closing))
        for i, (man, blob) in enumerate(chunks):
            transport.send(streamed_chunk_sid(sid, i), man, blob)
            self.stats["streamed_chunks"] += 1
        return transport.send(sid, closing, closing_blob)

    # -- arrivals (both modes; step thread only) -------------------------

    def _pump_arrivals(self) -> None:
        for di, transport in enumerate(self.transports):
            for arr in transport.poll():
                self._pending_place.append((di, arr))

    def _place(self) -> bool:
        """Adopt or fall back every buffered arrival its decode pool
        has room for (fallback re-submits through the engine queue, so
        it never needs a free slot up front). Chunk frames file into
        the assembler; the closing frame adopts the whole stream."""
        placed = False
        still = []
        for di, arr in self._pending_place:
            if arr.stream_id < 0:
                self._asm.add_chunk(arr)
                placed = True
                continue
            stream = self._by_sid.get(arr.stream_id)
            if stream is None or stream.state != "prefill":
                continue          # fenced/unknown stream: nothing to do
            pool = self.decodes[di]
            if arr.failed:
                _, notes = self._asm.take(arr.stream_id)
                reason = "; ".join(arr.defects) or "delivery failed"
                if notes:
                    reason += " [" + "; ".join(notes) + "]"
                self.report.record_fallback()
                pool.fallback(stream, reason)
                placed = True
                continue
            if not pool.has_room():
                still.append((di, arr))
                continue
            manifest = arr.manifest
            notes: List[str] = []
            try:
                if (isinstance(manifest, dict)
                        and manifest.get("format")
                        == HANDOFF_FORMAT_STREAMED):
                    chunks, notes = self._asm.take(arr.stream_id)
                    handoff = decode_handoff_streamed(
                        manifest, arr.blob, chunks)
                else:
                    handoff = decode_handoff(manifest, arr.blob)
                pool.place(stream, handoff)
            except (HandoffError, WeightsVersionSkew) as e:
                # wire-verified but structurally unusable (format skew,
                # missing/foreign chunk) or minted under a DIFFERENT
                # weights version than the decode engine serves (a
                # rollout in flight): same clean-re-prefill answer as a
                # failed delivery — the re-prefilled stream is entirely
                # the decode engine's version — with the per-chunk
                # defect history attached, so the log says WHY
                reason = str(e)
                if notes:
                    reason += " [" + "; ".join(notes) + "]"
                self.report.record_fallback()
                pool.fallback(stream, reason)
            placed = True
        self._pending_place = still
        return placed

    # -- synchronous conveyor --------------------------------------------

    def _transfer(self) -> bool:
        """Move every exportable held slot some decode pool has room
        for: export → encode → transport (seq/SHA frames, bounded
        re-send) → place, with delivery failure answered by a clean
        re-prefill. The step thread pays the wire inline — all of it
        booked as stall so the async path has an honest baseline."""
        moved = False
        for pool in self.prefills:
            for stream, req in pool.ready():
                di = self._pick_dest()
                if di is None:
                    self.stats["deferred"] += 1
                    return moved
                handoff = pool.export(req)
                t0 = time.monotonic()
                status = self._send_handoff(di, stream.stream_id,
                                            handoff)
                spent_ms = (time.monotonic() - t0) * 1000.0
                self.stats["transfer_ms_total"] += spent_ms
                self.stats["stall_ms_total"] += spent_ms
                self.stats["transfers"] += 1
                pool.release(req, aborted=(status == "failed"))
                # place immediately so has_room stays accurate for the
                # next held slot in this same pass
                self._pump_arrivals()
                self._place()
                moved = True
        return moved

    # -- asynchronous conveyor -------------------------------------------

    def _run_worker(self) -> None:
        """Worker leg: serialize + ship. No engine calls here — the
        handoff dict was exported on the step thread; errors are
        captured and re-raised from the next ``step()``."""
        while not self._stop.is_set():
            try:
                sid, handoff, di = self._q.get(timeout=self._POLL_S)
            except queue.Empty:
                continue
            try:
                t0 = time.monotonic()
                status = self._send_handoff(di, sid, handoff)
                self.stats["transfer_ms_total"] += (
                    (time.monotonic() - t0) * 1000.0)
                self._done.append((sid, status))
            except BaseException as e:  # noqa: BLE001 — surfaced in step()
                if self._error is None:
                    self._error = e
                self._done.append((sid, "failed"))
            finally:
                self.stats["transfers"] += 1
                self._q.task_done()

    def _raise_pending(self) -> None:
        if self._error is not None:
            e, self._error = self._error, None
            raise RuntimeError("async conveyor transfer failed") from e

    def _offer(self) -> bool:
        """Export ready held slots on the step thread and hand them to
        the worker (destination decode pool chosen here, at offer
        time, by least depth). ``skip`` backpressure leaves the slot
        held on a full queue (it re-offers next step); ``block`` waits
        — that wait is the only stall the async conveyor books. When
        every decode pool is saturated the slot defers instead."""
        offered = False
        for pool in self.prefills:
            for stream, req in pool.ready():
                sid = stream.stream_id
                if sid in self._inflight:
                    continue       # already on the wire; release pending
                di = self._pick_dest()
                if di is None:
                    self.stats["deferred"] += 1
                    return offered
                if self.backpressure == "skip" and self._q.full():
                    self.stats["skipped"] += 1
                    return offered
                handoff = pool.export(req)
                if self.backpressure == "skip":
                    try:
                        self._q.put_nowait((sid, handoff, di))
                    except queue.Full:  # raced the check: same answer
                        self.stats["skipped"] += 1
                        return offered
                else:
                    t0 = time.monotonic()
                    while True:
                        self._raise_pending()  # dead worker never drains
                        try:
                            self._q.put((sid, handoff, di),
                                        timeout=self._POLL_S)
                            break
                        except queue.Full:
                            continue
                    self.stats["stall_ms_total"] += (
                        (time.monotonic() - t0) * 1000.0)
                self._inflight[sid] = (pool, req)
                offered = True
        return offered

    def _reap(self) -> bool:
        """Release held slots whose transfers reached a terminal
        status (step thread — the engine's held map is not safe to
        mutate from the worker)."""
        reaped = False
        while self._done:
            sid, status = self._done.popleft()
            ent = self._inflight.pop(sid, None)
            if ent is not None:
                pool, req = ent
                pool.release(req, aborted=(status == "failed"))
            reaped = True
        return reaped

    def drain(self, deadline_s: Optional[float] = None) -> bool:
        """Wait for every queued and in-flight transfer to reach a
        terminal transport status, then reap and place. ``deadline_s``
        is seconds from now; a missed deadline returns ``False`` (never
        raises for lateness — mirror of ``AsyncSnapshotPlane.drain``).
        Synchronous conveyors have nothing in flight: always ``True``."""
        if not self.async_conveyor:
            return True
        deadline = (None if deadline_s is None
                    else time.monotonic() + float(deadline_s))
        with self._q.all_tasks_done:
            while self._q.unfinished_tasks:
                if deadline is None:
                    self._q.all_tasks_done.wait()
                    continue
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._q.all_tasks_done.wait(timeout=left)
        self._reap()
        self._pump_arrivals()
        self._place()
        return True

    def close(self) -> None:
        """Drain outstanding transfers and stop the worker. Idempotent;
        a closed fleet can still ``step()`` its engines (the conveyor
        leg is simply empty)."""
        if not self.async_conveyor or self._stop.is_set():
            return
        self._q.join()
        self._stop.set()
        self._worker.join()
        self._reap()

    # -- the conveyor loop ------------------------------------------------

    def step(self) -> bool:
        """One conveyor iteration; returns whether anything advanced."""
        if not self.async_conveyor:
            worked = False
            for pool in self.prefills:
                # each pool step syncs internally (int32 token pulls)
                worked = pool.step() or worked  # dlint: disable=DL104
            worked = self._transfer() or worked
            self._pump_arrivals()
            worked = self._place() or worked
            for pool in self.decodes:
                # each pool step syncs internally (int32 token pulls)
                worked = pool.step() or worked  # dlint: disable=DL104
            return worked
        self._raise_pending()
        worked = False
        for pool in self.prefills:
            # each pool step syncs internally (int32 token pulls)
            worked = pool.step() or worked  # dlint: disable=DL104
        worked = self._reap() or worked
        worked = self._offer() or worked
        self._pump_arrivals()
        worked = self._place() or worked
        for pool in self.decodes:
            # each pool step syncs internally (int32 token pulls)
            worked = pool.step() or worked  # dlint: disable=DL104
        return worked

    def idle(self) -> bool:
        for pool in self.prefills:
            if not pool.engine.idle() or pool.engine.held:
                return False
        for pool in self.decodes:
            if not pool.engine.idle():
                return False
        if self._pending_place:
            return False
        if self.async_conveyor and (self._inflight or self._done
                                    or self._q.unfinished_tasks):
            return False
        return True

    def run_until_drained(self, max_steps: int = 100_000) -> int:
        n = 0
        while not self.idle():
            if n >= max_steps:
                raise RuntimeError(
                    f"fleet failed to drain within {max_steps} steps")
            # each engine step syncs internally (int32 token pulls)
            worked = self.step()  # dlint: disable=DL104
            if not worked and self.async_conveyor:
                time.sleep(0.001)   # transfer in flight: yield to worker
            n += 1
        return n

    @property
    def overlap_fraction(self) -> float:
        """Fraction of wire wall-time hidden behind decode steps:
        ``1 − stall/transfer`` clamped to [0, 1]. The synchronous
        conveyor books stall == transfer, so it reads 0."""
        xfer = self.stats["transfer_ms_total"]
        if xfer <= 0:
            return 0.0
        return max(0.0, min(1.0,
                            1.0 - self.stats["stall_ms_total"] / xfer))

    def reports(self):
        return ([pool.engine.report for pool in self.prefills]
                + [pool.engine.report for pool in self.decodes])

    def transport_totals(self) -> dict:
        """Live wire-health counters folded across every transport:
        retransmits (delivery attempts beyond the first), reconnects
        (socket planes), duplicate-fenced frames, and streamed-chunk
        NACKs — the numbers that prove per-chunk re-send granularity
        and restart fencing actually engaged."""
        tot = {"retransmits": 0, "reconnects": 0, "dup_fenced": 0,
               "chunk_nacks": 0}
        for transport in self.transports:
            s = getattr(transport, "stats", {})
            tot["retransmits"] += max(
                0, int(s.get("attempts", 0)) - int(s.get("sent", 0)))
            r = transport.receiver_stats
            tot["dup_fenced"] += int(r.get("duplicates", 0))
            tot["chunk_nacks"] += int(r.get("chunk_nacked", 0))
            plane_stats = getattr(getattr(transport, "plane", None),
                                  "stats", None)
            if plane_stats:
                tot["reconnects"] += int(plane_stats.get("reconnects", 0))
        return tot

    def summary(self) -> dict:
        out = self.report.summary(self.reports())
        # fold the LIVE transport counters on top of whatever finished
        # transports were already recorded into the report
        live = out["fleet"]["transport"]
        for key, val in self.transport_totals().items():
            live[key] += val
        return out

"""Disaggregated prefill/decode pools over two serving engines.

The disaggregation argument (DistServe/Splitwise, PAPERS.md): prefill
is compute-bound and bursty, decode is memory-bandwidth-bound and
steady — co-locating them makes every long prompt a head-of-line stall
for every active decode stream. Here the split is explicit:

* :class:`PrefillPool` owns an engine that runs ``prefill_chunk`` to
  completion with ``max_new_tokens=1, hold=True`` — the first token is
  sampled on device and the finished slot PARKS (``Engine.held``: KV
  rows, cursor, and post-split PRNG key stay bound) instead of
  retiring.
* :class:`DecodePool` owns an engine that adopts exported slots
  (``Engine.import_handoff``) and decodes them to termination.
* :class:`DisaggregatedFleet` is the synchronous conveyor between
  them: every held prefill slot is exported, serialized through the
  :mod:`~chainermn_tpu.fleet.handoff` codec (``wire_format`` — ``f32``
  raw or ``int8-block``), passed through the chaos fault plane
  (``corrupt_handoff`` mutates the wire bytes exactly like a torn
  interconnect), and placed on the decode engine.

Contracts the tests pin: raw-format streams are BITWISE-identical to
the single-engine path (export → import is exact f32 bytes and the PRNG
key continues, never re-derives); a handoff that fails verification
(:class:`~chainermn_tpu.fleet.handoff.HandoffError`) falls back to a
CLEAN re-prefill of the full prompt on the decode engine — same seed,
so the one-split-per-token contract replays the identical stream — and
never a poisoned slot.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

import numpy as np

from chainermn_tpu.fleet.handoff import (HandoffError, decode_handoff,
                                         encode_handoff)
from chainermn_tpu.fleet.reports import FleetReport
from chainermn_tpu.resilience import chaos

__all__ = ["Stream", "PrefillPool", "DecodePool", "DisaggregatedFleet"]


class Stream:
    """One client stream crossing the prefill→decode boundary. The
    terminal ``tokens`` list is the SAME sequence a single engine's
    ``generate()`` would emit for this prompt/seed (bitwise under the
    raw wire format)."""

    def __init__(self, stream_id: int, prompt, max_new_tokens: int,
                 kw: dict):
        self.stream_id = stream_id
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.kw = dict(kw)            # eos_id / temperature / top_k / seed
        self.tokens: List[int] = []
        self.state = "queued"         # queued|prefill|decode|done
        self.fell_back = False        # handoff failed → re-prefilled

    @property
    def finished(self) -> bool:
        return self.state == "done"


class PrefillPool:
    """Prefill-side engine wrapper: prompts in, held slots out."""

    def __init__(self, engine):
        self.engine = engine
        self._by_id: Dict[int, Stream] = {}   # request_id → stream

    def submit(self, stream: Stream) -> None:
        req = self.engine.submit(stream.prompt, max_new_tokens=1,
                                 hold=True, **stream.kw)
        self._by_id[req.request_id] = stream
        stream.state = "prefill"

    def step(self) -> bool:
        """Advance iff there is prefill work (held slots alone are not
        work — they pin their cursors and wait for export)."""
        if self.engine.idle():
            return False
        self.engine.step()
        return True

    def ready(self) -> List[Tuple[Stream, object]]:
        """Held (stream, request) pairs awaiting export, oldest first."""
        reqs = sorted(self.engine.held.values(),
                      key=lambda r: r.request_id)
        return [(self._by_id[r.request_id], r) for r in reqs]

    def export(self, req) -> dict:
        """Export + release one held slot; returns the handoff dict."""
        handoff = self.engine.export_handoff(req)
        self.engine.release_held(req)
        self._by_id.pop(req.request_id, None)
        return handoff


class DecodePool:
    """Decode-side engine wrapper: adopts handoffs, drains streams."""

    def __init__(self, engine):
        self.engine = engine
        self._inflight: List[Tuple[object, Stream]] = []

    def has_room(self) -> bool:
        return bool(self.engine.free_slots)

    def place(self, stream: Stream, handoff: dict) -> None:
        """Adopt a VERIFIED handoff: the imported slot resumes the
        exporting engine's exact stream."""
        req = self.engine.import_handoff(
            handoff, stream.prompt, max_new_tokens=stream.max_new_tokens)
        stream.state = "decode"
        self._inflight.append((req, stream))

    def fallback(self, stream: Stream) -> None:
        """Handoff failed verification → CLEAN re-prefill of the full
        prompt on this engine. Same seed, so the per-token key-split
        contract replays the identical stream; the suspect bytes never
        touch a slot."""
        req = self.engine.submit(stream.prompt,
                                 max_new_tokens=stream.max_new_tokens,
                                 **stream.kw)
        stream.state = "decode"
        stream.fell_back = True
        self._inflight.append((req, stream))

    def step(self) -> bool:
        worked = False
        if not self.engine.idle():
            self.engine.step()
            worked = True
        still = []
        for req, stream in self._inflight:
            if req.finished:
                stream.tokens = list(req.tokens)
                stream.state = "done"
            else:
                still.append((req, stream))
        self._inflight = still
        return worked


class DisaggregatedFleet:
    """The synchronous conveyor: submit → prefill → handoff → decode.

    ``wire_format`` picks the handoff codec (``"f32"`` raw/bitwise,
    ``"int8-block"`` quantized at ~0.254× the wire bytes); ``report``
    accumulates the fleet counters (handoffs, wire bytes by format,
    fallbacks) that ``bench.py``'s fleet gate reads.
    """

    def __init__(self, prefill_engine, decode_engine, *,
                 wire_format: str = "f32",
                 report: Optional[FleetReport] = None):
        self.prefill = PrefillPool(prefill_engine)
        self.decode = DecodePool(decode_engine)
        self.wire_format = wire_format
        self.report = report or FleetReport()
        self._ids = itertools.count()
        self.streams: List[Stream] = []

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               **kw) -> Stream:
        mnt = (max_new_tokens if max_new_tokens is not None
               else self.prefill.engine.config.max_new_tokens)
        stream = Stream(next(self._ids), prompt, mnt, kw)
        self.streams.append(stream)
        self.prefill.submit(stream)
        return stream

    def _transfer(self) -> bool:
        """Move every exportable held slot the decode pool has room
        for: export → encode → (chaos fault plane) → verify → place,
        with :class:`HandoffError` answered by a clean re-prefill."""
        moved = False
        for stream, req in self.prefill.ready():
            if not self.decode.has_room():
                break
            handoff = self.prefill.export(req)
            manifest, blob = encode_handoff(handoff, self.wire_format)
            self.report.record_handoff(self.wire_format, len(blob))
            # the wire: corrupt_handoff faults tear/flip bytes HERE,
            # between the sender's digest and the receiver's check
            blob = chaos.on_handoff(blob)
            try:
                self.decode.place(stream, decode_handoff(manifest, blob))
            except HandoffError:
                self.report.record_fallback()
                self.decode.fallback(stream)
            moved = True
        return moved

    def step(self) -> bool:
        """One conveyor iteration; returns whether anything advanced."""
        worked = self.prefill.step()
        worked = self._transfer() or worked
        worked = self.decode.step() or worked
        return worked

    def idle(self) -> bool:
        return (self.prefill.engine.idle()
                and not self.prefill.engine.held
                and self.decode.engine.idle())

    def run_until_drained(self, max_steps: int = 100_000) -> int:
        n = 0
        while not self.idle():
            if n >= max_steps:
                raise RuntimeError(
                    f"fleet failed to drain within {max_steps} steps")
            # each engine step syncs internally (int32 token pulls)
            self.step()  # dlint: disable=DL104
            n += 1
        return n

    def reports(self):
        return [self.prefill.engine.report, self.decode.engine.report]

    def summary(self) -> dict:
        return self.report.summary(self.reports())

"""Disaggregated prefill/decode pools over two serving engines.

The disaggregation argument (DistServe/Splitwise, PAPERS.md): prefill
is compute-bound and bursty, decode is memory-bandwidth-bound and
steady — co-locating them makes every long prompt a head-of-line stall
for every active decode stream. Here the split is explicit:

* :class:`PrefillPool` owns an engine that runs ``prefill_chunk`` to
  completion with ``max_new_tokens=1, hold=True`` — the first token is
  sampled on device and the finished slot PARKS (``Engine.held``: KV
  rows, cursor, and post-split PRNG key stay bound) instead of
  retiring. The slot stays held until the TRANSPORT reports a terminal
  status for its handoff (deferred release), so an aborted transfer
  can still fall back cleanly.
* :class:`DecodePool` owns an engine that adopts exported slots
  (``Engine.import_handoff``) and decodes them to termination.
* :class:`DisaggregatedFleet` is the conveyor between them. Every held
  prefill slot is exported, serialized through the
  :mod:`~chainermn_tpu.fleet.handoff` codec (``wire_format`` — ``f32``
  raw or ``int8-block``), and shipped over a
  :mod:`~chainermn_tpu.fleet.transport` — seq-numbered, SHA-verified
  frames with NACK → bounded re-send, the layer the wire-level chaos
  faults (drop/delay/dup/corrupt/truncate) tear at.

Two conveyor disciplines:

* **synchronous** (default) — ``step()`` does export → send → place
  inline; the step thread pays every wire millisecond. Simple, and the
  bitwise reference the async path is checked against.
* **asynchronous** (``async_conveyor=True``) — encode+send move onto a
  bounded worker queue (the ``AsyncSnapshotPlane`` double-buffer
  discipline, checkpointing/async_plane.py) so the wire overlaps
  decode steps. Engine calls — export, release, import — STAY on the
  step thread (the engine is not thread-safe and ``_decode`` iterates
  ``held``); only serialization and transport ride the worker.
  ``backpressure="block"`` stalls the step thread when ``max_pending``
  transfers are queued; ``"skip"`` leaves the slot held and retries
  next step (counted in ``stats["skipped"]``). ``drain(deadline_s=)``
  bounds shutdown; worker errors surface on the next ``step()``.

Contracts the tests pin: raw-format streams are BITWISE-identical to
the single-engine path (export → import is exact f32 bytes and the PRNG
key continues, never re-derives) in BOTH conveyor modes; a handoff the
transport cannot deliver intact within its attempt budget falls back to
a CLEAN re-prefill of the full prompt on the decode engine — same seed,
so the one-split-per-token contract replays the identical stream — and
never a poisoned slot.
"""

from __future__ import annotations

import collections
import itertools
import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from chainermn_tpu.fleet.handoff import (HandoffError, decode_handoff,
                                         encode_handoff)
from chainermn_tpu.fleet.reports import FleetReport
from chainermn_tpu.fleet.transport import InProcessTransport

__all__ = ["Stream", "PrefillPool", "DecodePool", "DisaggregatedFleet"]


class Stream:
    """One client stream crossing the prefill→decode boundary. The
    terminal ``tokens`` list is the SAME sequence a single engine's
    ``generate()`` would emit for this prompt/seed (bitwise under the
    raw wire format)."""

    def __init__(self, stream_id: int, prompt, max_new_tokens: int,
                 kw: dict):
        self.stream_id = stream_id
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.kw = dict(kw)            # eos_id / temperature / top_k / seed
        self.tokens: List[int] = []
        self.state = "queued"         # queued|prefill|decode|done
        self.fell_back = False        # handoff failed → re-prefilled

    @property
    def finished(self) -> bool:
        return self.state == "done"


class PrefillPool:
    """Prefill-side engine wrapper: prompts in, held slots out."""

    def __init__(self, engine):
        self.engine = engine
        self._by_id: Dict[int, Stream] = {}   # request_id → stream

    def submit(self, stream: Stream) -> None:
        req = self.engine.submit(stream.prompt, max_new_tokens=1,
                                 hold=True, **stream.kw)
        self._by_id[req.request_id] = stream
        stream.state = "prefill"

    def step(self) -> bool:
        """Advance iff there is prefill work (held slots alone are not
        work — they pin their cursors and wait for export)."""
        if self.engine.idle():
            return False
        self.engine.step()
        return True

    def ready(self) -> List[Tuple[Stream, object]]:
        """Held (stream, request) pairs awaiting export, oldest first."""
        reqs = sorted(self.engine.held.values(),
                      key=lambda r: r.request_id)
        return [(self._by_id[r.request_id], r) for r in reqs]

    def export(self, req) -> dict:
        """Export one held slot (a pure read of device state). The slot
        STAYS held until :meth:`release` — deferred so the conveyor can
        wait for the transport's terminal status and, on an aborted
        transfer, release with the abort accounted."""
        return self.engine.export_handoff(req)

    def release(self, req, aborted: bool = False) -> None:
        """Release a held slot whose handoff reached a terminal
        transport status (``adopted``/``duplicate`` → clean retire;
        ``failed`` → aborted retire, the receiver re-prefills)."""
        if aborted:
            self.engine.abort_held(req)
        else:
            self.engine.release_held(req)
        self._by_id.pop(req.request_id, None)


class DecodePool:
    """Decode-side engine wrapper: adopts handoffs, drains streams."""

    def __init__(self, engine):
        self.engine = engine
        self._inflight: List[Tuple[object, Stream]] = []

    def has_room(self) -> bool:
        return bool(self.engine.free_slots)

    def place(self, stream: Stream, handoff: dict) -> None:
        """Adopt a VERIFIED handoff: the imported slot resumes the
        exporting engine's exact stream."""
        req = self.engine.import_handoff(
            handoff, stream.prompt, max_new_tokens=stream.max_new_tokens)
        stream.state = "decode"
        self._inflight.append((req, stream))

    def fallback(self, stream: Stream) -> None:
        """Handoff failed verification or delivery → CLEAN re-prefill
        of the full prompt on this engine. Same seed, so the per-token
        key-split contract replays the identical stream; the suspect
        bytes never touch a slot."""
        req = self.engine.submit(stream.prompt,
                                 max_new_tokens=stream.max_new_tokens,
                                 **stream.kw)
        stream.state = "decode"
        stream.fell_back = True
        self._inflight.append((req, stream))

    def step(self) -> bool:
        worked = False
        if not self.engine.idle():
            self.engine.step()
            worked = True
        still = []
        for req, stream in self._inflight:
            if req.finished:
                stream.tokens = list(req.tokens)
                stream.state = "done"
            else:
                still.append((req, stream))
        self._inflight = still
        return worked


class DisaggregatedFleet:
    """The conveyor: submit → prefill → handoff transport → decode.

    ``wire_format`` picks the handoff codec (``"f32"`` raw/bitwise,
    ``"int8-block"`` quantized at ~0.254× the wire bytes); ``report``
    accumulates the fleet counters (handoffs, wire bytes by format,
    fallbacks) that ``bench.py``'s fleet gate reads; ``transport``
    defaults to an :class:`~chainermn_tpu.fleet.transport.
    InProcessTransport` (pass one with ``wire_delay_ms`` to model DCN
    latency, or wire the pools across processes via
    ``tools/fleet_lm.py --hosts``).

    With ``async_conveyor=True`` the encode+send leg runs on a worker
    thread behind a bounded queue — see the module docstring for the
    threading discipline and backpressure semantics. ``stats`` then
    separates ``stall_ms_total`` (step-thread time lost to the
    conveyor) from ``transfer_ms_total`` (worker wall-time on the
    wire); their ratio is :attr:`overlap_fraction`. The synchronous
    conveyor books every transfer millisecond as stall — by
    construction its overlap is 0.
    """

    _POLL_S = 0.05

    def __init__(self, prefill_engine, decode_engine, *,
                 wire_format: str = "f32",
                 report: Optional[FleetReport] = None,
                 transport=None,
                 async_conveyor: bool = False,
                 max_pending: int = 2,
                 backpressure: str = "block"):
        if backpressure not in ("block", "skip"):
            raise ValueError(
                f"backpressure must be 'block' or 'skip': {backpressure!r}")
        self.prefill = PrefillPool(prefill_engine)
        self.decode = DecodePool(decode_engine)
        self.wire_format = wire_format
        self.report = report or FleetReport()
        self.transport = transport or InProcessTransport()
        self.async_conveyor = bool(async_conveyor)
        self.backpressure = backpressure
        self._ids = itertools.count()
        self.streams: List[Stream] = []
        self._by_sid: Dict[int, Stream] = {}
        self._pending_place: list = []        # verified Arrivals, no room yet
        self.stats = {"transfers": 0, "skipped": 0,
                      "stall_ms_total": 0.0, "transfer_ms_total": 0.0}
        if self.async_conveyor:
            self._q: queue.Queue = queue.Queue(max(1, int(max_pending)))
            self._inflight: Dict[int, object] = {}   # sid → held req
            self._done: collections.deque = collections.deque()
            self._error: Optional[BaseException] = None
            self._stop = threading.Event()
            self._worker = threading.Thread(
                target=self._run_worker, name="fleet-conveyor", daemon=True)
            self._worker.start()

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               **kw) -> Stream:
        mnt = (max_new_tokens if max_new_tokens is not None
               else self.prefill.engine.config.max_new_tokens)
        stream = Stream(next(self._ids), prompt, mnt, kw)
        self.streams.append(stream)
        self._by_sid[stream.stream_id] = stream
        self.prefill.submit(stream)
        return stream

    # -- arrivals (both modes; step thread only) -------------------------

    def _pump_arrivals(self) -> None:
        self._pending_place.extend(self.transport.poll())

    def _place(self) -> bool:
        """Adopt or fall back every buffered arrival the decode pool
        has room for (fallback re-submits through the engine queue, so
        it never needs a free slot up front)."""
        placed = False
        still = []
        for arr in self._pending_place:
            stream = self._by_sid.get(arr.stream_id)
            if stream is None:
                continue          # fenced/unknown stream: nothing to do
            if arr.failed:
                self.report.record_fallback()
                self.decode.fallback(stream)
                placed = True
                continue
            if not self.decode.has_room():
                still.append(arr)
                continue
            try:
                self.decode.place(stream,
                                  decode_handoff(arr.manifest, arr.blob))
            except HandoffError:
                # wire-verified but structurally unusable (format skew):
                # same clean-re-prefill answer as a failed delivery
                self.report.record_fallback()
                self.decode.fallback(stream)
            placed = True
        self._pending_place = still
        return placed

    # -- synchronous conveyor --------------------------------------------

    def _transfer(self) -> bool:
        """Move every exportable held slot the decode pool has room
        for: export → encode → transport (seq/SHA frames, bounded
        re-send) → place, with delivery failure answered by a clean
        re-prefill. The step thread pays the wire inline — all of it
        booked as stall so the async path has an honest baseline."""
        moved = False
        for stream, req in self.prefill.ready():
            if not self.decode.has_room():
                break
            handoff = self.prefill.export(req)
            manifest, blob = encode_handoff(handoff, self.wire_format)
            self.report.record_handoff(self.wire_format, len(blob))
            t0 = time.monotonic()
            status = self.transport.send(stream.stream_id, manifest, blob)
            spent_ms = (time.monotonic() - t0) * 1000.0
            self.stats["transfer_ms_total"] += spent_ms
            self.stats["stall_ms_total"] += spent_ms
            self.stats["transfers"] += 1
            self.prefill.release(req, aborted=(status == "failed"))
            # place immediately so has_room stays accurate for the next
            # held slot in this same pass
            self._pump_arrivals()
            self._place()
            moved = True
        return moved

    # -- asynchronous conveyor -------------------------------------------

    def _run_worker(self) -> None:
        """Worker leg: serialize + ship. No engine calls here — the
        handoff dict was exported on the step thread; errors are
        captured and re-raised from the next ``step()``."""
        while not self._stop.is_set():
            try:
                sid, handoff = self._q.get(timeout=self._POLL_S)
            except queue.Empty:
                continue
            try:
                manifest, blob = encode_handoff(handoff, self.wire_format)
                self.report.record_handoff(self.wire_format, len(blob))
                t0 = time.monotonic()
                status = self.transport.send(sid, manifest, blob)
                self.stats["transfer_ms_total"] += (
                    (time.monotonic() - t0) * 1000.0)
                self._done.append((sid, status))
            except BaseException as e:  # noqa: BLE001 — surfaced in step()
                if self._error is None:
                    self._error = e
                self._done.append((sid, "failed"))
            finally:
                self.stats["transfers"] += 1
                self._q.task_done()

    def _raise_pending(self) -> None:
        if self._error is not None:
            e, self._error = self._error, None
            raise RuntimeError("async conveyor transfer failed") from e

    def _offer(self) -> bool:
        """Export ready held slots on the step thread and hand them to
        the worker. ``skip`` backpressure leaves the slot held on a
        full queue (it re-offers next step); ``block`` waits — that
        wait is the only stall the async conveyor books."""
        offered = False
        for stream, req in self.prefill.ready():
            sid = stream.stream_id
            if sid in self._inflight:
                continue           # already on the wire; release pending
            if self.backpressure == "skip" and self._q.full():
                self.stats["skipped"] += 1
                break
            handoff = self.prefill.export(req)
            if self.backpressure == "skip":
                try:
                    self._q.put_nowait((sid, handoff))
                except queue.Full:  # raced the check above: same answer
                    self.stats["skipped"] += 1
                    break
            else:
                t0 = time.monotonic()
                while True:
                    self._raise_pending()   # a dead worker never drains
                    try:
                        self._q.put((sid, handoff), timeout=self._POLL_S)
                        break
                    except queue.Full:
                        continue
                self.stats["stall_ms_total"] += (
                    (time.monotonic() - t0) * 1000.0)
            self._inflight[sid] = req
            offered = True
        return offered

    def _reap(self) -> bool:
        """Release held slots whose transfers reached a terminal
        status (step thread — the engine's held map is not safe to
        mutate from the worker)."""
        reaped = False
        while self._done:
            sid, status = self._done.popleft()
            req = self._inflight.pop(sid, None)
            if req is not None:
                self.prefill.release(req, aborted=(status == "failed"))
            reaped = True
        return reaped

    def drain(self, deadline_s: Optional[float] = None) -> bool:
        """Wait for every queued and in-flight transfer to reach a
        terminal transport status, then reap and place. ``deadline_s``
        is seconds from now; a missed deadline returns ``False`` (never
        raises for lateness — mirror of ``AsyncSnapshotPlane.drain``).
        Synchronous conveyors have nothing in flight: always ``True``."""
        if not self.async_conveyor:
            return True
        deadline = (None if deadline_s is None
                    else time.monotonic() + float(deadline_s))
        with self._q.all_tasks_done:
            while self._q.unfinished_tasks:
                if deadline is None:
                    self._q.all_tasks_done.wait()
                    continue
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._q.all_tasks_done.wait(timeout=left)
        self._reap()
        self._pump_arrivals()
        self._place()
        return True

    def close(self) -> None:
        """Drain outstanding transfers and stop the worker. Idempotent;
        a closed fleet can still ``step()`` its engines (the conveyor
        leg is simply empty)."""
        if not self.async_conveyor or self._stop.is_set():
            return
        self._q.join()
        self._stop.set()
        self._worker.join()
        self._reap()

    # -- the conveyor loop ------------------------------------------------

    def step(self) -> bool:
        """One conveyor iteration; returns whether anything advanced."""
        if not self.async_conveyor:
            worked = self.prefill.step()
            worked = self._transfer() or worked
            self._pump_arrivals()
            worked = self._place() or worked
            worked = self.decode.step() or worked
            return worked
        self._raise_pending()
        worked = self.prefill.step()
        worked = self._reap() or worked
        worked = self._offer() or worked
        self._pump_arrivals()
        worked = self._place() or worked
        worked = self.decode.step() or worked
        return worked

    def idle(self) -> bool:
        if (not self.prefill.engine.idle()
                or self.prefill.engine.held
                or not self.decode.engine.idle()
                or self._pending_place):
            return False
        if self.async_conveyor and (self._inflight or self._done
                                    or self._q.unfinished_tasks):
            return False
        return True

    def run_until_drained(self, max_steps: int = 100_000) -> int:
        n = 0
        while not self.idle():
            if n >= max_steps:
                raise RuntimeError(
                    f"fleet failed to drain within {max_steps} steps")
            # each engine step syncs internally (int32 token pulls)
            worked = self.step()  # dlint: disable=DL104
            if not worked and self.async_conveyor:
                time.sleep(0.001)   # transfer in flight: yield to worker
            n += 1
        return n

    @property
    def overlap_fraction(self) -> float:
        """Fraction of wire wall-time hidden behind decode steps:
        ``1 − stall/transfer`` clamped to [0, 1]. The synchronous
        conveyor books stall == transfer, so it reads 0."""
        xfer = self.stats["transfer_ms_total"]
        if xfer <= 0:
            return 0.0
        return max(0.0, min(1.0,
                            1.0 - self.stats["stall_ms_total"] / xfer))

    def reports(self):
        return [self.prefill.engine.report, self.decode.engine.report]

    def summary(self) -> dict:
        return self.report.summary(self.reports())

"""Replica router: one front door over N serving engines.

Each :class:`EngineReplica` owns a single-threaded ``serving.Engine``
behind its own worker thread and inbox (the same ownership discipline
as ``serving.Frontend``, multiplied). The :class:`Router` fronts them
with:

* **load-aware + session-affine placement** — least queue depth
  (inbox + engine queue + occupied slots) among live replicas, except
  that a ``session=`` tag STICKS to the replica already serving it
  (in-flight conversational streams keep their locality; the sticky
  mapping survives only while its replica does);
* **queue-depth backpressure** — with ``max_queue_depth`` set, a
  submission finding EVERY live replica at its bound raises
  ``serving.AdmissionRejected`` with the ``RpcPolicy`` backoff base as
  its retry-after hint, exactly like a single ``Frontend``;
* **health-driven re-queue** — replica workers heartbeat
  :class:`~chainermn_tpu.fleet.health.FleetHealth` every iteration; a
  silent or dead-threaded replica (chaos ``kill_replica``, a raise, a
  real SIGKILL in the supervised drill) is declared dead and its
  unfinished work re-queues onto survivors WITHOUT dropping client
  futures. A re-queued request re-runs from its seed, and the
  one-key-split-per-token contract (serving/sampling.py) makes the
  replayed stream identical — zero dropped, zero duplicated tokens,
  which the chaos drill asserts literally.

The router's dispatch loop and ``result()`` keep every wait BOUNDED
(``get_nowait`` + idle sleep, probe-sliced future waits) — dlint DL111
polices exactly this loop shape, because one ``inbox.get()`` with no
timeout here turns a replica death into a frozen fleet.
"""

from __future__ import annotations

import itertools
import queue as _queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Dict, List, Optional

from chainermn_tpu.fleet.health import FleetHealth
from chainermn_tpu.fleet.reports import FleetReport
from chainermn_tpu.resilience import chaos
from chainermn_tpu.resilience.policy import RpcPolicy, policy
from chainermn_tpu.resilience.watchdog import current_watchdog
from chainermn_tpu.serving.frontend import (AdmissionRejected,
                                            DeadlineExceeded)

__all__ = ["EngineReplica", "Router"]

_IDLE_WAIT_S = 0.002


class _FleetItem:
    """One routed request: prompt + kwargs + the client future the
    router owns end-to-end (a replica death re-queues the item; the
    future only ever resolves once, on whichever replica finishes)."""

    __slots__ = ("item_id", "prompt", "kw", "future", "session")

    def __init__(self, item_id: int, prompt, kw: dict,
                 session: Optional[str]):
        self.item_id = item_id
        self.prompt = prompt
        self.kw = kw
        self.future: Future = Future()
        self.session = session


class EngineReplica:
    """One engine + worker thread + inbox. The worker: admit from the
    inbox, step the engine when it has work, resolve finished futures,
    heartbeat. The chaos ``kill_replica`` fault is checked per WORKING
    iteration (idle polls don't advance the counter, so
    ``kill_replica@step=N,replica=R`` is deterministic at any poll
    rate) and kills the thread mid-state — inflight slots and queued
    items stay exactly where they were, which is the point."""

    def __init__(self, replica_id: int, engine,
                 health: Optional[FleetHealth] = None):
        self.replica_id = int(replica_id)
        self.engine = engine
        self.inbox: _queue.Queue = _queue.Queue()
        self.inflight: Dict[int, tuple] = {}      # item_id → (item, req)
        self.lock = threading.Lock()
        self._health = health
        self._stop = threading.Event()
        self._killed = False
        self._clean_exit = False
        self._work_iter = 0
        self.thread = threading.Thread(
            target=self._run, name=f"fleet-replica-{replica_id}",
            daemon=True)

    def start(self) -> None:
        self.thread.start()

    def depth(self) -> int:
        """Placement load: inbox + engine queue + occupied slots."""
        return (self.inbox.qsize() + len(self.engine.queue)
                + len(self.engine.active) + len(self.engine.prefilling))

    def kill(self) -> None:
        """Die DIRTY (test hook, same observable as the chaos fault):
        the worker exits without the clean flag, abandoning its state
        for the router's health sweep to re-queue."""
        self._killed = True

    def stop(self) -> None:
        self._stop.set()
        self.thread.join(timeout=30)

    def dead(self) -> bool:
        return not self.thread.is_alive() and not self._clean_exit

    def drain_unfinished(self) -> List[_FleetItem]:
        """After death: every item whose future is still open, in
        submission order (inflight first — they were admitted first —
        then the never-admitted inbox backlog).

        The join makes the common deaths (chaos kill, ``kill()``, a
        raise) fully race-free — the worker is gone before we touch its
        state. A wedged-but-alive worker (heartbeat death) is blocked
        INSIDE a dispatch, past admission, so it holds no item in hand;
        snapshotting under the replica lock (bounded acquire — a wedged
        dispatch may hold it forever) and clearing ``inflight`` fences
        it off these futures, and the ``done()`` guard on resolution
        makes any residual overlap harmless."""
        self.thread.join(timeout=5.0)
        got = self.lock.acquire(timeout=1.0)
        try:
            items = [item for _iid, (item, _req)
                     in sorted(self.inflight.items())]
            if got:
                self.inflight.clear()
            try:
                while True:
                    items.append(self.inbox.get_nowait())
            except _queue.Empty:
                pass
        finally:
            if got:
                self.lock.release()
        return [it for it in items if not it.future.done()]

    def _run(self) -> None:
        while not self._stop.is_set():
            if self._killed:
                return                       # dirty exit: state abandoned
            if self._health is not None:
                self._health.beat(self.replica_id)
            worked = False
            try:
                while True:
                    item = self.inbox.get_nowait()
                    with self.lock:
                        try:
                            req = self.engine.submit(item.prompt,
                                                     **item.kw)
                            self.inflight[item.item_id] = (item, req)
                        except Exception as e:   # bad request, not fatal
                            item.future.set_exception(e)
                    worked = True
            except _queue.Empty:
                pass
            with self.lock:
                if not self.engine.idle():
                    if chaos.on_replica_step(self.replica_id,
                                             self._work_iter):
                        self._killed = True
                        return               # chaos kill: dirty exit
                    self._work_iter += 1
                    # one [n_slots, k] int32 pull per dispatch
                    self.engine.step()  # dlint: disable=DL104
                    worked = True
                    for iid, (item, req) in list(self.inflight.items()):
                        if req.finished:
                            del self.inflight[iid]
                            if not item.future.done():
                                item.future.set_result(req)
            if not worked:
                time.sleep(_IDLE_WAIT_S)
        self._clean_exit = True


class Router:
    """The fleet front door. Construct with engines, submit from any
    thread, ``result()`` with deadline-bounded waits, ``close()`` when
    done (context manager supported)."""

    def __init__(self, engines, *, rpc_policy: Optional[RpcPolicy] = None,
                 watchdog=None, max_queue_depth: Optional[int] = None,
                 health_timeout_ms: Optional[int] = None,
                 report: Optional[FleetReport] = None):
        if not engines:
            raise ValueError("Router needs at least one engine")
        self._policy = rpc_policy
        self._watchdog = watchdog
        self.max_queue_depth = max_queue_depth
        self.report = report or FleetReport()
        self.health = FleetHealth(range(len(engines)),
                                  timeout_ms=health_timeout_ms)
        self.replicas: Dict[int, EngineReplica] = {
            i: EngineReplica(i, eng, self.health)
            for i, eng in enumerate(engines)}
        self._pending: deque = deque()
        self._lock = threading.Lock()
        self._sessions: Dict[str, int] = {}       # session → replica_id
        self._handled_dead: set = set()
        self._ids = itertools.count()
        self._stop = threading.Event()
        for rep in self.replicas.values():
            rep.start()
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-router", daemon=True)
        self._thread.start()

    # ----------------------------------------------------------------
    # client face (any thread)
    # ----------------------------------------------------------------

    def _alive(self) -> List[EngineReplica]:
        return [self.replicas[r] for r in self.health.alive()
                if not self.replicas[r].dead()]

    def submit(self, prompt, *, session: Optional[str] = None,
               **kw) -> Future:
        """Route one request; kwargs pass through to ``Engine.submit``.
        ``session`` opts into sticky placement. Raises
        :class:`~chainermn_tpu.serving.frontend.AdmissionRejected` when
        every live replica sits at ``max_queue_depth`` — shed at the
        door with a retry-after hint, not a timeout ten layers in."""
        if self._stop.is_set():
            raise RuntimeError("router is closed")
        if self.max_queue_depth is not None:
            alive = self._alive()
            with self._lock:
                backlog = len(self._pending)
            # the not-yet-placed router backlog counts against the
            # fleet's headroom too — otherwise a burst outruns the
            # dispatch loop and sails past the bound unrejected
            total = sum(r.depth() for r in alive) + backlog
            if alive and total >= self.max_queue_depth * len(alive):
                pol = self._policy or policy()
                self.report.record_rejected()
                raise AdmissionRejected(
                    f"fleet backlog {total} at the bound "
                    f"({self.max_queue_depth} × {len(alive)} live "
                    f"replicas); retry after {pol.backoff_base_ms} ms",
                    retry_after_ms=pol.backoff_base_ms)
        item = _FleetItem(next(self._ids), prompt, kw, session)
        with self._lock:
            self._pending.append(item)
        return item.future

    def result(self, future: Future, timeout_ms: Optional[int] = None):
        """Deadline-bounded wait sliced at ``probe_ms`` (the DL111-clean
        shape: every slice is a bounded wait, and a dead router thread
        surfaces on the next probe, not after the full budget)."""
        pol = self._policy or policy()
        budget_ms = timeout_ms if timeout_ms is not None else pol.timeout_ms
        deadline = time.monotonic() + budget_ms / 1e3
        while True:
            left = deadline - time.monotonic()
            if left <= 0:
                raise DeadlineExceeded(
                    f"no result within {budget_ms} ms "
                    f"(probe={pol.probe_ms} ms)")
            try:
                return future.result(timeout=min(pol.probe_ms / 1e3, left))
            except FutureTimeout:
                if not self._thread.is_alive() and not future.done():
                    raise RuntimeError(
                        "router thread died with the request in flight")

    def drain(self, timeout_ms: Optional[int] = None) -> None:
        """Block until no routed work remains anywhere in the fleet
        (pending, inboxes, engines, inflight) — replica deaths along
        the way re-queue through the health sweep and still drain."""
        pol = self._policy or policy()
        budget_ms = timeout_ms if timeout_ms is not None else pol.timeout_ms
        deadline = time.monotonic() + budget_ms / 1e3
        while time.monotonic() < deadline:
            with self._lock:
                quiet = not self._pending
            if quiet and all(
                    rep.dead() or (rep.inbox.qsize() == 0
                                   and not rep.inflight
                                   and rep.engine.idle())
                    for rep in self.replicas.values()):
                return
            time.sleep(_IDLE_WAIT_S)
        raise DeadlineExceeded(f"fleet not drained within {budget_ms} ms")

    def close(self) -> None:
        self._stop.set()
        for rep in self.replicas.values():
            rep.stop()
        self._thread.join(timeout=30)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def reports(self):
        return [rep.engine.report for rep in self.replicas.values()]

    def summary(self) -> dict:
        return self.report.summary(self.reports())

    # ----------------------------------------------------------------
    # dispatch loop (router thread)
    # ----------------------------------------------------------------

    def _place(self, item: _FleetItem) -> Optional[EngineReplica]:
        """Session-affine, else least-depth, among live replicas with
        headroom. Returns None when nothing can take the item yet."""
        alive = self._alive()
        if not alive:
            return None
        if item.session is not None:
            rid = self._sessions.get(item.session)
            if rid is not None and self.health.is_alive(rid) \
                    and not self.replicas[rid].dead():
                return self.replicas[rid]
        candidates = alive
        if self.max_queue_depth is not None:
            candidates = [r for r in alive
                          if r.depth() < self.max_queue_depth]
            if not candidates:
                return None
        return min(candidates, key=lambda r: (r.depth(), r.replica_id))

    def _handle_dead(self, rid: int) -> None:
        """Re-queue a dead replica's unfinished work at the FRONT of
        pending, futures intact — the replay-from-seed contract makes
        the survivor's stream identical to the one that died."""
        rep = self.replicas[rid]
        # FENCE first: a heartbeat-declared death may be a wedged-but-
        # running worker (e.g. a stalled dispatch) — shoot it in the
        # head so it cannot race the survivor for these futures
        rep.kill()
        items = rep.drain_unfinished()
        self.report.record_replica_dead()
        self.report.record_requeue(len(items))
        for session, mapped in list(self._sessions.items()):
            if mapped == rid:
                del self._sessions[session]
        with self._lock:
            for item in reversed(items):
                self._pending.appendleft(item)

    def _sweep_dead(self) -> bool:
        """Two death signals, one verdict: heartbeat silence past the
        probe deadline (FleetHealth) and worker-thread death observed
        directly (a chaos kill or a raise stops beats AND the thread —
        the thread check notices within one loop pass instead of one
        probe period)."""
        worked = False
        for rid, rep in self.replicas.items():
            if rep.dead() and self.health.is_alive(rid):
                self.health.mark_dead(rid, "worker thread died")
        newly = set(self.health.check()) | {
            rid for rid in self.health.dead
            if rid not in self._handled_dead}
        for rid in sorted(newly):
            self._handled_dead.add(rid)
            self._handle_dead(rid)
            worked = True
        return worked

    def _poll_watchdog(self) -> None:
        from chainermn_tpu.comm.object_plane import JobAbortedError

        wd = self._watchdog or current_watchdog()
        if wd is None:
            return
        try:
            wd.check()
        except JobAbortedError as e:
            # bounded abortion, fleet-wide: fail every open future now
            items = []
            with self._lock:
                items.extend(self._pending)
                self._pending.clear()
            for rep in self.replicas.values():
                with rep.lock:
                    rep.engine.abort_all()
                items.extend(it for it, _r in rep.inflight.values())
                rep.inflight.clear()
                items.extend(rep.drain_unfinished())
            for item in items:
                if not item.future.done():
                    item.future.set_exception(JobAbortedError(str(e)))

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._poll_watchdog()
            worked = self._sweep_dead()
            if not self._alive():
                # no survivor can ever take these — fail fast rather
                # than letting clients ride out the full deadline
                stranded = []
                with self._lock:
                    stranded.extend(self._pending)
                    self._pending.clear()
                for item in stranded:
                    if not item.future.done():
                        item.future.set_exception(RuntimeError(
                            "no live replicas left in the fleet"))
            while True:
                with self._lock:
                    if not self._pending:
                        break
                    item = self._pending[0]
                if item.future.done():       # resolved while re-queued
                    with self._lock:
                        if self._pending and self._pending[0] is item:
                            self._pending.popleft()
                    continue
                rep = self._place(item)
                if rep is None:
                    break                    # no headroom/survivor yet
                with self._lock:
                    if not self._pending or self._pending[0] is not item:
                        continue
                    self._pending.popleft()
                if item.session is not None:
                    self._sessions[item.session] = rep.replica_id
                rep.inbox.put(item)
                worked = True
            if not worked:
                time.sleep(_IDLE_WAIT_S)
        # teardown: replicas were stopped by close(); fail what's left
        leftovers = []
        with self._lock:
            leftovers.extend(self._pending)
            self._pending.clear()
        for rep in self.replicas.values():
            leftovers.extend(it for it, _r in rep.inflight.values())
            leftovers.extend(rep.drain_unfinished())
        for item in leftovers:
            if not item.future.done():
                item.future.set_exception(
                    RuntimeError("router closed mid-request"))

"""Replica router: one front door over N serving engines.

Each :class:`EngineReplica` owns a single-threaded ``serving.Engine``
behind its own worker thread and inbox (the same ownership discipline
as ``serving.Frontend``, multiplied). The :class:`Router` fronts them
with:

* **load-aware + session-affine placement** — least queue depth
  (inbox + engine queue + occupied slots) among live replicas, except
  that a ``session=`` tag STICKS to the replica already serving it
  (in-flight conversational streams keep their locality; the sticky
  mapping survives only while its replica does);
* **queue-depth backpressure** — with ``max_queue_depth`` set, a
  submission finding EVERY live replica at its bound raises
  ``serving.AdmissionRejected`` with the ``RpcPolicy`` backoff base as
  its retry-after hint, exactly like a single ``Frontend``;
* **health-driven re-queue** — replica workers heartbeat
  :class:`~chainermn_tpu.fleet.health.FleetHealth` every iteration; a
  silent or dead-threaded replica (chaos ``kill_replica``, a raise, a
  real SIGKILL in the supervised drill) is declared dead and its
  unfinished work re-queues onto survivors WITHOUT dropping client
  futures. A re-queued request re-runs from its seed, and the
  one-key-split-per-token contract (serving/sampling.py) makes the
  replayed stream identical — zero dropped, zero duplicated tokens,
  which the chaos drill asserts literally;
* **replica lifecycle + live migration** — ``UP → DRAINING →
  DRAINED/DEAD``. :meth:`Router.drain` takes one replica out of
  service WITHOUT replaying its work from scratch: placement stops,
  the never-admitted backlog re-queues untouched, and every actively
  decoding session is frozen at a token boundary
  (``Engine.export_session``), shipped over the handoff transport
  (SHA-verified frames, NACK/re-send under the RpcPolicy budget), and
  adopted by the least-depth survivor (``Engine.import_session``) —
  the continued stream is BITWISE the never-migrated one. Any failure
  along the way (transport budget exhausted, corrupt frame, no
  destination, a death mid-migration) falls back to the SAME
  replay-from-seed re-queue a death uses, so a failed migration is
  never worse than a death. The drained replica then decommissions
  cleanly; ``_sweep_dead`` skips it. :meth:`Router.readmit` closes the
  loop — a DRAINED replica (weights swapped by ``fleet/rollout.py``)
  re-registers with a fresh worker thread and rejoins placement, the
  READMIT leg of the rolling-update lifecycle CANARY → DRAIN → SWAP →
  READMIT.

The router's dispatch loop and ``result()`` keep every wait BOUNDED
(``get_nowait`` + idle sleep, probe-sliced future waits) — dlint DL111
polices exactly this loop shape, because one ``inbox.get()`` with no
timeout here turns a replica death into a frozen fleet.
"""

from __future__ import annotations

import itertools
import queue as _queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Dict, List, Optional

from chainermn_tpu.fleet.handoff import (HandoffError, decode_handoff,
                                         encode_handoff)
from chainermn_tpu.fleet.health import FleetHealth
from chainermn_tpu.fleet.reports import FleetReport
from chainermn_tpu.fleet.transport import InProcessTransport
from chainermn_tpu.resilience import chaos
from chainermn_tpu.resilience.policy import RpcPolicy, policy
from chainermn_tpu.resilience.watchdog import current_watchdog
from chainermn_tpu.serving.frontend import (AdmissionRejected,
                                            DeadlineExceeded)

__all__ = ["EngineReplica", "Router"]

_IDLE_WAIT_S = 0.002


class _FleetItem:
    """One routed request: prompt + kwargs + the client future the
    router owns end-to-end (a replica death re-queues the item; the
    future only ever resolves once, on whichever replica finishes)."""

    __slots__ = ("item_id", "prompt", "kw", "future", "session")

    def __init__(self, item_id: int, prompt, kw: dict,
                 session: Optional[str]):
        self.item_id = item_id
        self.prompt = prompt
        self.kw = kw
        self.future: Future = Future()
        self.session = session


class EngineReplica:
    """One engine + worker thread + inbox. The worker: admit from the
    inbox, step the engine when it has work, resolve finished futures,
    heartbeat. The chaos ``kill_replica`` fault is checked per WORKING
    iteration (idle polls don't advance the counter, so
    ``kill_replica@step=N,replica=R`` is deterministic at any poll
    rate) and kills the thread mid-state — inflight slots and queued
    items stay exactly where they were, which is the point."""

    def __init__(self, replica_id: int, engine,
                 health: Optional[FleetHealth] = None):
        self.replica_id = int(replica_id)
        self.engine = engine
        self.inbox: _queue.Queue = _queue.Queue()
        self.inflight: Dict[int, tuple] = {}      # item_id → (item, req)
        self.lock = threading.Lock()
        self.draining = False         # excluded from placement; sessions
        #                               migrating off (Router.drain)
        self.drained = False          # decommissioned cleanly
        self._health = health
        self._stop = threading.Event()
        self._killed = False
        self._clean_exit = False
        self._work_iter = 0
        self.thread = threading.Thread(
            target=self._run, name=f"fleet-replica-{replica_id}",
            daemon=True)

    def start(self) -> None:
        self.thread.start()

    def state(self) -> str:
        """Lifecycle state: ``UP → DRAINING → DRAINED`` (clean
        decommission via ``Router.drain``) or ``DEAD`` (dirty exit —
        the health sweep replays its sessions)."""
        if self.dead():
            return "DEAD"
        if self.drained:
            return "DRAINED"
        if self.draining:
            return "DRAINING"
        return "UP"

    def depth(self) -> int:
        """Placement load: inbox + engine queue + occupied slots."""
        return (self.inbox.qsize() + len(self.engine.queue)
                + len(self.engine.active) + len(self.engine.prefilling))

    def kill(self) -> None:
        """Die DIRTY (test hook, same observable as the chaos fault):
        the worker exits without the clean flag, abandoning its state
        for the router's health sweep to re-queue."""
        self._killed = True

    def stop(self) -> None:
        self._stop.set()
        self.thread.join(timeout=30)

    def dead(self) -> bool:
        return not self.thread.is_alive() and not self._clean_exit

    def drain_unfinished(self) -> List[_FleetItem]:
        """After death: every item whose future is still open, in
        submission order (inflight first — they were admitted first —
        then the never-admitted inbox backlog).

        The join makes the common deaths (chaos kill, ``kill()``, a
        raise) fully race-free — the worker is gone before we touch its
        state. A wedged-but-alive worker (heartbeat death) is blocked
        INSIDE a dispatch, past admission, so it holds no item in hand;
        snapshotting under the replica lock (bounded acquire — a wedged
        dispatch may hold it forever) and clearing ``inflight`` fences
        it off these futures, and the ``done()`` guard on resolution
        makes any residual overlap harmless."""
        self.thread.join(timeout=5.0)
        got = self.lock.acquire(timeout=1.0)
        try:
            items = [item for _iid, (item, _req)
                     in sorted(self.inflight.items())]
            if got:
                self.inflight.clear()
            try:
                while True:
                    items.append(self.inbox.get_nowait())
            except _queue.Empty:
                pass
        finally:
            if got:
                self.lock.release()
        return [it for it in items if not it.future.done()]

    def _run(self) -> None:
        while not self._stop.is_set():
            if self._killed:
                return                       # dirty exit: state abandoned
            if self._health is not None:
                self._health.beat(self.replica_id)
            worked = False
            try:
                while True:
                    item = self.inbox.get_nowait()
                    with self.lock:
                        try:
                            req = self.engine.submit(item.prompt,
                                                     **item.kw)
                            self.inflight[item.item_id] = (item, req)
                        except Exception as e:   # bad request, not fatal
                            item.future.set_exception(e)
                    worked = True
            except _queue.Empty:
                pass
            with self.lock:
                if not self.engine.idle():
                    if chaos.on_replica_step(self.replica_id,
                                             self._work_iter):
                        self._killed = True
                        return               # chaos kill: dirty exit
                    self._work_iter += 1
                    # one [n_slots, k] int32 pull per dispatch
                    self.engine.step()  # dlint: disable=DL104
                    worked = True
                    for iid, (item, req) in list(self.inflight.items()):
                        if req.finished:
                            del self.inflight[iid]
                            if not item.future.done():
                                item.future.set_result(req)
            if not worked:
                time.sleep(_IDLE_WAIT_S)
        self._clean_exit = True


class Router:
    """The fleet front door. Construct with engines, submit from any
    thread, ``result()`` with deadline-bounded waits, ``close()`` when
    done (context manager supported)."""

    def __init__(self, engines, *, rpc_policy: Optional[RpcPolicy] = None,
                 watchdog=None, max_queue_depth: Optional[int] = None,
                 health_timeout_ms: Optional[int] = None,
                 report: Optional[FleetReport] = None,
                 migration_transport=None,
                 migration_wire_format: str = "f32"):
        if not engines:
            raise ValueError("Router needs at least one engine")
        self._policy = rpc_policy
        self._watchdog = watchdog
        self.max_queue_depth = max_queue_depth
        self.report = report or FleetReport()
        # session-migration wire (Router.drain): any transport with the
        # send/poll faces; the in-process one rides the same chaos
        # on_wire + NACK/re-send protocol as the cross-host plane
        self._mig_transport = (migration_transport
                               or InProcessTransport(pol=rpc_policy))
        self._mig_format = migration_wire_format
        self._mig_arrivals: Dict[int, object] = {}   # stream_id → Arrival
        self.health = FleetHealth(range(len(engines)),
                                  timeout_ms=health_timeout_ms)
        self.replicas: Dict[int, EngineReplica] = {
            i: EngineReplica(i, eng, self.health)
            for i, eng in enumerate(engines)}
        self._pending: deque = deque()
        self._lock = threading.Lock()
        self._sessions: Dict[str, int] = {}       # session → replica_id
        self._handled_dead: set = set()
        self._ids = itertools.count()
        self._stop = threading.Event()
        for rep in self.replicas.values():
            rep.start()
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-router", daemon=True)
        self._thread.start()

    # ----------------------------------------------------------------
    # client face (any thread)
    # ----------------------------------------------------------------

    def _alive(self) -> List[EngineReplica]:
        return [self.replicas[r] for r in self.health.alive()
                if not self.replicas[r].dead()]

    def _placeable(self) -> List[EngineReplica]:
        """Replicas new work may land on: alive and not on their way
        out of service (a DRAINING replica still finishes or migrates
        what it has, but takes nothing new)."""
        return [r for r in self._alive()
                if not r.draining and not r.drained]

    def submit(self, prompt, *, session: Optional[str] = None,
               **kw) -> Future:
        """Route one request; kwargs pass through to ``Engine.submit``.
        ``session`` opts into sticky placement. Raises
        :class:`~chainermn_tpu.serving.frontend.AdmissionRejected` when
        every placeable replica sits at ``max_queue_depth`` — shed at
        the door with a retry-after hint that SCALES with how far past
        the bound the fleet is (a client seeing 4× the base backoff
        knows the fleet is deeply backed up, not momentarily full)."""
        if self._stop.is_set():
            raise RuntimeError("router is closed")
        if self.max_queue_depth is not None:
            live = self._placeable()
            with self._lock:
                backlog = len(self._pending)
            # the not-yet-placed router backlog counts against the
            # fleet's headroom too — otherwise a burst outruns the
            # dispatch loop and sails past the bound unrejected
            total = sum(r.depth() for r in live) + backlog
            bound = self.max_queue_depth * len(live)
            if live and total >= bound:
                pol = self._policy or policy()
                self.report.record_rejected()
                retry = self._retry_after_ms(pol, total, bound,
                                             len(live))
                raise AdmissionRejected(
                    f"fleet backlog {total} at the bound "
                    f"({self.max_queue_depth} × {len(live)} placeable "
                    f"replicas); retry after {retry} ms",
                    retry_after_ms=retry)
        item = _FleetItem(next(self._ids), prompt, kw, session)
        with self._lock:
            self._pending.append(item)
        return item.future

    def _retry_after_ms(self, pol: RpcPolicy, total: int, bound: int,
                        n_live: int) -> int:
        """Aggregate-depth-scaled retry hint: exactly at the bound the
        base backoff (the single-Frontend behaviour), then linear in
        the excess backlog per configured replica-slot of headroom,
        capped at 16× so a pathological burst can't push clients into
        hour-long retries."""
        per = max(1, n_live * max(1, self.max_queue_depth or 1))
        scale = min(16.0, 1.0 + max(0, total - bound) / per)
        return int(pol.backoff_base_ms * scale)

    def result(self, future: Future, timeout_ms: Optional[int] = None):
        """Deadline-bounded wait sliced at ``probe_ms`` (the DL111-clean
        shape: every slice is a bounded wait, and a dead router thread
        surfaces on the next probe, not after the full budget)."""
        pol = self._policy or policy()
        budget_ms = timeout_ms if timeout_ms is not None else pol.timeout_ms
        deadline = time.monotonic() + budget_ms / 1e3
        while True:
            left = deadline - time.monotonic()
            if left <= 0:
                raise DeadlineExceeded(
                    f"no result within {budget_ms} ms "
                    f"(probe={pol.probe_ms} ms)")
            try:
                return future.result(timeout=min(pol.probe_ms / 1e3, left))
            except FutureTimeout:
                if not self._thread.is_alive() and not future.done():
                    raise RuntimeError(
                        "router thread died with the request in flight")

    def quiesce(self, timeout_ms: Optional[int] = None) -> None:
        """Block until no routed work remains anywhere in the fleet
        (pending, inboxes, engines, inflight) — replica deaths along
        the way re-queue through the health sweep and still drain."""
        pol = self._policy or policy()
        budget_ms = timeout_ms if timeout_ms is not None else pol.timeout_ms
        deadline = time.monotonic() + budget_ms / 1e3
        while time.monotonic() < deadline:
            with self._lock:
                quiet = not self._pending
            if quiet and all(
                    rep.dead() or (rep.inbox.qsize() == 0
                                   and not rep.inflight
                                   and rep.engine.idle())
                    for rep in self.replicas.values()):
                return
            time.sleep(_IDLE_WAIT_S)
        raise DeadlineExceeded(f"fleet not quiet within {budget_ms} ms")

    def shed_pending(self) -> int:
        """Cancel every request that has not STARTED decoding — the
        graceful-retirement shed (``tools/fleet_lm.py`` calls this on
        SIGUSR1, finishes what is in flight, and exits 0; the shed ids
        are simply absent from the output JSONL, so the next
        incarnation's idempotent replay re-submits exactly them).
        Sheds three never-started tiers: the router backlog, replica
        inbox backlogs, and requests still sitting in an engine queue.
        Actively decoding streams are untouched. Returns the number
        shed.

        Race-free by construction: a router-backlog item is either
        still in ``_pending`` (we pop + cancel it; the dispatch loop's
        ``future.done()`` re-check skips it) or already popped (the
        loop owns it; we never see it). Engine-queued requests are
        removed under ``rep.lock`` — the same lock the worker steps
        under — so a request observed ``queued`` cannot be admitted
        out from under the removal."""
        with self._lock:
            items = list(self._pending)
            self._pending.clear()
        n = sum(1 for item in items if item.future.cancel())
        for rep in self.replicas.values():
            if rep.dead():
                continue               # the death sweep owns its items
            n += sum(1 for item in self._pull_inbox(rep)
                     if item.future.cancel())
            with rep.lock:
                for iid, (item, req) in list(rep.inflight.items()):
                    if req.state != "queued":
                        continue       # decoding/prefilling: finishes
                    try:
                        rep.engine.queue.remove(req)
                    except ValueError:
                        continue
                    rep.inflight.pop(iid)
                    req.state = "aborted"
                    rep.engine.report.record_retire(req.request_id,
                                                    aborted=True)
                    if item.future.cancel():
                        n += 1
        return n

    # ----------------------------------------------------------------
    # replica lifecycle: UP → DRAINING → DRAINED/DEAD
    # ----------------------------------------------------------------

    def drain(self, replica_id: int,
              deadline_ms: Optional[int] = None) -> dict:
        """Take one replica out of service WITHOUT losing a token:
        placement stops immediately, the never-admitted backlog
        re-queues untouched, every actively decoding session migrates
        to the least-depth survivor over the handoff transport
        (``export_session`` → encode → SHA-verified frames under the
        NACK/re-send budget → ``import_session``), and the replica then
        decommissions cleanly (state ``DRAINED``; the health sweep
        skips it). Work that cannot migrate yet (engine-queued,
        mid-prefill) gets time to ripen until ``deadline_ms``
        (``RpcPolicy.timeout_ms`` by default); at the deadline the
        remainder is evacuated onto the replay-from-seed path — the
        exact machinery a replica DEATH uses, so the failure mode of a
        drain is never worse than the failure it prevents. Runs on the
        caller's thread; returns ``{"migrated", "requeued", "state"}``.
        """
        rep = self.replicas.get(int(replica_id))
        if rep is None:
            raise ValueError(f"unknown replica {replica_id}")
        if rep.drained or rep.draining:
            return {"migrated": 0, "requeued": 0, "state": rep.state()}
        if rep.dead() or not self.health.is_alive(rep.replica_id):
            raise ValueError(
                f"replica {replica_id} is dead — the health sweep "
                "already owns its sessions")
        if not [r for r in self._placeable() if r is not rep]:
            raise ValueError(
                "cannot drain the last placeable replica — its "
                "sessions would have nowhere to go")
        rep.draining = True
        # sticky sessions re-place on survivors from here on
        for session, mapped in list(self._sessions.items()):
            if mapped == rep.replica_id:
                del self._sessions[session]
        pol = self._policy or policy()
        budget_ms = (deadline_ms if deadline_ms is not None
                     else pol.timeout_ms)
        deadline = time.monotonic() + budget_ms / 1e3
        migrated = 0
        requeued = self._requeue_items(self._pull_inbox(rep))
        while not rep.dead():
            with rep.lock:
                pairs = [(iid, item, req) for iid, (item, req)
                         in sorted(rep.inflight.items())]
            if not pairs:
                with rep.lock:
                    busy = bool(rep.inflight) or not rep.engine.idle()
                if not busy and rep.inbox.qsize() == 0:
                    break
            if time.monotonic() >= deadline:
                requeued += self._requeue_items(self._evacuate(rep))
                break
            progress = False
            for iid, item, req in pairs:
                outcome = self._migrate_one(rep, iid, item, req)
                if outcome == "migrated":
                    migrated += 1
                    progress = True
                elif outcome == "requeued":
                    requeued += 1
                    progress = True
            # a burst may have raced into the inbox before the worker
            # observed the draining flag — pull it back out
            requeued += self._requeue_items(self._pull_inbox(rep))
            if not progress:
                time.sleep(_IDLE_WAIT_S)
        if not rep.dead():
            # decommission: a CLEAN exit — pre-register with the sweep
            # so the stopped heartbeat is not mistaken for a death
            self._handled_dead.add(rep.replica_id)
            rep.stop()
            rep.drained = True
            rep.draining = False
            self.health.mark_dead(rep.replica_id,
                                  "drained and decommissioned")
            self.report.record_drained()
        return {"migrated": migrated, "requeued": requeued,
                "state": rep.state()}

    def readmit(self, replica_id: int) -> None:
        """Bring a cleanly DRAINED replica back into service — the
        READMIT leg of a rolling weight update (``fleet/rollout.py``
        drains, swaps the verified snapshot in, then readmits). The
        engine keeps its identity (and its freshly swapped weights); a
        NEW worker thread wraps it, the health verdict is withdrawn,
        and placement sees the replica again on the next dispatch pass.
        A DEAD replica does not readmit — the supervisor restart path
        owns dirty exits."""
        rep = self.replicas.get(int(replica_id))
        if rep is None:
            raise ValueError(f"unknown replica {replica_id}")
        if not rep.drained:
            raise ValueError(
                f"replica {replica_id} is {rep.state()} — only a "
                "cleanly DRAINED replica readmits (a DEAD one restarts "
                "under the supervisor instead)")
        new = EngineReplica(rep.replica_id, rep.engine, self.health)
        # start BEFORE publishing: an unstarted worker reads as dead()
        # to the sweep, and the _handled_dead fence comes off LAST so
        # no intermediate state can be mistaken for a fresh death
        new.start()
        self.replicas[rep.replica_id] = new
        self.health.revive(rep.replica_id)
        self._handled_dead.discard(rep.replica_id)

    def _pull_inbox(self, rep: EngineReplica) -> List[_FleetItem]:
        """Drain a replica's never-admitted inbox backlog (these items
        have no engine state — re-queueing them is trivially lossless)."""
        items: List[_FleetItem] = []
        try:
            while True:
                items.append(rep.inbox.get_nowait())
        except _queue.Empty:
            pass
        return [it for it in items if not it.future.done()]

    def _requeue_items(self, items: List[_FleetItem]) -> int:
        """Back to the FRONT of pending, futures intact — the shared
        tail of both the death path and every failed migration."""
        if not items:
            return 0
        self.report.record_requeue(len(items))
        with self._lock:
            for item in reversed(items):
                self._pending.appendleft(item)
        return len(items)

    def _evacuate(self, rep: EngineReplica) -> List[_FleetItem]:
        """Deadline-forced fallback: pop every in-flight item (fencing
        the worker off their futures), abort the engine-side requests,
        and hand the items back for a replay from seed."""
        with rep.lock:
            items = [item for _iid, (item, _req)
                     in sorted(rep.inflight.items())]
            rep.inflight.clear()
            rep.engine.abort_all()
        items.extend(self._pull_inbox(rep))
        return [it for it in items if not it.future.done()]

    def _pick_dest(self, src: EngineReplica) -> Optional[EngineReplica]:
        """Least-depth survivor with a free slot to adopt into (the
        peek is racy — the authoritative check is ``import_session``
        under the destination lock; a miss keeps the session frozen
        and retries the adoption)."""
        cands = [r for r in self._placeable()
                 if r is not src and r.engine.free_slots]
        if not cands:
            return None
        return min(cands, key=lambda r: (r.depth(), r.replica_id))

    def _take_arrival(self, stream_id: int):
        arr = self._mig_arrivals.pop(stream_id, None)
        if arr is not None:
            return arr
        for a in self._mig_transport.poll():
            if a.stream_id == stream_id:
                arr = a
            else:
                self._mig_arrivals[a.stream_id] = a
        return arr

    def _migrate_one(self, rep: EngineReplica, iid: int,
                     item: _FleetItem, req) -> str:
        """Move one in-flight session off a draining replica. Returns
        ``migrated`` (adopted bitwise by a survivor), ``requeued``
        (fallback to replay from seed), ``pending`` (not migratable
        yet — engine-queued, mid-prefill, or every survivor's slots
        are full; let it ripen), or ``done`` (resolved while we
        looked)."""
        if self._pick_dest(rep) is None:
            # a saturated survivor is TRANSIENT — shipping bytes now
            # would leave them with nowhere to adopt and burn the
            # replay fallback on a non-failure; retry once a slot
            # frees (the deadline bounds the wait)
            return "pending"
        arrival = self._mig_arrivals.pop(item.item_id, None)
        if arrival is None:
            with rep.lock:
                if rep.inflight.get(iid, (None, None))[0] is not item:
                    return "done"
                if item.future.done() or req.finished:
                    rep.inflight.pop(iid, None)
                    return "done"
                try:
                    session = rep.engine.export_session(req)
                except ValueError:
                    return "pending"
                # fenced: the source worker can no longer resolve this
                # future, and the death sweep can no longer re-queue it
                rep.inflight.pop(iid, None)
            manifest, blob = encode_handoff(session, self._mig_format)
            status = self._mig_transport.send(item.item_id, manifest, blob)
            arrival = self._take_arrival(item.item_id)
            if (status not in ("adopted", "duplicate") or arrival is None
                    or arrival.failed):
                arrival = None
        else:
            # a prior attempt already shipped and the wire verified the
            # frame; the session stayed FROZEN on the source, so those
            # bytes are still current — retry adoption only
            with rep.lock:
                if rep.inflight.get(iid, (None, None))[0] is not item:
                    return "done"
                rep.inflight.pop(iid, None)
                if item.future.done() or req.finished:
                    try:
                        rep.engine.release_held(req)
                    except ValueError:
                        pass
                    return "done"
        dest = None
        newreq = None
        handoff = None
        if arrival is not None:
            try:
                handoff = decode_handoff(arrival.manifest, arrival.blob)
            except HandoffError:
                handoff = None
            if handoff is not None:
                dest = self._pick_dest(rep)
                if dest is not None:
                    with dest.lock:
                        try:
                            newreq = dest.engine.import_session(
                                handoff, item.prompt)
                            dest.inflight[iid] = (item, newreq)
                        except RuntimeError:
                            newreq = None  # slot raced away — transient
                        except Exception:
                            newreq = None
                            handoff = None  # structural — real failure
        if newreq is None:
            if handoff is not None:
                # the wire delivered a verified frame but the survivor
                # slot raced away at adoption time (the _pick_dest peek
                # is advisory) — abandon the ATTEMPT, not the stream:
                # keep the session frozen on the source (resuming would
                # decode tokens the adopter re-emits from the snapshot,
                # double-counting them), cache the arrival for the
                # retry, and let it ripen until a slot frees
                self._mig_arrivals[item.item_id] = arrival
                with rep.lock:
                    rep.inflight[iid] = (item, req)
                return "pending"
            # transport budget exhausted / torn frame: free the source
            # slot and replay from seed — the failure mode IS the death
            # path, never worse
            with rep.lock:
                try:
                    rep.engine.abort_held(req)
                except ValueError:
                    pass
            self.report.record_migration_fallback()
            self._requeue_items(
                [item] if not item.future.done() else [])
            return "requeued"
        self.report.record_migration(self._mig_format, len(arrival.blob))
        if item.session is not None:
            self._sessions[item.session] = dest.replica_id
        # adopt-before-ack chaos window: the destination owns the
        # stream now; killing it here must land in replay-from-seed
        if chaos.on_migration(item.item_id):
            dest.kill()
        with rep.lock:
            try:
                rep.engine.release_held(req)
            except ValueError:
                pass
        return "migrated"

    def close(self) -> None:
        self._stop.set()
        for rep in self.replicas.values():
            rep.stop()
        self._thread.join(timeout=30)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def reports(self):
        return [rep.engine.report for rep in self.replicas.values()]

    def summary(self) -> dict:
        """Fleet summary plus live lifecycle visibility: per-replica
        states and the ``draining`` set, so a caller watching capacity
        can see reduced headroom BEFORE rejections start."""
        out = self.report.summary(self.reports())
        states = {rid: rep.state()
                  for rid, rep in sorted(self.replicas.items())}
        out["fleet"]["replica_states"] = states
        out["fleet"]["draining"] = sorted(
            rid for rid, s in states.items() if s == "DRAINING")
        out["fleet"]["weights_versions"] = {
            rid: getattr(rep.engine, "weights_version", None)
            for rid, rep in sorted(self.replicas.items())}
        # live wire-health counters off the migration transport (the
        # FleetReport block carries the fold of FINISHED transports;
        # this one is the router's own, still-running wire)
        mig = self._mig_transport
        sender = getattr(mig, "stats", {})
        recv = mig.receiver_stats
        plane_stats = getattr(getattr(mig, "plane", None), "stats", {})
        live = out["fleet"]["transport"]
        live["retransmits"] += max(0, int(sender.get("attempts", 0))
                                   - int(sender.get("sent", 0)))
        live["reconnects"] += int((plane_stats or {}).get(
            "reconnects", 0))
        live["dup_fenced"] += int(recv.get("duplicates", 0))
        live["chunk_nacks"] += int(recv.get("chunk_nacked", 0))
        return out

    # ----------------------------------------------------------------
    # dispatch loop (router thread)
    # ----------------------------------------------------------------

    def _place(self, item: _FleetItem) -> Optional[EngineReplica]:
        """Session-affine, else least-depth, among placeable replicas
        with headroom. Returns None when nothing can take the item yet.
        A DRAINING replica is never a target — not even for its own
        sticky sessions (drain already unstuck them; a straggler
        mapping re-places like any other item)."""
        live = self._placeable()
        if not live:
            return None
        if item.session is not None:
            rid = self._sessions.get(item.session)
            if rid is not None and self.health.is_alive(rid) \
                    and not self.replicas[rid].dead() \
                    and not self.replicas[rid].draining \
                    and not self.replicas[rid].drained:
                return self.replicas[rid]
        candidates = live
        if self.max_queue_depth is not None:
            candidates = [r for r in live
                          if r.depth() < self.max_queue_depth]
            if not candidates:
                return None
        return min(candidates, key=lambda r: (r.depth(), r.replica_id))

    def _handle_dead(self, rid: int) -> None:
        """Re-queue a dead replica's unfinished work at the FRONT of
        pending, futures intact — the replay-from-seed contract makes
        the survivor's stream identical to the one that died."""
        rep = self.replicas[rid]
        # FENCE first: a heartbeat-declared death may be a wedged-but-
        # running worker (e.g. a stalled dispatch) — shoot it in the
        # head so it cannot race the survivor for these futures
        rep.kill()
        items = rep.drain_unfinished()
        self.report.record_replica_dead()
        for session, mapped in list(self._sessions.items()):
            if mapped == rid:
                del self._sessions[session]
        self._requeue_items(items)

    def _sweep_dead(self) -> bool:
        """Two death signals, one verdict: heartbeat silence past the
        probe deadline (FleetHealth) and worker-thread death observed
        directly (a chaos kill or a raise stops beats AND the thread —
        the thread check notices within one loop pass instead of one
        probe period). A DRAINED replica pre-registers in
        ``_handled_dead`` before its heartbeat stops, so a clean
        decommission never reads as a death."""
        worked = False
        for rid, rep in self.replicas.items():
            if rep.dead() and self.health.is_alive(rid):
                self.health.mark_dead(rid, "worker thread died")
        newly = {rid for rid
                 in set(self.health.check()) | set(self.health.dead)
                 if rid not in self._handled_dead}
        for rid in sorted(newly):
            self._handled_dead.add(rid)
            self._handle_dead(rid)
            worked = True
        return worked

    def _poll_watchdog(self) -> None:
        from chainermn_tpu.comm.object_plane import JobAbortedError

        wd = self._watchdog or current_watchdog()
        if wd is None:
            return
        try:
            wd.check()
        except JobAbortedError as e:
            # bounded abortion, fleet-wide: fail every open future now
            items = []
            with self._lock:
                items.extend(self._pending)
                self._pending.clear()
            for rep in self.replicas.values():
                with rep.lock:
                    rep.engine.abort_all()
                items.extend(it for it, _r in rep.inflight.values())
                rep.inflight.clear()
                items.extend(rep.drain_unfinished())
            for item in items:
                if not item.future.done():
                    item.future.set_exception(JobAbortedError(str(e)))

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._poll_watchdog()
            worked = self._sweep_dead()
            if not self._alive():
                # no survivor can ever take these — fail fast rather
                # than letting clients ride out the full deadline
                stranded = []
                with self._lock:
                    stranded.extend(self._pending)
                    self._pending.clear()
                for item in stranded:
                    if not item.future.done():
                        item.future.set_exception(RuntimeError(
                            "no live replicas left in the fleet"))
            while True:
                with self._lock:
                    if not self._pending:
                        break
                    item = self._pending[0]
                if item.future.done():       # resolved while re-queued
                    with self._lock:
                        if self._pending and self._pending[0] is item:
                            self._pending.popleft()
                    continue
                rep = self._place(item)
                if rep is None:
                    break                    # no headroom/survivor yet
                with self._lock:
                    if not self._pending or self._pending[0] is not item:
                        continue
                    self._pending.popleft()
                if item.session is not None:
                    self._sessions[item.session] = rep.replica_id
                rep.inbox.put(item)
                worked = True
            if not worked:
                time.sleep(_IDLE_WAIT_S)
        # teardown: replicas were stopped by close(); fail what's left
        leftovers = []
        with self._lock:
            leftovers.extend(self._pending)
            self._pending.clear()
        for rep in self.replicas.values():
            leftovers.extend(it for it, _r in rep.inflight.values())
            leftovers.extend(rep.drain_unfinished())
        for item in leftovers:
            if not item.future.done():
                item.future.set_exception(
                    RuntimeError("router closed mid-request"))

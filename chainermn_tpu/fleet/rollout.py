"""Zero-downtime rolling weight updates for a live Router fleet.

A weight change used to mean killing the fleet. This controller walks a
live fleet from weights v1 to v2 with zero dropped or duplicated tokens
and zero downtime, one replica at a time, under the lifecycle

    CANARY  → the candidate snapshot must reproduce a pinned prompt set
              BITWISE against a v2 oracle (greedy, fixed seeds) on an
              off-traffic engine before any traffic moves; a miscompare
              aborts with the fleet untouched;
    DRAIN   → the replica leaves service through the PR 17
              ``UP → DRAINING → DRAINED`` lifecycle — live sessions
              migrate to survivors (``Router.drain``), so its streams
              never stop;
    SWAP    → the verified v2 snapshot installs in place
              (``Engine.swap_weights`` → ``ServingStep.load_params`` —
              no recompile: params are per-call arguments to every
              jitted program);
    READMIT → a fresh worker thread re-registers the replica
              (``Router.readmit``) and load-aware placement re-balances
              onto it before the next replica is taken.

**The relay.** Weights move over a chunked relay on the handoff
transport (``InProcessTransport``/``ObjectPlaneTransport`` — per-frame
SHA verify, NACK → bounded re-send, duplicate fencing, exactly wire
format 5's discipline): the snapshot is encoded ONCE
(``serving.weights.encode_weights``, ``f32`` or the ``int8-block``
publish codec), split into fixed-size chunks each carrying its own
byte-count + SHA-256 manifest, and closed by a frame committing every
chunk digest plus the full-payload weights manifest. Each replica that
finishes receiving becomes the next hop's FORWARDER, so the publisher's
egress is ~1× the snapshot regardless of fleet size (HiCCL's
hierarchical composition applied to weight broadcast). Every receiver
re-verifies the assembled payload against the weights manifest
(``decode_weights``) before a byte reaches an engine.

**Failure modes** (the point):

* canary miscompare (or chaos ``canary_mismatch``) — abort; zero
  traffic moved, zero replicas touched; ``canary_failures`` counts it.
* corrupt/truncated relay chunk (chaos ``corrupt_rollout_chunk``) —
  the transport NACKs and re-sends that chunk; persistent damage
  exhausts the attempt budget, the hop fails, and the rollout ROLLS
  BACK: every already-swapped replica walks back to v1 through the
  same drain → swap → readmit path. The fleet ends fully on v1, still
  serving.
* replica death inside the swap window (chaos ``kill_mid_swap``, a
  real SIGKILL in the supervised drill) — classified as a CRASH: the
  replica stays out of service for its supervisor, whose restart loads
  whichever version its local manifest verifies
  (``serving/weights.py``); the walk continues on the rest.
* version skew — every handoff/session manifest carries
  ``weights_version``; a v2 frame arriving at a v1 engine (or vice
  versa) is REFUSED (``WeightsVersionSkew``) and the stream falls back
  to a clean re-prefill / replay-from-seed, so every emitted stream is
  entirely ONE version, bitwise against that version's oracle — never
  silently mixed.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from chainermn_tpu.fleet.transport import InProcessTransport
from chainermn_tpu.resilience import chaos
from chainermn_tpu.serving.weights import (WeightsError, decode_weights,
                                           encode_weights)

__all__ = ["RolloutController", "RolloutError", "DEFAULT_CHUNK_BYTES"]

#: relay chunk payload size (1 MiB): big enough that the per-chunk
#: manifest is noise, small enough that a corrupt chunk's re-send is
#: cheap relative to the snapshot
DEFAULT_CHUNK_BYTES = 1 << 20


class RolloutError(RuntimeError):
    """The rollout could not even start (bad arguments, a fleet too
    small to drain). Mid-walk failures do NOT raise — they roll back
    and report ``status='rolled_back'``."""


class RolloutController:
    """Walks a live :class:`~chainermn_tpu.fleet.router.Router` fleet
    from one weights version to the next.

    ``engine_factory(params, weights_version)`` builds the OFF-TRAFFIC
    canary engine from candidate params (for the real engine:
    ``lambda p, v: Engine(model, p, cfg, weights_version=v)``; the
    FakeEngine campaign passes its fake factory). ``transport_factory``
    builds one relay hop's transport (default: an in-process transport
    tagged ``chaos_kind='rollout'``, so rollout chaos never damages
    ordinary handoff traffic and vice versa). ``like`` is the params
    template receivers unflatten against (None keeps the flat dict —
    what the FakeEngine swap face takes)."""

    def __init__(self, router, engine_factory: Callable[[Any, str], Any],
                 *, transport_factory: Optional[Callable[[], Any]] = None,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                 wire_format: Optional[str] = None,
                 like: Any = None,
                 drain_deadline_ms: Optional[int] = None):
        if chunk_bytes < 1:
            raise RolloutError("chunk_bytes must be >= 1")
        self.router = router
        self.engine_factory = engine_factory
        self.transport_factory = (transport_factory or (
            lambda: InProcessTransport(chaos_kind="rollout")))
        self.chunk_bytes = int(chunk_bytes)
        self.wire_format = wire_format
        self.like = like
        self.drain_deadline_ms = drain_deadline_ms

    # ----------------------------------------------------------------
    # relay
    # ----------------------------------------------------------------

    def _frames(self, manifest: dict, data: bytes):
        """Split the encoded snapshot into SHA-manifested chunk frames
        plus the closing frame committing every chunk digest and the
        full-payload weights manifest (wire format 5's shape applied to
        weights)."""
        chunks: List[Tuple[dict, bytes]] = []
        table: List[dict] = []
        for i in range(0, max(1, len(data)), self.chunk_bytes):
            blob = data[i:i + self.chunk_bytes]
            man = {"kind": "rollout_chunk",
                   "index": len(chunks),
                   "bytes": len(blob),
                   "sha256": hashlib.sha256(blob).hexdigest()}
            chunks.append((man, blob))
            table.append({"index": man["index"], "bytes": man["bytes"],
                          "sha256": man["sha256"]})
        closing_blob = json.dumps(
            {"weights": manifest, "chunks": table},
            sort_keys=True).encode()
        closing_man = {"kind": "rollout_closing",
                       "count": len(chunks),
                       "bytes": len(closing_blob),
                       "sha256": hashlib.sha256(closing_blob).hexdigest()}
        return chunks, (closing_man, closing_blob)

    def _ship_hop(self, manifest: dict, chunks, closing,
                  ) -> Tuple[Optional[Any], int, List[str]]:
        """One relay hop: ship every chunk + the closing frame over a
        fresh transport, then assemble, verify, and decode on the
        receiving side. Returns ``(params, wire_bytes, defects)`` —
        params is None when the hop FAILED (a chunk exhausted the
        NACK/re-send budget, or the assembled payload refused the
        weights manifest). ``wire_bytes`` counts every adopted payload
        byte, so the caller's accounting is exact."""
        t = self.transport_factory()
        shipped = 0
        defects: List[str] = []
        try:
            for sid, (man, blob) in enumerate(
                    list(chunks) + [closing]):
                status = t.send(sid, man, blob)
                if status not in ("adopted", "duplicate"):
                    defects.extend(getattr(t, "last_send_defects", ()))
                    defects.append(
                        f"chunk {man.get('index', 'closing')} "
                        f"undeliverable (status={status})")
                    return None, shipped, defects
                shipped += len(blob)
            arrivals = {}
            for a in t.poll():
                if a.failed:
                    defects.extend(a.defects)
                    continue
                arrivals[a.stream_id] = a
            closing_arr = arrivals.get(len(chunks))
            if closing_arr is None:
                defects.append("closing frame never arrived")
                return None, shipped, defects
            committed = json.loads(closing_arr.blob.decode())
            parts: List[bytes] = []
            for ent in committed["chunks"]:
                a = arrivals.get(int(ent["index"]))
                if a is None:
                    defects.append(f"chunk {ent['index']} missing")
                    return None, shipped, defects
                if (len(a.blob) != int(ent["bytes"])
                        or hashlib.sha256(a.blob).hexdigest()
                        != ent["sha256"]):
                    defects.append(
                        f"chunk {ent['index']} does not match the "
                        "closing commitment")
                    return None, shipped, defects
                parts.append(a.blob)
            try:
                params = decode_weights(committed["weights"],
                                        b"".join(parts), like=self.like)
            except WeightsError as e:
                defects.append(str(e))
                return None, shipped, defects
            return params, shipped, defects
        finally:
            t.close()

    # ----------------------------------------------------------------
    # canary
    # ----------------------------------------------------------------

    def _canary_check(self, params: Any, version: str,
                      prompts: Sequence[Tuple[Any, int, int]],
                      oracle: Sequence[Sequence[int]]) -> List[int]:
        """Replay the pinned prompt set (greedy, fixed seeds) on an
        OFF-TRAFFIC engine built from the candidate params and compare
        bitwise against the caller's v2 oracle. Returns the indices
        that miscompared (chaos ``canary_mismatch`` forces ``[-1]``)."""
        if len(prompts) != len(oracle):
            raise RolloutError(
                f"{len(prompts)} canary prompts vs {len(oracle)} oracle "
                "streams")
        eng = self.engine_factory(params, version)
        reqs = []
        for prompt, seed, n in prompts:
            reqs.append(eng.submit(np.asarray(prompt, np.int32),
                                   max_new_tokens=int(n),
                                   seed=int(seed)))
        eng.run_until_drained()
        mismatched = [i for i, (req, want) in enumerate(zip(reqs, oracle))
                      if list(req.tokens) != [int(x) for x in want]]
        if chaos.on_canary():
            mismatched.append(-1)
        return mismatched

    # ----------------------------------------------------------------
    # the walk
    # ----------------------------------------------------------------

    def rollout(self, params: Any, version: str, *,
                canary_prompts: Sequence[Tuple[Any, int, int]],
                canary_oracle: Sequence[Sequence[int]],
                from_version: Optional[str] = None) -> dict:
        """Walk the fleet to ``params``/``version``. Blocking (runs on
        the caller's thread, like ``Router.drain``); traffic keeps
        flowing throughout — at most one replica is out of placement at
        any instant.

        ``canary_prompts`` is a sequence of ``(prompt, seed,
        max_new_tokens)``; ``canary_oracle`` the matching v2 streams
        (greedy, fixed seeds — produce them on a reference engine
        holding the v2 snapshot). ``from_version`` stamps any engine
        still serving UNVERSIONED weights before the walk, so the skew
        fence has a v1 name to refuse against (engines already
        versioned are left alone).

        Single-host note: the canary replays on THIS thread, and jit
        tracing holds the GIL — on a co-located drill, build the
        ``Router`` with a ``health_timeout_ms`` that covers compile
        time, or the starved worker heartbeats read as replica deaths.

        Returns a status dict::

            {"status":   "completed" | "aborted" | "rolled_back",
             "version":  the target version,
             "swapped":  replicas now serving it,
             "crashed":  replicas lost inside their swap window,
             "rolled_back": replicas walked back to v1,
             "publisher_egress_bytes": hop-0 relay bytes (~1× snapshot),
             "relay_wire_bytes":       all hops' relay bytes,
             "reason":   why, for aborted/rolled_back}
        """
        report = self.router.report
        targets = sorted(rid for rid, rep in self.router.replicas.items()
                         if rep.state() == "UP")
        if len(targets) < 2:
            raise RolloutError(
                f"{len(targets)} UP replicas — a rolling update needs "
                "at least 2 (each drain migrates onto survivors)")
        if from_version is not None:
            for rid in targets:
                eng = self.router.replicas[rid].engine
                if getattr(eng, "weights_version", None) is None:
                    eng.weights_version = from_version

        manifest, data = encode_weights(
            params, wire_format=self.wire_format, weights_version=version)
        chunks, closing = self._frames(manifest, data)

        # CANARY: the candidate decodes and replays OFF-TRAFFIC before
        # a single byte moves fleet-ward. The canary engine is built
        # from the same (manifest, payload) pair the relay will ship,
        # so what it verified is what the fleet receives.
        try:
            canary_params = decode_weights(manifest, data, like=self.like)
        except WeightsError as e:
            report.record_canary_failure()
            return {"status": "aborted", "version": version,
                    "swapped": [], "crashed": [], "rolled_back": [],
                    "publisher_egress_bytes": 0, "relay_wire_bytes": 0,
                    "reason": f"candidate snapshot refused: {e}"}
        mismatched = self._canary_check(canary_params, version,
                                        canary_prompts, canary_oracle)
        if mismatched:
            report.record_canary_failure()
            return {"status": "aborted", "version": version,
                    "swapped": [], "crashed": [], "rolled_back": [],
                    "publisher_egress_bytes": 0, "relay_wire_bytes": 0,
                    "reason": ("canary miscompared on prompt(s) "
                               f"{mismatched} — fleet untouched")}

        swapped: List[Tuple[int, Any, Any]] = []   # rid, old params/ver
        crashed: List[int] = []
        egress = 0
        total_wire = 0
        failure: Optional[str] = None
        for hop, rid in enumerate(targets):
            # relay: hop 0 is the publisher's single upload; every
            # later hop forwards from the previous finished receiver
            hop_params, wire, defects = self._ship_hop(
                manifest, chunks, closing)
            total_wire += wire
            report.record_rollout_wire(wire)
            if hop == 0:
                egress = wire
            if hop_params is None:
                failure = (f"relay to replica {rid} failed: "
                           + "; ".join(defects[-3:] or ("unknown",)))
                break
            try:
                old = self._swap_and_readmit_guarded(rid, hop_params,
                                                     version, crashed)
            except Exception as e:      # drain refused / engine error
                failure = (f"replica {rid} could not swap: "
                           f"{type(e).__name__}: {e}")
                break
            if old is not None:
                swapped.append((rid, old[0], old[1]))

        if failure is None:
            report.record_rollout_completed()
            return {"status": "completed", "version": version,
                    "swapped": [rid for rid, _p, _v in swapped],
                    "crashed": crashed, "rolled_back": [],
                    "publisher_egress_bytes": egress,
                    "relay_wire_bytes": total_wire, "reason": None}

        # ROLLBACK: walk every already-swapped replica back to v1
        # through the SAME drain path, newest first. The stashed params
        # are the engine's internal (converted) form — converted=True.
        walked_back: List[int] = []
        for rid, old_params, old_version in reversed(swapped):
            self.router.drain(rid, deadline_ms=self.drain_deadline_ms)
            self.router.replicas[rid].engine.swap_weights(
                old_params, old_version, converted=True)
            self.router.readmit(rid)
            walked_back.append(rid)
        report.record_rollout_rolled_back()
        return {"status": "rolled_back", "version": version,
                "swapped": [], "crashed": crashed,
                "rolled_back": walked_back,
                "publisher_egress_bytes": egress,
                "relay_wire_bytes": total_wire, "reason": failure}

    def _swap_and_readmit_guarded(self, rid: int, params: Any,
                                  version: str, crashed: List[int]):
        """The swap window with its chaos hook: after DRAIN, before
        READMIT, ``kill_mid_swap`` may fire — the in-process analogue
        of SIGKILLing the replica's host mid-swap. The replica then
        stays OUT of service (state DRAINED, never readmitted), exactly
        like a crashed host waiting for its supervisor, whose restart
        loads whichever version its local manifest verifies. Returns
        the previous (params, version), or None when the replica was
        lost to the window."""
        self.router.drain(rid, deadline_ms=self.drain_deadline_ms)
        if chaos.on_swap(rid):
            crashed.append(rid)
            return None
        rep = self.router.replicas[rid]
        old = rep.engine.swap_weights(params, version)
        self.router.readmit(rid)
        return old

"""Per-replica health for the fleet router.

The training fleet already has a liveness organ — the heartbeat
watchdog (``resilience/watchdog.py``) declares a PROCESS dead when its
beats stop. The router needs the same verdict per REPLICA: each
``EngineReplica`` worker beats once per scheduler iteration, and the
router's dispatch loop asks :class:`FleetHealth` who has gone silent
longer than the probe deadline (``RpcPolicy.probe_ms`` by default — the
same constant that slices ``Frontend.result`` waits, so "how long until
we notice" is one number fleet-wide).

A death verdict here is a ROUTING decision, not a teardown: the router
answers by re-queueing the dead replica's in-flight requests onto
survivors with their client futures intact (``router.Router.
_handle_dead``). Explicit ``mark_dead`` exists for deaths detected out
of band (a worker thread that raised, a chaos ``kill_replica``) — it
wins immediately instead of waiting out the silence deadline.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from chainermn_tpu.resilience.policy import policy

__all__ = ["FleetHealth"]


class FleetHealth:
    """Deadline-based replica liveness (injectable clock for tests)."""

    def __init__(self, replica_ids, timeout_ms: Optional[int] = None,
                 time_fn=time.monotonic):
        self._time = time_fn
        self.timeout_ms = (timeout_ms if timeout_ms is not None
                           else policy().probe_ms)
        now = self._time()
        self._last_beat: Dict[int, float] = {int(r): now
                                             for r in replica_ids}
        self._dead: Dict[int, str] = {}

    def beat(self, replica: int) -> None:
        """One heartbeat — replica workers call this every iteration."""
        if replica not in self._dead:
            self._last_beat[replica] = self._time()

    def mark_dead(self, replica: int, reason: str = "marked dead") -> None:
        """Out-of-band death (worker raised / chaos kill): immediate."""
        if replica in self._last_beat and replica not in self._dead:
            self._dead[replica] = reason

    def revive(self, replica: int, reason: str = "readmitted") -> None:
        """Readmit a decommissioned replica (the READMIT leg of a
        rolling weight update — ``Router.readmit``): the death verdict
        is withdrawn and the beat clock restarts NOW, so the deadline
        sweep gives the fresh worker a full ``timeout_ms`` before it can
        be declared dead again. Only a replica this tracker knows may
        come back; reviving a live one is a no-op."""
        if replica not in self._last_beat:
            raise ValueError(f"unknown replica {replica} ({reason})")
        self._last_beat[replica] = self._time()
        self._dead.pop(replica, None)

    def check(self) -> List[int]:
        """Deadline sweep: returns replicas NEWLY declared dead (silent
        past ``timeout_ms``). Idempotent per death — a replica is
        reported exactly once, then stays in ``dead``."""
        now = self._time()
        newly = []
        for r, t in self._last_beat.items():
            if r in self._dead:
                continue
            if (now - t) * 1e3 > self.timeout_ms:
                self._dead[r] = (
                    f"no heartbeat for {self.timeout_ms} ms")
                newly.append(r)
        return newly

    def alive(self) -> List[int]:
        return sorted(r for r in self._last_beat if r not in self._dead)

    def is_alive(self, replica: int) -> bool:
        return replica in self._last_beat and replica not in self._dead

    @property
    def dead(self) -> Dict[int, str]:
        """replica → reason, for every declared death so far."""
        return dict(self._dead)

"""ZeRO-family sharded training over the data axis.

Three rungs, all beyond the reference's surface (ChainerMN replicates
everything per rank) but the natural TPU extension of its flat-buffer +
reduce-scatter machinery:

- **ZeRO-1** (``make_zero1_train_step``): optimizer state sharded; grads
  arrive by ``psum_scatter`` (which is also ZeRO-2's gradient sharding —
  reduce-scatter in place of all-reduce), params re-assembled by
  ``all_gather``.
- **ZeRO-3 / FSDP** (``make_fsdp_train_step``): parameters and optimizer
  state sharded per-leaf; XLA's SPMD partitioner inserts the just-in-time
  per-layer gathers and gradient reduce-scatters.

ZeRO-1 layout: parameters are flattened to one vector (the reference's
``_memory_utility`` flat-buffer idea, now load-bearing — SURVEY.md §2.5's
`_MultiNodeOptimizer` replicates a whole local optimizer instead), padded to
a multiple of the axis size, and sharded on the leading dim. Reduce-scatter +
all-gather is the same total communication volume as one all-reduce (it is
how a ring all-reduce decomposes — the reference's TwoDimensionalCommunicator
hand-wrote exactly this split) at 1/N the optimizer memory: Adam's m/v for
ResNet-50 drop from 2x model size per chip to 2x/N.
"""

from __future__ import annotations

import math
import warnings
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax, shard_map
from jax.flatten_util import ravel_pytree
from jax.sharding import PartitionSpec as P


def _resolve_rs(grad_reducer, comm) -> Tuple[Optional[Callable], Optional[object]]:
    """Resolve a ``grad_reducer=`` argument for the ZeRO flat paths.

    Returns ``(rs, ef_reducer)``: ``rs`` is the stateless flat-vector
    mean-reduce-scatter callable (or ``None`` for the legacy inline
    ``psum_scatter / n`` — bit-identical default); ``ef_reducer`` is
    non-None when the reducer is STATEFUL (error feedback), in which
    case ``rs`` is ``None`` and the step factories thread the per-rank
    residual through ``reduce_scatter_flat_ef`` — the residual lives in
    the flat-bucket frame (full padded vector per rank), rides the
    optimizer state as ``_ReducerWrappedState`` exactly as in the DP
    path, and is sharded ``P(ax)`` on its stacked leading axis.

    Every strategy must preserve the tile-``r``-to-rank-``r`` scatter
    layout — the sharded optimizer state depends on it
    (``GradReducer.reduce_scatter_flat``).
    """
    from chainermn_tpu.collectives import make_grad_reducer

    reducer = make_grad_reducer(grad_reducer, comm, op="mean")
    if reducer is None:
        return None, None
    if reducer.stateful:
        if not hasattr(reducer, "reduce_scatter_flat_ef"):
            raise ValueError(
                f"grad_reducer {reducer.name!r} is stateful but "
                "implements no reduce_scatter_flat_ef — the ZeRO flat "
                "paths cannot thread its per-rank state; pass a "
                "stateless reducer here, or use "
                "make_data_parallel_train_step")
        return None, reducer
    ax = comm.axis_name
    n = comm.size
    return (lambda g: reducer.reduce_scatter_flat(g, ax, n)), None


def _require_elementwise(optimizer, params) -> None:
    """Refuse optimizers the flat ZeRO layouts would silently mis-train.

    ZeRO-1/2 run ``optimizer.update`` on each device's 1/N SHARD of the
    packed flat vector, with shard-local optimizer state; only
    ELEMENT-WISE transforms compute the same update there as on the
    parameter pytree. Anything that couples elements would produce wrong
    updates with no error: per-layer trust ratios (LARS/LAMB), masked
    weight decay, ``multi_transform``, and also whole-tree reductions
    like ``clip_by_global_norm`` — each shard would clip by its OWN
    shard's norm, not the global one.

    Probe, don't blocklist: build a tiny pytree with the real params'
    STRUCTURE and per-leaf NDIMS (masks and ndim-keyed rules see the
    real shape ranks), run ``update`` on the whole tree (the semantic
    oracle) and per contiguous shard of the flat pack with independent
    states (the sharded execution, N=2, split point nudged OFF leaf
    boundaries so a per-leaf transform can never see shards that
    coincide with its leaves), and compare. A flat-side crash
    (``multi_transform``'s structure check) is the same verdict,
    refused with the cause chained.
    """
    leaves = jax.tree_util.tree_leaves(params)
    treedef = jax.tree_util.tree_structure(params)
    probe_leaves, grad_leaves = [], []
    for i, l in enumerate(leaves):
        shape = (2,) * np.ndim(l)
        sz = int(np.prod(shape, initial=1))
        # distinct per-leaf magnitudes so per-leaf norms differ — a
        # trust-ratio transform cannot accidentally agree with its flat run
        base = np.linspace(0.1, 0.9, sz, dtype=np.float32) * (i + 1)
        probe_leaves.append(jnp.asarray(base.reshape(shape)))
        grad_leaves.append(jnp.asarray(
            (base[::-1] * 0.01 + 0.003).reshape(shape)))
    probe = jax.tree_util.tree_unflatten(treedef, probe_leaves)
    gprobe = jax.tree_util.tree_unflatten(treedef, grad_leaves)

    fv = ravel_pytree(probe)[0]
    total = fv.size
    fv_p = fv
    if total % 2:  # pad like the real layout pads to the shard quantum
        fv_p = jnp.concatenate([fv, jnp.zeros((1,), fv.dtype)])
    # split point: near the middle but NEVER on a leaf boundary — a
    # 2-leaf tree split exactly at its boundary would make each probe
    # shard one whole leaf, and a per-leaf transform (LAMB) would agree
    # with its own shard run by construction (review finding, r5)
    boundaries = set(np.cumsum([int(np.prod((2,) * np.ndim(l),
                                            initial=1))
                                for l in leaves]).tolist())
    split = fv_p.size // 2
    if split in boundaries and fv_p.size - split > 1:
        split += 1
    msg = (
        "this optimizer is not element-wise: its update on a parameter "
        "pytree differs from its update run per-shard on the same values "
        "flat-packed, so ZeRO-1/2's flat layouts would silently compute "
        "wrong updates (per-layer trust ratios, masked weight decay, "
        "multi_transform, whole-tree norms like clip_by_global_norm). "
        "Use make_fsdp_train_step instead — FSDP shards per-leaf, keeps "
        "parameter structure intact, and computes tree-wide reductions "
        "globally via XLA's sharding propagation."
    )
    # two gradient scales x two CHAINED steps per scale. The scales:
    # threshold-gated coupling (clip_by_global_norm) is a no-op on tiny
    # gradients — the large scale activates any threshold up to ~1e4x
    # the probe norm (a transform gated even higher is inert at every
    # realistic gradient magnitude). The chained steps, with the
    # gradient DIRECTION changing between them (a positional ramp tilts
    # step 2's mass toward the tail shard): a whole-tree normalizer
    # followed by a scale-invariant transform (clip-then-adam) maps any
    # CONSTANT-direction gradient stream to the same sign updates in
    # both modes, so one step — or two steps along one direction —
    # cannot see it; with the direction change, tree and shard clip
    # factors mix differently into the carried moments and diverge.
    _, unravel_g = ravel_pytree(gprobe)
    ramp = unravel_g(jnp.linspace(0.2, 5.0, total,
                                  dtype=ravel_pytree(gprobe)[0].dtype))
    for gscale in (1.0, 1e4):
        state_t = optimizer.init(probe)
        states_s = None
        for step_i, mul in enumerate((gscale, 3.0 * gscale)):
            g_s = jax.tree_util.tree_map(lambda g: g * mul, gprobe)
            if step_i == 1:
                g_s = jax.tree_util.tree_map(
                    lambda g, r: g * r, g_s, ramp)
            u_tree, state_t = optimizer.update(g_s, state_t, probe)
            gv = ravel_pytree(g_s)[0]
            if total % 2:
                gv = jnp.concatenate([gv, jnp.zeros((1,), gv.dtype)])
            try:
                parts = []
                new_states = []
                spans = ((0, split), (split, fv_p.size))
                for s, (lo, hi) in enumerate(spans):  # per-shard states
                    fs = fv_p[lo:hi]
                    gs = gv[lo:hi]
                    st = (optimizer.init(fs) if states_s is None
                          else states_s[s])
                    u_s, st = optimizer.update(gs, st, fs)
                    parts.append(u_s)
                    new_states.append(st)
                states_s = new_states
                u_flat = jnp.concatenate(parts)[:total]
            except Exception as e:
                raise ValueError(msg) from e
            got = np.asarray(ravel_pytree(u_tree)[0])
            want = np.asarray(u_flat)
            if got.shape != want.shape or not np.allclose(
                    got, want, rtol=1e-5, atol=1e-8):
                raise ValueError(msg)
        states_s = None


def _padded_size(total: int, n: int) -> int:
    """Flat-vector length after padding for an n-way shard.

    Pads to a device-count-INDEPENDENT quantum when the axis size allows
    it: any n dividing 256 yields the same padded GLOBAL length, so
    sharded snapshots reshard across device counts (8 <-> 4 etc.,
    extensions/checkpoint.py's splicing restore) instead of tripping the
    global-shape check on pad-length mismatch. One definition on purpose
    — zero1 and zero2 snapshots must agree.

    DELIBERATE compatibility break (2026-07-31): snapshots written with
    the pre-quantum n-multiple padding have a different global length
    and fail restore with 'different model'; re-save from a live run.
    """
    q = 256 if 256 % n == 0 else n
    return total + ((-total) % q)


class _BucketLayout:
    """Bucket-major ZeRO layout: parameter leaves greedily packed into
    buckets of ≤ ``bucket_bytes`` (comm/xla.py's ``plan_buckets``), each
    bucket padded and sharded independently.

    Why: with ONE flat vector, the backward's full gradient must exist
    as a single padded buffer before the one big ``psum_scatter`` — peak
    live gradient = full model (the r2/r3 ZeRO-1 wart). With buckets,
    each full-size bucket gradient is reduce-scattered the moment its
    leaves exist and DIES there; backward produces leaves in
    reverse-layer order, so late buckets scatter while early layers are
    still differentiating. Peak live gradient ≈ leaves-in-flight + one
    bucket (evidenced by compiled buffer-assignment stats in the tests).

    State layout is a TUPLE of per-bucket flat vectors, each padded and
    ``P(ax)``-sharded independently (optax transforms run element-wise
    over the tuple pytree). Each bucket's GLOBAL vector is plain bucket
    content — device-count-independent — so sharded snapshots reshard
    across device counts exactly like the unbucketed single vector
    (quantum padding, extensions/checkpoint.py splicing), per bucket
    leaf. The bucket plan is a pure function of (leaf sizes,
    bucket_bytes), so the layout reconstructs deterministically for
    :func:`zero1_params`. NOT interchangeable with the unbucketed
    layout: snapshots written one way must be restored the same way.
    """

    def __init__(self, params, n: int, bucket_bytes: int):
        from chainermn_tpu.comm.xla import plan_buckets

        leaves, self.treedef = jax.tree_util.tree_flatten(params)
        self.shapes = [jnp.shape(l) for l in leaves]
        self.sizes = [int(np.prod(s, initial=1)) for s in self.shapes]
        dtypes = {jnp.asarray(l).dtype for l in leaves}
        if len(dtypes) != 1:
            raise ValueError(
                f"ZeRO flat layouts need a single param dtype, got "
                f"{sorted(str(d) for d in dtypes)}")
        (self.dtype,) = dtypes
        self.buckets = plan_buckets(
            [(i, self.sizes[i] * self.dtype.itemsize)
             for i in range(len(leaves))], bucket_bytes)
        self.totals = [sum(self.sizes[i] for i in b) for b in self.buckets]
        self.padded = [_padded_size(t, n) for t in self.totals]
        self.shard_lens = [p // n for p in self.padded]
        self.shard_offs = list(np.cumsum([0] + self.shard_lens[:-1]))
        self.shard_len = sum(self.shard_lens)
        self.n = n

    def pack_buckets(self, tree):
        """Leaves → one padded flat vector per bucket."""
        leaves = jax.tree_util.tree_leaves(tree)
        out = []
        for b, padded in zip(self.buckets, self.padded):
            parts = [leaves[i].reshape(-1) for i in b]
            total = sum(self.sizes[i] for i in b)
            if padded != total:
                parts.append(jnp.zeros((padded - total,),
                                       parts[0].dtype))
            out.append(jnp.concatenate(parts) if len(parts) > 1
                       else parts[0])
        return out

    def unpack_full(self, bucket_fulls):
        """Per-bucket FULL vectors → the parameter pytree."""
        leaves = []
        for b, full, total in zip(self.buckets, bucket_fulls, self.totals):
            off = 0
            for i in b:
                leaves.append(
                    lax.slice_in_dim(full, off, off + self.sizes[i])
                    .reshape(self.shapes[i]))
                off += self.sizes[i]
        # leaves arrive in bucket order == leaf order (buckets partition
        # the leaf sequence in order)
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


def make_zero1_train_step(
    model,
    optimizer: optax.GradientTransformation,
    comm,
    params,
    loss_fn: Optional[Callable] = None,
    donate: bool = True,
    bucket_bytes: Optional[int] = None,
    grad_reducer=None,
) -> Tuple[Callable, Tuple]:
    """Build a jitted ZeRO-1 data-parallel train step and its initial state.

    Returns ``(step, state)``::

        step, state = make_zero1_train_step(model, optax.adam(1e-3), comm,
                                            params)
        state, metrics = step(state, x, y)
        params = zero1_params(state, params)   # re-assembled pytree

    ``state = (param_shard, opt_state)``: ``param_shard`` is the flat
    parameter vector sharded over the communicator axis; ``opt_state`` is the
    optimizer state over that shard (scalar leaves, e.g. step counts, stay
    replicated).

    Restrictions: the communicator must span a single mesh axis (split a
    hybrid mesh first); parameter leaves must share one dtype
    (``ravel_pytree`` concatenates them — fp32 params with bf16 *compute* is
    fine, the model casts internally); models with mutable collections (BN
    stats) should use
    :func:`~chainermn_tpu.training.step.make_data_parallel_train_step`; and
    ``optimizer`` must be element-wise (sgd/momentum/adam/adamw...). The
    update runs on the flat parameter vector, so structure-dependent
    transforms — per-layer trust ratios (LARS/LAMB), masked weight decay,
    ``multi_transform`` — would compute wrong updates; construction
    PROBES the optimizer (tree-vs-flat update on a synthetic pytree,
    :func:`_require_elementwise`) and raises instead of mis-training.

    The gradient reduction op is ``mean`` (the reference's
    ``allreduce_grad`` contract); do NOT additionally wrap ``optimizer`` in
    ``create_multi_node_optimizer``.

    ``bucket_bytes``: pack parameter leaves into independent reduction
    buckets (:class:`_BucketLayout`). The backward's full-size gradient
    then never exists as one buffer — each bucket is reduce-scattered as
    soon as its leaves are produced and freed immediately, so peak live
    gradient drops from full-model to ≈ one bucket. Numerics are
    identical; the STATE LAYOUT is not — pass the same ``bucket_bytes``
    to :func:`zero1_params` and keep it fixed across snapshot
    save/restore.

    ``grad_reducer``: reduction strategy for the gradient reduce-scatter
    (docs/collectives.md). Default ``None`` is today's flat
    ``psum_scatter`` — bit-identical. A STATEFUL reducer (quantized
    with error feedback) wraps the optimizer state in
    ``_ReducerWrappedState`` exactly as the DP path does: the per-rank
    residual lives in the flat-bucket frame (one full padded vector per
    rank — the frame the rank quantizes in, indifferent to the tile
    layout), globally stacked ``(n, padded)`` and sharded ``P(ax)``,
    riding checkpoints like any other optimizer-state leaf (see
    :func:`_resolve_rs`).
    """
    from chainermn_tpu.training.step import classifier_loss

    _require_elementwise(optimizer, params)
    lf = loss_fn or classifier_loss
    mesh = comm.mesh
    ax = comm.axis_name  # raises on multi-axis comms (single-axis only)
    n = comm.size
    axes = comm.axis_names
    dspec = P(ax)
    rs, ef_reducer = _resolve_rs(grad_reducer, comm)
    if rs is None and ef_reducer is None:
        # dlint: disable=DL106 — this IS the reducer plumbing
        rs = lambda g: lax.psum_scatter(g, ax, tiled=True) / n

    if bucket_bytes is not None:
        return _make_zero1_bucketed(model, optimizer, comm, params, lf,
                                    donate, bucket_bytes, rs, ef_reducer)

    from chainermn_tpu.optimizers import _ReducerWrappedState

    flat, unravel = ravel_pytree(params)
    total = flat.size
    padded = _padded_size(total, n)
    shard_shape = (padded // n,)

    # -- initial state ---------------------------------------------------
    def init_fn(params):
        v = ravel_pytree(params)[0]
        if padded != total:
            v = jnp.concatenate(
                [v, jnp.zeros((padded - total,), v.dtype)])
        i = lax.axis_index(ax)
        shard = lax.dynamic_slice_in_dim(v, i * shard_shape[0],
                                         shard_shape[0])
        opt = optimizer.init(shard)
        if ef_reducer is not None:
            opt = _ReducerWrappedState(
                opt, (jnp.zeros((1, padded), v.dtype),))
        return shard, opt

    abs_opt = jax.eval_shape(
        optimizer.init, jax.ShapeDtypeStruct(shard_shape, flat.dtype))
    opt_specs = jax.tree_util.tree_map(
        lambda l: P(ax) if l.shape == shard_shape else P(), abs_opt)
    if ef_reducer is not None:
        opt_specs = _ReducerWrappedState(opt_specs, (P(ax),))

    state = jax.jit(shard_map(
        init_fn, mesh=mesh, in_specs=(P(),),
        out_specs=(P(ax), opt_specs), check_vma=False,
    ))(params)

    # -- the step --------------------------------------------------------
    def local_step(state, x, y):
        p_shard, opt_state = state
        full = lax.all_gather(p_shard, ax, tiled=True)
        p = unravel(full[:total])

        def f(p):
            loss, (acc, _) = lf(model, p, x, y, train=True)
            return loss, acc

        (loss, acc), grads = jax.value_and_grad(f, has_aux=True)(p)
        # the full flat gradient exists transiently here (one
        # model-size buffer feeding one scatter); pass bucket_bytes to
        # reduce-scatter per bucket instead — peak live gradient drops
        # to ≈ one bucket (evidence: compiled buffer-assignment stats,
        # tests/optimizers_tests/test_zero.py)
        g = ravel_pytree(grads)[0]
        if padded != total:
            g = jnp.concatenate([g, jnp.zeros((padded - total,), g.dtype)])
        if ef_reducer is not None:
            e = opt_state.reducer[0][0]  # this rank's residual, (padded,)
            g_shard, e_new = ef_reducer.reduce_scatter_flat_ef(
                g, e, ax, n)
            updates, inner = optimizer.update(g_shard, opt_state.inner,
                                              p_shard)
            opt_state = _ReducerWrappedState(inner, (e_new[None],))
        else:
            g_shard = rs(g)
            updates, opt_state = optimizer.update(g_shard, opt_state,
                                                  p_shard)
        p_shard = optax.apply_updates(p_shard, updates)
        metrics = {
            "main/loss": lax.pmean(loss, axes),
            "main/accuracy": lax.pmean(acc, axes),
        }
        return (p_shard, opt_state), metrics

    step = jax.jit(
        shard_map(
            local_step, mesh=mesh,
            in_specs=((P(ax), opt_specs), dspec, dspec),
            out_specs=((P(ax), opt_specs), P()),
        ),
        donate_argnums=(0,) if donate else (),
    )
    return step, state


def _bucketed_init(optimizer, comm, params, bucket_bytes,
                   ef_reducer=None):
    """Shared bucketed-state construction for ZeRO-1 and ZeRO-2: the
    layout, per-bucket P(ax) specs, opt-state specs, and the initial
    (tuple-of-shards, opt_state) — one definition so the two steps can
    never diverge on state layout. With a stateful (error-feedback)
    reducer the opt state is wrapped in ``_ReducerWrappedState`` whose
    ``reducer`` field holds one per-rank residual PER BUCKET, each in
    that bucket's padded flat frame, stacked ``(n, padded_b)`` and
    sharded ``P(ax)``."""
    from chainermn_tpu.optimizers import _ReducerWrappedState

    mesh = comm.mesh
    ax = comm.axis_name
    n = comm.size

    layout = _BucketLayout(params, n, bucket_bytes)
    shard_shapes = {(ln,) for ln in layout.shard_lens}

    def init_fn(params):
        i = lax.axis_index(ax)
        shards = tuple(
            lax.dynamic_slice_in_dim(v, i * ln, ln)
            for v, ln in zip(layout.pack_buckets(params),
                             layout.shard_lens)
        )
        opt = optimizer.init(shards)
        if ef_reducer is not None:
            opt = _ReducerWrappedState(opt, tuple(
                jnp.zeros((1, pb), layout.dtype)
                for pb in layout.padded))
        return shards, opt

    abs_shards = tuple(
        jax.ShapeDtypeStruct((ln,), layout.dtype)
        for ln in layout.shard_lens)
    abs_opt = jax.eval_shape(optimizer.init, abs_shards)
    opt_specs = jax.tree_util.tree_map(
        lambda l: P(ax) if l.shape in shard_shapes else P(), abs_opt)
    if ef_reducer is not None:
        opt_specs = _ReducerWrappedState(
            opt_specs, tuple(P(ax) for _ in layout.padded))
    shard_specs = tuple(P(ax) for _ in layout.buckets)

    state = jax.jit(shard_map(
        init_fn, mesh=mesh, in_specs=(P(),),
        out_specs=(shard_specs, opt_specs), check_vma=False,
    ))(params)
    return layout, shard_specs, opt_specs, state


def _make_zero1_bucketed(model, optimizer, comm, params, lf, donate,
                         bucket_bytes, rs, ef_reducer=None):
    """Bucketed ZeRO-1 (see ``make_zero1_train_step(bucket_bytes=...)``).

    Per step, per bucket: ``psum_scatter`` the bucket's padded gradient
    (mean) → concatenate the per-bucket shards into the flat aligned
    shard vector → one element-wise ``optimizer.update``. The per-bucket
    ``all_gather`` on the forward side re-assembles parameters with the
    same layout. XLA's liveness analysis frees each full-size bucket
    gradient at its scatter, and its latency-hiding scheduler can start
    late-layer buckets' collectives while early layers are still in
    backward (tests/comm_tests/test_overlap_schedule.py asserts the
    schedule interleaving for the DP path)."""
    from chainermn_tpu.optimizers import _ReducerWrappedState

    mesh = comm.mesh
    ax = comm.axis_name
    n = comm.size
    axes = comm.axis_names
    dspec = P(ax)

    layout, shard_specs, opt_specs, state = _bucketed_init(
        optimizer, comm, params, bucket_bytes, ef_reducer)

    def local_step(state, x, y):
        p_shards, opt_state = state
        fulls = [lax.all_gather(s, ax, tiled=True) for s in p_shards]
        p = layout.unpack_full(fulls)

        def f(p):
            loss, (acc, _) = lf(model, p, x, y, train=True)
            return loss, acc

        (loss, acc), grads = jax.value_and_grad(f, has_aux=True)(p)
        if ef_reducer is not None:
            pairs = [
                ef_reducer.reduce_scatter_flat_ef(g, e[0], ax, n)
                for g, e in zip(layout.pack_buckets(grads),
                                opt_state.reducer)]
            g_shards = tuple(gs for gs, _ in pairs)
            updates, inner = optimizer.update(g_shards, opt_state.inner,
                                              p_shards)
            opt_state = _ReducerWrappedState(
                inner, tuple(e_new[None] for _, e_new in pairs))
        else:
            g_shards = tuple(rs(g) for g in layout.pack_buckets(grads))
            updates, opt_state = optimizer.update(g_shards, opt_state,
                                                  p_shards)
        p_shards = optax.apply_updates(p_shards, updates)
        metrics = {
            "main/loss": lax.pmean(loss, axes),
            "main/accuracy": lax.pmean(acc, axes),
        }
        return (p_shards, opt_state), metrics

    step = jax.jit(
        shard_map(
            local_step, mesh=mesh,
            in_specs=((shard_specs, opt_specs), dspec, dspec),
            out_specs=((shard_specs, opt_specs), P()),
        ),
        donate_argnums=(0,) if donate else (),
    )
    return step, state


def make_zero2_train_step(
    model,
    optimizer: optax.GradientTransformation,
    comm,
    params,
    n_microbatches: int,
    loss_fn: Optional[Callable] = None,
    donate: bool = True,
    bucket_bytes: Optional[int] = None,
    grad_reducer=None,
) -> Tuple[Callable, Tuple]:
    """ZeRO-2: ZeRO-1 plus a SHARDED gradient accumulator.

    The local batch is split into ``n_microbatches``; each microbatch's
    full-size gradient exists only transiently inside its ``lax.scan``
    iteration — it is ``psum_scatter``-ed into the 1/N accumulator
    immediately. Across the accumulation window the persistent gradient
    memory is ``full/N`` instead of ZeRO-1's full-size gradient, which is
    the ZeRO-2 claim; optimizer state is sharded exactly as in ZeRO-1.

    ``bucket_bytes`` additionally kills the per-microbatch TRANSIENT:
    each bucket scatters into its own 1/N accumulator as the microbatch
    backward produces it (same :class:`_BucketLayout` and
    tuple-of-buckets state as the bucketed ZeRO-1 — pass the same value
    to :func:`zero1_params`), so peak live gradient inside one scan
    iteration ≈ one bucket.

    Same restrictions as :func:`make_zero1_train_step` (single-axis comm,
    element-wise optimizer, uniform param dtype, no mutable collections);
    the local batch must divide ``n_microbatches``. Returns
    ``(step, state)`` with the same state layout as ZeRO-1 (at equal
    ``bucket_bytes``), so :func:`zero1_params` re-assembles parameters
    for either.
    """
    _require_elementwise(optimizer, params)
    if bucket_bytes is not None:
        return _make_zero2_bucketed(model, optimizer, comm, params,
                                    n_microbatches, loss_fn, donate,
                                    bucket_bytes, grad_reducer)
    from chainermn_tpu.training.step import classifier_loss

    lf = loss_fn or classifier_loss
    mesh = comm.mesh
    ax = comm.axis_name
    n = comm.size
    axes = comm.axis_names
    dspec = P(ax)
    m = n_microbatches
    rs, ef_reducer = _resolve_rs(grad_reducer, comm)
    if rs is None and ef_reducer is None:
        # dlint: disable=DL106 — this IS the reducer plumbing
        rs = lambda g: lax.psum_scatter(g, ax, tiled=True) / n

    from chainermn_tpu.optimizers import _ReducerWrappedState

    flat, unravel = ravel_pytree(params)
    total = flat.size
    padded = _padded_size(total, n)
    shard_shape = (padded // n,)

    def init_fn(params):
        v = ravel_pytree(params)[0]
        if padded != total:
            v = jnp.concatenate(
                [v, jnp.zeros((padded - total,), v.dtype)])
        i = lax.axis_index(ax)
        shard = lax.dynamic_slice_in_dim(v, i * shard_shape[0],
                                         shard_shape[0])
        opt = optimizer.init(shard)
        if ef_reducer is not None:
            opt = _ReducerWrappedState(
                opt, (jnp.zeros((1, padded), v.dtype),))
        return shard, opt

    abs_opt = jax.eval_shape(
        optimizer.init, jax.ShapeDtypeStruct(shard_shape, flat.dtype))
    opt_specs = jax.tree_util.tree_map(
        lambda l: P(ax) if l.shape == shard_shape else P(), abs_opt)
    if ef_reducer is not None:
        opt_specs = _ReducerWrappedState(opt_specs, (P(ax),))

    state = jax.jit(shard_map(
        init_fn, mesh=mesh, in_specs=(P(),),
        out_specs=(P(ax), opt_specs), check_vma=False,
    ))(params)

    def local_step(state, x, y):
        p_shard, opt_state = state
        full = lax.all_gather(p_shard, ax, tiled=True)
        p = unravel(full[:total])

        bl = x.shape[0]
        assert bl % m == 0, (
            f"local batch {bl} not divisible by {m} microbatches")
        xm = x.reshape((m, bl // m) + x.shape[1:])
        ym = y.reshape((m, bl // m) + y.shape[1:])

        def micro(carry, xy):
            # error feedback applies PER SCATTER: each microbatch's
            # residual feeds the next microbatch's quantization
            acc, e, loss_a, acc_a = carry
            xi, yi = xy

            def f(p):
                loss, (a, _) = lf(model, p, xi, yi, train=True)
                return loss, a

            (loss, a), grads = jax.value_and_grad(f, has_aux=True)(p)
            g = ravel_pytree(grads)[0]
            if padded != total:
                g = jnp.concatenate(
                    [g, jnp.zeros((padded - total,), g.dtype)])
            # the full-size g dies here; only the 1/N shard accumulates
            if ef_reducer is not None:
                tile, e = ef_reducer.reduce_scatter_flat_ef(g, e, ax, n)
                acc = acc + tile
            else:
                acc = acc + rs(g)
            return (acc, e, loss_a + loss, acc_a + a), None

        from chainermn_tpu.utils import match_vma

        acc0 = match_vma(jnp.zeros(shard_shape, flat.dtype), p_shard)
        z = match_vma(jnp.zeros(()), full)
        e0 = (opt_state.reducer[0][0] if ef_reducer is not None
              else match_vma(jnp.zeros((0,), flat.dtype), p_shard))
        (g_shard, e_fin, loss_sum, acc_sum), _ = lax.scan(
            micro, (acc0, e0, z, z), (xm, ym))
        g_shard = g_shard / m
        if ef_reducer is not None:
            updates, inner = optimizer.update(g_shard, opt_state.inner,
                                              p_shard)
            opt_state = _ReducerWrappedState(inner, (e_fin[None],))
        else:
            updates, opt_state = optimizer.update(g_shard, opt_state,
                                                  p_shard)
        p_shard = optax.apply_updates(p_shard, updates)
        metrics = {
            "main/loss": lax.pmean(loss_sum / m, axes),
            "main/accuracy": lax.pmean(acc_sum / m, axes),
        }
        return (p_shard, opt_state), metrics

    step = jax.jit(
        shard_map(
            local_step, mesh=mesh,
            in_specs=((P(ax), opt_specs), dspec, dspec),
            out_specs=((P(ax), opt_specs), P()),
        ),
        donate_argnums=(0,) if donate else (),
    )
    return step, state


def _make_zero2_bucketed(model, optimizer, comm, params, n_microbatches,
                         loss_fn, donate, bucket_bytes, grad_reducer=None):
    """Bucketed ZeRO-2 (see ``make_zero2_train_step(bucket_bytes=...)``)."""
    from chainermn_tpu.training.step import classifier_loss
    from chainermn_tpu.utils import match_vma as _mv

    lf = loss_fn or classifier_loss
    mesh = comm.mesh
    ax = comm.axis_name
    n = comm.size
    axes = comm.axis_names
    dspec = P(ax)
    m = n_microbatches
    rs, ef_reducer = _resolve_rs(grad_reducer, comm)
    if rs is None and ef_reducer is None:
        rs = lambda g: lax.psum_scatter(g, ax, tiled=True) / n

    from chainermn_tpu.optimizers import _ReducerWrappedState

    layout, shard_specs, opt_specs, state = _bucketed_init(
        optimizer, comm, params, bucket_bytes, ef_reducer)

    def local_step(state, x, y):
        p_shards, opt_state = state
        fulls = [lax.all_gather(s, ax, tiled=True) for s in p_shards]
        p = layout.unpack_full(fulls)

        bl = x.shape[0]
        assert bl % m == 0, (
            f"local batch {bl} not divisible by {m} microbatches")
        xm = x.reshape((m, bl // m) + x.shape[1:])
        ym = y.reshape((m, bl // m) + y.shape[1:])

        def micro(carry, xy):
            # error feedback applies PER SCATTER: each bucket keeps its
            # own residual, updated every microbatch
            accs, es, loss_a, acc_a = carry
            xi, yi = xy

            def f(p):
                loss, (a, _) = lf(model, p, xi, yi, train=True)
                return loss, a

            (loss, a), grads = jax.value_and_grad(f, has_aux=True)(p)
            # each full-size BUCKET dies right here; only 1/N shards
            # persist across the accumulation window
            if ef_reducer is not None:
                pairs = [
                    ef_reducer.reduce_scatter_flat_ef(g, e, ax, n)
                    for g, e in zip(layout.pack_buckets(grads), es)]
                accs = tuple(acc + t for acc, (t, _) in zip(accs, pairs))
                es = tuple(e for _, e in pairs)
            else:
                accs = tuple(
                    acc + rs(g)
                    for acc, g in zip(accs, layout.pack_buckets(grads)))
            return (accs, es, loss_a + loss, acc_a + a), None

        accs0 = tuple(
            _mv(jnp.zeros((ln,), layout.dtype), s)
            for ln, s in zip(layout.shard_lens, p_shards))
        z = _mv(jnp.zeros(()), fulls[0])
        es0 = (tuple(e[0] for e in opt_state.reducer)
               if ef_reducer is not None else ())
        (g_shards, es_fin, loss_sum, acc_sum), _ = lax.scan(
            micro, (accs0, es0, z, z), (xm, ym))
        g_shards = tuple(g / m for g in g_shards)
        if ef_reducer is not None:
            updates, inner = optimizer.update(g_shards, opt_state.inner,
                                              p_shards)
            opt_state = _ReducerWrappedState(
                inner, tuple(e[None] for e in es_fin))
        else:
            updates, opt_state = optimizer.update(g_shards, opt_state,
                                                  p_shards)
        p_shards = optax.apply_updates(p_shards, updates)
        metrics = {
            "main/loss": lax.pmean(loss_sum / m, axes),
            "main/accuracy": lax.pmean(acc_sum / m, axes),
        }
        return (p_shards, opt_state), metrics

    step = jax.jit(
        shard_map(
            local_step, mesh=mesh,
            in_specs=((shard_specs, opt_specs), dspec, dspec),
            out_specs=((shard_specs, opt_specs), P()),
        ),
        donate_argnums=(0,) if donate else (),
    )
    return step, state


def zero1_params(state, like_params, bucket_bytes=None):
    """Re-assemble the full parameter pytree from a ZeRO-1 state (driver
    level — for checkpointing, eval, or export). Pass the SAME
    ``bucket_bytes`` the step was built with — the bucketed state layout
    is shard-major (:class:`_BucketLayout`) and silently permutes if
    read with the wrong plan."""
    if bucket_bytes is None:
        if isinstance(state[0], (tuple, list)):
            raise ValueError(
                "this state holds a TUPLE of bucket shards — it was "
                "built with bucket_bytes; pass the same bucket_bytes to "
                "zero1_params (stacking the buckets would interleave "
                "their padding and silently corrupt every later leaf)")
        flat, unravel = ravel_pytree(like_params)
        full = jnp.asarray(state[0]).reshape(-1)[: flat.size]
        return unravel(full)
    buckets = state[0]
    if not isinstance(buckets, (tuple, list)):
        raise ValueError(
            "bucket_bytes given but the state holds a single flat vector "
            "— it was built WITHOUT bucket_bytes; the two layouts are "
            "not interchangeable")
    # n is irrelevant to the layout here (each bucket's global vector is
    # plain bucket content); any value reproduces the same plan
    layout = _BucketLayout(like_params, 1, bucket_bytes)
    if len(buckets) != len(layout.buckets):
        raise ValueError(
            f"state has {len(buckets)} buckets but bucket_bytes="
            f"{bucket_bytes} plans {len(layout.buckets)} — pass the "
            "bucket_bytes the step was built with")
    fulls = [jnp.asarray(b).reshape(-1)[:t]
             for b, t in zip(buckets, layout.totals)]
    return layout.unpack_full(fulls)


# ---------------------------------------------------------------------------
# ZeRO-3 / FSDP: parameter sharding via XLA sharding propagation
# ---------------------------------------------------------------------------

def _first_divisible_dim_shardings(params, comm, start_dim: int):
    """The FSDP per-leaf rule: split each leaf over the communicator axis
    along its first divisible dimension at index >= ``start_dim`` (leaves
    too small to split stay replicated — the standard FSDP min-shard
    rule). One definition for both public variants so the rule cannot
    diverge."""
    from jax.sharding import NamedSharding

    n = comm.size
    ax = comm.axis_name

    def spec(l):
        for i, d in enumerate(getattr(l, "shape", ())):
            if i >= start_dim and d >= n and d % n == 0:
                return P(*([None] * i + [ax]))
        return P()

    return jax.tree_util.tree_map(
        lambda l: NamedSharding(comm.mesh, spec(l)), params)


def fsdp_shardings(params, comm):
    """Per-leaf NamedShardings for fully-sharded parameters: each leaf is
    split over the communicator axis along its first divisible
    dimension."""
    return _first_divisible_dim_shardings(params, comm, start_dim=0)


def fsdp_stack_shardings(params, comm):
    """:func:`fsdp_shardings` for pytrees of scanned layer STACKS
    (:func:`fsdp_scan_apply`): the same first-divisible-dim rule, but
    dim 0 — the ``lax.scan`` layer dim — is never chosen. Sharding the
    stack dim would turn every per-iteration layer slice into a
    cross-device gather of the SLICING instead of an in-body gather of
    the layer, defeating the scan's liveness bound."""
    return _first_divisible_dim_shardings(params, comm, start_dim=1)


def fsdp_scan_apply(block_fn, stacked, h, *, remat: bool = True):
    """Apply ``L`` homogeneous blocks by ``lax.scan`` over a stacked
    parameter pytree — the COMPILER-FORCED form of FSDP's per-layer
    liveness bound.

    ``stacked``'s leaves carry the layer dim first (``[L, ...]``); each
    scan iteration slices layer ``i``, whose sharded leaves XLA gathers
    INSIDE the loop body — and a loop body's temporaries die at
    iteration end, so peak gathered-parameter memory is ONE layer
    regardless of depth. This is a structural guarantee, not a scheduler
    preference: plain ``make_fsdp_train_step`` leaves gather timing to
    XLA's latency-hiding scheduler, which on a memory-rich compile
    happily prefetches EVERY layer's gather up front (measured: all
    gathered layers co-live, peak-memory slope ≈ 0.93 of full param
    bytes vs the 0.44 ideal on a v5e:2x4 AOT compile — see
    tests/optimizers_tests/test_zero.py's memory-evidence tests). A
    while-loop body is beyond loop-invariant motion, so the scan pins
    the bound.

    ``remat=True`` checkpoints the body: the backward re-gathers each
    layer instead of keeping forward gathers alive (the FSDP memory
    floor; per-layer activations are the only residuals).

    Shard the stack with :func:`fsdp_stack_shardings` (NOT plain
    :func:`fsdp_shardings`, whose first-divisible-dim rule would shard
    the stack dim whenever ``L % comm.size == 0``) and pass the result
    into ``make_fsdp_train_step(param_shardings=...)``. Use inside a
    custom ``loss_fn``::

        def loss_fn(model, p, x, y, train=True):
            h = embed(p["pre"], x)
            h = fsdp_scan_apply(block_apply, p["blocks"], h)
            return head_loss(p["post"], h, y)
    """

    def body(h, p_i):
        return block_fn(p_i, h), None

    if remat:
        body = jax.checkpoint(body)
    h, _ = lax.scan(body, h, stacked)
    return h


def _find_stacked_subtree(params, n):
    """Heuristic detector for scanned layer-stack pytrees
    (:func:`fsdp_scan_apply` input): an internal node with >= 2 array
    leaves that all share the same leading dim ``L >= 2`` (every leaf
    ndim >= 2, at least one ndim >= 3) with ``L % n == 0`` — exactly the
    shape class where :func:`fsdp_shardings`'s first-divisible-dim rule
    would shard the LAYER dim. Returns the subtree's key path as a
    string, or ``None``."""
    from jax.tree_util import tree_flatten_with_path

    groups = {}
    for kp, leaf in tree_flatten_with_path(params)[0]:
        shp = tuple(getattr(leaf, "shape", ()))
        groups.setdefault(tuple(kp[:-1]), []).append(shp)
    for parent, shapes in groups.items():
        if len(shapes) < 2:
            continue
        if not all(len(s) >= 2 for s in shapes):
            continue
        if not any(len(s) >= 3 for s in shapes):
            continue
        heads = {s[0] for s in shapes}
        if len(heads) != 1:
            continue
        L = heads.pop()
        if L >= 2 and L % n == 0:
            return "/".join(
                str(getattr(k, "key", getattr(k, "idx", k)))
                for k in parent) or "<root>"
    return None


def make_fsdp_train_step(
    model,
    optimizer: optax.GradientTransformation,
    comm,
    params,
    loss_fn: Optional[Callable] = None,
    donate: bool = True,
    remat=False,
    param_shardings=None,
    grad_reducer=None,
    param_wire: Optional[str] = None,
) -> Tuple[Callable, Tuple]:
    """ZeRO-3 (FSDP) data-parallel train step: parameters AND optimizer
    state live sharded over the data axis; every use gathers just-in-time.

    Where ZeRO-1 hand-writes the reduce-scatter/all-gather on a flat vector,
    full parameter sharding is expressed the TPU-native way: annotate each
    leaf's sharding and let XLA's SPMD partitioner insert the per-operand
    all-gathers in the forward/backward and the reduce-scatters on the
    gradients. With ``remat`` the backward re-gathers instead of keeping
    gathered layers alive across the forward.

    MEMORY HONESTY (measured, r5): gather TIMING is the latency-hiding
    scheduler's choice, bounded by available HBM — when memory is
    abundant relative to the model, XLA prefetches all-gathers far ahead
    and the gathered layers CO-LIVE (peak ≈ shard + all gathered layers;
    slope ≈ 0.93·full-param-bytes on a v5e:2x4 AOT compile of a 12-layer
    MLP). Under real memory pressure the scheduler trades prefetch depth
    for fit, but if a GUARANTEED per-layer bound is needed — peak ≈
    shard + ONE layer — express the layer stack with
    :func:`fsdp_scan_apply` + :func:`fsdp_stack_shardings`; the scan
    body pins the bound structurally (compiled-buffer evidence in
    tests/optimizers_tests/test_zero.py).

    Per-leaf structure is preserved (unlike the ZeRO-1 flat vector), so
    structure-dependent transforms (per-layer trust ratios, masked weight
    decay) remain correct here.

    ``param_shardings``: optional per-leaf ``NamedSharding`` pytree
    overriding :func:`fsdp_shardings` (e.g. a mixed tree where the
    scanned stack uses :func:`fsdp_stack_shardings`). Optimizer-state
    leaves follow the matching param leaf's sharding by shape. Without
    it, a params tree that LOOKS like a scanned layer stack (>= 2
    sibling leaves sharing a leading dim divisible by ``comm.size``)
    raises a ``UserWarning``: the default rule would shard the layer
    dim, which silently defeats :func:`fsdp_scan_apply`'s per-layer
    liveness bound.

    ``grad_reducer``: here the GSPMD partitioner owns the gradient
    collectives (that is the point of the annotation-driven style), so
    ``'flat'``/``'hierarchical'``/``'auto'`` are the IDENTITY — the
    decomposition of the partitioner-inserted reduce-scatter is XLA's
    choice, not ours. What CAN be expressed in the global view is the
    wire-format numerics: a stateless ``QuantizedReducer(ef=False)``
    applies its quantize→dequantize round-trip to each gradient leaf
    (every rank computes the identical global scale, so the global-view
    transform equals the per-rank wire compression). Stateful reducers
    (error feedback) raise — use ``make_data_parallel_train_step``.

    ``param_wire``: compress the parameter ALL-GATHER the same way —
    ``'bf16' | 'int8-block' | 'int4-block'`` quantize each sharded leaf
    blockwise, constrain the narrow codes (plus the f32 scale sidecar)
    replicated so the partitioner's gather moves the narrow dtype, and
    dequantize at the consumer (XLA fuses it — DL205 sees a narrow
    all-gather). The optimizer still updates master-f32 shards; the
    backward is a straight-through estimator (round() has zero
    gradient), so gradients flow as if the wire were exact. ``'f32'`` /
    ``None`` keep today's uncompressed gather.

    Returns ``(step, state)`` with ``state = (params, opt_state)`` sharded;
    use :func:`fsdp_gather_params` to re-assemble for export. Models with
    mutable collections (BN stats) should use
    ``make_data_parallel_train_step``.
    """
    from jax.sharding import NamedSharding

    from chainermn_tpu.training.step import classifier_loss

    lf = loss_fn or classifier_loss
    mesh = comm.mesh
    ax = comm.axis_name

    from chainermn_tpu.collectives import make_grad_reducer

    reducer = make_grad_reducer(grad_reducer, comm, op="mean")
    if reducer is not None and reducer.stateful:
        raise ValueError(
            f"grad_reducer {reducer.name!r} is stateful (error-feedback "
            "residuals); the FSDP step has no per-rank state to thread "
            "them through. Pass QuantizedReducer(ef=False), or use "
            "make_data_parallel_train_step for error feedback.")
    quant_mode = getattr(reducer, "mode", None) if (
        reducer is not None and reducer.name == "quantized") else None

    from chainermn_tpu.collectives.quantized import (
        QUANT_BLOCK, QUANT_MODES, block_dequantize, block_quantize)

    if param_wire == "f32":
        param_wire = None
    if param_wire is not None and param_wire not in QUANT_MODES:
        raise ValueError(
            f"unknown param_wire {param_wire!r}; expected one of "
            f"{('f32',) + QUANT_MODES}")
    if param_wire == "int8":
        param_wire = "int8-block"  # single-scale int8 gather has no
        # per-tensor accumulation to protect; blockwise strictly better

    if param_shardings is None:
        stacked_at = _find_stacked_subtree(params, comm.size)
        if stacked_at is not None:
            warnings.warn(
                f"make_fsdp_train_step: params[{stacked_at}] looks like a "
                "scanned layer stack (>= 2 leaves sharing a leading dim "
                f"divisible by comm.size={comm.size}); the default "
                "fsdp_shardings rule will shard the LAYER dim, turning "
                "each scan iteration's layer slice into a cross-device "
                "gather of the slicing and defeating fsdp_scan_apply's "
                "per-layer liveness bound. Pass "
                "param_shardings=fsdp_stack_shardings(params, comm) (or a "
                "mixed tree) to shard within layers instead.",
                UserWarning, stacklevel=2)
    pshard = (param_shardings if param_shardings is not None
              else fsdp_shardings(params, comm))
    params = jax.device_put(params, pshard)
    # pin the opt-state shardings with the same per-leaf rule (param-shaped
    # leaves shard identically, scalars replicate): an unpinned
    # jit(optimizer.init) materializes the zeros on one device — the output
    # has no value dependence on the sharded inputs for XLA to propagate
    abs_opt = jax.eval_shape(optimizer.init, params)
    if param_shardings is None:
        opt_shardings = fsdp_shardings(abs_opt, comm)
    else:
        # param-shaped opt leaves (adam's mu/nu...) inherit the OVERRIDDEN
        # param sharding. Matched by TREE-PATH SUFFIX + shape — an optax
        # state embeds whole param trees, so an opt leaf's path ends with
        # its param leaf's path; matching by shape alone would collide
        # across same-shaped leaves with different shardings. Longest
        # suffix wins; no match falls back to the default rule.
        from jax.tree_util import tree_flatten_with_path

        pleaves, _ = tree_flatten_with_path(params)
        pentries = [
            (tuple(kp), tuple(jnp.shape(pl)), sl)
            for (kp, pl), sl in zip(pleaves,
                                    jax.tree_util.tree_leaves(pshard))
        ]

        def match(kp, leaf, default):
            kp = tuple(kp)
            best = None
            for pp, shp, sl in pentries:
                if (shp == tuple(leaf.shape) and len(pp) <= len(kp)
                        and kp[len(kp) - len(pp):] == pp
                        and (best is None or len(pp) > len(best[0]))):
                    best = (pp, sl)
            return best[1] if best else default

        oleaves, otree = tree_flatten_with_path(abs_opt)
        default = jax.tree_util.tree_leaves(fsdp_shardings(abs_opt, comm))
        opt_shardings = jax.tree_util.tree_unflatten(
            otree, [match(kp, l, d)
                    for (kp, l), d in zip(oleaves, default)])
    opt_state = jax.jit(optimizer.init,
                        out_shardings=opt_shardings)(params)

    dsh = NamedSharding(mesh, P(ax))
    repl = NamedSharding(mesh, P())

    def _gather_deq(v, p_spec, k):
        # quantize THIS RANK'S shard, all-gather the narrow codes (plus
        # the f32 scale sidecar), dequantize every shard and reassemble
        # — an explicit shard_map, because a replicated-output sharding
        # constraint only pins layout, not where the quantize computes:
        # GSPMD is free to (and measured: does) gather f32 first
        n = comm.size

        def local(vs):
            shp = vs.shape
            if param_wire == "bf16":
                parts = lax.all_gather(
                    vs.astype(jnp.bfloat16), ax).astype(v.dtype)
            else:
                flat = vs.reshape(-1)
                blk = math.gcd(QUANT_BLOCK, flat.size) or 1
                q, s = block_quantize(flat, param_wire, blk)
                qg = lax.all_gather(q, ax)
                sg = lax.all_gather(s, ax)
                parts = jax.vmap(
                    lambda qq, ss: block_dequantize(
                        qq, ss, flat.size, param_wire, v.dtype,
                        blk).reshape(shp))(qg, sg)
            return jnp.concatenate([parts[i] for i in range(n)], axis=k)

        return shard_map(local, mesh=mesh, in_specs=(p_spec,),
                         out_specs=P(), check_vma=False)(v)

    def _param_wire_leaf(v, sharding):
        # forward sees the dequantized wire value; backward is the
        # identity onto the master-f32 shard (straight-through — the
        # quantizer's round() has zero gradient everywhere anyway)
        p_spec = sharding.spec
        if (not jnp.issubdtype(v.dtype, jnp.floating)
                or ax not in tuple(p_spec)):
            return v  # replicated leaf: nothing travels on the gather
        k = tuple(p_spec).index(ax)

        @jax.custom_vjp
        def gather(u):
            return _gather_deq(u, p_spec, k)

        gather.defvjp(lambda u: (_gather_deq(u, p_spec, k), None),
                      lambda _, g: (g,))
        return gather(v)

    def f(p, x, y):
        if param_wire is not None:
            p = jax.tree_util.tree_map(_param_wire_leaf, p, pshard)
        loss, (acc, _) = lf(model, p, x, y, train=True)
        return loss, acc

    if remat:
        policy = None if remat is True else remat
        f = jax.checkpoint(f, policy=policy)

    def _wire_roundtrip(g):
        # global-view stand-in for the quantized wire format: identical
        # on every rank, so == quantizing each rank's shard on the wire
        if not jnp.issubdtype(g.dtype, jnp.floating):
            return g
        if quant_mode == "bf16":
            return g.astype(jnp.bfloat16).astype(g.dtype)
        if quant_mode in ("int8-block", "int4-block"):
            q, s = block_quantize(g.reshape(-1), quant_mode)
            return block_dequantize(
                q, s, g.size, quant_mode, g.dtype).reshape(g.shape)
        amax = jnp.max(jnp.abs(g))
        scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(g.dtype)
        q = jnp.clip(jnp.round(g / scale), -127, 127)
        return q.astype(g.dtype) * scale

    def local_step(state, x, y):
        p, opt_state = state
        (loss, acc), grads = jax.value_and_grad(
            f, has_aux=True)(p, x, y)
        if quant_mode is not None:
            grads = jax.tree_util.tree_map(_wire_roundtrip, grads)
        updates, opt_state = optimizer.update(grads, opt_state, p)
        p = optax.apply_updates(p, updates)
        return (p, opt_state), {"main/loss": loss, "main/accuracy": acc}

    step = jax.jit(
        local_step,
        in_shardings=((pshard, opt_shardings), dsh, dsh),
        out_shardings=((pshard, opt_shardings), repl),
        donate_argnums=(0,) if donate else (),
    )
    return step, (params, opt_state)


def fsdp_gather_params(state):
    """Re-assemble the full (host-side) parameter pytree from an FSDP
    state — for checkpointing, eval, or export."""
    import numpy as np
    from jax.sharding import NamedSharding

    params = state[0]
    leaves = jax.tree_util.tree_leaves(params)
    if leaves and not all(l.is_fully_addressable for l in leaves):
        # multi-process: shards live on other hosts — replicate first (an
        # all-gather), after which every host can read its local copy
        mesh = leaves[0].sharding.mesh
        repl = jax.tree_util.tree_map(
            lambda _: NamedSharding(mesh, P()), params)
        params = jax.jit(lambda p: p, out_shardings=repl)(params)
        leaves = jax.tree_util.tree_leaves(params)
    for l in leaves:  # batch the D2H transfers before the first wait
        if hasattr(l, "copy_to_host_async"):
            l.copy_to_host_async()
    return jax.tree_util.tree_map(lambda l: np.asarray(l), params)


def zero_layout_manifest(params, comm, bucket_bytes=None) -> dict:
    """Shard-layout metadata for checkpoint manifests: the flat-frame
    geometry of a ZeRO-1/2 state — padding quantum, world, per-bucket
    padded lengths, and the ``(n, padded)`` EF-frame shapes — so
    offline tooling (tools/ckpt.py) and the reshard planner
    (checkpointing/reshard.py) can interpret flat leaves without the
    live train step. Attach via
    ``checkpointer.set_layout(zero_layout_manifest(params, comm))``;
    pure host metadata, no device computation."""
    n = comm.size
    total = sum(int(np.prod(jnp.shape(l), initial=1))
                for l in jax.tree_util.tree_leaves(params))
    if bucket_bytes is None:
        padded = _padded_size(total, n)
        return {"kind": "zero-flat", "quantum": 256, "n": n,
                "total": total, "padded": padded,
                "ef_frames": [[n, padded]]}
    layout = _BucketLayout(params, n, bucket_bytes)
    return {"kind": "zero-bucketed", "quantum": 256, "n": n,
            "bucket_bytes": int(bucket_bytes),
            "totals": [int(t) for t in layout.totals],
            "padded": [int(p) for p in layout.padded],
            "ef_frames": [[n, int(p)] for p in layout.padded]}


def fsdp_layout_manifest(params, comm, param_shardings=None) -> dict:
    """Shard-layout metadata for FSDP states: per-leaf path, global
    shape, and partition spec under the first-divisible-dim rule (or
    the explicit ``param_shardings``). Same manifest slot as
    :func:`zero_layout_manifest` (``checkpointer.set_layout``)."""
    pshard = param_shardings if param_shardings is not None \
        else fsdp_shardings(params, comm)
    rows = []
    named = jax.tree_util.tree_flatten_with_path(params)[0]
    shardings = jax.tree_util.tree_leaves(pshard)
    for (path, leaf), sh in zip(named, shardings):
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        spec = []
        for el in tuple(getattr(sh, "spec", ()) or ()):
            spec.append(list(el) if isinstance(el, tuple)
                        else (None if el is None else str(el)))
        rows.append({"path": key,
                     "shape": [int(d) for d in jnp.shape(leaf)],
                     "spec": spec})
    return {"kind": "fsdp", "n": comm.size, "leaves": rows}

"""ZeRO-1 optimizer-state sharding over the data axis.

Beyond the reference's surface (ChainerMN replicates optimizer state on every
rank — SURVEY.md §2.5's `_MultiNodeOptimizer` wraps a whole local optimizer),
but the TPU-natural extension of the same design: the gradient all-reduce is
split into a ``psum_scatter`` (each shard receives the reduced 1/N slice of
the flat gradient), the optimizer updates only its slice of parameters and
state, and the updated parameters are re-assembled with ``all_gather``. Same
total communication volume as one all-reduce (reduce-scatter + all-gather is
how a ring all-reduce decomposes anyway — the reference's
TwoDimensionalCommunicator hand-wrote exactly this split), 1/N the optimizer
memory: Adam's m/v for ResNet-50 drop from 2x model size per chip to 2x/N.

Layout: parameters are flattened to one vector (the reference's
``_memory_utility`` flat-buffer idea, now load-bearing), padded to a multiple
of the axis size, and sharded on the leading dim. The step gathers the full
vector and unravels it; XLA schedules the gather against early-layer compute.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax, shard_map
from jax.flatten_util import ravel_pytree
from jax.sharding import PartitionSpec as P


def make_zero1_train_step(
    model,
    optimizer: optax.GradientTransformation,
    comm,
    params,
    loss_fn: Optional[Callable] = None,
    donate: bool = True,
) -> Tuple[Callable, Tuple]:
    """Build a jitted ZeRO-1 data-parallel train step and its initial state.

    Returns ``(step, state)``::

        step, state = make_zero1_train_step(model, optax.adam(1e-3), comm,
                                            params)
        state, metrics = step(state, x, y)
        params = zero1_params(state, params)   # re-assembled pytree

    ``state = (param_shard, opt_state)``: ``param_shard`` is the flat
    parameter vector sharded over the communicator axis; ``opt_state`` is the
    optimizer state over that shard (scalar leaves, e.g. step counts, stay
    replicated).

    Restrictions: the communicator must span a single mesh axis (split a
    hybrid mesh first); parameter leaves must share one dtype
    (``ravel_pytree`` concatenates them — fp32 params with bf16 *compute* is
    fine, the model casts internally); models with mutable collections (BN
    stats) should use
    :func:`~chainermn_tpu.training.step.make_data_parallel_train_step`; and
    ``optimizer`` must be element-wise (sgd/momentum/adam/adamw...). The
    update runs on the flat parameter vector, so structure-dependent
    transforms — per-layer trust ratios (LARS/LAMB), masked weight decay,
    ``multi_transform`` — would silently compute wrong updates.

    The gradient reduction op is ``mean`` (the reference's
    ``allreduce_grad`` contract); do NOT additionally wrap ``optimizer`` in
    ``create_multi_node_optimizer``.
    """
    from chainermn_tpu.training.step import classifier_loss

    lf = loss_fn or classifier_loss
    mesh = comm.mesh
    ax = comm.axis_name  # raises on multi-axis comms (single-axis only)
    n = comm.size
    axes = comm.axis_names
    dspec = P(ax)

    flat, unravel = ravel_pytree(params)
    total = flat.size
    padded = total + ((-total) % n)
    shard_shape = (padded // n,)

    # -- initial state ---------------------------------------------------
    def init_fn(params):
        v = ravel_pytree(params)[0]
        if padded != total:
            v = jnp.concatenate(
                [v, jnp.zeros((padded - total,), v.dtype)])
        i = lax.axis_index(ax)
        shard = lax.dynamic_slice_in_dim(v, i * shard_shape[0],
                                         shard_shape[0])
        return shard, optimizer.init(shard)

    abs_opt = jax.eval_shape(
        optimizer.init, jax.ShapeDtypeStruct(shard_shape, flat.dtype))
    opt_specs = jax.tree_util.tree_map(
        lambda l: P(ax) if l.shape == shard_shape else P(), abs_opt)

    state = jax.jit(shard_map(
        init_fn, mesh=mesh, in_specs=(P(),),
        out_specs=(P(ax), opt_specs), check_vma=False,
    ))(params)

    # -- the step --------------------------------------------------------
    def local_step(state, x, y):
        p_shard, opt_state = state
        full = lax.all_gather(p_shard, ax, tiled=True)
        p = unravel(full[:total])

        def f(p):
            loss, (acc, _) = lf(model, p, x, y, train=True)
            return loss, acc

        (loss, acc), grads = jax.value_and_grad(f, has_aux=True)(p)
        g = ravel_pytree(grads)[0]
        if padded != total:
            g = jnp.concatenate([g, jnp.zeros((padded - total,), g.dtype)])
        g_shard = lax.psum_scatter(g, ax, tiled=True) / n
        updates, opt_state = optimizer.update(g_shard, opt_state, p_shard)
        p_shard = optax.apply_updates(p_shard, updates)
        metrics = {
            "main/loss": lax.pmean(loss, axes),
            "main/accuracy": lax.pmean(acc, axes),
        }
        return (p_shard, opt_state), metrics

    step = jax.jit(
        shard_map(
            local_step, mesh=mesh,
            in_specs=((P(ax), opt_specs), dspec, dspec),
            out_specs=((P(ax), opt_specs), P()),
        ),
        donate_argnums=(0,) if donate else (),
    )
    return step, state


def zero1_params(state, like_params):
    """Re-assemble the full parameter pytree from a ZeRO-1 state (driver
    level — for checkpointing, eval, or export)."""
    flat, unravel = ravel_pytree(like_params)
    full = jnp.asarray(state[0]).reshape(-1)[: flat.size]
    return unravel(full)

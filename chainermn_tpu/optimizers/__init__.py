"""Multi-node optimizer wrapper.

Reference: chainermn/optimizers/__init__.py (SURVEY.md §2.5; mount empty —
module path citation). ``create_multi_node_optimizer(opt, comm)`` wraps any
Chainer optimizer so ``update()`` runs ``communicator.allreduce_grad(model)``
between backward and the inner update, and ``setup()`` broadcasts initial
parameters. ``double_buffering=True`` overlaps step t-1's communication with
step t's compute at the cost of one-step-stale gradients
(``_DoubleBufferingOptimizer``).

TPU-native form: the wrapper is an :class:`optax.GradientTransformation`
whose ``update`` inserts the gradient all-reduce *inside the compiled step* —
XLA's latency-hiding scheduler then overlaps the collective with adjacent
compute automatically, which is what the reference's double-buffering thread
approximated by hand. The stale-gradient mode is still available as an
explicit opt-in (same accuracy caveats as the reference).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import optax

from chainermn_tpu.comm.base import CommunicatorBase
from chainermn_tpu.optimizers.zero import (  # noqa: F401
    fsdp_gather_params,
    fsdp_scan_apply,
    fsdp_shardings,
    fsdp_stack_shardings,
    make_fsdp_train_step,
    make_zero1_train_step,
    make_zero2_train_step,
    zero1_params,
)


class _DoubleBufferState(NamedTuple):
    inner: Any
    prev_grads: Any  # step t-1's reduced grads (applied this step)
    is_first: Any    # scalar flag; first step applies zeros


def create_multi_node_optimizer(
    actual_optimizer: optax.GradientTransformation,
    communicator: CommunicatorBase,
    double_buffering: bool = False,
    op: str = "mean",
) -> optax.GradientTransformation:
    """Wrap an optax optimizer with the gradient all-reduce.

    Use exactly like the inner optimizer::

        opt = create_multi_node_optimizer(optax.adam(1e-3), comm)
        state = opt.init(params)              # inside or outside jit
        updates, state = opt.update(grads, state, params)  # inside the step

    ``update`` must run inside the jitted (shard_map/pjit) training step so
    the all-reduce compiles into the program. ``allreduce_grad`` is
    varying-axis-aware (see XlaCommunicator.allreduce_grad), so this is safe
    both when autodiff already summed the gradients and when it did not.
    """
    if not double_buffering:

        def init(params):
            return actual_optimizer.init(params)

        def update(grads, state, params=None, **extra):
            grads = communicator.allreduce_grad(grads, op)
            return actual_optimizer.update(grads, state, params, **extra)

        return optax.GradientTransformation(init, update)

    import jax.numpy as jnp

    def init_db(params):
        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        return _DoubleBufferState(
            inner=actual_optimizer.init(params),
            prev_grads=zeros,
            is_first=jnp.array(True),
        )

    def update_db(grads, state, params=None, **extra):
        # Reference semantics (_DoubleBufferingOptimizer): apply step t-1's
        # reduced grads while step t's reduction is in flight; first step
        # applies nothing. In one compiled program "in flight" is the XLA
        # scheduler's overlap; the visible semantic is the one-step lag.
        reduced = communicator.allreduce_grad(grads, op)
        apply = jax.tree_util.tree_map(
            lambda p: jnp.where(state.is_first, jnp.zeros_like(p), p),
            state.prev_grads,
        )
        updates, inner = actual_optimizer.update(apply, state.inner, params, **extra)
        return updates, _DoubleBufferState(
            inner=inner, prev_grads=reduced, is_first=jnp.array(False)
        )

    return optax.GradientTransformation(init_db, update_db)

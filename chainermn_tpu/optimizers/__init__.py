"""Multi-node optimizer wrapper.

Reference: chainermn/optimizers/__init__.py (SURVEY.md §2.5; mount empty —
module path citation). ``create_multi_node_optimizer(opt, comm)`` wraps any
Chainer optimizer so ``update()`` runs ``communicator.allreduce_grad(model)``
between backward and the inner update, and ``setup()`` broadcasts initial
parameters. ``double_buffering=True`` overlaps step t-1's communication with
step t's compute at the cost of one-step-stale gradients
(``_DoubleBufferingOptimizer``).

TPU-native form: the wrapper is an :class:`optax.GradientTransformation`
whose ``update`` inserts the gradient all-reduce *inside the compiled step* —
XLA's latency-hiding scheduler then overlaps the collective with adjacent
compute automatically, which is what the reference's double-buffering thread
approximated by hand. The stale-gradient mode is still available as an
explicit opt-in (same accuracy caveats as the reference).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import optax

from chainermn_tpu.comm.base import CommunicatorBase
from chainermn_tpu.optimizers.zero import (  # noqa: F401
    fsdp_gather_params,
    fsdp_layout_manifest,
    fsdp_scan_apply,
    fsdp_shardings,
    fsdp_stack_shardings,
    make_fsdp_train_step,
    make_zero1_train_step,
    make_zero2_train_step,
    zero1_params,
    zero_layout_manifest,
)


class _DoubleBufferState(NamedTuple):
    inner: Any
    prev_grads: Any  # step t-1's reduced grads (applied this step)
    is_first: Any    # scalar flag; first step applies zeros


class _ReducerWrappedState(NamedTuple):
    """Optimizer state carrying explicit reducer state (error-feedback
    residuals) alongside the inner optimizer's. Only STATEFUL reducers
    introduce this wrapper — the default/stateless paths keep the inner
    state layout byte-for-byte, so existing checkpoints stay valid.

    Inside the compiled step ``reducer`` holds the per-rank view; at the
    driver level it holds the per-rank states stacked on a leading
    ``comm.size`` axis (``make_data_parallel_train_step`` shards and
    (un)stacks it around the update — the residuals are genuinely
    per-rank data, unlike the replicated inner state)."""

    inner: Any
    reducer: Any


class MultiNodeOptimizer(NamedTuple):
    """Duck-types :class:`optax.GradientTransformation` (same
    ``init``/``update`` fields — optax composes by duck typing) while
    exposing the bound :class:`~chainermn_tpu.collectives.GradReducer`
    so step factories can shard its state, and the tuned
    :class:`~chainermn_tpu.tuning.profile_db.SchedulePlan` (when
    ``tune=`` chose the knobs) so reports/benches can log what the
    tuner picked."""

    init: Any
    update: Any
    grad_reducer: Any = None
    plan: Any = None


def create_multi_node_optimizer(
    actual_optimizer: optax.GradientTransformation,
    communicator: CommunicatorBase,
    double_buffering: bool = False,
    op: str = "mean",
    grad_reducer: Any = None,
    tune: Any = None,
    model_key: Optional[str] = None,
    wire_format: Optional[str] = None,
    topology: Any = None,
) -> optax.GradientTransformation:
    """Wrap an optax optimizer with the gradient all-reduce.

    Use exactly like the inner optimizer::

        opt = create_multi_node_optimizer(optax.adam(1e-3), comm)
        state = opt.init(params)              # inside or outside jit
        updates, state = opt.update(grads, state, params)  # inside the step

    ``update`` must run inside the jitted (shard_map/pjit) training step so
    the all-reduce compiles into the program. ``allreduce_grad`` is
    varying-axis-aware (see XlaCommunicator.allreduce_grad), so this is safe
    both when autodiff already summed the gradients and when it did not.

    ``grad_reducer`` selects the reduction strategy (the reference's
    communicator-zoo axis, docs/collectives.md): ``None`` (default) and
    ``'flat'`` are today's psum — bit-identical; ``'hierarchical'``,
    ``'quantized'``, ``'auto'``, or a constructed
    :class:`~chainermn_tpu.collectives.GradReducer` instance select the
    two-level, error-feedback-quantized, or cost-model strategies. A
    STATEFUL reducer (quantized with error feedback) changes the state
    layout to :class:`_ReducerWrappedState` and must be initialized at
    the driver level (``opt.init(params)`` outside jit) — the residuals
    are per-rank and ride the optimizer state through the step and
    through checkpoints.

    ``tune`` injects a schedtune profile (docs/tuning.md): a
    :class:`~chainermn_tpu.tuning.profile_db.SchedulePlan`, a
    :class:`~chainermn_tpu.tuning.profile_db.ProfileDB`, a DB path, or
    ``True`` for the default DB location. The stored plan's strategy /
    ``bucket_bytes`` / ``bucket_order`` build the reducer (unless an
    explicit ``grad_reducer`` was also passed, which wins) and its
    ``double_buffering`` flag ORs into ``double_buffering``.
    ``model_key`` selects among plans stored for several model shapes
    (see ``tuning.model_key_for``; ``None`` accepts a sole/default
    plan). A plan whose topology fingerprint does not match this
    communicator's mesh raises ``ValueError`` — the wrong-machine
    profile bug dlint DL107 flags statically.

    ``wire_format`` selects the compressed wire
    (docs/collectives.md#quantized-wire-formats): ``'bf16' | 'int8' |
    'int8-block' | 'int4-block'`` are forwarded to the reducer being
    built (from a name or a tuned plan); an explicit value overrides a
    tuned plan's recorded format. ``'f32'``/``None`` keep the strategy's
    own default. Refused (ValueError) when the resolved strategy cannot
    compress — same rule as ``make_grad_reducer``.

    ``topology`` supplies the explicit
    :class:`~chainermn_tpu.tuning.topology.Topology` the ``tune`` plan
    was produced for, instead of the ``Topology.from_comm`` inference.
    Required when the plan was tuned for a tier decomposition the mesh
    does not expose (e.g. a factored ``(inter, intra)`` view of a
    single-axis mesh — synthesized programs carry their ``tier_sizes``
    and are rebuilt against this decomposition). Its total rank count
    must match the communicator.
    """
    from chainermn_tpu.collectives import make_grad_reducer

    plan = None
    if tune is not None:
        from chainermn_tpu.tuning import ProfileDB, SchedulePlan, Topology

        if topology is not None:
            if topology.n != communicator.size:
                raise ValueError(
                    f"explicit topology has {topology.n} ranks but the "
                    f"communicator has {communicator.size}")
            topo = topology
        else:
            topo = Topology.from_comm(communicator)
        if isinstance(tune, SchedulePlan):
            plan = tune
        else:
            db = tune if isinstance(tune, ProfileDB) else ProfileDB(
                tune if isinstance(tune, str) else None)
            plan = db.plan_for(topo, model_key)
            if plan is None:
                raise ValueError(
                    f"no tuned schedule for topology "
                    f"{topo.fingerprint()!r} (model_key={model_key!r}) "
                    f"in profile DB {db.path!r}; run tools/schedtune.py "
                    "on this machine first")
        if plan.fingerprint and plan.fingerprint != topo.fingerprint():
            raise ValueError(
                f"stale schedule profile: plan was tuned for "
                f"{plan.fingerprint!r} but this mesh is "
                f"{topo.fingerprint()!r} — wrong-machine profiles "
                "silently mis-tune (dlint DL107); re-run "
                "tools/schedtune.py here")
        if grad_reducer is None:
            wf = wire_format or getattr(plan, "wire_format", None)
            extra = {}
            if getattr(plan, "program", None) is not None:
                extra["program"] = plan.program  # 'synth' plans only
            grad_reducer = make_grad_reducer(
                plan.strategy, communicator, op=op,
                bucket_bytes=plan.bucket_bytes,
                bucket_order=plan.bucket_order,
                wire_format=wf, **extra)
        double_buffering = bool(double_buffering or plan.double_buffering)

    if isinstance(grad_reducer, str):
        reducer = make_grad_reducer(grad_reducer, communicator, op=op,
                                    wire_format=wire_format)
    else:
        if wire_format not in (None, "f32") and grad_reducer is None:
            raise ValueError(
                f"wire_format={wire_format!r} needs a compressing "
                "grad_reducer ('quantized' or 'auto'); the default flat "
                "psum carries f32")
        reducer = make_grad_reducer(grad_reducer, communicator, op=op)
    stateful = bool(reducer is not None and reducer.stateful)

    if reducer is None:
        def reduce_fn(grads, rstate):
            return communicator.allreduce_grad(grads, op), rstate
    else:
        reduce_fn = reducer.reduce

    import jax.numpy as jnp

    if not double_buffering:

        def inner_init(params):
            return actual_optimizer.init(params)

        def inner_update(grads, state, params=None, **extra):
            # state here is the INNER state; grads are already reduced
            return actual_optimizer.update(grads, state, params, **extra)

    else:

        def inner_init(params):
            zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
            return _DoubleBufferState(
                inner=actual_optimizer.init(params),
                prev_grads=zeros,
                is_first=jnp.array(True),
            )

        def inner_update(reduced, state, params=None, **extra):
            # Reference semantics (_DoubleBufferingOptimizer): apply step
            # t-1's reduced grads while step t's reduction is in flight;
            # first step applies nothing. In one compiled program "in
            # flight" is the XLA scheduler's overlap; the visible
            # semantic is the one-step lag.
            apply = jax.tree_util.tree_map(
                lambda p: jnp.where(state.is_first, jnp.zeros_like(p), p),
                state.prev_grads,
            )
            updates, inner = actual_optimizer.update(
                apply, state.inner, params, **extra)
            return updates, _DoubleBufferState(
                inner=inner, prev_grads=reduced, is_first=jnp.array(False)
            )

    if not stateful:

        def init(params):
            return inner_init(params)

        def update(grads, state, params=None, **extra):
            grads, _ = reduce_fn(grads, ())
            return inner_update(grads, state, params, **extra)

        if reducer is None:
            return optax.GradientTransformation(init, update)
        return MultiNodeOptimizer(init, update, reducer, plan)

    def init_st(params):
        return _ReducerWrappedState(
            inner=inner_init(params),
            reducer=reducer.init_global(params),
        )

    def update_st(grads, state, params=None, **extra):
        grads, rstate = reduce_fn(grads, state.reducer)
        updates, inner = inner_update(grads, state.inner, params, **extra)
        return updates, _ReducerWrappedState(inner=inner, reducer=rstate)

    return MultiNodeOptimizer(init_st, update_st, reducer, plan)

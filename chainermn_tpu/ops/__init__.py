from chainermn_tpu.ops.autotune import tune_flash_blocks
from chainermn_tpu.ops.flash_attention import flash_attention
from chainermn_tpu.ops.fused_ce import fused_ce_head, fused_lm_loss
from chainermn_tpu.ops.rotary import apply_rope, rope_angles

__all__ = [
    "flash_attention",
    "fused_ce_head",
    "fused_lm_loss",
    "tune_flash_blocks",
    "apply_rope",
    "rope_angles",
]

"""Rotary position embeddings (RoPE).

Pure elementwise XLA op — fuses into the surrounding projections, no
kernel needed. Split-half convention: the head dim is split into two
halves rotated against each other (the convention used by most open
models; equivalent to interleaved up to a fixed permutation of the head
dim, which the attention dot products cancel).
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_angles(positions, dim: int, theta: float = 10000.0):
    """positions [...]: int/float → (cos, sin) of shape [..., dim // 2]."""
    half = dim // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x, positions, theta: float = 10000.0):
    """x: [B, L, H, D] (D even); positions: [L] or [B, L] global indices.

    Returns x with each head's (first-half, second-half) pairs rotated by
    the position angle — computed in f32, cast back to x.dtype.
    """
    d = x.shape[-1]
    cos, sin = rope_angles(jnp.asarray(positions), d, theta)
    while cos.ndim < x.ndim - 1:  # broadcast over batch and/or heads
        cos, sin = cos[None], sin[None]
    cos = jnp.expand_dims(cos, -2)  # head axis
    sin = jnp.expand_dims(sin, -2)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)
    return out.astype(x.dtype)


def apply_rope_bhld(x, positions, theta: float = 10000.0):
    """Head-major variant: x [B, H, L, D]; positions [L] or [B, L]. Same
    rotation as :func:`apply_rope` with the L axis at position 2 (the
    pivot-free attention layout — ops/flash_attention.py
    ``layout='bhld'``)."""
    d = x.shape[-1]
    cos, sin = rope_angles(jnp.asarray(positions), d, theta)
    if cos.ndim == 3:                             # [B, L, D/2] → head axis
        cos, sin = cos[:, None], sin[:, None]
    else:
        cos, sin = cos[None, None], sin[None, None]  # [1, 1, L, D/2]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)
    return out.astype(x.dtype)

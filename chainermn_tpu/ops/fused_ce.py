"""Fused LM-head + softmax cross-entropy: logits never touch HBM.

The classic LM loss materializes [B·L, V] f32 logits (2 GB at the bench
shape) and round-trips them ~6× through HBM (head fwd write, CE read,
argmax-metric read, softmax recompute, dlogits write+read) — ~12 GB/step,
measured ~15-18 ms of the 155 ms step (docs/lm_roofline.md §1-2). This
module computes the head matmul and the cross-entropy TOGETHER, flash-
attention style:

* **forward**: grid (row-tile, vocab-tile); each [R, VT] logits tile
  lives only in VMEM; online running max / sum-exp / target-logit /
  argmax accumulate per row. Outputs are O(B·L): lse, target logit,
  argmax. HBM traffic = read h + read W once.
* **backward**: dlogits_ij = (softmax_ij − onehot_ij)·c is rebuilt per
  tile from the forward's lse (the flash trick). Like flash's dq vs
  dk/dv, the two parameter cotangents accumulate across DIFFERENT grid
  dims, so two passes: dh (rows outer, vocab inner — [R, D] scratch) and
  dW (vocab outer, rows inner — [D, VT] scratch). Each pass re-runs the
  head matmul once; matmul FLOPs total 3× the naive head's fwd+bwd 3× —
  identical — while logits HBM traffic disappears.

Numerics: logits accumulate in f32 (MXU native-dtype dots), the
softmax/lse math is f32 throughout — same as the unfused
`optax.softmax_cross_entropy_with_integer_labels` on f32 logits.

Reference analog: none (upstream seq2seq computes full softmax CE);
this is the TPU-native counterpart of the vocab-parallel CE idea applied
to the single-chip memory axis instead of the model-parallel axis.

MEASURED (v5e, 2026-07-31, bench_lm config): throughput-NEUTRAL —
105.4k tok/s fused vs 104.7k unfused at L=2048/b=8; 56.8k vs 56.7k at
L=8192/b=2. XLA's own CE fusion already avoids most of the naive
round-trips, so the win is MEMORY, not time: the [B·L, V] f32 buffer
(2 GB at the bench shape) disappears from the activation footprint.
Use it when logits memory is the binding constraint (big vocab, long L,
grad accumulation); the default losses stay unfused.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from chainermn_tpu.ops.flash_attention import _dimsem, _sds

_NEG = -1e30
# rows-outer passes accumulate across the vocab (innermost) dim only →
# rows can stay 'parallel'; the dW pass accumulates across rows with
# vocab outer, so both its dims must be 'arbitrary'-safe
_DIMSEM_ROWS = _dimsem(("parallel", "arbitrary"))
_DIMSEM_DW = _dimsem(("arbitrary", "arbitrary"))


def _fwd_kernel(h_ref, w_ref, y_ref, lse_ref, tl_ref, am_ref,
                m_acc, s_acc, t_acc, a_acc, *, vt, nv):
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        m_acc[:] = jnp.full_like(m_acc, _NEG)
        s_acc[:] = jnp.zeros_like(s_acc)
        t_acc[:] = jnp.zeros_like(t_acc)
        a_acc[:] = jnp.zeros_like(a_acc)

    logits = jax.lax.dot_general(
        h_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)        # [R, VT]
    m_prev = m_acc[:, :1]
    m_cur = jnp.max(logits, -1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    s_acc[:, :1] = s_acc[:, :1] * alpha + jnp.sum(
        jnp.exp(logits - m_new), -1, keepdims=True)
    m_acc[:, :1] = m_new
    # target logit: the tile holding each row's label contributes it
    y_loc = y_ref[...] - vi * vt                    # [R, 1]
    cols = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    hit = cols == y_loc
    t_acc[:, :1] += jnp.sum(jnp.where(hit, logits, 0.0), -1,
                            keepdims=True)
    # running argmax (metric): strictly-greater keeps the FIRST max,
    # matching jnp.argmax tie-breaking
    better = m_cur > m_prev
    # first-match argmax without lax.argmax (Mosaic-safe): the smallest
    # column index attaining the tile max
    is_max = logits == m_cur
    arg_cur = vi * vt + jnp.min(
        jnp.where(is_max, cols, jnp.int32(2 ** 30)), -1, keepdims=True)
    a_acc[:, :1] = jnp.where(better, arg_cur.astype(jnp.float32),
                             a_acc[:, :1])

    @pl.when(vi == nv - 1)
    def _fin():
        lse_ref[...] = m_acc[:, :1] + jnp.log(s_acc[:, :1])
        tl_ref[...] = t_acc[:, :1]
        am_ref[...] = a_acc[:, :1]


def _dh_kernel(h_ref, w_ref, y_ref, lse_ref, dh_ref, dh_acc, *, vt, nv):
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        dh_acc[:] = jnp.zeros_like(dh_acc)

    logits = jax.lax.dot_general(
        h_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    p = jnp.exp(logits - lse_ref[...])              # softmax tile
    y_loc = y_ref[...] - vi * vt
    cols = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    dl = p - jnp.where(cols == y_loc, 1.0, 0.0)     # [R, VT]
    dh_acc[:] += jax.lax.dot_general(
        dl.astype(w_ref.dtype), w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)         # [R, D]

    @pl.when(vi == nv - 1)
    def _fin():
        dh_ref[...] = dh_acc[:].astype(dh_ref.dtype)


def _dw_kernel(h_ref, w_ref, y_ref, lse_ref, dw_ref, dw_acc, *, vt, nr):
    vi = pl.program_id(0)
    ri = pl.program_id(1)

    @pl.when(ri == 0)
    def _init():
        dw_acc[:] = jnp.zeros_like(dw_acc)

    logits = jax.lax.dot_general(
        h_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    p = jnp.exp(logits - lse_ref[...])
    y_loc = y_ref[...] - vi * vt
    cols = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    dl = p - jnp.where(cols == y_loc, 1.0, 0.0)
    dw_acc[:] += jax.lax.dot_general(
        h_ref[...], dl.astype(h_ref.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)         # [D, VT]

    @pl.when(ri == nr - 1)
    def _fin():
        dw_ref[...] = dw_acc[:].astype(dw_ref.dtype)


def _pad_rows_to(x, n, fill=0):
    if x.shape[0] == n:
        return x
    pad = [(0, n - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, pad, constant_values=fill)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_ce_head(h, w, y, block_rows: int = 256, block_v: int = 2048):
    """``mean CE( h @ w , y )`` + argmax accuracy, logits never in HBM.

    h: [N, D] (bf16/f32 hidden states, rows = flattened B·L tokens);
    w: [D, V] head kernel; y: [N] int32 labels in [0, V).
    Returns ``(loss, acc)`` — scalars, differentiable w.r.t. h and w
    (y gets no gradient). Rows are padded internally to the block size;
    padded rows are masked out of both loss and accuracy.

    Under shard_map's varying-axis tracking, a REPLICATED head kernel
    next to batch-varying hidden states would fail the kernel's dot with
    mixed vma operands; ``_fwd`` pcasts ``w`` to ``h``'s varying axes
    (inside ``_fwd`` — the custom_vjp PRIMAL body is swapped for
    ``_fwd_rule`` under differentiation, so a pcast here would never run
    on a training path; ``_fwd`` is shared by both, and the pcast ``w``
    rides the residuals into ``_bwd_rule``). The compiled TPU path then
    runs fine inside shard_map (bench.py's gated LM config is exactly
    that); the INTERPRET-mode fallback still trips on kernel-internal
    constants under check_vma — on the CPU mesh, call it outside
    shard_map or with check_vma=False.
    """
    loss, acc, _ = _fwd(h, w, y, block_rows, block_v)
    return loss, acc


def _run_fwd(h, w, y, block_rows, block_v, interpret):
    n, d = h.shape
    v = w.shape[1]
    nr, nv = n // block_rows, v // block_v
    row = lambda r, vi: (r, 0)
    out_row = pl.BlockSpec((block_rows, 1), row, memory_space=pltpu.VMEM)
    lse, tl, am = pl.pallas_call(
        functools.partial(_fwd_kernel, vt=block_v, nv=nv),
        grid=(nr, nv),
        in_specs=[
            pl.BlockSpec((block_rows, d), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((d, block_v), lambda r, vi: (0, vi),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_rows, 1), row,
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(out_row, out_row, out_row),
        out_shape=(_sds(h, (n, 1), jnp.float32, w, y),
                   _sds(h, (n, 1), jnp.float32, w, y),
                   _sds(h, (n, 1), jnp.float32, w, y)),
        scratch_shapes=[pltpu.VMEM((block_rows, 128), jnp.float32)] * 4,
        interpret=interpret,
        compiler_params=_DIMSEM_ROWS,
    )(h, w, y)
    return lse, tl, am


def _fwd(h, w, y, block_rows, block_v):
    from chainermn_tpu.utils import match_vma

    interpret = jax.default_backend() != "tpu"
    w = match_vma(w, h)  # shard_map vma alignment (see fused_ce_head)
    n0, d = h.shape
    v = w.shape[1]
    if v % block_v:
        raise ValueError(f"vocab {v} must be a multiple of block_v "
                         f"{block_v}")
    n = -(-n0 // block_rows) * block_rows
    hp = _pad_rows_to(h, n)
    # padded labels point at column 0; their rows are masked below
    yp = _pad_rows_to(jnp.asarray(y, jnp.int32).reshape(-1, 1), n)
    lse, tl, am = _run_fwd(hp, w, yp, block_rows, block_v, interpret)
    valid = (jnp.arange(n) < n0)[:, None]
    per_tok = jnp.where(valid, lse - tl, 0.0)
    loss = jnp.sum(per_tok) / n0
    acc = jnp.sum(jnp.where(
        valid, (am == yp.astype(jnp.float32)).astype(jnp.float32),
        0.0)) / n0
    return loss, acc, (hp, w, yp, lse, n0)


def _fwd_rule(h, w, y, block_rows, block_v):
    loss, acc, res = _fwd(h, w, y, block_rows, block_v)
    return (loss, acc), res


def _bwd_rule(block_rows, block_v, res, g):
    dloss = g[0]  # d(acc) is discarded — a metric, not an objective
    hp, w, yp, lse, n0 = res
    interpret = jax.default_backend() != "tpu"
    n, d = hp.shape
    v = w.shape[1]
    nr, nv = n // block_rows, v // block_v
    # padded rows must contribute zero: poison their labels to -1 (no
    # onehot hit) AND zero their dl via lse -> +inf (softmax tile = 0)
    valid = (jnp.arange(n) < n0)[:, None]
    lse_b = jnp.where(valid, lse, jnp.float32(3e38))
    yb = jnp.where(valid, yp, -1)

    row = lambda r, vi: (r, 0)
    dh = pl.pallas_call(
        functools.partial(_dh_kernel, vt=block_v, nv=nv),
        grid=(nr, nv),
        in_specs=[
            pl.BlockSpec((block_rows, d), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((d, block_v), lambda r, vi: (0, vi),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_rows, 1), row,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_rows, 1), row, memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, d), row,
                               memory_space=pltpu.VMEM),
        out_shape=_sds(hp, (n, d), hp.dtype, w, yb, lse_b),
        scratch_shapes=[pltpu.VMEM((block_rows, d), jnp.float32)],
        interpret=interpret,
        compiler_params=_DIMSEM_ROWS,
    )(hp, w, yb, lse_b)

    # the dW pass holds a [D, VT] f32 scratch PLUS the [D, VT] weight
    # tile and [R, VT] recompute intermediates — at D=768/VT=2048 that
    # exceeds scoped VMEM in-program; halve its vocab tile independently.
    # The halved tile must still DIVIDE the vocab (a remainder would
    # leave the tail dW columns unwritten — silent gradient corruption);
    # when it doesn't, fall back to block_v itself, which _fwd already
    # validated — correct at a higher VMEM cost
    bv_dw = min(block_v, 1024)
    if v % bv_dw:
        bv_dw = block_v
    nv_dw = v // bv_dw
    dw = pl.pallas_call(
        functools.partial(_dw_kernel, vt=bv_dw, nr=nr),
        grid=(nv_dw, nr),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda vi, r: (r, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((d, bv_dw), lambda vi, r: (0, vi),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_rows, 1), lambda vi, r: (r, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_rows, 1), lambda vi, r: (r, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((d, bv_dw), lambda vi, r: (0, vi),
                               memory_space=pltpu.VMEM),
        out_shape=_sds(w, (d, v), w.dtype, hp, yb, lse_b),
        scratch_shapes=[pltpu.VMEM((d, bv_dw), jnp.float32)],
        interpret=interpret,
        compiler_params=_DIMSEM_DW,
    )(hp, w, yb, lse_b)

    c = dloss / n0
    return ((dh[:n0] * c).astype(hp.dtype), (dw * c).astype(w.dtype),
            None)


fused_ce_head.defvjp(_fwd_rule, _bwd_rule)


def fused_lm_loss(model, params, x, y, train=True, mutable=None,
                  extra_vars=None, rngs=None,
                  block_rows: int = 256, block_v: int = 2048):
    """Drop-in for ``lm_loss_with_aux`` on plain (non-TP-head, non-MoE)
    TransformerLM models: the [B, L, vocab] logits never materialize.
    Step-factory signature — use as ``loss_fn`` in
    ``make_data_parallel_train_step``."""
    if getattr(model, "moe_experts_per_device", 0):
        raise ValueError(
            "fused_lm_loss drops the MoE load-balancing aux (the 'losses' "
            "collection is not made mutable here) — experts would collapse "
            "silently; use lm_loss_with_aux for MoE models")
    if mutable:
        raise ValueError(
            "fused_lm_loss does not thread mutable collections through "
            f"apply (mutable={mutable!r} would be silently dropped); use "
            "lm_loss_with_aux for models with mutable state")
    del train  # TransformerLM has no train-dependent state (no dropout/BN)
    variables = {"params": params, **(extra_vars or {})}
    hidden = model.clone(return_hidden=True).apply(
        variables, x, rngs=rngs)                    # [B, L, D]
    b, l, d = hidden.shape
    w = params["lm_head"]["kernel"].astype(hidden.dtype)
    loss, acc = fused_ce_head(
        hidden.reshape(b * l, d), w,
        jnp.asarray(y, jnp.int32).reshape(-1), block_rows, block_v)
    return loss, (acc, {})

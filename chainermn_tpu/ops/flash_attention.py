"""Pallas flash attention — the fused hot-op kernel.

Reference parity note: the reference's only custom device kernels are CuPy
cast/pack elementwise kernels (SURVEY.md §2.2); XLA already fuses those here.
The kernel worth hand-writing on TPU is blockwise attention: one pass over
K/V tiles in VMEM with online softmax, never materializing the [L, L] score
matrix in HBM. Usable standalone; ring attention
(chainermn_tpu/parallel/ring_attention.py) currently uses its own XLA
blockwise compute and can adopt this kernel as the per-block inner loop.

Layout: [B, L, H, D] → kernel works on [B*H, L, D]. Grid is
(batch*heads, q_blocks, kv_blocks) with the kv dimension innermost; VMEM
scratch (acc, rowmax, rowsum) persists across the kv iteration of one
(bh, q_block) and is finalized on the last kv step. Causal masking compares
global row/col indices and skips fully-masked tiles.

Backward runs through a custom VJP that recomputes attention with the XLA
reference implementation — standard rematerialization (the bwd is
memory-bound anyway; fwd is where the fusion pays).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30  # finite stand-in: -inf breaks max/exp chains on the VPU


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, acc, mrow, lrow, *, scale,
               causal, bq, bk, nk):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        mrow[:] = jnp.full_like(mrow, _NEG_INF)
        lrow[:] = jnp.zeros_like(lrow)

    # causal: tile fully above the diagonal contributes nothing
    run = True
    if causal:
        run = qi * bq + bq - 1 >= ki * bk  # last q row sees first k col?

    @pl.when(run if causal else True)
    def _compute():
        q = q_ref[0].astype(jnp.float32)          # [bq, d]
        k = k_ref[0].astype(jnp.float32)          # [bk, d]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                  # [bq, bk]
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            mask = (qi * bq + rows) >= (ki * bk + cols)
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = mrow[:, :1]                       # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                     # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)            # [bq, 1]
        lrow[:, :1] = lrow[:, :1] * alpha + jnp.sum(p, -1, keepdims=True)
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        mrow[:, :1] = m_new

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[0] = (acc[:] / jnp.maximum(lrow[:, :1], 1e-30)).astype(
            o_ref.dtype)


def _flash_fwd_3d(q, k, v, *, causal, scale, block_q, block_k, interpret):
    """q: [BH, Lq, D]; k, v: [BH, Lk, D] → [BH, Lq, D]."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    bq = min(block_q, lq)
    bk = min(block_k, lk)
    assert lq % bq == 0 and lk % bk == 0, (
        f"sequence lengths ({lq}, {lk}) must be divisible by the block "
        f"sizes ({bq}, {bk})")
    nk = lk // bk

    kernel = functools.partial(
        _fa_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nk=nk)
    grid = (bh, lq // bq, nk)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, qi, ki: (b, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, qi, ki: (b, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, qi, ki: (b, ki, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, qi, ki: (b, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),     # acc
            pltpu.VMEM((bq, 128), jnp.float32),   # running max (col 0)
            pltpu.VMEM((bq, 128), jnp.float32),   # running sum (col 0)
        ],
        interpret=interpret,
    )(q, k, v)


def _reference(q, k, v, causal, scale):
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        mask = jnp.arange(lk)[None, :] <= jnp.arange(lq)[:, None]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None):
    """Fused blockwise attention. q, k, v: [B, L, H, D] → [B, Lq, H, D].

    ``interpret=None`` auto-selects: the Pallas interpreter off-TPU (tests),
    the compiled kernel on TPU.
    """
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)[0]


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    b, lq, h, d = q.shape
    lk = k.shape[1]
    to3 = lambda x, l: jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, l, x.shape[-1])
    out3 = _flash_fwd_3d(
        to3(q, lq), to3(k, lk), to3(v, lk),
        causal=causal, scale=scale, block_q=block_q, block_k=block_k,
        interpret=interpret)
    out = jnp.transpose(out3.reshape(b, h, lq, d), (0, 2, 1, 3))
    return out, (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    # rematerialized backward through the XLA reference (fwd owns the fusion
    # win; bwd recompute is the standard flash trade)
    q, k, v = res
    sc = scale if scale is not None else q.shape[-1] ** -0.5
    _, vjp = jax.vjp(lambda q, k, v: _reference(q, k, v, causal, sc), q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)

"""Pallas flash attention — the fused hot-op kernel.

Reference parity note: the reference's only custom device kernels are CuPy
cast/pack elementwise kernels (SURVEY.md §2.2); XLA already fuses those here.
The kernel worth hand-writing on TPU is blockwise attention: one pass over
K/V tiles in VMEM with online softmax, never materializing the [L, L] score
matrix in HBM. Supports GQA/MQA (index-map KV-head sharing), segment-id
packing, sliding windows, and automatic padding for TPU-illegal lengths.
Usable standalone; `ring_flash_attention`
(chainermn_tpu/parallel/ring_attention.py) runs these kernels as the
per-block inner loop of the sequence-parallel ring.

Layout: [B, L, H, D] → kernel works on [B*H, L, D]. Grid is
(batch*heads, q_blocks, kv_blocks) with the kv dimension innermost; VMEM
scratch (acc, rowmax, rowsum) persists across the kv iteration of one
(bh, q_block) and is finalized on the last kv step. Causal masking compares
global row/col indices and skips fully-masked tiles.

Backward is a pair of Pallas kernels (FlashAttention-2 style): the forward
saves only O and the per-row logsumexp; dq (kv-innermost grid) and dk/dv
(q-innermost grid) rebuild each P tile as exp(S − lse) and accumulate in
VMEM scratch, so the [L, L] score matrix never exists in HBM in either
direction.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30  # finite stand-in: -inf breaks max/exp chains on the VPU

# tuned default tile sizes (v5e, 2026-07-30 sweep — BASELINE.md); clamped
# to legal divisors of L per call, so they are safe for any length. The
# single source of truth: models/parallel wrappers import this.
DEFAULT_BLOCKS = (1024, 1024)


def _dimsem(dims=("parallel", "parallel", "arbitrary")):
    """Grid dims (batch*heads, tile, tile): the first two are independent,
    only the innermost accumulates — declaring this lets Mosaic pipeline
    the HBM block copies across grid steps instead of serializing
    copy→compute. None when the API is unavailable."""
    for cls_name in ("CompilerParams", "TPUCompilerParams"):
        cls = getattr(pltpu, cls_name, None)
        if cls is not None:
            try:
                return cls(dimension_semantics=dims)
            except Exception:
                continue
    return None


_DIMSEM = _dimsem()
# the fused backward accumulates dk/dv scratch ACROSS the qi grid dim
# (init at qi==0, flush at qi==nq-1) — a 'parallel' qi would let a
# megacore split it over TensorCores and silently return one core's
# partial sums; only the batch*heads dim is truly independent there
_DIMSEM_FUSED = _dimsem(("parallel", "arbitrary", "arbitrary"))


def _window_cap(block_k: int, window) -> int:
    """Cap block_k near the sliding window: tiles wider than the
    window defeat the band-tile skip (every q row would pay for a full
    k-tile of mostly-masked columns). Applied identically in the forward
    and backward rules so the custom_vjp pair stays consistent."""
    if window is None:
        return block_k
    return min(block_k, max(128, ((window + 127) // 128) * 128))


def _fit_block(block: int, l: int) -> int:
    """Largest divisor of ``l`` that is <= ``block``, preferring
    lane-aligned (multiple-of-128) tiles, then sublane-aligned (8).

    Keeps the tuned defaults usable for any length a caller brings
    (L=384 → 128, L=768 with block 512 → 384) instead of asserting.
    """
    b = min(block, l)
    for align in (128, 8, 1):
        cand = (b // align) * align
        while cand >= align:
            if l % cand == 0:
                return cand
            cand -= align
    return 1


def _padded_len(block: int, l: int) -> int:
    """Length after padding to make a TPU-legal block exist.

    A block is legal when it divides l AND (is a multiple of 8 OR equals
    l). Lengths like 2047 (divisors 89/23) or 100 (divisor 50) admit no
    legal block smaller than l worth using — pad to the next multiple of
    128 (8 for short rows) and mask the tail via segment ids."""
    blk = _fit_block(block, l)
    if blk % 8 == 0 or blk == l:
        return l
    step = 128 if l >= 128 else 8
    return ((l + step - 1) // step) * step


def _causal_live(qi, ki, bq, bk, window=None):
    """Whether tile (qi, ki) intersects the visible band: below the causal
    diagonal and, with a sliding window, within ``window`` positions of
    it (the first q row of the tile must still see the last k column)."""
    live = qi * bq + bq - 1 >= ki * bk
    if window is not None:
        live = jnp.logical_and(live, ki * bk + bk - 1 > qi * bq - window)
    return live


def _tile_scores(q_ref, k_ref, qi, ki, *, scale, causal, bq, bk,
                 qs_ref=None, ks_ref=None, window=None):
    """Scaled and masked score tile S = (Q Kᵀ)·scale (causal, sliding
    window, and/or segment masking).

    Shared by the forward and both backward kernels so masking semantics
    can never desynchronize between them. Segment masking (packed
    sequences) blanks positions whose query and key segment ids differ;
    a sliding window keeps only the last ``window`` positions (causal).
    """
    # native-dtype operands, f32 accumulation: a bf16 model's Q·Kᵀ runs at
    # the MXU's bf16 rate (upcasting first quartered throughput and paid
    # VPU casts); f32 inputs behave exactly as before
    s = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                  # [bq, bk]
    if causal:
        # NOTE(measured 2026-07-31): specializing interior tiles to skip
        # this masking via lax.cond on (qi, ki) regressed the LM bench
        # 96.6k → 84.3k tok/s — Mosaic's traced branch costs more than
        # the iota/compare/select it saves. Keep the mask unconditional.
        rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        keep = rows >= cols
        if window is not None:
            keep = jnp.logical_and(keep, rows - cols < window)
        s = jnp.where(keep, s, _NEG_INF)
    if qs_ref is not None:
        s = jnp.where(qs_ref[0] == ks_ref[0], s, _NEG_INF)  # (bq,1)==(1,bk)
    return s


def _masked_exp(s, shift, has_segs):
    """exp(s - shift) with masked entries forced to exactly 0.

    Segment masking can fully mask a row (padding) or a whole tile; there
    ``shift`` (running max or lse) is itself ≈ _NEG_INF and the naive
    exp(s - shift) = exp(0) = 1 (or overflows). Causal-only masking never
    produces such rows (column 0 is always visible), so the select is
    compiled in only when segments are present.
    """
    e = jnp.exp(s - shift)
    if has_segs:
        e = jnp.where(s <= 0.5 * _NEG_INF, 0.0, e)
    return e


def _fa_kernel(*refs, scale, causal, bq, bk, nk, has_segs=False,
               window=None):
    if has_segs:
        (q_ref, k_ref, v_ref, qs_ref, ks_ref, o_ref, lse_ref,
         acc, mrow, lrow) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc, mrow, lrow = refs
        qs_ref = ks_ref = None
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        mrow[:] = jnp.full_like(mrow, _NEG_INF)
        lrow[:] = jnp.zeros_like(lrow)

    def _compute():
        s = _tile_scores(q_ref, k_ref, qi, ki, scale=scale, causal=causal,
                         bq=bq, bk=bk, qs_ref=qs_ref, ks_ref=ks_ref,
                         window=window)
        m_prev = mrow[:, :1]                       # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = _masked_exp(s, m_new, has_segs)        # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)            # [bq, 1]
        lrow[:, :1] = lrow[:, :1] * alpha + jnp.sum(p, -1, keepdims=True)
        # P cast to V's dtype: bf16 MXU dot with f32 accumulation
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        mrow[:, :1] = m_new

    # causal: a tile fully above the diagonal contributes nothing. The
    # predicate must be TRACED even when trivially true: the Pallas
    # interpreter mishandles varying-axes tracking (shard_map check_vma)
    # for ref reads outside a traced cond.
    pl.when(_causal_live(qi, ki, bq, bk, window) if causal
            else ki >= 0)(_compute)

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[0] = (acc[:] / jnp.maximum(lrow[:, :1], 1e-30)).astype(
            o_ref.dtype)
        # logsumexp per row — the backward kernels rebuild P = exp(S - lse)
        lse_ref[0] = (mrow[:, :1] +
                      jnp.log(jnp.maximum(lrow[:, :1], 1e-30)))


def _sds(ref, shape, dtype, *more):
    """ShapeDtypeStruct declaring the union of the operands' varying mesh
    axes — required for pallas_call outputs inside shard_map
    (check_vma=True)."""
    vma = frozenset()
    for x in (ref,) + more:
        vma = vma | (getattr(jax.typeof(x), "vma", None) or frozenset())
    return (jax.ShapeDtypeStruct(shape, dtype, vma=vma)
            if vma else jax.ShapeDtypeStruct(shape, dtype))


def _kv_row_map(hq: int, hkv: int):
    """Grid row (over B*Hq) → KV array row (over B*Hkv).

    GQA/MQA share one KV head among ``hq // hkv`` consecutive query heads
    (repeat-interleave convention); the sharing happens in the BlockSpec
    index map, so the repeated KV never exists in HBM."""
    if hq == hkv:
        return lambda b, qi, ki: (b, ki, 0)
    g = hq // hkv
    return lambda b, qi, ki: ((b // hq) * hkv + (b % hq) // g, ki, 0)


def _seg_specs(hq, bq, bk, order_qk=True):
    """BlockSpecs for segment-id operands: q_seg [B, Lq, 1] tiles
    (1, bq, 1); kv_seg [B, 1, Lk] tiles (1, 1, bk) — both minimal legal
    TPU layouts (the block dim of 1 equals the array dim). Grid row b runs
    over B*Hq; segment ids are per batch, hence the ``b // hq``."""
    if order_qk:
        qmap = lambda b, qi, ki: (b // hq, qi, 0)
        kmap = lambda b, qi, ki: (b // hq, 0, ki)
    else:  # (b, ki, qi) grids
        qmap = lambda b, ki, qi: (b // hq, qi, 0)
        kmap = lambda b, ki, qi: (b // hq, 0, ki)
    return (pl.BlockSpec((1, bq, 1), qmap, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk), kmap, memory_space=pltpu.VMEM))


def _flash_fwd_3d(q, k, v, *, causal, scale, block_q, block_k, interpret,
                  hq=1, hkv=1, segs=None, window=None):
    """q: [B*Hq, Lq, D]; k, v: [B*Hkv, Lk, D] → ([B*Hq, Lq, D],
    lse [B*Hq, Lq, 1]). ``segs``: (q_seg [B, Lq, 1], kv_seg [B, 1, Lk]).

    lse rides a trailing dim of 1: TPU block shapes must have last-two dims
    divisible by (8, 128) OR equal to the array dims, so (1, bq, 1) on a
    [BH, Lq, 1] array is the minimal legal layout — 4 B/row in HBM (the
    earlier 128-lane broadcast moved 128x that)."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    bq = _fit_block(block_q, lq)
    bk = _fit_block(block_k, lk)
    nk = lk // bk
    kv_map = _kv_row_map(hq, hkv)

    kernel = functools.partial(
        _fa_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nk=nk,
        has_segs=segs is not None, window=window)
    grid = (bh, lq // bq, nk)
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda b, qi, ki: (b, qi, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, d), kv_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, d), kv_map, memory_space=pltpu.VMEM),
    ]
    operands = (q, k, v)
    if segs is not None:
        in_specs += list(_seg_specs(hq, bq, bk))
        operands += segs
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, bq, d), lambda b, qi, ki: (b, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, 1), lambda b, qi, ki: (b, qi, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(_sds(q, (bh, lq, d), q.dtype, k, v),
                   _sds(q, (bh, lq, 1), jnp.float32, k, v)),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),     # acc
            pltpu.VMEM((bq, 128), jnp.float32),   # running max (col 0)
            pltpu.VMEM((bq, 128), jnp.float32),   # running sum (col 0)
        ],
        interpret=interpret,
        compiler_params=_DIMSEM,
    )(*operands)
    return out, lse


# ---------------------------------------------------------------------------
# Backward kernels (FlashAttention-2 style): rebuild P from lse per tile,
# never materializing [L, L] in HBM — the memory bound that lets b=64/L=2048
# (and far longer L) train on one chip where the materializing backward
# allocated 8 GB score tensors per block.
# ---------------------------------------------------------------------------

def _fa_bwd_dq_kernel(*refs, scale, causal, bq, bk, nk,
                      has_segs=False, window=None):
    if has_segs:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dr_ref, qs_ref, ks_ref,
         dq_ref, dq_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dr_ref, dq_ref,
         dq_acc) = refs
        qs_ref = ks_ref = None
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _compute():
        s = _tile_scores(q_ref, k_ref, qi, ki, scale=scale, causal=causal,
                         bq=bq, bk=bk, qs_ref=qs_ref, ks_ref=ks_ref,
                         window=window)
        p = _masked_exp(s, lse_ref[0], has_segs)       # [bq, bk]
        # native-dtype MXU dots, f32 accumulation (see _tile_scores)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        ds = p * (dp - dr_ref[0]) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    # traced-predicate gate even when non-causal — see _fa_kernel
    pl.when(_causal_live(qi, ki, bq, bk, window) if causal
            else ki >= 0)(_compute)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(*refs, scale, causal, bq, bk, nq,
                       has_segs=False, window=None):
    if has_segs:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dr_ref, qs_ref, ks_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dr_ref, dk_ref, dv_ref,
         dk_acc, dv_acc) = refs
        qs_ref = ks_ref = None
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _compute():
        s = _tile_scores(q_ref, k_ref, qi, ki, scale=scale, causal=causal,
                         bq=bq, bk=bk, qs_ref=qs_ref, ks_ref=ks_ref,
                         window=window)
        p = _masked_exp(s, lse_ref[0], has_segs)       # [bq, bk]
        # native-dtype MXU dots, f32 accumulation (see _tile_scores)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, d]
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        ds = p * (dp - dr_ref[0]) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, d]

    # traced-predicate gate even when non-causal — see _fa_kernel
    pl.when(_causal_live(qi, ki, bq, bk, window) if causal
            else qi >= 0)(_compute)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _fa_bwd_fused_kernel(*refs, scale, causal, bq, bk, nq, nk,
                         has_segs=False, window=None):
    """One-pass backward: dq, dk, dv from a SINGLE rebuild of the score
    tile. The split dq/dkv kernels each recompute S, P and dP — 2 of the
    7 tile dots are pure duplication (plus double HBM reads of q/k/v/do).
    Here dk/dv accumulate across the qi sweep in whole-Lk VMEM scratch
    (f32 [Lk, D] each — 512 KB at L=2048/D=64), flushed on the last grid
    step; dq accumulates per qi exactly like the split kernel. Applicable
    while the scratch fits VMEM (see _FUSED_BWD_SCRATCH_BYTES); the split
    kernels remain the long-L path."""
    if has_segs:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dr_ref, qs_ref, ks_ref,
         dq_ref, dk_ref, dv_ref, dq_acc, dk_all, dv_all) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dr_ref, dq_ref, dk_ref,
         dv_ref, dq_acc, dk_all, dv_all) = refs
        qs_ref = ks_ref = None
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(jnp.logical_and(qi == 0, ki == 0))
    def _init_kv():
        dk_all[:] = jnp.zeros_like(dk_all)
        dv_all[:] = jnp.zeros_like(dv_all)

    @pl.when(ki == 0)
    def _init_q():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _compute():
        s = _tile_scores(q_ref, k_ref, qi, ki, scale=scale, causal=causal,
                         bq=bq, bk=bk, qs_ref=qs_ref, ks_ref=ks_ref,
                         window=window)
        p = _masked_exp(s, lse_ref[0], has_segs)       # [bq, bk]
        # native-dtype MXU dots, f32 accumulation (see _tile_scores)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        ds = p * (dp - dr_ref[0]) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        sl = pl.dslice(ki * bk, bk)
        dv_all[sl, :] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, d]
        dk_all[sl, :] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, d]

    # traced-predicate gate even when non-causal — see _fa_kernel
    pl.when(_causal_live(qi, ki, bq, bk, window) if causal
            else ki >= 0)(_compute)

    @pl.when(ki == nk - 1)
    def _finalize_q():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)

    @pl.when(jnp.logical_and(qi == nq - 1, ki == nk - 1))
    def _finalize_kv():
        dk_ref[0] = dk_all[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_all[:].astype(dv_ref.dtype)


# dk+dv whole-Lk f32 scratch budget for the fused backward (VMEM is
# ~16 MB/core; the [bq, bk] tile intermediates need the rest). Empirical
# v5e boundary (2026-07-31): Lk=4096/D=64 compiles ISOLATED but exceeds
# scoped VMEM by 2.6 MB inside the full LM train step (surrounding
# program raises the pressure), so the gate is the envelope measured
# safe IN-PROGRAM: Lk <= 2048 and 2 MB scratch — both corners verified
# in full 12-layer LM train steps on the chip (Lk=2048 at D=64 AND at
# D=128, the byte-budget boundary). Longer sequences take the split
# dq/dkv kernels.
_FUSED_BWD_SCRATCH_BYTES = 2 * 2 ** 20
_FUSED_BWD_MAX_LK = 2048


def _flash_bwd_slabbed(q, k, v, do, lse, dr, *, causal, scale, block_q,
                       block_k, interpret, hq, hkv, segs, slab):
    """Long-Lk FUSED backward: KV sliced into slabs that fit the fused
    kernel's whole-Lk VMEM scratch (r5). Per slab, causal structure is
    block-wise — q rows before the slab contribute nothing, the diagonal
    region runs with in-slab causal masking, rows after see the whole
    slab unmasked — the ring executor's visiting-block trichotomy
    (parallel/ring_attention.py `_ring_blocks`) applied serially on one
    chip. Every (q, kv) tile pair still pays the fused kernel's 5 dots
    (the split fallback pays 7), so sequences beyond the in-program
    envelope keep the fused backward's arithmetic. dq accumulates in
    f32 across slab contributions; each slab's dk/dv is the f32 sum of
    its diagonal and suffix calls, concatenated along Lk."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    q_seg = kv_seg = None
    if segs is not None:
        q_seg, kv_seg = segs
    dq32 = jnp.zeros((bh, lq, d), jnp.float32)
    dks, dvs = [], []
    for s0 in range(0, lk, slab):
        s1 = min(s0 + slab, lk)
        ks, vs = k[:, s0:s1], v[:, s0:s1]
        kvs = None if kv_seg is None else kv_seg[:, :, s0:s1]
        if causal:
            # diagonal region: q rows [s0, s1) (lq == lk asserted at
            # dispatch), in-slab causal; suffix: q rows [s1, lq) unmasked
            regions = [(s0, s1, True)]
            if s1 < lq:
                regions.append((s1, lq, False))
        else:
            regions = [(0, lq, False)]
        dk_acc = jnp.zeros((bh, s1 - s0, d), jnp.float32)
        dv_acc = jnp.zeros((bh, s1 - s0, d), jnp.float32)
        for r0, r1, diag in regions:
            sub_segs = None
            if q_seg is not None:
                sub_segs = (q_seg[:, r0:r1], kvs)
            dq_p, dk_p, dv_p = _flash_bwd_3d(
                q[:, r0:r1], ks, vs, do[:, r0:r1],
                lse[:, r0:r1], dr[:, r0:r1],
                causal=diag, scale=scale, block_q=block_q,
                block_k=block_k, interpret=interpret, hq=hq, hkv=hkv,
                segs=sub_segs)
            dq32 = dq32.at[:, r0:r1].add(dq_p.astype(jnp.float32))
            dk_acc = dk_acc + dk_p.astype(jnp.float32)
            dv_acc = dv_acc + dv_p.astype(jnp.float32)
        dks.append(dk_acc.astype(k.dtype))
        dvs.append(dv_acc.astype(v.dtype))
    return (dq32.astype(q.dtype), jnp.concatenate(dks, axis=1),
            jnp.concatenate(dvs, axis=1))


def _flash_bwd_3d(q, k, v, do, lse, dr, *, causal, scale, block_q, block_k,
                  interpret, hq=1, hkv=1, segs=None, window=None):
    """q/do: [B*Hq, Lq, D]; k/v: [B*Hkv, Lk, D]; lse/dr: [B*Hq, Lq] →
    (dq [B*Hq], dk, dv [B*Hq — caller reduces query-head groups when
    hkv < hq]). ``segs``: (q_seg [B, Lq, 1], kv_seg [B, 1, Lk])."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    fused_ok = (2 * lk * d * 4 <= _FUSED_BWD_SCRATCH_BYTES
                and lk <= _FUSED_BWD_MAX_LK)
    if not fused_ok and window is None and (not causal or lq == lk):
        # beyond the fused envelope: slab the KV range so each piece
        # fits it, keeping the 5-dot fused kernel (window masking is
        # position-relative and would break on slices — it stays on the
        # split path; causal slabbing needs the self-attention lq == lk
        # alignment)
        slab = min(_FUSED_BWD_MAX_LK,
                   _FUSED_BWD_SCRATCH_BYTES // (8 * d))
        slab -= slab % 128  # lane-aligned; >= 128 keeps legal tiles
        if slab >= 128:
            return _flash_bwd_slabbed(
                q, k, v, do, lse, dr, causal=causal, scale=scale,
                block_q=block_q, block_k=block_k, interpret=interpret,
                hq=hq, hkv=hkv, segs=segs, slab=slab)
    lse = lse.reshape(bh, lq, 1)   # minimal legal TPU block layout
    dr = dr.reshape(bh, lq, 1)
    bq = _fit_block(block_q, lq)
    bk = _fit_block(block_k, lk)
    nq, nk = lq // bq, lk // bk
    kv_map = _kv_row_map(hq, hkv)
    has_segs = segs is not None

    q_spec = pl.BlockSpec((1, bq, d), lambda b, qi, ki: (b, qi, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, bk, d), kv_map, memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, bq, 1), lambda b, qi, ki: (b, qi, 0),
                            memory_space=pltpu.VMEM)

    in_specs = [q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec]
    operands = (q, k, v, do, lse, dr)
    if has_segs:
        in_specs += list(_seg_specs(hq, bq, bk))
        operands += segs

    if fused_ok:  # the ONE envelope predicate, computed at dispatch
        dkv_full = pl.BlockSpec((1, lk, d), lambda b, qi, ki: (b, 0, 0),
                                memory_space=pltpu.VMEM)
        return pl.pallas_call(
            functools.partial(_fa_bwd_fused_kernel, scale=scale,
                              causal=causal, bq=bq, bk=bk, nq=nq, nk=nk,
                              has_segs=has_segs, window=window),
            grid=(bh, nq, nk),
            in_specs=in_specs,
            out_specs=(q_spec, dkv_full, dkv_full),
            out_shape=(_sds(q, (bh, lq, d), q.dtype, k, v, do),
                       _sds(k, (bh, lk, d), k.dtype, q, v, do),
                       _sds(v, (bh, lk, d), v.dtype, q, k, do)),
            scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32),
                            pltpu.VMEM((lk, d), jnp.float32),
                            pltpu.VMEM((lk, d), jnp.float32)],
            interpret=interpret,
            compiler_params=_DIMSEM_FUSED,
        )(*operands)

    dq = pl.pallas_call(
        functools.partial(_fa_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nk=nk, has_segs=has_segs,
                          window=window),
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=q_spec,
        out_shape=_sds(q, (bh, lq, d), q.dtype, k, v, do),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
        compiler_params=_DIMSEM,
    )(*operands)

    # dk/dv iterate q innermost; same index maps with (b, ki, qi). Outputs
    # stay per-QUERY-head ([B*Hq] rows) — for GQA the caller sums each
    # query-head group (the transpose of the index-map sharing above).
    q_spec2 = pl.BlockSpec((1, bq, d), lambda b, ki, qi: (b, qi, 0),
                           memory_space=pltpu.VMEM)
    kv_map2 = lambda b, ki, qi: kv_map(b, qi, ki)
    kv_spec2 = pl.BlockSpec((1, bk, d), kv_map2, memory_space=pltpu.VMEM)
    dkv_spec2 = pl.BlockSpec((1, bk, d), lambda b, ki, qi: (b, ki, 0),
                             memory_space=pltpu.VMEM)
    row_spec2 = pl.BlockSpec((1, bq, 1), lambda b, ki, qi: (b, qi, 0),
                             memory_space=pltpu.VMEM)
    in_specs2 = [q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2, row_spec2]
    if has_segs:
        in_specs2 += list(_seg_specs(hq, bq, bk, order_qk=False))
    dk, dv = pl.pallas_call(
        functools.partial(_fa_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq, has_segs=has_segs,
                          window=window),
        grid=(bh, nk, nq),
        in_specs=in_specs2,
        out_specs=(dkv_spec2, dkv_spec2),
        out_shape=(_sds(k, (bh, lk, d), k.dtype, q, v, do),
                   _sds(v, (bh, lk, d), v.dtype, q, k, do)),
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
        compiler_params=_DIMSEM,
    )(*operands)
    return dq, dk, dv


def _reference(q, k, v, causal, scale):
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        mask = jnp.arange(lk)[None, :] <= jnp.arange(lq)[:, None]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 9, 10, 11))
def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCKS[0],
                    block_k: int = DEFAULT_BLOCKS[1],
                    interpret: Optional[bool] = None,
                    segment_ids=None, window: Optional[int] = None,
                    bwd_blocks: Optional[Tuple[int, int]] = None,
                    layout: str = "blhd"):
    """Fused blockwise attention. q: [B, Lq, H, D]; k, v: [B, Lk, Hkv, D]
    → [B, Lq, H, D]. Hkv < H is GQA/MQA (H % Hkv == 0, repeat-interleave
    head sharing) — the shared KV is never replicated in HBM; the sharing
    lives in the kernel's block index maps.

    ``layout="bhld"``: q [B, H, Lq, D]; k, v [B, Hkv, Lk, D] →
    [B, H, Lq, D] — the PIVOT-FREE wire format. The kernels natively
    consume [B*H, L, D]; from bhld that is a zero-cost reshape, whereas
    from the default blhd layout every call transposes q/k/v in and the
    output (plus all four gradients) back out — ~15 ms/step of HBM
    copies on the 135M LM (docs/lm_roofline.md §5). A model that keeps
    its attention tensors head-major (projection einsums emit
    [B, H, L, D] directly — XLA folds the permutation into the matmul
    for free, measured 2026-07-31) pays zero layout traffic end to end;
    see ``TransformerLM(qkv_layout="bhld")``.

    ``segment_ids`` enables packed-sequence masking (the TPU-native answer
    to the reference seq2seq's variable-length batching — static shapes,
    many sequences per row): an int32 [B, L] array (self-attention) or a
    (q_seg [B, Lq], kv_seg [B, Lk]) pair; positions attend only within
    their segment (composed with causal). Rows whose segment matches no
    key (e.g. padding marked -1 vs 0-based ids) produce zero output and
    zero gradient.

    ``window`` (requires causal) is sliding-window attention: each query
    attends to its last ``window`` positions only; tiles fully outside
    the band are skipped, so compute scales with L·window instead of L².

    ``interpret=None`` auto-selects: the Pallas interpreter off-TPU (tests),
    the compiled kernel on TPU.

    Default blocks (1024, 1024) measured fastest on v5e with the
    native-dtype MXU + pipelined-DMA kernel (2026-07-30 sweep: 7.3 ms vs
    8.6 ms at (256,512) for the d=64/L=2048 LM shape; 6.6 vs 11.6 ms at
    d=128/L=8192; backward agrees) — see BASELINE.md. Block sizes are
    clamped to the largest divisor of L (lane-aligned where possible), so
    any length works; explicit blocks are only a tuning knob.
    """
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
                      segment_ids, window, bwd_blocks, layout)[0]


def _to3(x):
    b, l, h, d = x.shape
    return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, l, d)


def _norm_segs(segment_ids, lq, lk):
    """→ None or kernel-layout (q_seg [B, Lq, 1], kv_seg [B, 1, Lk])."""
    if segment_ids is None:
        return None
    if isinstance(segment_ids, (tuple, list)):
        qs, ks = segment_ids
    else:
        qs = ks = segment_ids
        if lq != lk:
            raise ValueError(
                "a single segment_ids array needs Lq == Lk; pass a "
                "(q_seg, kv_seg) pair for cross-attention")
    return (jnp.asarray(qs, jnp.int32)[:, :, None],
            jnp.asarray(ks, jnp.int32)[:, None, :])


def _pad_rows(x, n):
    return jnp.pad(x, ((0, 0), (0, n)) + ((0, 0),) * (x.ndim - 2))


def _apply_padding(q, k, v, segment_ids, block_q, block_k, batch=None):
    """Pad Lq/Lk to TPU-legal block lengths, masking the tail with
    segment ids (query pad −1, kv pad −2: matches nothing, including each
    other). Returns (q, k, v, effective_segment_ids, lq_pad, lk_pad) with
    the ORIGINAL arrays when no padding is needed. Works on blhd 4D
    arrays or (with ``batch`` given, since dim 0 is then B*H) on the
    kernel-native 3D [B*H, L, D] arrays — dim 1 is L either way."""
    b, lq = (batch if batch is not None else q.shape[0]), q.shape[1]
    lk = k.shape[1]
    lq_p, lk_p = _padded_len(block_q, lq), _padded_len(block_k, lk)
    if lq_p == lq and lk_p == lk:
        return q, k, v, segment_ids, 0, 0
    if segment_ids is None:
        qs, ks = jnp.zeros((b, lq), jnp.int32), jnp.zeros((b, lk), jnp.int32)
    elif isinstance(segment_ids, (tuple, list)):
        qs, ks = segment_ids
    else:
        qs = ks = segment_ids
    qs = jnp.where(_pad_rows(jnp.ones((b, lq), bool), lq_p - lq),
                   _pad_rows(jnp.asarray(qs, jnp.int32), lq_p - lq), -1)
    ks = jnp.where(_pad_rows(jnp.ones((b, lk), bool), lk_p - lk),
                   _pad_rows(jnp.asarray(ks, jnp.int32), lk_p - lk), -2)
    q = _pad_rows(q, lq_p - lq)
    k = _pad_rows(k, lk_p - lk)
    v = _pad_rows(v, lk_p - lk)
    return q, k, v, (qs, ks), lq_p - lq, lk_p - lk


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
               segment_ids=None, window=None, bwd_blocks=None,
               layout="blhd"):
    if window is not None and not causal:
        raise ValueError("window (sliding-window attention) requires "
                         "causal=True")
    if layout not in ("blhd", "bhld"):
        raise ValueError(f"layout must be 'blhd' or 'bhld', got "
                         f"{layout!r}")
    block_k = _window_cap(block_k, window)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if layout == "bhld":
        # head-major wire format: [B, H, L, D] ↔ [B*H, L, D] is a free
        # reshape — the transpose copies of the blhd path never happen
        b, h, lq, d = q.shape
        hk = k.shape[1]
        if h % hk:
            raise ValueError(
                f"query heads ({h}) must be a multiple of kv heads ({hk})")
        qp, kp, vp, segs_eff, _, _ = _apply_padding(
            q.reshape(b * h, lq, d), k.reshape(b * hk, -1, d),
            v.reshape(b * hk, -1, d), segment_ids, block_q, block_k,
            batch=b)
        segs = _norm_segs(segs_eff, qp.shape[1], kp.shape[1])
        out3, lse3 = _flash_fwd_3d(
            qp, kp, vp,
            causal=causal, scale=scale, block_q=block_q, block_k=block_k,
            interpret=interpret, hq=h, hkv=hk, segs=segs, window=window)
        out = out3.reshape(b, h, qp.shape[1], d)[:, :, :lq]
        return out, (q, k, v, out, lse3, segment_ids)
    b, lq, h, d = q.shape
    hk = k.shape[2]
    if h % hk:
        raise ValueError(
            f"query heads ({h}) must be a multiple of kv heads ({hk})")
    qp, kp, vp, segs_eff, _, _ = _apply_padding(
        q, k, v, segment_ids, block_q, block_k)
    segs = _norm_segs(segs_eff, qp.shape[1], kp.shape[1])
    out3, lse3 = _flash_fwd_3d(
        _to3(qp), _to3(kp), _to3(vp),
        causal=causal, scale=scale, block_q=block_q, block_k=block_k,
        interpret=interpret, hq=h, hkv=hk, segs=segs, window=window)
    out = jnp.transpose(out3.reshape(b, h, qp.shape[1], d),
                        (0, 2, 1, 3))[:, :lq]
    return out, (q, k, v, out, lse3, segment_ids)


def _flash_bwd(causal, scale, block_q, block_k, interpret, window,
               bwd_blocks, layout, res, g):
    # blockwise Pallas backward: P is rebuilt per tile from the forward's
    # logsumexp; [L, L] never touches HBM (the materializing fallback
    # allocated 8 GB f32 score tensors at b=64/L=2048/h=8)
    q, k, v, out, lse3, segment_ids = res
    if bwd_blocks is not None:
        # the backward kernels' VMEM/compute balance differs from the
        # forward's (4 live [bq, bk] f32 intermediates vs 2); let callers
        # tune them independently
        block_q, block_k = bwd_blocks
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_k = _window_cap(block_k, window)
    sc = scale if scale is not None else q.shape[-1] ** -0.5
    if layout == "bhld":
        # head-major: reshapes only, no transposes anywhere in backward
        b, h, lq, d = q.shape
        lk, hk = k.shape[2], k.shape[1]
        qp, kp, vp, segs_eff, pq, pk = _apply_padding(
            q.reshape(b * h, lq, d), k.reshape(b * hk, lk, d),
            v.reshape(b * hk, lk, d), segment_ids, block_q, block_k,
            batch=b)
        lq_p, lk_p = lq + pq, lk + pk
        if lse3.shape[1] != lq_p:
            raise ValueError(
                f"bwd_blocks pad Lq to {lq_p} but the forward's lse is "
                f"{lse3.shape[1]} long; pick bwd blocks with the same "
                "padded length (block-size multiples of the forward's)")
        segs = _norm_segs(segs_eff, lq_p, lk_p)
        g3 = g.reshape(b * h, lq, d)
        gp = _pad_rows(g3, pq) if pq else g3
        # D_i = Σ_d dO_i · O_i — rowwise, already head-major: no pivot
        dr3 = jnp.sum(g3.astype(jnp.float32)
                      * out.reshape(b * h, lq, d).astype(jnp.float32),
                      axis=-1)
        if pq:
            dr3 = _pad_rows(dr3, pq)
        dq3, dk3, dv3 = _flash_bwd_3d(
            qp, kp, vp, gp, lse3, dr3,
            causal=causal, scale=sc, block_q=block_q, block_k=block_k,
            interpret=interpret, hq=h, hkv=hk, segs=segs, window=window)
        if hk < h:
            grp = h // hk
            dk3 = dk3.reshape(b * hk, grp, lk_p, d).sum(1)
            dv3 = dv3.reshape(b * hk, grp, lk_p, d).sum(1)
        dsegs = jax.tree_util.tree_map(
            lambda s: np.zeros(s.shape, jax.dtypes.float0), segment_ids)
        return (dq3[:, :lq].reshape(b, h, lq, d),
                dk3[:, :lk].reshape(b, hk, lk, d),
                dv3[:, :lk].reshape(b, hk, lk, d), dsegs)
    b, lq, h, d = q.shape
    lk, hk = k.shape[1], k.shape[2]
    qp, kp, vp, segs_eff, pq, pk = _apply_padding(
        q, k, v, segment_ids, block_q, block_k)
    lq_p, lk_p = lq + pq, lk + pk
    if lse3.shape[1] != lq_p:
        raise ValueError(
            f"bwd_blocks pad Lq to {lq_p} but the forward's lse is "
            f"{lse3.shape[1]} long; pick bwd blocks with the same padded "
            "length (block-size multiples of the forward's)")
    segs = _norm_segs(segs_eff, lq_p, lk_p)
    gp = _pad_rows(g, pq) if pq else g
    # D_i = Σ_d dO_i · O_i — rowwise, cheap in XLA, f32 for stability
    dr = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    dr3 = jnp.pad(jnp.transpose(dr, (0, 2, 1)).reshape(b * h, lq),
                  ((0, 0), (0, pq)))
    dq3, dk3, dv3 = _flash_bwd_3d(
        _to3(qp), _to3(kp), _to3(vp), _to3(gp), lse3, dr3,
        causal=causal, scale=sc, block_q=block_q, block_k=block_k,
        interpret=interpret, hq=h, hkv=hk, segs=segs, window=window)
    if hk < h:
        # transpose of the index-map head sharing: sum each query-head group
        grp = h // hk
        dk3 = dk3.reshape(b * hk, grp, lk_p, d).sum(1)
        dv3 = dv3.reshape(b * hk, grp, lk_p, d).sum(1)
    back = lambda x3, hh, lp, l: jnp.transpose(
        x3.reshape(b, hh, lp, d), (0, 2, 1, 3))[:, :l]
    dsegs = jax.tree_util.tree_map(
        lambda s: np.zeros(s.shape, jax.dtypes.float0), segment_ids)
    return (back(dq3, h, lq_p, lq), back(dk3, hk, lk_p, lk),
            back(dv3, hk, lk_p, lk), dsegs)


flash_attention.defvjp(_flash_fwd, _flash_bwd)

"""ctypes binding for the chainermn_native C++ runtime.

Reference parity: the Cython NCCL binding + CuPy pack/unpack kernels were the
reference's compiled layer (SURVEY.md §2.2). On TPU the collectives are
XLA's, so the compiled layer here covers the host data path:
``pack``/``unpack`` (the ``_memory_utility`` analog), threaded
``gather_rows`` (batch assembly), and the double-buffered prefetch loader
(see chainermn_tpu/training/loader.py).

Builds lazily with g++ on first use (pybind11 is not in the toolchain; a
plain C ABI + ctypes is). Falls back to numpy implementations when no
compiler is available — same semantics, fewer threads.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

_SRC_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")


def _build_and_load() -> Optional[ctypes.CDLL]:
    so = os.path.join(_SRC_DIR, "libchainermn_native.so")
    src = os.path.join(_SRC_DIR, "chainermn_native.cpp")
    if not os.path.exists(so) or (
        os.path.exists(src) and os.path.getmtime(src) > os.path.getmtime(so)
    ):
        try:
            subprocess.run(
                ["g++", "-O3", "-std=c++17", "-fPIC", "-pthread", "-shared",
                 "-o", so, src],
                check=True, capture_output=True, timeout=120,
            )
        except Exception:
            return None
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        return None

    i64p = ctypes.POINTER(ctypes.c_int64)
    vpp = ctypes.POINTER(ctypes.c_void_p)
    lib.cmn_pack.argtypes = [vpp, i64p, i64p, ctypes.c_int64,
                             ctypes.c_void_p, ctypes.c_int]
    lib.cmn_unpack.argtypes = [ctypes.c_void_p, vpp, i64p, i64p,
                               ctypes.c_int64, ctypes.c_int]
    lib.cmn_gather_rows.argtypes = [ctypes.c_void_p, ctypes.c_int64, i64p,
                                    ctypes.c_int64, ctypes.c_void_p,
                                    ctypes.c_int]
    lib.cmn_loader_create.restype = ctypes.c_void_p
    lib.cmn_loader_create.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_int64, ctypes.c_int64,
                                      ctypes.c_int64, ctypes.c_int,
                                      ctypes.c_int]
    lib.cmn_loader_submit.argtypes = [ctypes.c_void_p, i64p, ctypes.c_int64]
    lib.cmn_loader_next.restype = ctypes.c_int
    lib.cmn_loader_next.argtypes = [ctypes.c_void_p, vpp, vpp]
    lib.cmn_loader_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.cmn_loader_destroy.argtypes = [ctypes.c_void_p]
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if not _TRIED:
            _LIB = _build_and_load()
            _TRIED = True
        return _LIB


def available() -> bool:
    return get_lib() is not None


# ---------------------------------------------------------------------------
# pack / unpack (reference: _memory_utility.pack_params / unpack_params)
# ---------------------------------------------------------------------------


def pack(arrays: Sequence[np.ndarray], n_threads: int = 4) -> np.ndarray:
    """Concatenate arrays' bytes into one flat uint8 buffer."""
    arrays = [np.ascontiguousarray(a) for a in arrays]
    sizes = np.array([a.nbytes for a in arrays], dtype=np.int64)
    offsets = np.zeros_like(sizes)
    np.cumsum(sizes[:-1], out=offsets[1:])
    flat = np.empty(int(sizes.sum()), dtype=np.uint8)
    lib = get_lib()
    if lib is None:
        for a, o, s in zip(arrays, offsets, sizes):
            flat[o:o + s] = a.view(np.uint8).reshape(-1)
        return flat
    srcs = (ctypes.c_void_p * len(arrays))(
        *[a.ctypes.data for a in arrays])
    lib.cmn_pack(srcs, sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                 offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                 len(arrays), flat.ctypes.data, n_threads)
    return flat


def unpack(flat: np.ndarray, like: Sequence[np.ndarray],
           n_threads: int = 4) -> List[np.ndarray]:
    """Split a flat uint8 buffer back into arrays shaped like ``like``."""
    sizes = np.array([a.nbytes for a in like], dtype=np.int64)
    offsets = np.zeros_like(sizes)
    np.cumsum(sizes[:-1], out=offsets[1:])
    outs = [np.empty_like(a) for a in like]
    lib = get_lib()
    if lib is None:
        for o, off, s in zip(outs, offsets, sizes):
            o.view(np.uint8).reshape(-1)[:] = flat[off:off + s]
        return outs
    dsts = (ctypes.c_void_p * len(outs))(*[o.ctypes.data for o in outs])
    lib.cmn_unpack(flat.ctypes.data, dsts,
                   sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                   offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                   len(outs), n_threads)
    return outs


# ---------------------------------------------------------------------------
# row gather (batch assembly primitive)
# ---------------------------------------------------------------------------


def gather_rows(base: np.ndarray, indices: np.ndarray,
                out: Optional[np.ndarray] = None,
                n_threads: int = 4) -> np.ndarray:
    """out[i] = base[indices[i]] — threaded when the native lib is up."""
    base = np.ascontiguousarray(base)
    indices = np.ascontiguousarray(indices, dtype=np.int64)
    if out is None:
        out = np.empty((len(indices),) + base.shape[1:], base.dtype)
    lib = get_lib()
    if lib is None:
        np.take(base, indices, axis=0, out=out)
        return out
    row_bytes = base.dtype.itemsize * int(np.prod(base.shape[1:], initial=1))
    lib.cmn_gather_rows(
        base.ctypes.data, row_bytes,
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(indices), out.ctypes.data, n_threads)
    return out

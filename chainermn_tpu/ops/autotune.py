"""On-device block-size autotuning for the flash attention kernels.

The tuned defaults in `ops/flash_attention.py` (1024, 1024) were measured
on v5e at d=128; other head dims, sequence lengths, or TPU generations
can prefer different tiles (BASELINE.md's sweep saw 2x spread). This
sweeps candidate (block_q, block_k) pairs with the REAL kernels on the
current default device and returns the fastest — profile-and-iterate as
a one-call utility.

Results are memoized per (shape, dtype, causal, window) key for the
process lifetime; tuning cost is a few hundred ms per new shape on TPU.
Off-TPU (interpreter) the defaults are returned untimed — interpreter
timings would be meaningless.
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

_CACHE: dict = {}

_CANDIDATES = ((128, 256), (256, 512), (512, 512), (512, 1024),
               (1024, 512), (1024, 1024))


def tune_flash_blocks(batch: int, seq_len: int, heads: int, head_dim: int,
                      kv_heads: Optional[int] = None,
                      dtype=jnp.bfloat16, causal: bool = True,
                      window: Optional[int] = None,
                      include_backward: bool = True,
                      candidates=_CANDIDATES,
                      iters: int = 3) -> Tuple[int, int]:
    """Return the fastest (block_q, block_k) for this attention shape.

    Times `flash_attention` (forward, or full value-and-grad when
    ``include_backward``) for each candidate on the default backend and
    memoizes. Use the result as the ``block_q``/``block_k`` arguments or
    `TransformerBlock`'s ``attention_blocks``.
    """
    from chainermn_tpu.ops.flash_attention import (DEFAULT_BLOCKS,
                                                   _fit_block,
                                                   _padded_len,
                                                   _window_cap,
                                                   flash_attention)

    key = (batch, seq_len, heads, head_dim, kv_heads, str(dtype), causal,
           window, include_backward)
    if key in _CACHE:
        return _CACHE[key]
    if jax.default_backend() != "tpu":
        _CACHE[key] = DEFAULT_BLOCKS  # defaults; interpreter timing is noise
        return _CACHE[key]

    hkv = kv_heads or heads
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (batch, seq_len, heads, head_dim), dtype)
    k = jax.random.normal(ks[1], (batch, seq_len, hkv, head_dim), dtype)
    v = jax.random.normal(ks[2], (batch, seq_len, hkv, head_dim), dtype)

    best, best_dt = DEFAULT_BLOCKS, float("inf")
    # the kernel clamps blocks to divisors of L (_fit_block) and a window
    # caps block_k: candidates mapping to the same effective pair alias
    # the same compiled kernel — dedup so each is timed once (short
    # sequences, e.g. L=512, collapse several candidates)
    seen = set()
    deduped = []
    for bq, bk in candidates:
        # mirror the kernel wrapper's composition exactly:
        # window-cap → pad-to-legal-length → clamp-to-divisor
        bkc = _window_cap(bk, window)
        eff = (_fit_block(bq, _padded_len(bq, seq_len)),
               _fit_block(bkc, _padded_len(bkc, seq_len)))
        if eff not in seen:
            seen.add(eff)
            deduped.append((bq, bk))
    for bq, bk in deduped:
        def loss(q, k, v, bq=bq, bk=bk):
            out = flash_attention(q, k, v, causal, None, bq, bk, None,
                                  None, window)
            return jnp.sum(out.astype(jnp.float32)) * 1e-3

        fn = (jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
              if include_backward else jax.jit(loss))
        try:
            out = fn(q, k, v)
            # sync via value fetch: block_until_ready can return early on
            # tunneled platform plugins (see bench.py)
            leaf = out[0] if isinstance(out, tuple) else out
            float(jnp.sum(leaf.astype(jnp.float32) * 0) + 1)
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(q, k, v)
            leaf = out[0] if isinstance(out, tuple) else out
            float(jnp.sum(leaf.astype(jnp.float32) * 0) + 1)
            dt = (time.perf_counter() - t0) / iters
        except Exception:
            continue  # candidate illegal for this shape (VMEM, layout)
        if dt < best_dt:
            best, best_dt = (bq, bk), dt
    _CACHE[key] = best
    return best

"""Distributed checkpointer with consensus resume.

Reference: chainermn/extensions/checkpoint.py (SURVEY.md §2.5, §3.5; mount
empty — module path citation): each rank writes its own
``snapshot_iter_<N>.<rank>`` file, keeps a rolling window, and on resume all
ranks agree on the newest iteration present on *every* rank before loading —
the package's restart-based fault-tolerance story.

TPU-native mapping: the writers are processes; device arrays are pulled to
host (they are replicated or re-shardable on load) and stored as flattened
npz + a JSON manifest. The consensus election ("newest iteration all ranks
hold") rides the host object plane exactly like the reference's allgather.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import queue
import re
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from chainermn_tpu.comm.base import CommunicatorBase
from chainermn_tpu.resilience import chaos as _chaos

#: subdirectory (under the checkpointer's path) where ring-neighbor
#: replicas land — written by resilience/replica.py, read by the
#: election/restore fallbacks below
REPLICA_DIRNAME = "replicas"


def _sha256_file(fn: str) -> str:
    h = hashlib.sha256()
    with open(fn, "rb") as fh:
        for block in iter(lambda: fh.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _fsync_file(fn: str) -> None:
    fd = os.open(fn, os.O_RDONLY)
    try:
        os.fsync(fd)
    except OSError:
        pass  # fsync unsupported (some tmpfs) — rename is still atomic
    finally:
        os.close(fd)


def read_manifest(fn: str) -> Optional[dict]:
    """The sidecar JSON manifest published next to snapshot file ``fn``
    (None when missing or torn). Beyond the integrity keys
    (``format``/``sha256``/``bytes``), manifests written since the async
    plane carry a COVERAGE map — ``iteration``, ``world``, ``axes`` (the
    saving mesh's axis→size), per-leaf ``gshape``/``nshards``, and any
    optimizer ``layout`` registered via
    :meth:`MultiNodeCheckpointer.set_layout` — enough for offline
    tooling (tools/ckpt.py) and the reshard planner
    (checkpointing/reshard.py) to interpret the file set without
    loading a single array."""
    try:
        with open(fn + ".json", encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _leaf_dict(state):
    """Pytree → flat {leaf_i: array} dict (orbax-friendly: a dict of
    arrays restores against any pytree with the same leaf order)."""
    leaves = jax.tree_util.tree_flatten(state)[0]
    return {f"leaf_{i}": l for i, l in enumerate(leaves)}


def _is_device_sharded(l) -> bool:
    """True for jax.Arrays whose data is split across devices — pulling
    those to host as one array would materialize the GLOBAL leaf (an OOM
    at real scale for FSDP/ZeRO states, and impossible multi-process where
    the leaf is not even fully addressable)."""
    return (isinstance(l, jax.Array)
            and hasattr(l, "sharding")
            and not l.sharding.is_fully_replicated)


def _flatten_state(state):
    """Pytree → {key: np.ndarray} with device-sharded leaves stored as
    per-ADDRESSABLE-shard arrays (VERDICT r1 #6).

    Replicated leaves: one ``leaf_i`` array (the local replica). Sharded
    leaves: ``leaf_i_nshards``/``leaf_i_gshape`` manifest entries plus one
    ``leaf_i_s<k>`` array per addressable shard, ordered by device id — no
    process ever holds more than its own shards on the host. Restore
    (``maybe_load``) reassembles them against the template leaf's sharding
    via ``jax.make_array_from_single_device_arrays`` — same-sharding fast
    path, and RESHARDING onto a different mesh by splicing ranges from
    the saved index manifests (beyond the reference's rigid per-rank
    snapshot files, SURVEY.md §3.5; VERDICT r2 #5).
    """
    leaves, treedef = jax.tree_util.tree_flatten(state)
    uniq = {
        i: _unique_shards(l)
        for i, l in enumerate(leaves) if _is_device_sharded(l)
    }
    # batch the D2H transfers: start every copy before waiting on any
    for i, l in enumerate(leaves):
        if i in uniq:
            for s in uniq[i]:
                if hasattr(s.data, "copy_to_host_async"):
                    s.data.copy_to_host_async()
        elif hasattr(l, "copy_to_host_async"):
            l.copy_to_host_async()
    arrays = {}
    for i, l in enumerate(leaves):
        if i in uniq:
            shards = uniq[i]
            arrays[f"leaf_{i}_nshards"] = np.int64(len(shards))
            arrays[f"leaf_{i}_gshape"] = np.asarray(l.shape, np.int64)
            for k, s in enumerate(shards):
                arrays[f"leaf_{i}_s{k}"] = np.asarray(s.data)
                arrays[f"leaf_{i}_idx{k}"] = _index_array(s.index)
        else:
            arrays[f"leaf_{i}"] = np.asarray(l)
    return arrays, treedef


def _index_array(index) -> np.ndarray:
    """A shard's global index (tuple of slices) as an [ndim, 2] int64
    array — the save/restore matching key for replicated placements."""
    return np.asarray(
        [(s.start if s.start is not None else 0,
          s.stop if s.stop is not None else -1) for s in index],
        np.int64).reshape(len(index), 2)


def _bounds(index, gshape):
    """Concrete (start, stop) per dim from a shard index (tuple of
    slices; None start/stop mean the full dimension)."""
    return [(s.start if s.start is not None else 0,
             s.stop if s.stop is not None else d)
            for s, d in zip(index, gshape)]


class _SpliceTargets:
    """Resharding-restore assembly for ONE leaf: the ranges THIS process
    needs (its template shards), filled incrementally from whatever saved
    pieces intersect them. Shard data is only np.asarray'd (npz is lazy)
    when a piece actually intersects a needed range, so no process ever
    materializes shards it does not need — the module's
    no-global-leaf-on-host contract extends to resharding."""

    def __init__(self, refs, gshape, dtype):
        self.gshape = gshape
        self.bounds = [_bounds(r.index, gshape) for r in refs]
        self.bufs = [
            np.empty(tuple(b - a for a, b in tb), dtype)
            for tb in self.bounds
        ]
        self.vols = [b.size for b in self.bufs]
        self.covered = [0] * len(self.bufs)
        self._seen = set()

    def consume(self, src, i):
        """Fold leaf ``i``'s pieces from one snapshot file in. Saved
        shards are a disjoint partition of the global array (replicas
        deduplicated at save), so coverage is countable by intersection
        volume; duplicate indices across files are skipped."""
        if f"leaf_{i}_nshards" not in set(getattr(src, "files", src)):
            return
        for k in range(int(src[f"leaf_{i}_nshards"])):
            idx = np.asarray(src[f"leaf_{i}_idx{k}"])
            key = idx.tobytes()
            if key in self._seen:
                continue
            sb = [(int(a), int(b) if b != -1 else int(d))
                  for (a, b), d in zip(idx, self.gshape)]
            arr = None
            for t, tb in enumerate(self.bounds):
                inter = [(max(a1, a2), min(b1, b2))
                         for (a1, b1), (a2, b2) in zip(tb, sb)]
                if any(b <= a for a, b in inter):
                    continue
                if arr is None:
                    arr = np.asarray(src[f"leaf_{i}_s{k}"])
                dst = tuple(slice(a - ta, b - ta)
                            for (a, b), (ta, _) in zip(inter, tb))
                srcsl = tuple(slice(a - sa, b - sa)
                              for (a, b), (sa, _) in zip(inter, sb))
                self.bufs[t][dst] = arr[srcsl]
                self.covered[t] += int(np.prod(
                    [b - a for a, b in inter], initial=1))
                if self.covered[t] > self.vols[t]:
                    # volume accounting assumes saved shards are a
                    # DISJOINT partition (replicas deduplicated at save);
                    # partially-overlapping shards would double-count and
                    # make `complete` lie in both directions
                    raise ValueError(
                        f"snapshot leaf {i}: saved shards overlap "
                        f"(covered {self.covered[t]} > {self.vols[t]} "
                        f"elements of target range {t}) — snapshot files "
                        "are not a disjoint partition; was the snapshot "
                        "written by mixed runs?")
            if arr is not None:
                self._seen.add(key)

    @property
    def complete(self) -> bool:
        return self.covered == self.vols

    def require_complete(self, i):
        if not self.complete:
            raise ValueError(
                f"snapshot leaf {i}: saved shards cover only "
                f"{self.covered}/{self.vols} elements of this process's "
                "target ranges — snapshot incomplete (a peer process's "
                "file is missing?)")


class _PeerSnapshots:
    """Lazy, cached handles on peer processes' snapshot files for one
    restore — opened only if the local file cannot cover a spliced
    leaf's ranges, reused across leaves, closed by ``maybe_load``.

    Ring replicas (``replicas/snapshot_iter_N.R``, pushed by
    resilience/replica.py) are searched after the primaries: a dead
    host's shard is recoverable from the copy its neighbor holds, and
    the splice dedup (``_SpliceTargets._seen``) makes a
    primary+replica double listing harmless."""

    def __init__(self, path: str, it: int, inter_rank: int,
                 inter_size: int):
        # enumerate by GLOB, not by the restoring run's inter_size: the
        # saving run may have had more processes (reshard 2-proc → 1-proc
        # must still read file .1). A strict \.\d+$ match keeps manifest
        # sidecars (snapshot_iter_N.R.json) and tmp files out.
        import glob as _glob

        pat = re.compile(rf"snapshot_iter_{it}\.(\d+)$")
        self._files = []
        for d in (path, os.path.join(path, REPLICA_DIRNAME)):
            self._files.extend(sorted(
                fn for fn in _glob.glob(os.path.join(
                    d, f"snapshot_iter_{it}.*"))
                if (m := pat.search(os.path.basename(fn)))
                and int(m.group(1)) != inter_rank
                and not os.path.isdir(fn)))  # orbax snapshots are dirs
        self._open: dict = {}

    def __iter__(self):
        for fn in self._files:
            if fn not in self._open:
                self._open[fn] = (np.load(fn, allow_pickle=False)
                                  if os.path.exists(fn) else None)
            if self._open[fn] is not None:
                yield self._open[fn]

    def close(self):
        for z in self._open.values():
            if z is not None and hasattr(z, "close"):
                z.close()
        self._open = {}


def _unique_shards(l):
    """Addressable shards deduplicated by global index (device-id order).

    A partially-replicated leaf (e.g. P('fsdp') on an (fsdp, tp) mesh)
    holds identical replica shards on several devices — writing each would
    multiply snapshot size and D2H traffic by the replication factor."""
    seen = set()
    out = []
    for s in sorted(l.addressable_shards, key=lambda s: s.device.id):
        key = _index_array(s.index).tobytes()
        if key not in seen:
            seen.add(key)
            out.append(s)
    return out


class MultiNodeCheckpointer:
    """Snapshot/restore a training state pytree, one file per process.

    ``async_write=True`` moves the disk write off the training thread: the
    device→host pull still happens inside ``save`` (the snapshot must capture
    the state *now* — the caller's next donating train step reuses those
    buffers), but serialization + atomic publish + GC run on a background
    writer thread, the same split the reference's double-buffering applied to
    communication. ``flush()`` joins outstanding writes; every read-side
    operation (election, load) flushes first so it only ever sees published
    files.
    """

    def __init__(self, name: str, comm: CommunicatorBase, path: str = ".",
                 cp_interval: int = 5, async_write: bool = False,
                 backend: str = "npz"):
        self.name = name
        self.comm = comm
        self.path = os.path.join(path, name)
        self.cp_interval = cp_interval  # snapshots kept in the window
        self.async_write = async_write
        if backend not in ("npz", "orbax"):
            raise ValueError(f"unknown checkpoint backend {backend!r}")
        self.backend = backend
        self._orbax = None  # lazy StandardCheckpointer (tensorstore/zarr)
        self._queue: Optional[queue.Queue] = None
        self._writer: Optional[threading.Thread] = None
        self._write_error: Optional[BaseException] = None
        self.replica_path = os.path.join(self.path, REPLICA_DIRNAME)
        # iterations GC must never delete: anything a caller pinned via
        # protect(), plus the last consensus winner (`_elected`, a single
        # slot REPLACED at each election — the only iteration known
        # valid on EVERY rank). A GC racing a failed save would
        # otherwise delete the one file the election can still agree on.
        self._protected: set = set()
        self._elected: Optional[int] = None
        #: optional optimizer shard-layout metadata (zero_layout_manifest
        #: / fsdp_layout_manifest) folded into every published manifest
        self.layout: Optional[dict] = None
        # every process writes its own snapshot file and may have its own
        # (non-shared) filesystem — each must create the directory
        os.makedirs(self.path, exist_ok=True)
        self._pre_election_barrier()

    def _pre_election_barrier(self):
        """Host-plane barrier when the communicator offers one (bounded
        waits, watchdog-abortable, no device collectives needed — a dead
        peer raises instead of hanging); device barrier as fallback."""
        hb = getattr(self.comm, "host_barrier", None)
        if callable(hb):
            hb()
        elif hasattr(self.comm, "barrier"):
            self.comm.barrier()

    # -- async writer ---------------------------------------------------

    def _ensure_writer(self):
        if self._writer is not None and self._writer.is_alive():
            return
        # bounded queue = backpressure: a disk slower than the save cadence
        # stalls save() instead of accumulating host copies of the full
        # training state until OOM (one in flight + one queued, the same
        # budget as the reference's double buffering)
        self._queue = queue.Queue(maxsize=1)
        self._writer = threading.Thread(
            target=self._writer_loop, name=f"ckpt-writer-{self.name}",
            daemon=True,
        )
        self._writer.start()
        self._register_atexit()

    def _register_atexit(self):
        # a script that never calls close() must not lose checkpoints:
        # at interpreter shutdown nothing can catch, so report instead of
        # raising. Registered once per checkpointer (both backends).
        if getattr(self, "_atexit_done", False):
            return
        self._atexit_done = True
        import atexit

        def _close_at_exit():
            try:
                self.close()
            except Exception as e:
                import warnings

                warnings.warn(f"checkpoint writer at exit: {e}")

        atexit.register(_close_at_exit)

    def _writer_loop(self):
        while True:
            # same-process producer, sentinel-terminated: close() always
            # delivers the None wake-up, so an unbounded get can't wedge
            # on a dead REMOTE peer (the hazard DL111 polices)
            item = self._queue.get()  # dlint: disable=DL111
            try:
                if item is None:
                    return
                arrays, fn, meta = item
                self._publish(arrays, fn, meta=meta)
            except BaseException as e:  # surfaced on next save/flush
                self._write_error = e
            finally:
                self._queue.task_done()

    def _raise_pending(self):
        if self._write_error is not None:
            e, self._write_error = self._write_error, None
            raise RuntimeError(
                f"async checkpoint write failed: {e!r}") from e

    def _drain(self):
        """Join queued writes WITHOUT raising — the collective read path
        (election) must reach its allgather even when this process's last
        write failed, or the other ranks hang in the collective; a failed
        write was never published, so the election skips it naturally."""
        if self._orbax is not None:
            try:
                self._orbax.wait_until_finished()
                self._gc()
            except Exception as e:
                import warnings

                warnings.warn(f"async checkpoint write failed (election "
                              f"will skip the unpublished snapshot): {e!r}")
        if self._queue is not None:
            self._queue.join()
        if self._write_error is not None:
            import warnings

            # consume the error: it is surfaced here, once — leaving it set
            # would re-raise from the atexit close() long after the fact
            e, self._write_error = self._write_error, None
            warnings.warn(
                f"async checkpoint write failed (election will skip the "
                f"unpublished snapshot): {e!r}")

    def flush(self):
        """Block until every queued snapshot is published."""
        if self._orbax is not None:
            self._orbax.wait_until_finished()
            self._gc()
        if self._queue is not None:
            self._queue.join()
        self._raise_pending()

    def close(self):
        """Join outstanding writes (trainer finalization hook)."""
        if self._orbax is not None:
            self._orbax.wait_until_finished()
            self._gc()
        if self._writer is not None and self._writer.is_alive():
            self._queue.join()
            self._queue.put(None)
            self._writer.join()
        self._writer = None
        self._raise_pending()

    # -- save -----------------------------------------------------------

    def _publish(self, arrays: dict, fn: str,
                 meta: Optional[dict] = None):
        """Atomic, verifiable publish: write to a tmp name, fsync, rename
        into place, then publish a sidecar JSON manifest carrying the
        file's SHA-256 (itself tmp+fsync+renamed). A crash at any point
        leaves either the previous snapshot (tmp never renamed) or a
        data file whose manifest proves it intact — a torn or corrupted
        file FAILS verification and is excluded from the consensus
        election instead of poisoning the restore.

        ``meta`` (the coverage map from :meth:`_coverage_meta`) is folded
        into the manifest under non-integrity keys — readers that only
        verify (serving/weights.py) ignore it; the reshard planner and
        tools/ckpt.py read the file set's geometry from it."""
        # chaos harness: pre-publish injection point — a full disk
        # (enospc) raises HERE with nothing published; slow_disk stalls
        _chaos.on_publish(fn)
        tmp = fn + ".npz"
        np.savez(tmp, **arrays)
        _fsync_file(tmp)
        sha = _sha256_file(tmp)
        size = os.path.getsize(tmp)
        os.replace(tmp, fn)  # atomic publish
        manifest = dict(meta or {})
        manifest.update({"format": 1, "sha256": sha, "bytes": size})
        mtmp = fn + ".json.tmp"
        with open(mtmp, "w", encoding="utf-8") as fh:
            json.dump(manifest, fh)
            fh.flush()
            try:
                os.fsync(fh.fileno())
            except OSError:
                pass
        os.replace(mtmp, fn + ".json")
        # chaos harness: torn/corrupt-snapshot injection point — damage
        # happens AFTER a fully valid publish, exactly like a bad disk
        _chaos.on_checkpoint(fn)
        self._gc()

    def set_layout(self, layout: Optional[dict]) -> None:
        """Attach optimizer shard-layout metadata (see
        ``optimizers/zero.py:zero_layout_manifest`` /
        ``fsdp_layout_manifest``) to every subsequently published
        manifest, so offline tools can interpret flat ZeRO/FSDP leaves
        without the live train step."""
        self.layout = layout

    def _coverage_meta(self, arrays: dict, iteration: int) -> dict:
        """The manifest coverage map for one flattened snapshot: saving
        iteration/world, the mesh's axis→size map, and per-leaf global
        shape + local shard count. Host-side metadata only — nothing
        here touches device arrays."""
        leaves: Dict[str, dict] = {}
        for k in arrays:
            m = re.match(r"leaf_(\d+)_nshards$", k)
            if m:
                leaves[m.group(1)] = {
                    "gshape": [int(d)
                               for d in arrays[f"leaf_{m.group(1)}_gshape"]],
                    "nshards": int(arrays[k])}
                continue
            m = re.match(r"leaf_(\d+)$", k)
            if m:
                leaves[m.group(1)] = {
                    "gshape": [int(d) for d in np.shape(arrays[k])],
                    "nshards": 0}
        meta = {"iteration": int(iteration),
                "world": int(self.comm.inter_size),
                "leaves": leaves}
        mesh = getattr(self.comm, "mesh", None)
        if mesh is not None:
            try:
                meta["axes"] = {str(a): int(s) for a, s in zip(
                    mesh.axis_names, np.shape(mesh.devices))}
            except Exception:  # noqa: BLE001 — metadata is best-effort
                pass
        if self.layout is not None:
            meta["layout"] = self.layout
        return meta

    def _orbax_ck(self):
        if self._orbax is None:
            import orbax.checkpoint as ocp

            self._orbax = ocp.StandardCheckpointer()
        return self._orbax

    def save(self, state: Any, iteration: int,
             host_state: Any = None) -> str:
        """Snapshot ``state`` (device pytree) plus optional ``host_state``
        (a small picklable dict: iterator position, RNG state, epoch
        counters — see ``StandardUpdater.host_state_dict``) under this
        rank's file for ``iteration``."""
        self._raise_pending()
        fn = os.path.join(
            self.path, f"snapshot_iter_{iteration}.{self.comm.inter_rank}"
        )
        if self.backend == "orbax":
            # orbax is natively async (tensorstore writers) and atomic
            # (tmp-dir + rename); our thread/queue machinery is redundant
            ck = self._orbax_ck()
            if not self.async_write:
                ck.wait_until_finished()
            ck.save(os.path.abspath(fn), _leaf_dict(state), force=True)
            if self.async_write:
                # the in-flight snapshot is invisible to _gc (tmp-dir name
                # doesn't match); prune completed ones so directories don't
                # accumulate across a long run
                self._register_atexit()
                self._gc()
            else:
                ck.wait_until_finished()
                self._gc()
            return fn
        arrays, treedef = _flatten_state(state)
        # saving-run world size: the completeness election checks the
        # file set against THIS, so a snapshot stays electable after the
        # process count changes (scale-up/down resharding) while a crash
        # that lost one rank's file still reads as incomplete
        arrays["__world__"] = np.int64(self.comm.inter_size)
        if host_state is not None:
            # host-side state rides the npz as pickled bytes (a uint8
            # array, so allow_pickle stays False on load) — covered by
            # the same SHA-256 as the device state
            arrays["__host_state__"] = np.frombuffer(
                pickle.dumps(host_state, pickle.HIGHEST_PROTOCOL),
                np.uint8).copy()
        meta = self._coverage_meta(arrays, iteration)
        if self.async_write:
            self._ensure_writer()
            self._queue.put((arrays, fn, meta))
        else:
            self._publish(arrays, fn, meta=meta)
        return fn

    def _iters_on_disk(self) -> List[int]:
        pat = re.compile(
            rf"snapshot_iter_(\d+)\.{self.comm.inter_rank}$"
        )
        out = []
        if os.path.isdir(self.path):
            for f in os.listdir(self.path):
                m = pat.match(f)
                if m:
                    out.append(int(m.group(1)))
        return sorted(out)

    def protect(self, iteration: int) -> None:
        """Pin ``iteration`` against the rolling-window GC (idempotent,
        permanent for this process — e.g. a milestone snapshot).

        The election separately pins its CURRENT winner (a single slot,
        replaced at each election, so pins don't accumulate across a
        long run). Protection is per-process state — a restarted
        process re-derives it from its next election."""
        self._protected.add(int(iteration))

    def _gc(self):
        """Rolling-window GC, consensus-aware.

        Deletes this rank's snapshots older than the ``cp_interval``
        window, EXCEPT (1) protected iterations — the last consensus
        winner, still possibly the only iteration valid on every rank —
        and (2) the newest iteration whose own file passes integrity
        verification: when the latest save failed or published a file
        that doesn't verify (full disk, torn write, chaos), the window
        would otherwise slide past the last GOOD snapshot and strand the
        next election with only broken files."""
        import shutil

        iters = self._iters_on_disk()
        drop = iters[:-self.cp_interval] if self.cp_interval else iters
        if not drop:
            return
        keep = set(self._protected)
        if self._elected is not None:
            keep.add(self._elected)
        valid = [
            it for it in iters
            if self._verify_snapshot_file(os.path.join(
                self.path,
                f"snapshot_iter_{it}.{self.comm.inter_rank}"))
        ]
        if valid:
            keep.add(max(valid))
        for it in drop:
            if it in keep:
                continue
            fn = os.path.join(
                self.path, f"snapshot_iter_{it}.{self.comm.inter_rank}")
            try:
                if os.path.isdir(fn):   # orbax snapshots are directories
                    shutil.rmtree(fn, ignore_errors=True)
                else:
                    os.remove(fn)
            except OSError:
                pass
            try:
                os.remove(fn + ".json")
            except OSError:
                pass

    # -- integrity -------------------------------------------------------

    def _verify_snapshot_file(self, fn: str) -> bool:
        """Is this snapshot file intact? A sidecar manifest (``fn.json``)
        carries the published file's SHA-256 and byte size; mismatch —
        a torn write, truncation, or bit rot — marks the file invalid so
        the election skips it. Files without a manifest (pre-hardening
        snapshots, orbax directories) are accepted as-is for
        compatibility. Results are cached by (mtime, size)."""
        if os.path.isdir(fn):
            return True  # orbax: tensorstore does its own checksumming
        if not os.path.exists(fn):
            return False
        mf = fn + ".json"
        if not os.path.exists(mf):
            return True
        try:
            with open(mf, encoding="utf-8") as fh:
                manifest = json.load(fh)
        except (OSError, ValueError):
            return False  # torn manifest: treat the snapshot as suspect
        st = os.stat(fn)
        key = (fn, st.st_mtime_ns, st.st_size)
        cache = getattr(self, "_verify_cache", None)
        if cache is None:
            cache = self._verify_cache = {}
        if key in cache:
            return cache[key]
        if manifest.get("bytes") not in (None, st.st_size):
            ok = False  # fast path: truncation shows in the size alone
        else:
            try:
                ok = _sha256_file(fn) == manifest.get("sha256")
            except OSError:
                ok = False
        if len(cache) > 128:
            cache.clear()
        cache[key] = ok
        return ok

    def _replica_file(self, it: int, rank: Optional[int] = None) -> str:
        """Path a ring replica of (iteration, rank) would live at —
        pushed by a neighbor via resilience/replica.py."""
        if rank is None:
            rank = self.comm.inter_rank
        return os.path.join(self.replica_path, f"snapshot_iter_{it}.{rank}")

    def _own_file(self, it: int) -> Optional[str]:
        """This rank's readable copy of iteration ``it``: the primary
        snapshot file when it verifies, else the ring replica a neighbor
        pushed back (the dead-host recovery path), else None."""
        primary = os.path.join(
            self.path, f"snapshot_iter_{it}.{self.comm.inter_rank}")
        if os.path.isdir(primary):
            return primary  # orbax: tensorstore checksums itself
        for fn in (primary, self._replica_file(it)):
            if (os.path.exists(fn) and not os.path.isdir(fn)
                    and self._verify_snapshot_file(fn)):
                return fn
        return None

    def _replica_iters_on_disk(self) -> List[int]:
        """Iterations for which a VALID replica of THIS rank's shard sits
        in the replica directory (written by a ring neighbor; on a shared
        filesystem, or restored to this host out of band)."""
        pat = re.compile(
            rf"snapshot_iter_(\d+)\.{self.comm.inter_rank}$")
        out = []
        if os.path.isdir(self.replica_path):
            for f in os.listdir(self.replica_path):
                m = pat.match(f)
                if m and self._verify_snapshot_file(
                        os.path.join(self.replica_path, f)):
                    out.append(int(m.group(1)))
        return sorted(out)

    def _valid_iters_on_disk(self) -> List[int]:
        """This rank's iterations whose snapshot files pass integrity
        verification — the election's own-file inventory. Ring replicas
        of this rank's shard count too: a restarted rank whose local
        disk is gone still votes for the iterations its neighbor
        preserved, so the election can land on the NEWEST iteration
        instead of falling back to an older common one."""
        own = [
            it for it in self._iters_on_disk()
            if self._verify_snapshot_file(os.path.join(
                self.path,
                f"snapshot_iter_{it}.{self.comm.inter_rank}"))
        ]
        return sorted(set(own) | set(self._replica_iters_on_disk()))

    # -- trainer integration --------------------------------------------

    def __call__(self, trainer):
        """Trainer-extension protocol (reference idiom:
        ``trainer.extend(checkpointer)``): snapshot the updater's state —
        device pytree plus host state (iterator position, RNG) when the
        updater provides it — at each trigger point."""
        host_fn = getattr(trainer.updater, "host_state_dict", None)
        self.save(trainer.updater.state, trainer.updater.iteration,
                  host_state=host_fn() if callable(host_fn) else None)

    def emergency_save(self, trainer, deadline_s: Optional[float] = None):
        """Last-chance synchronous snapshot (preemption / crash path).

        Bypasses the async writer queue entirely — the process is about
        to die, so the write must be on THIS thread and published before
        return. No collective is involved (saves are per-rank), so every
        rank can run it independently inside its own grace window;
        ``deadline_s`` (monotonic) skips the write when the window has
        already closed — a partial write past the deadline would only be
        garbage for the election to reject."""
        if deadline_s is not None and time.monotonic() >= deadline_s:
            return None
        host_fn = getattr(trainer.updater, "host_state_dict", None)
        state = trainer.updater.state
        iteration = trainer.updater.iteration
        fn = os.path.join(
            self.path, f"snapshot_iter_{iteration}.{self.comm.inter_rank}")
        if self.backend == "orbax":
            ck = self._orbax_ck()
            ck.save(os.path.abspath(fn), _leaf_dict(state), force=True)
            ck.wait_until_finished()
            return fn
        arrays, _ = _flatten_state(state)
        arrays["__world__"] = np.int64(self.comm.inter_size)
        host_state = host_fn() if callable(host_fn) else None
        if host_state is not None:
            arrays["__host_state__"] = np.frombuffer(
                pickle.dumps(host_state, pickle.HIGHEST_PROTOCOL),
                np.uint8).copy()
        self._publish(arrays, fn,
                      meta=self._coverage_meta(arrays, iteration))
        return fn

    def load_host_state(self, iteration: int) -> Any:
        """The pickled host state stored with this rank's snapshot for
        ``iteration`` — primary file or ring replica (None when the
        snapshot predates host state or the file is not this rank's to
        read)."""
        fn = self._own_file(iteration)
        if fn is None or os.path.isdir(fn):
            return None
        with np.load(fn, allow_pickle=False) as z:
            if "__host_state__" not in z.files:
                return None
            return pickle.loads(z["__host_state__"].tobytes())

    def resume(self, updater) -> Optional[int]:
        """Restore the updater from the newest complete snapshot, if any.

        Sets ``updater.state`` and ``updater.iteration`` and returns the
        restored iteration (None when nothing restorable exists). When the
        snapshot carries host state and the updater supports it
        (``load_host_state``), the iterator position, epoch counters, and
        shuffling RNG are restored too — the resumed run continues on the
        exact next batch. Otherwise the data iterator restarts from its
        beginning — the reference's restart-based contract, where resumed
        epochs reshuffle.
        """
        state, it = self.maybe_load(updater.state)
        if it is not None:
            updater.state = state
            updater.iteration = it
            host = self.load_host_state(it)
            restore = getattr(updater, "load_host_state", None)
            if host is not None and callable(restore):
                restore(host)
                return it
            # fast-forward the iterator's epoch counter, or an epoch-based
            # stop trigger would re-run every completed epoch on the
            # restored state (the position WITHIN the epoch restarts —
            # the reference's restart semantics)
            iterator = getattr(updater, "iterator", None)
            if (iterator is not None and hasattr(iterator, "epoch")
                    and hasattr(iterator, "batch_size")
                    and hasattr(iterator, "dataset")):
                n = len(iterator.dataset)
                if n:
                    iterator.epoch = it * iterator.batch_size // n
        return it

    # -- resume ---------------------------------------------------------

    def _complete_iters_on_disk(self) -> List[int]:
        """Iterations whose snapshot FILE SET is complete as seen from
        this filesystem: all ranks of the SAVING run's world (recorded
        in each file as ``__world__``) are present — which need not
        match the restoring run's process count (scale-up/scale-down
        resharding). Snapshots without the marker (orbax directories,
        pre-marker files) fall back to rank-suffix contiguity."""
        by_iter: dict = {}
        for d in (self.path, self.replica_path):
            if not os.path.isdir(d):
                continue
            for f in os.listdir(d):
                m = re.match(r"snapshot_iter_(\d+)\.(\d+)$", f)
                # regular files only: orbax snapshots are DIRECTORIES a
                # peer process cannot np.load, so scale-up (which loads
                # every leaf from peer files) stays npz-territory — an
                # orbax new-rank simply never elects, gracefully.
                # Replicas count toward completeness: a dead rank's
                # shard held by its ring neighbor still makes the set
                # loadable (the splice path reads the replica).
                if (m and not os.path.isdir(os.path.join(d, f))
                        and self._verify_snapshot_file(
                            os.path.join(d, f))):
                    by_iter.setdefault(int(m.group(1)), set()).add(
                        int(m.group(2)))
        out = []
        for it, ranks in by_iter.items():
            world = self._saved_world(it)
            need = (set(range(world)) if world
                    else set(range(max(ranks) + 1)))
            if need <= ranks:
                out.append(it)
        return sorted(out)

    def _saved_world(self, it: int) -> Optional[int]:
        """The saving run's process count, from ANY surviving file of
        iteration ``it`` — primary or replica, any rank: when rank 0's
        file is the one that died with its host, the marker must still
        be readable (None when unknowable: orbax directory, no marker,
        or no file at all)."""
        import glob as _glob

        pat = re.compile(rf"snapshot_iter_{it}\.\d+$")
        for d in (self.path, self.replica_path):
            for fn in sorted(_glob.glob(
                    os.path.join(d, f"snapshot_iter_{it}.*"))):
                if (not pat.search(os.path.basename(fn))
                        or os.path.isdir(fn)):
                    continue
                try:
                    with np.load(fn, allow_pickle=False) as z:
                        if "__world__" in z.files:
                            return int(z["__world__"])
                except Exception:  # noqa: BLE001 — unreadable = skip
                    continue
        return None

    def latest_common_iteration(self) -> Optional[int]:
        """Consensus election (reference: allgather of per-rank snapshot
        inventories, intersected). Each process's view is the UNION of
        its OWN files (works on non-shared filesystems, exactly the
        reference semantics) and the complete smaller-world snapshots it
        can see (the scale-up path: a rank new since the save has no own
        file but, on a shared filesystem, sees the saved ranks' complete
        set). The intersection still rejects snapshots any current
        OLD rank is missing."""
        self._drain()
        # the complete-set view covers peers' files, so a peer's
        # in-flight save is a race the own-file view never had: barrier
        # first — every process enters the election only after its own
        # saves returned, so post-barrier listings see them all
        self._pre_election_barrier()
        # VALID files only: a corrupt or torn snapshot (SHA mismatch
        # against its manifest) is excluded from this rank's inventory,
        # so the intersection falls back to the newest iteration intact
        # on every rank instead of electing a file nobody can load
        mine = sorted(set(self._valid_iters_on_disk())
                      | set(self._complete_iters_on_disk()))
        all_lists = self.comm.allgather_obj(mine)
        common = set(all_lists[0])
        for lst in all_lists[1:]:
            common &= set(lst)
        if common:
            # pin the winner against the rolling-window GC: until the
            # NEXT election it may be the only iteration every rank
            # still agrees on, and a GC racing a failed save must not
            # delete it out from under a retry of the restore
            self._elected = max(common)
            return max(common)
        return None

    def maybe_load(self, state: Any, iteration: Optional[int] = None,
                   allow_incomplete: bool = False,
                   leaf_resharder: Optional[Any] = None):
        """Restore ``state`` from the newest complete snapshot (or the given
        iteration). Returns (state, iteration) — unchanged state and None if
        nothing restorable exists.

        Resharding: a different device MESH restores fine (splicing, see
        ``_load_sharded_leaf``), onto FEWER processes (peer files are
        discovered by glob) and onto MORE (a rank with no own snapshot
        file loads every leaf from the peers' files). Cross-process
        resharding is npz-backend territory; orbax snapshots reshard
        within one process's file set.

        ``allow_incomplete=True`` is the elastic shrink-to-fit escape
        hatch (resilience/elastic.py): bypass the complete-file-set gate
        when this rank has no own file, and let the splice-level
        completeness check (``_SpliceTargets.require_complete``) decide —
        for fully-replicated leaves any one surviving file holds the
        whole state, so a dead rank's missing file need not block the
        resume. Leave it False everywhere else: the gate is what keeps a
        scale-up from silently loading wrong state.

        ``leaf_resharder`` is the multi-axis escape hatch for leaves
        whose saved GLOBAL shape differs from the template's — by
        construction only world-dependent frames (the flat-bucket EF
        residual stacks, optimizers/zero.py) hit this. It is called as
        ``leaf_resharder(i, ref, saved_gshape, fetch_full)`` where
        ``fetch_full()`` splices the full saved global array on host;
        returning an ndarray of the template's shape re-scatters it onto
        the template's sharding, returning None falls through to the
        usual different-model error. See
        ``checkpointing/reshard.py:default_leaf_resharder``."""
        self._drain()
        it = iteration if iteration is not None else self.latest_common_iteration()
        if it is None:
            return state, None
        self._elected = it
        fn = os.path.join(
            self.path, f"snapshot_iter_{it}.{self.comm.inter_rank}"
        )
        if self.backend == "orbax":
            if not os.path.exists(fn):
                raise FileNotFoundError(
                    f"{fn}: no orbax snapshot for this rank — restoring "
                    "onto more processes than saved is npz-backend only")
            loaded = self._orbax_ck().restore(
                os.path.abspath(fn), _leaf_dict(state))
        elif self._own_file(it) is not None:
            # primary when it verifies, else the ring replica a neighbor
            # pushed back — the restarted-host recovery path
            loaded = np.load(self._own_file(it), allow_pickle=False)
        elif os.path.exists(fn):
            raise ValueError(
                f"{fn}: snapshot file fails SHA-256 verification "
                "against its manifest (torn write or corruption) — "
                "refusing to load; the consensus election excludes "
                "such files, so pass no explicit iteration to fall "
                "back to the newest intact snapshot")
        else:
            # scale-up: this rank did not exist in the saving run — every
            # leaf comes from the peers' files. Only COMPLETE snapshots
            # qualify: a file set short of its saved world means a rank's
            # file is missing, not a smaller saving run, and loading a
            # peer's copy would silently hand this rank wrong state
            # (unless the elastic caller explicitly opted in, above).
            if (not allow_incomplete
                    and it not in self._complete_iters_on_disk()):
                raise FileNotFoundError(
                    f"{fn}: no snapshot file for this rank and iteration "
                    f"{it} is not a complete smaller-world snapshot")
            loaded = {}
        leaves, treedef = jax.tree_util.tree_flatten(state)
        keys = set(getattr(loaded, "files", loaded))
        new_leaves = []
        peers = _PeerSnapshots(self.path, it, self.comm.inter_rank,
                               self.comm.inter_size)
        try:
            for i, ref in enumerate(leaves):
                if f"leaf_{i}_nshards" in keys:
                    new_leaves.append(self._load_sharded_leaf(
                        loaded, i, ref, peers,
                        leaf_resharder=leaf_resharder))
                elif f"leaf_{i}" in keys:
                    new_leaves.append(self._plain_leaf(
                        loaded, i, ref, leaf_resharder=leaf_resharder))
                else:
                    new_leaves.append(self._leaf_from_peers(
                        i, ref, peers, it,
                        leaf_resharder=leaf_resharder))
        finally:
            peers.close()
        return jax.tree_util.tree_unflatten(treedef, new_leaves), it

    def _leaf_from_peers(self, i: int, ref, peers, it: int,
                         leaf_resharder=None):
        """Load leaf ``i`` when this process's own snapshot file lacks it
        (a rank that did not exist in the saving run)."""
        for z in peers:
            zk = set(getattr(z, "files", z))
            if f"leaf_{i}_nshards" in zk:
                return self._load_sharded_leaf(
                    z, i, ref, peers, leaf_resharder=leaf_resharder)
            if f"leaf_{i}" in zk:
                return self._plain_leaf(
                    z, i, ref, leaf_resharder=leaf_resharder)
        raise ValueError(
            f"snapshot iteration {it}: leaf {i} appears in no snapshot "
            "file — incomplete snapshot set")

    @staticmethod
    def _plain_leaf(loaded, i: int, ref, leaf_resharder=None):
        arr = loaded[f"leaf_{i}"]
        if (leaf_resharder is not None and hasattr(ref, "shape")
                and tuple(np.shape(arr)) != tuple(ref.shape)):
            # a replicated-saved world-dependent frame (e.g. an EF stack
            # snapshot from a 1-device run) restoring onto a different
            # world: same escape hatch as the sharded path
            out = leaf_resharder(i, ref, tuple(np.shape(arr)),
                                 lambda: np.asarray(arr))
            if out is not None:
                arr = np.asarray(out)
        # honor the reference leaf's sharding only when it was actually
        # committed — device_put on an uncommitted default-device array
        # would PIN the restored leaf to one device and clash with
        # replicated/sharded leaves inside the next jitted step
        if hasattr(ref, "sharding") and getattr(ref, "committed", False):
            return jax.device_put(arr, ref.sharding)
        if hasattr(ref, "dtype"):
            return jnp.asarray(arr, ref.dtype)
        return arr

    def _load_sharded_leaf(self, loaded, i: int, ref, peers,
                           leaf_resharder=None):
        """Reassemble a per-shard-saved leaf onto the template's sharding —
        each process device_puts only its own shards; no host ever sees the
        global array.

        Fast path: the template's shard indices match the saved ones
        (same mesh/sharding) — each index maps to one saved array.
        RESHARDING path (VERDICT r2 #5): on any index mismatch, each
        template shard is SPLICED from the overlapping ranges of the
        saved shards — the per-shard index manifests already on disk
        describe exactly which global slice every saved array covers, so
        restoring onto a different mesh (fewer/more devices, different
        partitioning) is pure interval arithmetic, consulting peer
        processes' snapshot files only when the local file does not
        cover a needed range."""
        n = int(loaded[f"leaf_{i}_nshards"])
        gshape = tuple(int(d) for d in loaded[f"leaf_{i}_gshape"])
        if not hasattr(ref, "dtype") or not hasattr(ref, "shape"):
            raise ValueError(
                f"snapshot leaf {i} was saved device-sharded ({n} shards, "
                f"global shape {gshape}) but the template leaf is not an "
                "array")
        def splice(targets):
            sp = _SpliceTargets(targets, gshape, np.dtype(ref.dtype))
            sp.consume(loaded, i)
            if not sp.complete:
                for z in peers:  # lazy: opened only when actually needed
                    sp.consume(z, i)
                    if sp.complete:
                        break
            sp.require_complete(i)
            return sp.bufs

        if tuple(ref.shape) != gshape:
            if leaf_resharder is not None:
                import types

                full = types.SimpleNamespace(
                    index=tuple(slice(0, d) for d in gshape))
                out = leaf_resharder(i, ref, gshape,
                                     lambda: splice([full])[0])
                if out is not None:
                    out = np.asarray(out)
                    if tuple(out.shape) != tuple(ref.shape):
                        raise ValueError(
                            f"leaf_resharder returned shape "
                            f"{tuple(out.shape)} for leaf {i}; template "
                            f"is {tuple(ref.shape)}")
                    if (hasattr(ref, "sharding")
                            and getattr(ref, "committed", False)):
                        return jax.device_put(out, ref.sharding)
                    return jnp.asarray(out, ref.dtype)
            hint = ""
            if (len(gshape) == 1 and len(ref.shape) == 1
                    and abs(gshape[0] - ref.shape[0]) < 256):
                # a flat-vector leaf off by less than one padding quantum:
                # almost certainly a ZeRO snapshot from before the
                # 2026-07-31 device-count-independent padding change
                # (optimizers/zero.py _padded_size), not a model change
                hint = (" (a flat ZeRO-1/2 vector off by <256 elements "
                        "suggests a pre-quantum-padding snapshot — "
                        "re-save from a live run; see "
                        "optimizers/zero.py:_padded_size)")
            raise ValueError(
                f"snapshot leaf {i}: saved global shape {gshape}, "
                f"template is {tuple(ref.shape)} — different model, not "
                f"a resharding{hint}")

        if not _is_device_sharded(ref):
            # REPLICATED template: the caller asks for the whole leaf on
            # every device, so assembling the global range on host is the
            # requested behavior, not a contract breach (sharded→
            # replicated resharding)
            import types

            full = types.SimpleNamespace(
                index=tuple(slice(0, d) for d in gshape))
            (buf,) = splice([full])
            if (hasattr(ref, "sharding")
                    and getattr(ref, "committed", False)):
                return jax.device_put(buf, ref.sharding)
            return jnp.asarray(buf, ref.dtype)

        # index-keyed lookup: replica shards (deduplicated at save) fan the
        # one saved copy back out to every device holding that index. Only
        # the SMALL idx arrays are read here — shard data stays lazy so
        # the resharding branch never materializes shards it won't splice
        saved_idx = {
            np.asarray(loaded[f"leaf_{i}_idx{k}"]).tobytes(): k
            for k in range(n)
        }
        refs = sorted(ref.addressable_shards, key=lambda s: s.device.id)
        if all(_index_array(r.index).tobytes() in saved_idx for r in refs):
            singles = [
                jax.device_put(
                    loaded[f"leaf_{i}_s"
                           f"{saved_idx[_index_array(r.index).tobytes()]}"],
                    r.device)
                for r in refs
            ]
        else:
            singles = [jax.device_put(buf, r.device)
                       for buf, r in zip(splice(refs), refs)]
        return jax.make_array_from_single_device_arrays(
            gshape, ref.sharding, singles)


def create_multi_node_checkpointer(name: str, comm: CommunicatorBase,
                                   path: str = ".", cp_interval: int = 5,
                                   async_write: bool = False,
                                   **kwargs) -> MultiNodeCheckpointer:
    """Factory matching the reference name (chainermn/extensions/checkpoint.py)."""
    return MultiNodeCheckpointer(name, comm, path=path,
                                 cp_interval=cp_interval,
                                 async_write=async_write, **kwargs)

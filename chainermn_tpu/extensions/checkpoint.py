"""Distributed checkpointer with consensus resume.

Reference: chainermn/extensions/checkpoint.py (SURVEY.md §2.5, §3.5; mount
empty — module path citation): each rank writes its own
``snapshot_iter_<N>.<rank>`` file, keeps a rolling window, and on resume all
ranks agree on the newest iteration present on *every* rank before loading —
the package's restart-based fault-tolerance story.

TPU-native mapping: the writers are processes; device arrays are pulled to
host (they are replicated or re-shardable on load) and stored as flattened
npz + a JSON manifest. The consensus election ("newest iteration all ranks
hold") rides the host object plane exactly like the reference's allgather.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, List, Optional

import numpy as np

import jax

from chainermn_tpu.comm.base import CommunicatorBase


def _flatten_state(state) -> dict:
    leaves, treedef = jax.tree_util.tree_flatten(state)
    arrays = {f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)}
    return arrays, treedef


class MultiNodeCheckpointer:
    """Snapshot/restore a training state pytree, one file per process."""

    def __init__(self, name: str, comm: CommunicatorBase, path: str = ".",
                 cp_interval: int = 5):
        self.name = name
        self.comm = comm
        self.path = os.path.join(path, name)
        self.cp_interval = cp_interval  # snapshots kept in the window
        # every process writes its own snapshot file and may have its own
        # (non-shared) filesystem — each must create the directory
        os.makedirs(self.path, exist_ok=True)
        if hasattr(comm, "barrier"):
            comm.barrier()

    # -- save -----------------------------------------------------------

    def save(self, state: Any, iteration: int) -> str:
        fn = os.path.join(
            self.path, f"snapshot_iter_{iteration}.{self.comm.inter_rank}"
        )
        arrays, treedef = _flatten_state(state)
        np.savez(fn + ".npz", **arrays)
        os.replace(fn + ".npz", fn)  # atomic publish
        self._gc()
        return fn

    def _iters_on_disk(self) -> List[int]:
        pat = re.compile(
            rf"snapshot_iter_(\d+)\.{self.comm.inter_rank}$"
        )
        out = []
        if os.path.isdir(self.path):
            for f in os.listdir(self.path):
                m = pat.match(f)
                if m:
                    out.append(int(m.group(1)))
        return sorted(out)

    def _gc(self):
        iters = self._iters_on_disk()
        for it in iters[:-self.cp_interval]:
            try:
                os.remove(os.path.join(
                    self.path, f"snapshot_iter_{it}.{self.comm.inter_rank}"))
            except OSError:
                pass

    # -- resume ---------------------------------------------------------

    def latest_common_iteration(self) -> Optional[int]:
        """Consensus election: newest iteration present on ALL processes
        (reference: allgather of per-rank snapshot inventories)."""
        mine = self._iters_on_disk()
        all_lists = self.comm.allgather_obj(mine)
        common = set(all_lists[0])
        for lst in all_lists[1:]:
            common &= set(lst)
        return max(common) if common else None

    def maybe_load(self, state: Any, iteration: Optional[int] = None):
        """Restore ``state`` from the newest complete snapshot (or the given
        iteration). Returns (state, iteration) — unchanged state and None if
        nothing restorable exists."""
        it = iteration if iteration is not None else self.latest_common_iteration()
        if it is None:
            return state, None
        fn = os.path.join(
            self.path, f"snapshot_iter_{it}.{self.comm.inter_rank}"
        )
        loaded = np.load(fn, allow_pickle=False)
        leaves, treedef = jax.tree_util.tree_flatten(state)
        new_leaves = []
        for i, ref in enumerate(leaves):
            arr = loaded[f"leaf_{i}"]
            if hasattr(ref, "sharding"):
                arr = jax.device_put(arr, ref.sharding)
            elif hasattr(ref, "dtype"):
                arr = arr.astype(ref.dtype)
            new_leaves.append(arr)
        return jax.tree_util.tree_unflatten(treedef, new_leaves), it


def create_multi_node_checkpointer(name: str, comm: CommunicatorBase,
                                   path: str = ".", cp_interval: int = 5,
                                   **kwargs) -> MultiNodeCheckpointer:
    """Factory matching the reference name (chainermn/extensions/checkpoint.py)."""
    return MultiNodeCheckpointer(name, comm, path=path, cp_interval=cp_interval)

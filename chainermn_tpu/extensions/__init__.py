"""Training-loop extensions: evaluator, persistent-state sync, abort hook.

Reference: chainermn/extensions/__init__.py and chainermn/global_except_hook.py
(SURVEY.md §2.5; mount empty — module path citations).
"""

from __future__ import annotations

import sys
import traceback
from typing import Any, Callable

import jax

from chainermn_tpu.comm.base import CommunicatorBase
from .checkpoint import MultiNodeCheckpointer, create_multi_node_checkpointer

__all__ = [
    "create_multi_node_evaluator",
    "AllreducePersistent",
    "allreduce_persistent",
    "MultiNodeCheckpointer",
    "create_multi_node_checkpointer",
    "install_global_except_hook",
]


def create_multi_node_evaluator(actual_evaluator, communicator: CommunicatorBase):
    """Each process evaluates its shard; scalar results are averaged across
    the process plane (reference: allreduce_obj mean of the result dict).

    ``actual_evaluator`` is any callable returning a dict of scalar metrics;
    the wrapper keeps its attributes (reference delegates the same way).
    """

    class _MultiNodeEvaluator:
        def __init__(self, ev, comm):
            self._ev = ev
            self._comm = comm

        def __call__(self, trainer=None, *args, **kwargs):
            # Run the inner evaluator WITHOUT the trainer so it cannot
            # publish un-reduced local metrics; publish only the job-wide
            # means (the whole point of the multi-node evaluator).
            local = self._ev(*args, **kwargs)
            scalars = {k: float(v) for k, v in local.items()}
            reduced = self._comm.allreduce_obj(scalars, "mean")
            if trainer is not None:
                trainer.observation.update(reduced)
            return reduced

        def __getattr__(self, name):
            return getattr(self._ev, name)

    return _MultiNodeEvaluator(actual_evaluator, communicator)


def allreduce_persistent(state, communicator: CommunicatorBase, op: str = "mean"):
    """Average persistent (non-gradient) arrays — BN running stats — across
    ranks so snapshots and eval see consistent values.

    Reference: AllreducePersistent extension (chainermn/extensions/). Call on
    the state pytree inside the jitted step (varying leaves get reduced) or
    on driver-level stacked arrays.
    """
    return communicator.allreduce_grad(state, op)


class AllreducePersistent:
    """Extension-object form for trainer integration (reference API shape)."""

    def __init__(self, model_state_getter: Callable[[], Any],
                 communicator: CommunicatorBase,
                 model_state_setter: Callable[[Any], None]):
        self._get = model_state_getter
        self._set = model_state_setter
        self._comm = communicator

    def __call__(self, trainer=None):
        self._set(allreduce_persistent(self._get(), self._comm))


def install_global_except_hook(communicator: CommunicatorBase = None):
    """Fail-fast job abort: any uncaught exception tears the whole job down.

    Reference: chainermn/global_except_hook.py — prints the traceback and
    calls MPI_Abort so no rank is left deadlocked inside a collective. Here:
    print, post the abort poison key (peers' object-plane probes raise
    within seconds), hard-exit. NOT a graceful ``jax.distributed.shutdown``
    — on the coordinator host that blocks waiting for the very peers that
    are stuck in collectives, leaving the job wedged (observed). With one
    process it degrades to print-and-exit, still avoiding a wedged TPU
    runtime on partially-enqueued programs.
    """

    prev_hook = sys.excepthook

    def _hook(exc_type, exc_value, exc_tb):
        try:
            sys.stderr.write("chainermn_tpu: uncaught exception — aborting job\n")
            traceback.print_exception(exc_type, exc_value, exc_tb)
            sys.stderr.flush()
        finally:
            try:
                if jax.process_count() > 1:
                    from chainermn_tpu.comm.object_plane import post_abort

                    post_abort(f"{exc_type.__name__}: {exc_value} "
                               f"(process {jax.process_index()})")
            except Exception:
                pass
            import os

            os._exit(13)

    sys.excepthook = _hook
    return prev_hook

"""XlaCommunicator — the communicator stack, rebuilt on a device mesh.

The reference implements seven hand-built collective algorithms
(reference modules, per SURVEY.md §2.1: chainermn/communicators/
{naive,flat,hierarchical,two_dimensional,single_node,non_cuda_aware,pure_nccl}
_communicator.py — mount was empty, so module paths only). Each one is a
topology-aware composition of NCCL (intra-node) and MPI (inter-node)
primitives: pure_nccl = one flat NCCL ring; hierarchical = intra-node reduce →
inter-node allreduce → intra-node bcast; two_dimensional = reduce-scatter /
allreduce / all-gather.

On TPU this entire taxonomy collapses into **one** communicator over a
:class:`jax.sharding.Mesh`: XLA's collective lowering already performs the
hierarchical / 2-D decompositions over ICI (intra-slice) and DCN
(inter-slice), chosen per topology by the compiler. The legacy names are kept
as aliases that shape the mesh (see :mod:`chainermn_tpu.comm.factory`) so
reference scripts keep working.

Dual-mode collectives:

* called on **tracers** (inside ``jit`` / ``shard_map`` with the mesh axes
  bound) → ``lax.psum`` / ``all_gather`` / ``all_to_all`` / ``ppermute``;
* called on **concrete arrays** → driver-level ops on *stacked per-rank*
  arrays (leading axis == ``size``), jitted with sharding constraints so XLA
  still emits real collectives when inputs live sharded in HBM.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .base import CommunicatorBase
from .object_plane import ObjectPlane

DEFAULT_AXIS = "r"

# Default gradient bucket for DCN-facing communicators (hierarchical /
# two_dimensional aliases). Derivation in docs/scaling_model.md §4: a
# bucket must be (a) large enough that per-collective launch latency
# (~100 µs over DCN) is <10% of its transfer time at ~25 GB/s per-host
# DCN bandwidth → ≥ 4 MB, and (b) small enough that a typical model's
# gradients split into ≥ ~8 buckets so the first reduction can overlap
# the rest of the backward (ResNet-50 bf16 grads = 51 MB → 13 buckets;
# the 124M LM = 248 MB → 62). 4 MiB satisfies both ends.
DEFAULT_DCN_BUCKET_BYTES = 4 * 2 ** 20


def plan_buckets(sized_items, bucket_bytes):
    """Greedy in-order packing of ``(key, nbytes)`` items into buckets of
    at most ``bucket_bytes`` (an oversized single item gets its own
    bucket). Returns a list of key-lists. Pure — the unit the scaling
    model's tests assert against (docs/scaling_model.md §4)."""
    buckets, cur, cur_bytes = [], [], 0
    for key, nb in sized_items:
        if cur and cur_bytes + nb > bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(key)
        cur_bytes += nb
    if cur:
        buckets.append(cur)
    return buckets


def _is_tracer(x) -> bool:
    leaves = jax.tree_util.tree_leaves(x)
    return any(isinstance(l, jax.core.Tracer) for l in leaves)


def _reduce_in_graph(x, axes, op: str):
    if op == "sum":
        return lax.psum(x, axes)
    if op == "mean":
        return lax.pmean(x, axes)
    if op == "max":
        return lax.pmax(x, axes)
    if op == "min":
        return lax.pmin(x, axes)
    raise ValueError(f"unsupported allreduce op: {op!r}")


def _reduce_stacked(x, op: str):
    if op == "sum":
        return jnp.sum(x, axis=0)
    if op == "mean":
        return jnp.mean(x, axis=0)
    if op == "max":
        return jnp.max(x, axis=0)
    if op == "min":
        return jnp.min(x, axis=0)
    raise ValueError(f"unsupported allreduce op: {op!r}")


class XlaCommunicator(CommunicatorBase):
    """Communicator over (a sub-axis-set of) a JAX device mesh.

    Args:
      mesh: the backing mesh. If ``None``, a default mesh over all devices is
        built (1-D axis ``'r'`` single-process; ``('dcn', 'ici')`` when
        multiple processes participate).
      axes: the mesh axis names this communicator reduces over, in order.
        Defaults to all mesh axes. A model-parallel script builds one mesh
        ``('data', 'model')`` and two communicators sharing it.
      allreduce_grad_dtype: optional communication dtype for
        :meth:`allreduce_grad` (reference: ``allreduce_grad_dtype`` — fp16
        comm for fp32 params in pure_nccl_communicator.py). On TPU the
        natural choice is ``jnp.bfloat16``.
    """

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        axes: Optional[Sequence[str]] = None,
        allreduce_grad_dtype: Optional[Any] = None,
        dcn_bucket_bytes: Optional[int] = None,
        host_staged: bool = False,
        _object_plane: Optional[ObjectPlane] = None,
    ):
        if mesh is None:
            mesh = _default_mesh()
        self._mesh = mesh
        self._axes: Tuple[str, ...] = tuple(axes) if axes else tuple(mesh.axis_names)
        for a in self._axes:
            if a not in mesh.axis_names:
                raise ValueError(f"axis {a!r} not in mesh axes {mesh.axis_names}")
        self._grad_dtype = allreduce_grad_dtype
        self._bucket_bytes = dcn_bucket_bytes
        self._host_staged = host_staged
        self._obj = _object_plane or ObjectPlane()
        self._jit_cache = {}
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        self._size = int(math.prod(sizes[a] for a in self._axes))

    # -- topology -------------------------------------------------------

    @property
    def size(self) -> int:
        return self._size

    @property
    def rank(self) -> int:
        """Rank of this process's first device IN THIS COMMUNICATOR —
        dense in ``[0, size)``, so it is always a valid root/peer for
        this communicator's collectives (the reference invariant
        ``0 <= rank < size``). On a sub-axis communicator a rank names a
        device group; this is the first group containing one of this
        process's devices. Single-controller: 0, and the driver stands
        in for every rank. The mesh-global flat position (the old
        convention, which could exceed ``size`` on sub-axis
        communicators) lives at :attr:`global_index`."""
        if jax.process_count() == 1:
            return 0
        pid = jax.process_index()
        groups = self._comm_device_groups()
        for i in range(groups.shape[0]):
            if any(int(d.process_index) == pid for d in groups[i]):
                return i
        return 0

    @property
    def global_index(self) -> int:
        """Flat-mesh index of this process's first addressable device —
        a MESH coordinate, not a rank: it can reach ``mesh.devices.size``
        on sub-axis communicators, so never pass it as a root (dlint
        DL103). Use it for mesh-global bookkeeping (labels, logs);
        use :attr:`rank` to address this communicator's collectives."""
        if jax.process_count() == 1:
            return 0
        flat = self._mesh.devices.reshape(-1)
        for i, d in enumerate(flat):
            if d.process_index == jax.process_index():
                return int(i)
        return 0

    @property
    def intra_size(self) -> int:
        return jax.local_device_count()

    @property
    def intra_rank(self) -> int:
        """Always 0 — DOCUMENTED DEVIATION under the process=node mapping.

        Reference contract (communicator_base.py ``intra_rank``): this
        rank's position within its node, produced by MPI's hostname
        split and used to pick the node-local CUDA device. This
        framework's process model (MIGRATION.md §Process model) maps one
        JAX PROCESS to the reference's "node": a process owns all its
        local devices (``intra_size`` of them), so as the node's only
        member its within-node rank is identically 0 — consistent with
        ``rank`` being the process's FIRST addressable device and with
        ``inter_rank``/``inter_size`` being the process index/count
        (checkpoint shard naming, ``scatter_dataset``, and rank-0
        election all build on that). Device selection, the reference's
        only use of ``intra_rank``, is ``jax.local_devices()`` here.
        Tested: tests/comm_tests/test_communicator.py (single-process)
        and test_multiprocess_collectives.py (two processes, one host —
        still 0 on both, because a process IS a node, hosts don't enter
        the mapping).
        """
        return 0

    @property
    def inter_rank(self) -> int:
        return jax.process_index()

    @property
    def inter_size(self) -> int:
        return jax.process_count()

    # -- mesh access ----------------------------------------------------

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return self._axes

    @property
    def axis_name(self) -> str:
        """The single axis name (errors if this communicator spans several —
        split first, or address axes explicitly)."""
        if len(self._axes) != 1:
            raise ValueError(
                f"communicator spans axes {self._axes}; use .axis_names or split()"
            )
        return self._axes[0]

    def axis_index(self):
        """In-graph rank of the executing shard (reference: ``comm.rank``
        inside rank-branching code; here a traced value)."""
        idx = lax.axis_index(self._axes[0])
        for a in self._axes[1:]:
            idx = idx * lax.axis_size(a) + lax.axis_index(a)
        return idx

    # -- sub-communicators ---------------------------------------------

    def split(self, color, key=None, rank: Optional[int] = None
              ) -> "XlaCommunicator":
        """Split into per-color sub-communicators (reference:
        ``CommunicatorBase.split(color, key)``, any MPI coloring).

        ``color`` may be a length-``size`` sequence (every rank's color, the
        SPMD single-controller form of the reference's per-rank argument) or
        the closed forms ``('block', k)`` / ``('stride', k)``.

        Regular partitions (block/strided) take the fast path: the
        communicator's device block is re-factored into a 2-D mesh, so the
        sub-communicator's collectives stay addressable inside ONE compiled
        program spanning the parent mesh. Arbitrary colorings build a fresh
        sub-mesh from the color group's devices — fully supported for
        driver-level collectives, the object plane, and per-group
        shard_map programs, but (by construction) an irregular group is not
        a named axis of the parent mesh, so it cannot be psum-addressed
        from a program compiled over the parent.

        ``rank`` selects whose color group to return (default: this
        process's rank) — the single-controller escape hatch for driving
        several groups from one script.

        ``key`` (MPI rank-ordering within each group) is fully honored:
        each group's devices enter its (sub-)mesh sorted by
        ``(key[rank], rank)`` — exactly ``MPI_Comm_split``'s tie-broken
        ordering — so a reordering key permutes shard identities by
        permuting the mesh's device array (device order IS rank order on
        a mesh; upstream ``CommunicatorBase.split`` → ``MPI_Comm_split``,
        any key). A scalar key carries no ordering information and is
        ignored, like MPI's all-equal-keys case.
        """
        n = self._size
        keys = None
        if key is not None:
            try:
                keys = list(key)
            except TypeError:
                keys = None  # scalar key: no ordering information
            if keys is not None and len(keys) != n:
                raise ValueError(f"need {n} keys, got {len(keys)}")

        def order(members):
            if keys is None:
                return list(members)
            return sorted(members, key=lambda i: (keys[i], i))
        kind = None
        if isinstance(color, tuple) and color[0] in ("block", "stride"):
            kind, k = color
            if k <= 0 or n % k != 0:
                raise ValueError(f"group size {k} does not divide world {n}")
        else:
            colors = list(color)
            if len(colors) != n:
                raise ValueError(f"need {n} colors, got {len(colors)}")
            # An explicit rank asks for THAT rank's group as its own mesh —
            # honor it even for colorings that happen to be regular, so a
            # per-group driving loop never silently gets the SPMD
            # axes-refactored communicator instead.
            if rank is None:
                k = n // (max(colors) + 1) if max(colors) >= 0 else n
                if (k > 0 and n % k == 0
                        and colors == [r // k for r in range(n)]):
                    kind = "block"
                elif (k > 0 and n % k == 0
                      and colors == [r % (n // k) for r in range(n)]):
                    kind = "stride"
            if kind is None:
                # per-group sub-mesh from the color's device list
                r = self.rank if rank is None else rank
                if not 0 <= r < n:
                    raise ValueError(f"rank {r} out of range [0, {n})")
                members = order(
                    [i for i in range(n) if colors[i] == colors[r]])
                sub = self._comm_devices()[members]
                mesh = Mesh(sub, (f"{self._axes[0]}_split",))
                return XlaCommunicator(
                    mesh=mesh,
                    allreduce_grad_dtype=self._grad_dtype,
                    dcn_bucket_bytes=self._bucket_bytes,
                    host_staged=self._host_staged,
                    _object_plane=self._obj,
                )
        # Re-factor the communicator's device block into a 2-D mesh whose
        # second ("intra") axis walks the members of one color group.
        flat = self._comm_devices()
        inter, intra = f"{self._axes[0]}_inter", f"{self._axes[0]}_intra"
        if kind == "block":
            # group g = ranks [g*k, (g+1)*k): row-major factorization;
            # each row walks its group in (key, rank) order
            rows = [order(range(g * k, (g + 1) * k)) for g in range(n // k)]
            mesh = Mesh(flat[np.asarray(rows)], (inter, intra))
        else:
            # group c = ranks {c, c+G, c+2G, ...} with G = n//k groups:
            # element [m, c] of the (k, G) grid is group c's m-th member
            # in (key, rank) order (rank m*G + c when key is None)
            G = n // k
            cols = [order(range(c, n, G)) for c in range(G)]
            mesh = Mesh(flat[np.asarray(cols).T], (intra, inter))
        owned = (intra,)
        return XlaCommunicator(
            mesh=mesh,
            axes=owned,
            allreduce_grad_dtype=self._grad_dtype,
            dcn_bucket_bytes=self._bucket_bytes,
            host_staged=self._host_staged,
            _object_plane=self._obj,
        )

    def _require_all_processes(self, what: str) -> None:
        """Object-plane transports barrier over ALL processes and assume
        every process contributes — a split() sub-communicator spanning a
        subset of processes would hang on the absent peers (and their
        sequence numbers would desynchronize), so refuse up front."""
        procs = {int(d.process_index) for d in self._comm_devices()}
        if procs != set(range(jax.process_count())):
            raise NotImplementedError(
                f"{what} on a sub-communicator whose devices span only a "
                "subset of processes are not supported (the object-plane "
                f"transport barriers over all {jax.process_count()} "
                "processes); use the compiled in-graph collectives on the "
                "sub-mesh instead")

    def _comm_device_groups(self) -> np.ndarray:
        """(size, k) device array: row r is rank r's device group — one
        member per complementary mesh coordinate (k == 1 when the
        communicator spans the whole mesh)."""
        names = self._mesh.axis_names
        perm = [names.index(a) for a in self._axes] + [
            i for i, a in enumerate(names) if a not in self._axes
        ]
        d = np.transpose(self._mesh.devices, perm)
        return d.reshape(self._size, -1)

    def _comm_devices(self) -> np.ndarray:
        """Rank-representative devices (each rank group's first member),
        flattened in rank order."""
        return self._comm_device_groups()[:, 0]

    # -- array collectives ----------------------------------------------

    def allreduce(self, x, op: str = "sum"):
        if _is_tracer(x):
            return jax.tree_util.tree_map(
                lambda l: _reduce_in_graph(l, self._axes, op), x
            )
        if self._host_staged:
            return self._host_staged_allreduce(x, op)
        return self._driver(("allreduce", op), x, stacked_in=True)

    def _host_staged_allreduce(self, x, op, comm_dtype=None):
        """Driver-level allreduce through host memory + the object plane —
        the reference NonCudaAwareCommunicator's path (device → host
        staging buffer → MPI → device; non_cuda_aware_communicator.py).
        Debugging fallback, not a perf path; in-graph collectives stay
        compiled (there is no host in a compiled program to stage through).

        Stacking contract: single-process, the leading axis is the FULL
        rank space (``comm.size``, the single-controller driver contract);
        multi-process, each process stacks only its LOCAL ranks
        (``size // inter_size``) and the cross-process reduction of the
        per-process partials rides the object plane — never a compiled
        collective.
        """
        np_ops = {"sum": np.sum, "max": np.max, "min": np.min}
        if op not in np_ops and op != "mean":
            raise ValueError(f"unsupported allreduce op: {op!r}")
        if self.inter_size > 1:
            self._require_all_processes("host-staged collectives")
        if self.inter_size > 1 and self._size % self.inter_size:
            raise ValueError(
                f"host-staged allreduce needs equal per-process rank "
                f"counts; size {self._size} over {self.inter_size} "
                "processes")
        expected = (self._size if self.inter_size == 1
                    else self._size // self.inter_size)
        # mean = global sum / global count (a mean of per-process means
        # would only be correct by the equal-count guarantee; the sum form
        # is correct by construction)
        base_op = "sum" if op == "mean" else op

        def one(l):
            l = np.asarray(l)  # device → host
            if l.ndim == 0 or l.shape[0] != expected:
                raise ValueError(
                    f"host-staged collective expects a stacked array with "
                    f"leading axis {expected} "
                    f"({'per-rank' if self.inter_size == 1 else 'LOCAL ranks'}),"
                    f" got {l.shape}")
            orig = l.dtype
            if comm_dtype is not None:
                l = l.astype(comm_dtype)
            red = np_ops[base_op](l, axis=0)
            if self.inter_size > 1:
                parts = self._obj.allgather_obj(red)  # host transport
                red = np_ops[base_op](np.stack(parts), axis=0)
            red = np.asarray(red, orig)  # comm-dtype round-trip ends here
            if op == "mean":
                # match the compiled path's promotion: integer means are
                # float (jnp.mean semantics), float dtypes are preserved
                res = orig if np.issubdtype(orig, np.floating) \
                    else np.float32
                red = np.asarray(red / self._size, res)
            return self._replicate(red)  # host → device

        return jax.tree_util.tree_map(one, x)

    def bcast(self, x, root: int = 0):
        if _is_tracer(x):
            # Masked psum: select root's value, zero elsewhere, sum. The mask
            # must be a where (not multiply) so NaN/Inf garbage in non-root
            # buffers — bcast's contract is that they are don't-care — cannot
            # poison the result.
            def _b(l):
                keep = self.axis_index() == root
                return lax.psum(
                    jnp.where(keep, l, jnp.zeros_like(l)), self._axes
                )

            return jax.tree_util.tree_map(_b, x)
        # Driver level: in a single-controller program the caller holds the
        # root's value — broadcast is replication placement. (No stacked
        # form: a leading dim equal to comm.size would be ambiguous with
        # genuine data; slice the root yourself if you hold a stack.)
        return self._replicate(x)

    def allgather(self, x):
        if _is_tracer(x):
            return jax.tree_util.tree_map(
                lambda l: lax.all_gather(l, self._axes), x
            )
        # stacked in, stacked out (every rank sees all): replicate.
        return self._replicate(x)

    def alltoall(self, x):
        if _is_tracer(x):
            return jax.tree_util.tree_map(
                lambda l: lax.all_to_all(
                    l, self._axes, split_axis=0, concat_axis=0, tiled=True
                ),
                x,
            )
        # stacked [size, size, ...]: out[s, r] = in[r, s]
        if self._host_staged:
            if self.inter_size > 1:
                raise NotImplementedError(
                    "host-staged alltoall is single-controller only (the "
                    "stacked [size, size, ...] form); multi-process "
                    "exchanges go through send_obj/recv_obj or the "
                    "compiled in-graph alltoall")

            def _a2a(l):
                l = np.asarray(l)
                if l.ndim < 2 or l.shape[0] != self._size:
                    raise ValueError(
                        f"host-staged alltoall expects a stacked "
                        f"[{self._size}, {self._size}, ...] array, got "
                        f"{l.shape}")
                return self._replicate(np.swapaxes(l, 0, 1))

            return jax.tree_util.tree_map(_a2a, x)
        return self._driver(("alltoall",), x, stacked_in=True)

    def gather(self, x, root: int = 0):
        """Reference ``gather`` (mpi_communicator_base.py): root receives
        the rank-ordered stack, other ranks receive None.

        In-graph the compiled analog is ``all_gather`` (an SPMD program
        cannot return None on some shards). Driver level: single-process,
        the stacked-input contract applies and the single controller IS
        the root — the validated stack comes back replicated; multi-
        process, each process contributes its LOCAL ranks' stack and only
        the process owning ``root`` gets the full rank-ordered stack
        (object-plane transport), everyone else None.
        """
        if _is_tracer(x):
            return jax.tree_util.tree_map(
                lambda l: lax.all_gather(l, self._axes), x
            )
        if not 0 <= root < self._size:
            raise ValueError(f"root {root} out of range [0, {self._size})")
        if self.inter_size == 1:
            def _chk(l):
                l = jnp.asarray(l)
                if l.ndim == 0 or l.shape[0] != self._size:
                    raise ValueError(
                        f"driver-level gather expects a stacked per-rank "
                        f"array with leading axis {self._size}, got shape "
                        f"{l.shape}")
                return l

            return self._replicate(jax.tree_util.tree_map(_chk, x))
        self._require_all_processes("driver-level gather")
        procs = [int(d.process_index) for d in self._comm_devices()]
        parts = self._obj.gather_obj(
            jax.tree_util.tree_map(np.asarray, x), root=procs[root])
        if parts is None:
            return None
        # reassemble per-process local stacks into global rank order
        slot = []
        seen: dict = {}
        for p in procs:
            slot.append((p, seen.get(p, 0)))
            seen[p] = seen.get(p, 0) + 1

        def _one(*proc_leaves):
            for p, l in enumerate(proc_leaves):
                if np.ndim(l) == 0 or np.shape(l)[0] != seen.get(p, 0):
                    raise ValueError(
                        f"process {p} must stack its {seen.get(p, 0)} "
                        f"LOCAL ranks on the leading axis, got "
                        f"{np.shape(l)}")
            return np.stack([proc_leaves[p][i] for p, i in slot])

        return jax.tree_util.tree_map(_one, *parts)

    def scatter(self, x, root: int = 0):
        if _is_tracer(x):
            def _s(l):
                # Each shard takes its own slice of the (replicated) input.
                return lax.dynamic_index_in_dim(
                    l, self.axis_index(), axis=0, keepdims=False
                )

            return jax.tree_util.tree_map(_s, x)
        # Driver: shard the leading axis over the communicator's mesh axes.
        spec = P(self._axes if len(self._axes) > 1 else self._axes[0])
        sharding = NamedSharding(self._mesh, spec)
        return jax.tree_util.tree_map(
            lambda l: jax.device_put(jnp.asarray(l), sharding), x
        )

    def send(self, x, dest: int, tag: int = 0, as_rank: int = None):
        """Eager point-to-point send of concrete arrays.

        Reference (mpi_communicator_base.py): mid-script blocking
        ``comm.send(array, dest, tag)`` between processes. In-graph
        (tracer) P2P must use :mod:`chainermn_tpu.functions` — compiled
        ``ppermute`` — but on concrete arrays this routes device→host →
        chunked object plane → peer process, so reference-shaped eager
        scripts run unchanged.

        Eager P2P addresses ANY rank (the reference's rank is a process —
        one MPI rank per GPU; here a process may host several ranks).
        The object-plane channel is qualified by BOTH endpoint ranks, so
        messages to co-located ranks of one process ride separate ordered
        channels and never interleave. A multi-device process sends as
        its canonical (first) rank by default — ``as_rank`` sends as one
        of its other local ranks, mirroring ``recv(..., as_rank=...)``.
        """
        if _is_tracer(x):
            raise RuntimeError(
                "comm.send was called on a traced value: inside a jitted "
                "(shard_map) program point-to-point transfers are compiled "
                "collective-permutes — use chainermn_tpu.functions.send/recv"
            )
        src_rank = self.rank if as_rank is None else as_rank
        dest_proc = self._rank_process(dest)
        if dest_proc == jax.process_index():
            raise ValueError(
                f"eager send to rank {dest} targets this same process; "
                "same-process shards exchange data inside the compiled "
                "program (chainermn_tpu.functions.send/recv)"
            )
        if self._rank_process(src_rank) != jax.process_index():
            raise ValueError(
                f"as_rank {src_rank} is not a local rank of this process")
        payload = jax.tree_util.tree_map(np.asarray, x)  # device_get
        self._obj.send_obj(payload, dest_proc,
                           self._p2p_tag(tag, src_rank, dest))

    def recv(self, src: int, tag: int = 0, as_rank: int = None):
        """Eager point-to-point receive (see :meth:`send`); returns
        device-committed arrays. ``as_rank``: receive on behalf of a
        specific local rank of this process (default: canonical)."""
        src_proc = self._rank_process(src)
        if src_proc == jax.process_index():
            raise ValueError(
                f"eager recv from rank {src} targets this same process; "
                "same-process shards exchange data inside the compiled "
                "program (chainermn_tpu.functions.send/recv)"
            )
        me = self.rank if as_rank is None else as_rank
        if self._rank_process(me) != jax.process_index():
            raise ValueError(
                f"as_rank {me} is not a local rank of this process")
        obj = self._obj.recv_obj(src_proc, self._p2p_tag(tag, src, me))
        return jax.tree_util.tree_map(
            lambda l: jnp.asarray(l) if isinstance(l, np.ndarray) else l,
            obj,
        )

    @staticmethod
    def _p2p_tag(tag, src_rank: int, dest_rank: int) -> str:
        """One ordered channel per (tag, src RANK, dest RANK) — finer
        than the object plane's per-process channels, so co-located
        ranks' messages cannot interleave."""
        return f"{tag}.r{int(src_rank)}.{int(dest_rank)}"

    def _rank_process(self, rank: int) -> int:
        """Owning process of the given rank."""
        if not 0 <= rank < self._size:
            raise ValueError(f"rank {rank} out of range [0, {self._size})")
        procs = [int(d.process_index) for d in self._comm_devices()]
        return procs[rank]

    def _replicate(self, x):
        repl = NamedSharding(self._mesh, P())
        return jax.tree_util.tree_map(
            lambda l: jax.device_put(jnp.asarray(l), repl), x
        )

    def _driver_fn(self, key: tuple):
        kind = key[0]
        if kind == "allreduce":
            op = key[1]
            return lambda l: _reduce_stacked(l, op)
        if kind == "alltoall":
            return lambda l: jnp.swapaxes(l, 0, 1)
        if kind == "allreduce_grad":
            op, cdt = key[1], key[2]

            def f(l):
                orig = l.dtype
                v = l.astype(cdt) if cdt is not None else l
                return _reduce_stacked(v, op).astype(orig)

            return f
        raise KeyError(key)

    def _driver(self, key: tuple, x, stacked_in: bool):
        """Apply a cached jitted leaf op (replicated output) over a pytree.

        Jitted callables are cached per (op, args) key — a fresh ``jax.jit``
        per call would defeat the compilation cache and retrace every step.
        """
        jitted = self._jit_cache.get(key)
        if jitted is None:
            repl = NamedSharding(self._mesh, P())
            jitted = jax.jit(self._driver_fn(key), out_shardings=repl)
            self._jit_cache[key] = jitted

        def _one(l):
            l = jnp.asarray(l)
            if stacked_in and (l.ndim == 0 or l.shape[0] != self._size):
                raise ValueError(
                    f"driver-level collective expects a stacked per-rank array "
                    f"with leading axis {self._size}, got shape {l.shape}; "
                    "inside jit/shard_map the in-graph form is used instead"
                )
            return jitted(l)

        return jax.tree_util.tree_map(_one, x)

    # -- object collectives ---------------------------------------------

    def bcast_obj(self, obj, root: int = 0):
        return self._obj.bcast_obj(obj, root)

    def gather_obj(self, obj, root: int = 0):
        return self._obj.gather_obj(obj, root)

    def allgather_obj(self, obj):
        return self._obj.allgather_obj(obj)

    def allreduce_obj(self, obj, op: str = "sum"):
        objs = self._obj.allgather_obj(obj)
        red = {
            "sum": lambda a, b: jax.tree_util.tree_map(lambda x, y: x + y, a, b),
            "max": lambda a, b: jax.tree_util.tree_map(max, a, b),
            "min": lambda a, b: jax.tree_util.tree_map(min, a, b),
        }
        if op == "mean":
            out = functools.reduce(red["sum"], objs)
            return jax.tree_util.tree_map(lambda x: x / len(objs), out)
        return functools.reduce(red[op], objs)

    def send_obj(self, obj, dest: int, tag: int = 0):
        self._obj.send_obj(obj, dest, tag)

    def recv_obj(self, src: int, tag: int = 0):
        return self._obj.recv_obj(src, tag)

    def scatter_obj(self, objs, root: int = 0):
        return self._obj.scatter_obj(objs, root)

    def host_barrier(self) -> None:
        """Process-plane barrier over the coordinator KV store — every
        wait is guarded (liveness probes, abort key, watchdog), so a dead
        peer yields a bounded JobAbortedError, not an infinite device
        rendezvous. Use for host-side sync points (checkpoint elections);
        :meth:`barrier` stays the device-collective barrier."""
        self._obj.barrier()

    # -- model-level ops ------------------------------------------------

    def bcast_data(self, params, root: int = 0):
        """Replicate a parameter pytree over the communicator's devices.

        Reference semantics (mpi_communicator_base.py `bcast_data`): pack the
        model's params into one buffer, broadcast from root, unpack — making
        every rank's initial parameters identical. Single-controller JAX has
        one source of truth already, so this lowers to replication placement
        (plus a host-plane broadcast when processes may disagree).

        ``root`` is a rank in this communicator's rank space — dense in
        ``[0, size)``, same as :attr:`rank`; multi-process it selects the
        SOURCE process — the owner of rank ``root``'s device — whose
        values every other process receives (the reference broadcasts
        from an arbitrary root the same way). Single-process the one
        process is every rank, so any root is trivially honored. On a
        communicator spanning a SUBSET of the mesh axes, a rank names a
        device GROUP (one member per complementary mesh coordinate) that
        can straddle processes, so multi-process only ``root=0`` (whose
        group contains the mesh origin) is accepted — split a full-mesh
        communicator for arbitrary roots.
        """
        spans_all = self._size == self._mesh.devices.size
        if not 0 <= root < self._size:
            raise ValueError(
                f"bcast_data root {root} out of range for a "
                f"size-{self.size} communicator (roots are communicator "
                "ranks, dense in [0, size) — comm.rank space, not "
                "comm.global_index)")
        if self.inter_size > 1:
            from jax.experimental import multihost_utils

            if not spans_all and root != 0:
                raise ValueError(
                    f"bcast_data(root={root}) on a communicator spanning "
                    f"axes {self._axes} of mesh {self._mesh.axis_names}: a "
                    "sub-axis rank is a device group that may straddle "
                    "processes, so a non-zero root has no single source "
                    "process; use root=0 or a full-mesh communicator")
            root_proc = int(self._comm_devices()[root].process_index)
            params = multihost_utils.broadcast_one_to_all(
                params, is_source=jax.process_index() == root_proc)
        repl = NamedSharding(self._mesh, P())
        return jax.tree_util.tree_map(
            lambda l: jax.device_put(jnp.asarray(l), repl), params
        )

    def allreduce_grad(self, grads, op: str = "mean"):
        """All-reduce a gradient pytree (the reference's hot path).

        Reference (pure_nccl_communicator.py): pack all grads into one flat
        GPU buffer (optionally casting to ``allreduce_grad_dtype``), one NCCL
        allreduce, unpack and scale by 1/N. Here: per-leaf psum over the mesh
        axes with optional cast to the communication dtype; XLA fuses the
        casts into the collective and its latency-hiding scheduler overlaps
        it with adjacent compute — the flat-buffer packing is the compiler's
        job, not ours.

        **Reduction-aware:** under ``shard_map``'s default varying-axis
        tracking (``check_vma=True``), differentiating w.r.t. replicated
        (``P()``) parameters already inserts the cross-shard psum — the
        incoming gradient is the *global sum* and is invariant along the mesh
        axes. This method therefore psums only over axes the gradient still
        *varies* on (read from ``jax.typeof(g).vma``) and then applies the
        1/N scaling for ``op='mean'`` — so it is correct, and communicates
        the minimum, in both ``check_vma`` modes.

        Contract for ``op='mean'``: the result is the mean over ranks of the
        per-rank local gradients — the reference's semantics. A leaf whose
        gradient never had per-rank contributions (computed purely from
        replicated values, e.g. a weight-decay term evaluated outside any
        data-dependent path) is indistinguishable from an autodiff-psummed
        per-rank sum and will also be scaled by 1/N; fold such regularizers
        into the per-rank loss (where they belong) or use ``op='sum'``.

        **Bucketing** (``dcn_bucket_bytes`` on the communicator): leaves are
        packed into flat buffers of at most that many bytes and reduced one
        buffer at a time — the reference FlatCommunicator's pack, bounded.
        Over ICI XLA's own fusion makes this a wash; the knob exists for the
        multi-slice (DCN) regime, where collective message size vs. overlap
        granularity is the tuning surface (SURVEY.md §7 "hard parts").
        """
        cdt = self._grad_dtype

        def _varying_axes(l):
            # Probe whether vma tracking is live; axis_index varies by
            # construction, so an empty vma there means tracking is off.
            if not jax.typeof(lax.axis_index(self._axes[0])).vma:
                return self._axes
            vma = jax.typeof(l).vma
            return tuple(a for a in self._axes if a in vma)

        if (_is_tracer(grads) and self._bucket_bytes
                and op in ("sum", "mean")):
            return self._bucketed_allreduce_grad(grads, op, _varying_axes)

        def _ar(l):
            varying = _varying_axes(l)
            if op in ("max", "min"):
                # invariant axes hold equal values; reducing them is identity
                return _reduce_in_graph(l, varying, op) if varying else l
            orig = l.dtype
            if varying:
                if cdt is not None and orig != cdt:
                    l = l.astype(cdt)
                l = lax.psum(l, varying)
                if l.dtype != orig:
                    l = l.astype(orig)
            if op == "mean":
                l = l / self._size
            elif op != "sum":
                raise ValueError(f"unsupported allreduce_grad op: {op!r}")
            return l

        if _is_tracer(grads):
            return jax.tree_util.tree_map(_ar, grads)
        # Driver level: stacked per-rank grads (e.g. out of a per-device map).
        if self._host_staged:
            # the reference NonCudaAwareCommunicator's actual hot path:
            # grads staged through host, comm-dtype cast included
            return self._host_staged_allreduce(grads, op, comm_dtype=cdt)
        return self._driver(("allreduce_grad", op, cdt), grads, stacked_in=True)

    def _bucketed_allreduce_grad(self, grads, op, varying_axes_of):
        """Flat-packed psum in ≤``dcn_bucket_bytes`` buffers.

        Leaves are grouped by (varying axes, dtype-after-cast) — only
        same-typed leaves can share a buffer — then packed greedily in
        pytree order (:func:`plan_buckets`). Invariant leaves skip
        communication entirely (they are already global sums under vma
        tracking)."""
        from collections import defaultdict

        cdt = self._grad_dtype
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        out = [None] * len(leaves)
        groups = defaultdict(list)
        for i, l in enumerate(leaves):
            va = varying_axes_of(l)
            if not va:
                out[i] = l / self._size if op == "mean" else l
                continue
            comm_dtype = cdt if cdt is not None else l.dtype
            groups[(va, jnp.dtype(comm_dtype))].append(i)

        for (va, comm_dtype), idxs in groups.items():
            buckets = plan_buckets(
                [(i, leaves[i].size * comm_dtype.itemsize) for i in idxs],
                self._bucket_bytes)
            for bucket in buckets:
                flat = jnp.concatenate(
                    [leaves[i].astype(comm_dtype).ravel() for i in bucket])
                red = lax.psum(flat, va)
                off = 0
                for i in bucket:
                    l = leaves[i]
                    piece = red[off:off + l.size].reshape(l.shape).astype(
                        l.dtype)
                    off += l.size
                    out[i] = piece / self._size if op == "mean" else piece
        return jax.tree_util.tree_unflatten(treedef, out)

    # -- misc -----------------------------------------------------------

    def barrier(self) -> None:
        """Host barrier across processes (reference: MPI Barrier)."""
        if self.inter_size > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("chainermn_tpu_barrier")


def _default_mesh() -> Mesh:
    """Default mesh over every device.

    Single process: 1-D ``('r',)``. Multi-process: ``('dcn', 'ici')`` with the
    DCN axis over processes — the analog of the reference's inter-node MPI ×
    intra-node NCCL factorization (hierarchical_communicator.py), which XLA's
    collective lowering reproduces automatically for this mesh.
    """
    devs = np.asarray(jax.devices())
    if jax.process_count() > 1:
        local = jax.local_device_count()
        grid = devs.reshape(jax.process_count(), local)
        return Mesh(grid, ("dcn", "ici"))
    return Mesh(devs, (DEFAULT_AXIS,))

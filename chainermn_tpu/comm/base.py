"""Abstract communicator contract.

TPU-native re-design of the reference's ``CommunicatorBase``
(reference: chainermn/communicators/communicator_base.py — module path cited from
SURVEY.md; the reference mount was empty, so line numbers are unavailable).

The reference contract is an MPI-rank-per-GPU object exposing
``rank/size/intra_rank/intra_size/inter_rank/inter_size``, array collectives,
pickled-object collectives, and model-level ``bcast_data``/``allreduce_grad``.

This rebuild keeps the exact surface but maps it onto the JAX single-controller
SPMD model:

* **Device ranks** are coordinates in a :class:`jax.sharding.Mesh`. ``size`` is
  the number of devices the communicator spans; ``rank`` is the global index of
  this process's first addressable device (0 in single-process runs, where the
  driver acts on behalf of every rank).
* **intra/inter** mirror the reference's node topology: ``intra`` = devices
  local to this process (ICI-connected), ``inter`` = across processes (DCN).
* **Array collectives** are dual-mode: called on tracers (inside ``jit`` /
  ``shard_map``) they lower to XLA collectives (``psum``, ``all_gather``,
  ``all_to_all``, ``ppermute``) over the communicator's mesh axes; called on
  concrete arrays they operate on *stacked per-rank* values (leading axis ==
  ``size``) and are jitted so XLA inserts the real collectives for sharded
  inputs.
* **Object collectives** ride the host object plane (``jax.distributed`` /
  multihost utilities), whose world is the *process* space — the analog of the
  reference's MPI object plane.
"""

from __future__ import annotations

import abc
from typing import Any, Optional, Sequence


class CommunicatorBase(abc.ABC):
    """Abstract base for all communicators.

    Matches the reference ABC's method surface (SURVEY.md §2.1). Concrete
    subclasses: :class:`~chainermn_tpu.comm.xla.XlaCommunicator` and its
    single-device degenerate forms.
    """

    # ------------------------------------------------------------------
    # topology
    # ------------------------------------------------------------------

    @property
    @abc.abstractmethod
    def size(self) -> int:
        """Total number of ranks (devices) this communicator spans."""

    @property
    @abc.abstractmethod
    def rank(self) -> int:
        """Rank of this process's first local device in THIS
        communicator — dense in ``[0, size)`` (the reference invariant),
        so it is always a valid root/peer for this communicator's
        collectives. See :attr:`global_index` for the mesh-global
        position."""

    @property
    def global_index(self) -> int:
        """Mesh-global flat index of this process's first device. Equal
        to :attr:`rank` on a full-mesh communicator; on sub-axis
        communicators it can exceed ``size`` — a coordinate for
        bookkeeping, never a root (dlint DL103)."""
        return self.rank

    @property
    @abc.abstractmethod
    def intra_rank(self) -> int:
        """Rank within this process's local (ICI-connected) device group."""

    @property
    @abc.abstractmethod
    def intra_size(self) -> int:
        """Number of local devices (reference: GPUs per node)."""

    @property
    @abc.abstractmethod
    def inter_rank(self) -> int:
        """Process index (reference: node index)."""

    @property
    @abc.abstractmethod
    def inter_size(self) -> int:
        """Number of processes (reference: node count)."""

    # ------------------------------------------------------------------
    # mesh access (rebuild-specific, the idiomatic seam)
    # ------------------------------------------------------------------

    @property
    @abc.abstractmethod
    def mesh(self):
        """The :class:`jax.sharding.Mesh` backing this communicator."""

    @property
    @abc.abstractmethod
    def axis_names(self) -> tuple:
        """Mesh axis names this communicator reduces over (ordered)."""

    # ------------------------------------------------------------------
    # sub-communicators
    # ------------------------------------------------------------------

    @abc.abstractmethod
    def split(self, color: int, key: int) -> "CommunicatorBase":
        """Create a sub-communicator (reference: ``MPI_Comm_split`` semantics).

        In the mesh world a split is a factorization: ranks with equal
        ``color`` form a group. Only regular partitions (equal-sized,
        stride-contiguous groups) are supported, because irregular groups
        cannot be expressed as a mesh axis.
        """

    # ------------------------------------------------------------------
    # array collectives (dual-mode: in-graph on tracers, driver on arrays)
    # ------------------------------------------------------------------

    @abc.abstractmethod
    def allreduce(self, x, op: str = "sum"):
        """All-reduce. Tracer: psum-family over mesh axes. Concrete: reduce
        the stacked leading rank axis."""

    @abc.abstractmethod
    def bcast(self, x, root: int = 0):
        """Broadcast from ``root``."""

    @abc.abstractmethod
    def allgather(self, x):
        """Gather every rank's array on every rank (stacked on axis 0)."""

    @abc.abstractmethod
    def alltoall(self, x):
        """All-to-all: rank r's chunk s goes to rank s's slot r."""

    @abc.abstractmethod
    def gather(self, x, root: int = 0):
        """Gather to ``root`` (single-controller: the driver holds it)."""

    @abc.abstractmethod
    def scatter(self, x, root: int = 0):
        """Scatter ``root``'s stacked array across ranks."""

    @abc.abstractmethod
    def send(self, x, dest: int, tag: int = 0):
        """Point-to-point send (in-graph only; lowers to collective-permute)."""

    @abc.abstractmethod
    def recv(self, src: int, tag: int = 0):
        """Point-to-point recv (in-graph only; lowers to collective-permute)."""

    # ------------------------------------------------------------------
    # object collectives (process-plane; reference: pickled MPI messages)
    # ------------------------------------------------------------------

    @abc.abstractmethod
    def bcast_obj(self, obj: Any, root: int = 0) -> Any:
        ...

    @abc.abstractmethod
    def gather_obj(self, obj: Any, root: int = 0) -> Optional[Sequence[Any]]:
        ...

    @abc.abstractmethod
    def allgather_obj(self, obj: Any) -> Sequence[Any]:
        ...

    @abc.abstractmethod
    def allreduce_obj(self, obj: Any, op: str = "sum") -> Any:
        ...

    @abc.abstractmethod
    def send_obj(self, obj: Any, dest: int, tag: int = 0) -> None:
        ...

    @abc.abstractmethod
    def recv_obj(self, src: int, tag: int = 0) -> Any:
        ...

    def host_barrier(self) -> None:
        """Process-plane barrier over the HOST transport (coordinator KV
        store where available): bounded waits that the object plane's
        fail-fast probes and the resilience watchdog can interrupt — a
        dead peer raises instead of hanging forever. Default falls back
        to :meth:`barrier` for communicators without a host transport."""
        barrier = getattr(self, "barrier", None)
        if callable(barrier):
            barrier()

    # ------------------------------------------------------------------
    # model-level ops (the reference's headline API)
    # ------------------------------------------------------------------

    @abc.abstractmethod
    def bcast_data(self, params, root: int = 0):
        """Synchronize a parameter pytree across ranks (reference:
        ``bcast_data(model)`` packing params into one buffer and
        broadcasting). Single-controller: replicate over the mesh."""

    @abc.abstractmethod
    def allreduce_grad(self, grads, op: str = "mean"):
        """All-reduce a gradient pytree (reference: the hot
        ``allreduce_grad(model)`` pack → NCCL allreduce → unpack × 1/N).
        Lowered to per-leaf ``psum``/``pmean`` fused by XLA; optional
        communication dtype (``allreduce_grad_dtype``) casts before the
        collective and back after."""

    # ------------------------------------------------------------------
    # misc
    # ------------------------------------------------------------------

    def finalize(self) -> None:
        """Release resources (reference: NCCL comm destroy). No-op here —
        XLA owns collective lifetimes."""

    @property
    def is_master(self) -> bool:
        """True on the process that should do logging/reporting (the
        reference convention ``if comm.rank == 0:``)."""
        return self.inter_rank == 0

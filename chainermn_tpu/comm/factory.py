"""create_communicator — name → communicator factory.

Reference: chainermn/communicators/__init__.py's ``create_communicator(name,
mpi_comm, allreduce_grad_dtype)`` mapping seven names to seven hand-built
NCCL/MPI compositions (SURVEY.md §2.1; reference mount empty — module path
citation only).

On TPU all seven collapse into :class:`XlaCommunicator`; the names survive as
aliases so reference scripts run unchanged. Where a name encoded a topology
choice (hierarchical / two_dimensional), the alias shapes the default mesh the
same way — and XLA's collective lowering then *is* the algorithm the reference
hand-wrote.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

import jax
from jax.sharding import Mesh

from .xla import DEFAULT_AXIS, DEFAULT_DCN_BUCKET_BYTES, XlaCommunicator

_COMM_NAMES = (
    "xla",          # the native name
    "naive",        # reference: per-param MPI allreduce, works anywhere
    "flat",         # reference: one fused buffer, flat allreduce
    "hierarchical", # reference: NCCL intra-node + MPI inter-node
    "two_dimensional",  # reference: reduce-scatter / allreduce / all-gather
    "single_node",  # reference: NCCL within one node only
    "non_cuda_aware",   # reference: host-staged MPI
    "pure_nccl",    # reference: flat NCCL-2 ring, the perf path
)


def create_communicator(
    communicator_name: str = "xla",
    mesh: Optional[Mesh] = None,
    allreduce_grad_dtype: Optional[Any] = None,
    axes=None,
    dcn_bucket_bytes: Optional[int] = None,
) -> XlaCommunicator:
    """Create a communicator by name.

    All names return an :class:`XlaCommunicator`; legacy names are topology
    aliases. ``mesh``/``axes`` allow full control (e.g. a ``('data','model')``
    mesh with two communicators for hybrid parallelism).
    ``dcn_bucket_bytes`` bounds the flat-packed gradient buffers of
    ``allreduce_grad`` — the multi-slice (DCN) overlap-granularity knob.
    The DCN-facing aliases (hierarchical / two_dimensional) default to
    ``DEFAULT_DCN_BUCKET_BYTES`` (4 MiB; derivation in
    docs/scaling_model.md §4); pass an explicit value (or 0/None via a
    plain 'xla' communicator) to override.
    """
    name = communicator_name
    if name not in _COMM_NAMES:
        raise ValueError(
            f"unknown communicator {name!r}; expected one of {_COMM_NAMES}"
        )
    if dcn_bucket_bytes is None and name in ("hierarchical",
                                             "two_dimensional"):
        dcn_bucket_bytes = DEFAULT_DCN_BUCKET_BYTES

    if mesh is None:
        if name == "single_node":
            if jax.process_count() > 1:
                raise ValueError(
                    "'single_node' requires a single process (reference "
                    "asserts inter_size == 1 in single_node_communicator.py)"
                )
            mesh = Mesh(np.asarray(jax.local_devices()), (DEFAULT_AXIS,))
        elif name in ("hierarchical", "two_dimensional"):
            # Explicit 2-level (dcn, ici) factorization even single-process:
            # these names exist to exercise the hierarchical lowering.
            devs = np.asarray(jax.devices())
            local = jax.local_device_count()
            mesh = Mesh(devs.reshape(-1, local), ("dcn", "ici"))

    comm = XlaCommunicator(
        mesh=mesh, axes=axes, allreduce_grad_dtype=allreduce_grad_dtype,
        dcn_bucket_bytes=dcn_bucket_bytes,
        # reference parity: NonCudaAwareCommunicator stages driver-level
        # arrays through host memory (non_cuda_aware_communicator.py)
        host_staged=(name == "non_cuda_aware"),
    )
    comm.name = name
    return comm

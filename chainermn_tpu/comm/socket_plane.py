"""SocketObjectPlane — the real network data plane.

TCP point-to-point object transport behind the exact object-plane
contract (``send_obj`` / ``recv_obj`` / ``try_recv_obj``, per-channel
tags and sequence numbers), so :class:`~chainermn_tpu.fleet.transport.
ObjectPlaneTransport` runs over it unchanged — the production sibling
of the coordinator-KV :class:`~chainermn_tpu.comm.object_plane.
ObjectPlane` and the drill-harness :class:`~chainermn_tpu.comm.
object_plane.FsObjectPlane`.

Wire discipline:

* **length-prefixed, SHA-framed messages** — every frame is a fixed
  binary header (magic, kind, src, tag, seq, payload length, sha256)
  followed by the pickled payload. A torn stream (partial write, RST
  mid-frame) fails the length or digest check; the reader drops the
  connection rather than deliver damaged bytes — the sender reconnects
  and the layer above re-sends.
* **frame batching / coalescing** — frames smaller than
  ``coalesce_bytes`` (acks, NACKs, control messages) are buffered per
  peer and flushed in one ``sendall`` when the batch fills, a large
  frame follows, or the ``coalesce_ms`` window closes (a background
  flusher bounds the added latency) — the small-ack syscall storm of a
  chatty handoff protocol collapses into a few writes.
* **RpcPolicy-budgeted timeouts everywhere** — connects and reads run
  under ``settimeout`` derived from :class:`~chainermn_tpu.resilience.
  policy.RpcPolicy` (connect = one probe slice, reconnect attempts ride
  the jittered ``backoff_ms`` ladder, bounded by an attempt cap). No
  socket in this module ever blocks unbounded on a dead peer.
* **half-open detection** — a connection is not usable until its
  HELLO/HELLO-ACK handshake round-trips within the probe budget, so a
  connect that lands in a dead NAT entry (or a peer that accepted and
  wedged) times out and retries instead of wedging the sender; an
  established connection that stops accepting bytes hits the send
  timeout, is torn down, and is re-handshaked.
* **restart fencing (the FsObjectPlane HWM discipline over TCP)** — the
  HELLO carries the sender's incarnation and per-tag sequence
  high-water marks; the HELLO-ACK answers with the receiver's consumed
  positions. A reborn *sender* (fresh counters) is bumped up to the
  receiver's position so it never reuses a sequence number — its
  replayed streams arrive as fresh frames and the transport's resolved
  fence answers them ``duplicate``. A reborn *receiver* fast-forwards
  past frames a previous incarnation consumed, and frames lost with a
  dead connection become known holes the reader skips (``floor``) —
  the layer above's ack timeout owns their re-send. Stale frames below
  the consumed position are counted and dropped, never re-delivered.

Delivery semantics match the other planes: ``send_obj`` is fire-and-
forget — it tries to put the frame on a live connection (reconnecting
under the backoff ladder if needed) and on exhaustion counts the frame
as dropped rather than raising, because loss is exactly what the
transport's RpcPolicy-bounded ack/NACK/re-send protocol exists to
absorb. ``try_recv_obj`` commits the reader position only on success:
a timeout leaves the channel intact for the next poll.

Chaos: ``chaos.on_socket("send")`` can answer ``reset_conn`` (the
connection dies under the frame) or ``partial_write`` (half the frame
is written, then the connection dies); ``chaos.on_socket("accept")``
sleeps the acceptor (``stall_accept``). Either connection fault tears
the socket down and the plane re-sends the same frame on a fresh
connection — against a live peer a connection fault costs a redial,
never a frame, because ctrl traffic above the plane has no ack/re-send
of its own. The socket drills drive the same bitwise oracle as the
PR 14 wire-chaos matrix through these.
"""

from __future__ import annotations

import hashlib
import pickle
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from chainermn_tpu.resilience import chaos as _chaos
from chainermn_tpu.resilience.policy import RpcPolicy, policy as _rpc_policy

__all__ = ["SocketObjectPlane", "pick_free_endpoints"]

_MAGIC = b"CMTP"
_KIND_HELLO = 0
_KIND_HELLO_ACK = 1
_KIND_OBJ = 2

#: header: magic(4s) kind(B) src(I) tag(q) seq(Q) length(Q) sha256(32s)
_HDR = struct.Struct("!4sBIqQQ32s")


def pick_free_endpoints(n: int) -> List[str]:
    """``n`` localhost ``host:port`` endpoints with currently-free
    ports (bind-0 probe; tests and the bench gate hand these to every
    rank before any plane binds — a tiny race window on a busy CI box,
    same trade every ephemeral-port harness makes)."""
    eps = []
    socks = []
    try:
        for _ in range(n):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.bind(("127.0.0.1", 0))
            socks.append(s)
            eps.append(f"127.0.0.1:{s.getsockname()[1]}")
    finally:
        for s in socks:
            s.close()
    return eps


def _encode_frame(kind: int, src: int, tag: int, seq: int,
                  payload: bytes) -> bytes:
    digest = hashlib.sha256(payload).digest()
    return _HDR.pack(_MAGIC, kind, src, tag, seq,
                     len(payload), digest) + payload


class _PeerOut:
    """Sender-side state for one destination: the connection, the
    per-tag sequence counters, and the coalescing buffer."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.sock: Optional[socket.socket] = None
        self.seq: Dict[int, int] = {}       # tag → next seq
        self.lost: Dict[int, int] = {}      # tag → maybe-lost HWM
        self.batch: List[bytes] = []        # coalesced small frames
        self.batch_bytes = 0
        self.batch_since = 0.0              # monotonic of oldest frame

    def mark_lost(self) -> None:
        """Every seq assigned so far may be lost (the connection died
        or delivery was abandoned) — the next HELLO advertises this
        high-water mark so the receiver can skip the holes."""
        for tag, nxt in self.seq.items():
            if nxt > self.lost.get(tag, 0):
                self.lost[tag] = nxt


class SocketObjectPlane:
    """TCP object plane over ``endpoints[i] = "host:port"`` per rank.

    Binds ``endpoints[index]`` and accepts peer connections on a
    daemon thread; outgoing connections are made lazily per
    destination. ``incarnation`` defaults to the supervisor's restart
    counter (``$CHAINERMN_TPU_RESTART_COUNT``) so a reborn process
    re-handshakes as a new incarnation without any caller wiring."""

    #: bounded connect/delivery attempts per send (the jittered
    #: backoff ladder between them; exhaustion drops the frame)
    CONNECT_ATTEMPTS = 4

    def __init__(self, endpoints: List[str], index: int, *,
                 pol: Optional[RpcPolicy] = None,
                 incarnation: Optional[int] = None,
                 coalesce_ms: float = 2.0,
                 coalesce_bytes: int = 4096,
                 coalesce_frames: int = 16) -> None:
        self.endpoints = [self._parse(e) for e in endpoints]
        self.process_index = int(index)
        self.process_count = len(endpoints)
        if not 0 <= self.process_index < self.process_count:
            raise ValueError(f"index {index} outside "
                             f"[0, {self.process_count})")
        self.policy = pol or _rpc_policy()
        if incarnation is None:
            import os
            try:
                incarnation = int(
                    os.environ.get("CHAINERMN_TPU_RESTART_COUNT", "0"))
            except ValueError:
                incarnation = 0
        self.incarnation = int(incarnation)
        self.coalesce_ms = float(coalesce_ms)
        self.coalesce_bytes = int(coalesce_bytes)
        self.coalesce_frames = int(coalesce_frames)
        self.stats = {"connects": 0, "reconnects": 0, "frames_sent": 0,
                      "frames_recv": 0, "bytes_sent": 0, "bytes_recv": 0,
                      "batched_frames": 0, "flushes": 0,
                      "stale_frames": 0, "corrupt_frames": 0,
                      "send_dropped": 0, "resent_frames": 0,
                      "hellos": 0}
        self._out: Dict[int, _PeerOut] = {}
        self._out_lock = threading.Lock()
        # receiver side: (src, tag) → {seq: payload}; positions commit
        # only on a successful try_recv (the poller contract)
        self._cond = threading.Condition()
        self._buf: Dict[Tuple[int, int], Dict[int, bytes]] = {}
        self._pos: Dict[Tuple[int, int], int] = {}
        self._floor: Dict[Tuple[int, int], int] = {}  # known-lost holes
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.settimeout(self._probe_s())
        self._srv.bind(self.endpoints[self.process_index])
        self._srv.listen(max(4, 2 * self.process_count))
        self._spawn(self._accept_loop, "sockplane-accept")
        self._spawn(self._flush_loop, "sockplane-flush")

    # -- plumbing --------------------------------------------------------

    @staticmethod
    def _parse(ep) -> Tuple[str, int]:
        """Accepts ``"host:port"`` (bare ``:port`` → 127.0.0.1) or an
        already-split ``(host, port)`` pair."""
        if isinstance(ep, (tuple, list)):
            host, port = ep
            return (str(host) or "127.0.0.1", int(port))
        host, _, port = ep.rpartition(":")
        return (host or "127.0.0.1", int(port))

    def _probe_s(self) -> float:
        """One probe slice in seconds — the per-socket-op timeout (and
        the half-open detection bound: no read/connect/accept waits
        longer than this before re-checking liveness/stop)."""
        return max(0.05, min(self.policy.probe_ms, 10_000) / 1000.0)

    def _spawn(self, fn, name: str) -> None:
        th = threading.Thread(target=fn, name=name, daemon=True)
        th.start()
        self._threads.append(th)

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._out_lock:
            peers = list(self._out.items())
        for dest, peer in peers:
            with peer.lock:
                # a frame sent right before close() (an eof, a final
                # ack) may still sit in the coalescing batch — put it
                # on the wire before the connection dies
                self._flush_batch(peer, dest)
                self._drop_conn(peer)
        for th in self._threads:
            th.join(timeout=2 * self._probe_s())

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def gc(self, src: int, tag: int = 0) -> int:
        """Frames are freed as they are consumed; nothing to prune
        (the transport calls this on planes that need it)."""
        return 0

    # -- sender face -----------------------------------------------------

    def send_obj(self, obj: Any, dest: int, tag: int = 0) -> None:
        if dest == self.process_index:
            raise RuntimeError("send_obj to self has no wire")
        payload = pickle.dumps(obj)
        peer = self._peer(dest)
        with peer.lock:
            # connect (and handshake) BEFORE drawing a seq: the
            # HELLO-ACK seeds this peer's counters from the receiver's
            # consumed position, so a reborn sender's very first frame
            # already carries a never-before-used sequence number
            sock = self._conn(peer, dest)
            seq = peer.seq.get(tag, 0)
            # the frame owns this seq even if delivery fails — a lost
            # seq is a hole the next HELLO advertises, mirroring
            # FsObjectPlane's never-reuse discipline
            peer.seq[tag] = seq + 1
            frame = _encode_frame(_KIND_OBJ, self.process_index,
                                  tag, seq, payload)
            fault = _chaos.on_socket("send")
            if fault is not None:
                self._apply_send_fault(peer, dest, frame, fault)
                return
            if sock is None:
                # connect budget already exhausted under the ladder —
                # the frame is lost; the layer above re-sends
                peer.mark_lost()
                self.stats["send_dropped"] += 1
                return
            if len(frame) < self.coalesce_bytes:
                if not peer.batch:
                    peer.batch_since = time.monotonic()
                peer.batch.append(frame)
                peer.batch_bytes += len(frame)
                self.stats["batched_frames"] += 1
                if (len(peer.batch) >= self.coalesce_frames
                        or peer.batch_bytes >= self.coalesce_bytes):
                    self._flush_batch(peer, dest)
                return
            self._flush_batch(peer, dest)
            self._write(peer, dest, frame)

    def _peer(self, dest: int) -> _PeerOut:
        with self._out_lock:
            peer = self._out.get(dest)
            if peer is None:
                peer = self._out[dest] = _PeerOut()
            return peer

    def _apply_send_fault(self, peer: _PeerOut, dest: int,
                          frame: bytes, fault: str) -> None:
        """Injected connection fault (chaos.on_socket): the batch is
        flushed first so only THIS frame is hit. The connection dies
        (for ``partial_write``, with a torn half-frame on the wire the
        reader will discard at EOF) — then the SAME frame is re-sent
        through the reconnect ladder. Against a live peer a connection
        fault costs a redial, never a frame: the plane must not leak
        loss to ack-less traffic (ctrl frames) riding above it."""
        self._flush_batch(peer, dest)
        sock = self._conn(peer, dest)
        if fault == "partial_write" and sock is not None:
            try:
                sock.sendall(frame[:max(1, len(frame) // 2)])
            except OSError:
                pass
        self._drop_conn(peer)
        self.stats["reconnects"] += 1
        self.stats["resent_frames"] += 1
        self._write(peer, dest, frame)

    def _conn(self, peer: _PeerOut,
              dest: int) -> Optional[socket.socket]:
        """The live connection to ``dest``, dialing + handshaking under
        the backoff ladder if needed (caller holds ``peer.lock``)."""
        if peer.sock is not None:
            return peer.sock
        for attempt in range(self.CONNECT_ATTEMPTS):
            if self._stop.is_set():
                return None
            try:
                sock = socket.create_connection(
                    self.endpoints[dest], timeout=self._probe_s())
                sock.settimeout(self._probe_s())
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
                self._handshake(sock, peer)
                if peer.sock is None:
                    self.stats["connects"] += 1
                else:  # pragma: no cover — replaced conn (defensive)
                    self.stats["reconnects"] += 1
                peer.sock = sock
                return sock
            except (OSError, TimeoutError, pickle.PickleError,
                    ValueError):
                # connect refused/timed out, or a half-open peer ate
                # the HELLO without answering: back off and redial
                if attempt + 1 < self.CONNECT_ATTEMPTS:
                    time.sleep(self.policy.backoff_ms(attempt) / 1000.0)
        return None

    def _handshake(self, sock: socket.socket, peer: _PeerOut) -> None:
        """HELLO → HELLO-ACK within one probe budget, or the connection
        is unusable (half-open detection). Seeds this sender's seq
        counters from the receiver's consumed positions so a reborn
        incarnation never reuses a sequence number."""
        hello = {"src": self.process_index,
                 "incarnation": self.incarnation,
                 "seqs": dict(peer.lost)}
        payload = pickle.dumps(hello)
        sock.sendall(_encode_frame(_KIND_HELLO, self.process_index,
                                   0, 0, payload))
        kind, _src, _tag, _seq, ack = self._read_frame(sock)
        if kind != _KIND_HELLO_ACK:
            raise ValueError(f"expected HELLO-ACK, got kind {kind}")
        positions = pickle.loads(ack).get("positions", {})
        for tag, pos in positions.items():
            peer.seq[int(tag)] = max(peer.seq.get(int(tag), 0), int(pos))
        self.stats["hellos"] += 1

    def _drop_conn(self, peer: _PeerOut) -> None:
        if peer.sock is not None:
            try:
                peer.sock.close()
            except OSError:
                pass
            peer.sock = None
        peer.batch, peer.batch_bytes = [], 0
        peer.mark_lost()

    def _write(self, peer: _PeerOut, dest: int, data: bytes) -> None:
        """Put bytes on the wire, reconnecting once per attempt under
        the ladder; exhaustion counts the frame dropped (the transport
        above re-sends — loss here is a NACK/timeout there)."""
        for attempt in range(self.CONNECT_ATTEMPTS):
            sock = self._conn(peer, dest)
            if sock is None:
                break
            try:
                sock.sendall(data)
                self.stats["frames_sent"] += 1
                self.stats["bytes_sent"] += len(data)
                return
            except OSError:
                # send timeout or RST: half-open/dead conn — tear down,
                # back off, re-handshake, retry the same bytes
                self._drop_conn(peer)
                self.stats["reconnects"] += 1
                if attempt + 1 < self.CONNECT_ATTEMPTS:
                    time.sleep(self.policy.backoff_ms(attempt) / 1000.0)
        peer.mark_lost()
        self.stats["send_dropped"] += 1

    def _flush_batch(self, peer: _PeerOut, dest: int) -> None:
        if not peer.batch:
            return
        data = b"".join(peer.batch)
        n = len(peer.batch)
        peer.batch, peer.batch_bytes = [], 0
        self.stats["flushes"] += 1
        self._write(peer, dest, data)
        self.stats["frames_sent"] += n - 1   # _write counted one

    def _flush_loop(self) -> None:
        """Background flusher: closes every coalescing window within
        ``coalesce_ms`` so a lone ack never waits on more traffic."""
        while not self._stop.is_set():
            time.sleep(self.coalesce_ms / 1000.0)
            with self._out_lock:
                items = list(self._out.items())
            now = time.monotonic()
            for dest, peer in items:
                with peer.lock:
                    if (peer.batch and now - peer.batch_since
                            >= self.coalesce_ms / 1000.0):
                        self._flush_batch(peer, dest)

    # -- receiver face ---------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            _chaos.on_socket("accept")
            try:
                conn, _addr = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return                     # listener closed
            conn.settimeout(self._probe_s())
            self._spawn(lambda c=conn: self._reader(c), "sockplane-read")

    @staticmethod
    def _read_exact(sock: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            part = sock.recv(n - len(buf))
            if not part:
                raise ConnectionError("peer closed")
            buf += part
        return buf

    def _read_frame(self, sock: socket.socket):
        """One framed message off ``sock``; raises on torn/corrupt
        bytes (the caller drops the connection — resync happens at the
        next handshake, never inside a damaged stream)."""
        while True:
            try:
                hdr = self._read_exact(sock, _HDR.size)
                break
            except socket.timeout:
                if self._stop.is_set():
                    raise ConnectionError("plane closed") from None
        magic, kind, src, tag, seq, length, digest = _HDR.unpack(hdr)
        if magic != _MAGIC:
            raise ValueError("bad frame magic (desynced stream)")
        payload = self._read_exact(sock, length)
        if hashlib.sha256(payload).digest() != digest:
            raise ValueError("frame sha256 mismatch")
        return kind, src, tag, seq, payload

    def _reader(self, conn: socket.socket) -> None:
        src = None
        try:
            while not self._stop.is_set():
                try:
                    kind, fsrc, tag, seq, payload = self._read_frame(conn)
                except socket.timeout:
                    continue               # idle conn: keep listening
                if kind == _KIND_HELLO:
                    src = self._on_hello(conn, payload)
                elif kind == _KIND_OBJ:
                    self._on_obj(fsrc, tag, seq, payload)
                self.stats["frames_recv"] += 1
                self.stats["bytes_recv"] += _HDR.size + len(payload)
        except (ConnectionError, ValueError, OSError) as e:
            if isinstance(e, ValueError):
                self.stats["corrupt_frames"] += 1
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _on_hello(self, conn: socket.socket, payload: bytes) -> int:
        hello = pickle.loads(payload)
        src = int(hello["src"])
        with self._cond:
            for tag, nxt in hello.get("seqs", {}).items():
                chan = (src, int(tag))
                # frames below the sender's announced counter that we
                # neither consumed nor hold are lost with the old
                # connection: known holes the read path may skip
                self._floor[chan] = max(self._floor.get(chan, 0),
                                        int(nxt))
            # consumed position, pushed past anything still buffered
            # from the old incarnation, so a reborn sender seeded from
            # it can never collide with an undelivered frame
            positions: Dict[int, int] = {}
            for (s, tag), pos in self._pos.items():
                if s == src:
                    positions[tag] = pos
            for (s, tag), pending in self._buf.items():
                if s == src and pending:
                    positions[tag] = max(positions.get(tag, 0),
                                         max(pending) + 1)
            self._cond.notify_all()
        ack = pickle.dumps({"positions": positions,
                            "incarnation": self.incarnation})
        conn.sendall(_encode_frame(_KIND_HELLO_ACK, self.process_index,
                                   0, 0, ack))
        return src

    def _on_obj(self, src: int, tag: int, seq: int,
                payload: bytes) -> None:
        chan = (src, tag)
        with self._cond:
            if seq < self._pos.get(chan, 0):
                self.stats["stale_frames"] += 1   # already consumed
                return
            self._buf.setdefault(chan, {})[seq] = payload
            self._cond.notify_all()

    def recv_obj(self, src: int, tag: int = 0) -> Any:
        return self.try_recv_obj(src, tag,
                                 timeout_ms=self.policy.timeout_ms)

    def try_recv_obj(self, src: int, tag: int = 0,
                     timeout_ms: Optional[int] = None) -> Any:
        """Bounded receive; the reader position advances only on
        success, so a timed-out poll retries the same slot later.
        Holes below the re-handshake floor (frames lost with a dead
        connection) are skipped — their payloads re-arrive under fresh
        sequence numbers when the layer above re-sends."""
        if timeout_ms is None:
            timeout_ms = self.policy.timeout_ms
        deadline = time.monotonic() + max(0, timeout_ms) / 1000.0
        chan = (src, tag)
        with self._cond:
            while True:
                pos = self._pos.get(chan, 0)
                buf = self._buf.get(chan, {})
                floor = self._floor.get(chan, 0)
                while pos < floor and pos not in buf:
                    pos += 1               # known-lost hole: skip
                if pos in buf:
                    payload = buf.pop(pos)
                    self._pos[chan] = pos + 1
                    return pickle.loads(payload)
                # position commits only on delivery — a skipped hole
                # is re-evaluated next poll, so a frame that was
                # merely slow (not lost) is never discarded
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"no object on channel {chan} within "
                        f"{timeout_ms} ms")
                self._cond.wait(timeout=min(left, self._probe_s()))

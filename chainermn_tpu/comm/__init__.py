from .base import CommunicatorBase
from .factory import create_communicator
from .xla import DEFAULT_AXIS, XlaCommunicator

__all__ = [
    "CommunicatorBase",
    "XlaCommunicator",
    "create_communicator",
    "DEFAULT_AXIS",
]
